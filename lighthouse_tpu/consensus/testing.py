"""Deterministic test fixtures: interop keypairs + synthetic states/blocks.

Twin of the reference's interop genesis + harness seeding
(beacon_node/genesis/src/interop.rs, beacon_chain/src/test_utils.rs:324
`generate_deterministic_keypairs`): validator i's secret key is the standard
interop derivation sha256(uint64_le(i) padded to 32) reduced mod the curve
order, so fixtures here are reproducible and match other interop tooling.
"""

from __future__ import annotations

from functools import lru_cache

from ..crypto.bls import api as bls
from ..crypto.bls.params import R as CURVE_ORDER
from ..ops import sha256
from .containers import BeaconBlockHeader, Checkpoint, Fork, Validator, types_for
from .spec import ChainSpec, Preset

FAR_FUTURE_EPOCH = 2**64 - 1


@lru_cache(maxsize=4096)
def interop_secret_key(index: int) -> bls.SecretKey:
    sk = (
        int.from_bytes(sha256(index.to_bytes(32, "little")), "little")
        % CURVE_ORDER
    )
    return bls.SecretKey(sk)


@lru_cache(maxsize=64)
def _interop_keypairs_cached(n: int) -> tuple:
    return tuple(
        (interop_secret_key(i), interop_secret_key(i).public_key())
        for i in range(n)
    )


def interop_keypairs(n: int) -> list[tuple[bls.SecretKey, bls.PublicKey]]:
    return list(_interop_keypairs_cached(n))


def phase0_spec(preset: Preset) -> ChainSpec:
    """A forks-off ChainSpec: everything stays at the genesis fork version
    (the shape most unit fixtures want; fork-transition tests override)."""
    return ChainSpec(
        preset=preset,
        config_name=f"{preset.name}-phase0-test",
        altair_fork_epoch=None,
        bellatrix_fork_epoch=None,
        capella_fork_epoch=None,
        deneb_fork_epoch=None,
    )


# Built genesis states keyed by every spec field the construction reads.
# interop_state is called once per node per test, and the altair+ variants
# pay a sync-committee computation each time — caching lets a scenario run
# dozens of in-process nodes off one genesis build.  Values are deep-copied
# on the way out, so callers mutate freely (same semantics as rebuilding).
_INTEROP_STATE_CACHE: dict[tuple, object] = {}
_INTEROP_STATE_CACHE_MAX = 16


def interop_state(
    n_validators: int,
    spec: ChainSpec,
    balance: int | None = None,
    fork: str = "base",
    registry_padding: int = 0,
):
    """Genesis-like BeaconState (chosen fork variant) with n interop
    validators, plus the keypairs.  genesis_validators_root is computed per
    spec (the root of the validator registry).

    ``registry_padding`` appends that many *inactive* synthetic validators
    (never-activated, zero balance) after the interop set, and freezes the
    whole registry for copy-on-write sharing — the cheap-node path that lets
    scenarios run registry-scale states across dozens of in-process nodes.
    """
    key = (
        n_validators, balance, fork, spec.preset, spec.config_name,
        spec.max_effective_balance, spec.min_genesis_time,
        bytes(spec.genesis_fork_version),
        bytes(getattr(spec, f"{fork}_fork_version"))
        if fork != "base"
        and getattr(spec, f"{fork}_fork_epoch", None) is not None
        else None,
        registry_padding,
    )
    cached = _INTEROP_STATE_CACHE.get(key)
    if cached is not None:
        return cached.copy(), interop_keypairs(n_validators)
    state, keypairs = _build_interop_state(
        n_validators, spec, balance, fork, registry_padding
    )
    if len(_INTEROP_STATE_CACHE) >= _INTEROP_STATE_CACHE_MAX:
        _INTEROP_STATE_CACHE.pop(next(iter(_INTEROP_STATE_CACHE)))
    _INTEROP_STATE_CACHE[key] = state.copy()
    return state, keypairs


def _padding_validators(count: int, offset: int) -> list:
    """Inactive registry filler: unique synthetic pubkeys (no BLS key behind
    them — they never sign), FAR epochs everywhere, zero effective balance.
    Kept frozen so copies/roots share them."""
    out = []
    for i in range(count):
        v = Validator(
            pubkey=b"\xfa" + (offset + i).to_bytes(8, "little") + b"\x00" * 39,
            withdrawal_credentials=b"\x00" * 32,
            effective_balance=0,
            slashed=False,
            activation_eligibility_epoch=FAR_FUTURE_EPOCH,
            activation_epoch=FAR_FUTURE_EPOCH,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH,
        )
        out.append(v.freeze())
    return out


def _build_interop_state(
    n_validators: int,
    spec: ChainSpec,
    balance: int | None = None,
    fork: str = "base",
    registry_padding: int = 0,
):
    preset = spec.preset
    T = types_for(preset)
    balance = balance if balance is not None else spec.max_effective_balance
    keypairs = interop_keypairs(n_validators)
    validators = [
        Validator(
            pubkey=pk.to_bytes(),
            withdrawal_credentials=b"\x00" * 32,
            effective_balance=spec.max_effective_balance,
            slashed=False,
            activation_eligibility_epoch=0,
            activation_epoch=0,
            exit_epoch=FAR_FUTURE_EPOCH,
            withdrawable_epoch=FAR_FUTURE_EPOCH,
        )
        for _, pk in keypairs
    ]
    if registry_padding:
        for v in validators:
            v.freeze()
        validators += _padding_validators(registry_padding, n_validators)
        Validator.bulk_roots(validators)
    state_cls = T.BeaconState_BY_FORK[fork]
    # A genesis state at a scheduled fork carries that fork's version (the
    # reference harness does the same when spawning e.g. a bellatrix-genesis
    # chain); forks-off specs keep the genesis version everywhere.
    version = spec.genesis_fork_version
    if fork != "base" and getattr(spec, f"{fork}_fork_epoch", None) is not None:
        version = getattr(spec, f"{fork}_fork_version")
    state = state_cls(
        genesis_time=spec.min_genesis_time,
        slot=0,
        fork=Fork(
            previous_version=version,
            current_version=version,
            epoch=0,
        ),
        latest_block_header=BeaconBlockHeader(),
        validators=validators,
        balances=[balance] * n_validators + [0] * registry_padding,
        randao_mixes=[bytes(32)] * preset.epochs_per_historical_vector,
        finalized_checkpoint=Checkpoint(),
    )
    gvr = state_cls._fields["validators"].hash_tree_root(validators)
    state.genesis_validators_root = gvr
    n_total = len(validators)
    if fork != "base":
        state.previous_epoch_participation = [0] * n_total
        state.current_epoch_participation = [0] * n_total
        state.inactivity_scores = [0] * n_total
        from .state_processing.per_epoch import compute_sync_committee

        state.current_sync_committee = compute_sync_committee(state, 0, spec)
        state.next_sync_committee = compute_sync_committee(
            state, preset.epochs_per_sync_committee_period, spec
        )
    return state, keypairs


def pubkey_getter(state):
    """A decompression cache over the state's validators — the
    ValidatorPubkeyCache analog (validator_pubkey_cache.rs:9-16)."""
    cache: dict[int, bls.PublicKey] = {}

    def get(index: int):
        if index in cache:
            return cache[index]
        if index >= len(state.validators):
            return None
        pk = bls.PublicKey.from_bytes(bytes(state.validators[index].pubkey))
        cache[index] = pk
        return pk

    return get


def apply_epoch_handler(state, handler: str, spec) -> None:
    """Run ONE epoch-processing sub-step on ``state`` in place — the
    dispatch the EF `epoch_processing` runner families use
    (testing/ef_tests/src/cases/epoch_processing.rs runs exactly one
    sub-transition per case)."""
    from .state_processing import per_epoch as E
    from .state_processing.arrays import ValidatorArrays

    preset = spec.preset
    va = ValidatorArrays.extract(state)
    n = len(state.validators)
    current = E.get_current_epoch(state, preset)
    previous = max(current, 1) - 1
    prev_flags = E._flags(state, "previous", n)
    curr_flags = E._flags(state, "current", n)
    if handler == "justification_and_finalization":
        E.process_justification_and_finalization(
            state, va, prev_flags, curr_flags, current, previous, spec
        )
    elif handler == "inactivity_updates":
        E.process_inactivity_updates(
            state, va, prev_flags, current, previous, spec
        )
    elif handler == "rewards_and_penalties":
        E.process_rewards_and_penalties(
            state, va, prev_flags, current, previous, spec
        )
    elif handler == "registry_updates":
        E.process_registry_updates(state, va, current, spec)
    elif handler == "slashings":
        from .state_processing.forks import (
            proportional_slashing_multiplier,
            state_fork_name,
        )

        E.process_slashings(
            state, va, current, spec,
            multiplier=proportional_slashing_multiplier(
                state_fork_name(state), preset
            ),
        )
    elif handler == "effective_balance_updates":
        E.process_effective_balance_updates(va, spec)
    else:
        raise KeyError(f"unknown epoch handler {handler}")
    va.writeback(state)


def apply_operation(state, handler: str, op, spec, verify: bool = False):
    """Apply ONE block operation in place (the EF `operations` runner
    dispatch — testing/ef_tests/src/cases/operations.rs); raises on an
    invalid operation."""
    from .state_processing import per_block as PB

    get_pk = pubkey_getter(state)
    if handler == "attestation":
        from . import committees as cm

        cc = cm.CommitteeCache(state, int(op.data.target.epoch), spec.preset)
        PB.process_attestation(state, op, spec, cc, verify, get_pk)
    elif handler == "proposer_slashing":
        PB.process_proposer_slashing(state, op, spec, verify, get_pk)
    elif handler == "attester_slashing":
        PB.process_attester_slashing(state, op, spec, verify, get_pk)
    elif handler == "voluntary_exit":
        PB.process_voluntary_exit(state, op, spec, verify, get_pk)
    elif handler == "deposit":
        PB.process_deposit(state, op, spec)
    else:
        raise KeyError(f"unknown operation handler {handler}")
