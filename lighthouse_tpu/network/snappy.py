"""Snappy compression — block format (gossip) and framed format (req/resp).

The reference's wire stack compresses gossip payloads with raw-block snappy
and req/resp chunks with framed snappy (rpc/codec/, `ssz_snappy`;
Cargo.toml:104 pulls the `snap` crate).  No snappy library ships in this
image, so this is a from-scratch implementation of the public format spec:

* decompress: full tag support (literals, 1/2/4-byte-offset copies).
* compress: greedy hash-table matcher emitting literals + copy tags —
  real compression (SSZ states/blocks are highly repetitive), not just
  literal passthrough.
* framed format: stream identifier, compressed/uncompressed chunks with
  masked CRC32-C (the Castagnoli polynomial, implemented here too).

Interops with any spec-conforming snappy (round-trip tested both ways in
tests/test_network.py — including against reference-format fixtures built
from the format spec's worked examples).
"""

from __future__ import annotations

import struct

MAX_BLOCK = 65536  # framed-format max uncompressed chunk


class SnappyError(ValueError):
    pass


# ---------------------------------------------------------------------------
# varint
# ---------------------------------------------------------------------------


def _write_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    shift = n = 0
    while True:
        if pos >= len(data):
            raise SnappyError("truncated varint")
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7
        if shift > 63:
            raise SnappyError("varint overflow")


# ---------------------------------------------------------------------------
# block format
# ---------------------------------------------------------------------------


def compress_block(data: bytes) -> bytes:
    """Greedy hash-match compressor (4-byte matches, 64KB window)."""
    out = bytearray(_write_uvarint(len(data)))
    n = len(data)
    if n == 0:
        return bytes(out)
    table: dict[int, int] = {}
    i = 0
    lit_start = 0

    def emit_literal(start: int, end: int):
        length = end - start
        while length > 0:
            run = min(length, 60)  # keep the 1-byte tag form for simplicity
            if run < 60:
                out.append((run - 1) << 2)
            else:
                out.append(60 << 2)
                out.append(run - 1)
            out.extend(data[start : start + run])
            start += run
            length -= run

    def emit_copy(offset: int, length: int):
        while length > 0:
            if 4 <= length <= 11 and offset < 2048:
                out.append(
                    0b01 | ((length - 4) << 2) | ((offset >> 8) << 5)
                )
                out.append(offset & 0xFF)
                length = 0
            else:
                run = min(length, 64)
                if run < 4:  # too short for a copy tag: emit as literal
                    break
                out.append(0b10 | ((run - 1) << 2))
                out.extend(struct.pack("<H", offset))
                length -= run
        return length

    while i + 4 <= n:
        key = int.from_bytes(data[i : i + 4], "little")
        cand = table.get(key)
        table[key] = i
        if cand is not None and i - cand <= 0xFFFF and data[cand : cand + 4] == data[i : i + 4]:
            # extend the match
            m = 4
            while i + m < n and data[cand + m] == data[i + m] and m < 64:
                m += 1
            emit_literal(lit_start, i)
            left = emit_copy(i - cand, m)
            i += m - left
            lit_start = i
        else:
            i += 1
    emit_literal(lit_start, n)
    return bytes(out)


def decompress_block(data: bytes) -> bytes:
    want, pos = _read_uvarint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 0b11
        if kind == 0b00:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                if pos + extra > n:
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            if pos + length > n:
                raise SnappyError("truncated literal")
            out += data[pos : pos + length]
            pos += length
            continue
        if kind == 0b01:  # copy, 1-byte offset
            length = ((tag >> 2) & 0b111) + 4
            if pos >= n:
                raise SnappyError("truncated copy1")
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 0b10:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            if pos + 2 > n:
                raise SnappyError("truncated copy2")
            offset = struct.unpack_from("<H", data, pos)[0]
            pos += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            if pos + 4 > n:
                raise SnappyError("truncated copy4")
            offset = struct.unpack_from("<I", data, pos)[0]
            pos += 4
        if offset == 0 or offset > len(out):
            raise SnappyError("invalid copy offset")
        for _ in range(length):  # may overlap: byte-by-byte
            out.append(out[-offset])
    if len(out) != want:
        raise SnappyError(f"length mismatch: header {want}, got {len(out)}")
    return bytes(out)


# ---------------------------------------------------------------------------
# CRC32-C (Castagnoli), masked per the framing spec
# ---------------------------------------------------------------------------

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    c = crc32c(data)
    return ((c >> 15) | (c << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# framed format
# ---------------------------------------------------------------------------

STREAM_IDENTIFIER = b"\xff\x06\x00\x00sNaPpY"


def compress_framed(data: bytes) -> bytes:
    out = bytearray(STREAM_IDENTIFIER)
    for i in range(0, max(len(data), 1), MAX_BLOCK):
        chunk = data[i : i + MAX_BLOCK]
        crc = _masked_crc(chunk)
        comp = compress_block(chunk)
        if len(comp) < len(chunk):
            body = struct.pack("<I", crc) + comp
            out += b"\x00" + struct.pack("<I", len(body))[:3] + body
        else:
            body = struct.pack("<I", crc) + chunk
            out += b"\x01" + struct.pack("<I", len(body))[:3] + body
    return bytes(out)


def decompress_framed_prefix(data: bytes, want: int) -> tuple[bytes, int]:
    """Decompress until ``want`` output bytes, returning (output, bytes
    CONSUMED from data) — the incremental reader for back-to-back
    ssz_snappy response chunks sharing one stream."""
    pos, out = 0, bytearray()
    seen_header = False
    data_frames = 0
    while pos < len(data):
        if pos + 4 > len(data):
            raise SnappyError("truncated chunk header")
        ctype = data[pos]
        length = int.from_bytes(data[pos + 1 : pos + 4], "little")
        body = data[pos + 4 : pos + 4 + length]
        if len(body) != length:
            raise SnappyError("truncated chunk body")
        pos += 4 + length
        if ctype == 0xFF:
            if body != STREAM_IDENTIFIER[4:]:
                raise SnappyError("bad stream identifier")
            seen_header = True
            continue
        if not seen_header:
            raise SnappyError("chunk before stream identifier")
        if ctype in (0x00, 0x01):
            if len(body) < 4:
                raise SnappyError("chunk body shorter than its CRC")
            crc = struct.unpack("<I", body[:4])[0]
            chunk = decompress_block(body[4:]) if ctype == 0x00 else body[4:]
            if _masked_crc(chunk) != crc:
                raise SnappyError("chunk CRC mismatch")
            out += chunk
            data_frames += 1
            if len(out) >= want and data_frames >= 1:
                # Payload complete.  Consume any CONTIGUOUS trailing
                # skippable frames (types 0x80-0xFE incl. padding) that
                # still belong to THIS snappy stream — other spec-legal
                # encoders may emit them, and leaving them unconsumed
                # would make the next coded chunk's parse start inside a
                # padding frame (ADVICE r3).
                while pos + 4 <= len(data) and 0x80 <= data[pos] <= 0xFE:
                    skip_len = int.from_bytes(data[pos + 1 : pos + 4], "little")
                    if pos + 4 + skip_len > len(data):
                        break  # truncated padding: leave for the caller
                    pos += 4 + skip_len
                break
        elif 0x80 <= ctype <= 0xFE:  # skippable (0xFE = padding)
            continue
        else:
            raise SnappyError(f"unskippable unknown chunk type {ctype:#x}")
    if len(out) < want:
        raise SnappyError(f"stream ended at {len(out)}/{want} bytes")
    return bytes(out[:want]), pos


def decompress_framed(data: bytes) -> bytes:
    pos, out = 0, bytearray()
    seen_header = False
    while pos < len(data):
        if pos + 4 > len(data):
            raise SnappyError("truncated chunk header")
        ctype = data[pos]
        length = int.from_bytes(data[pos + 1 : pos + 4], "little")
        pos += 4
        if pos + length > len(data):
            raise SnappyError("truncated chunk body")
        body = data[pos : pos + length]
        pos += length
        if ctype == 0xFF:  # stream identifier
            if body != STREAM_IDENTIFIER[4:]:
                raise SnappyError("bad stream identifier")
            seen_header = True
            continue
        if not seen_header:
            raise SnappyError("chunk before stream identifier")
        if ctype in (0x00, 0x01) and len(body) < 4:
            raise SnappyError("chunk body shorter than its CRC")
        if ctype == 0x00:  # compressed
            crc = struct.unpack("<I", body[:4])[0]
            chunk = decompress_block(body[4:])
        elif ctype == 0x01:  # uncompressed
            crc = struct.unpack("<I", body[:4])[0]
            chunk = body[4:]
        elif 0x80 <= ctype <= 0xFD:  # skippable
            continue
        else:
            raise SnappyError(f"unskippable unknown chunk type {ctype:#x}")
        if _masked_crc(chunk) != crc:
            raise SnappyError("chunk CRC mismatch")
        out += chunk
    return bytes(out)
