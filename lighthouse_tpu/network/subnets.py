"""Duty-driven subnet subscription + ENR advertisement.

Twin of beacon_node/network/src/subnet_service/attestation_subnets.rs (679
LoC) and sync_subnets.rs: decide WHICH attestation/sync subnets a node
joins and when — long-lived subnets advertised in the ENR `attnets` /
`syncnets` bitfields (discovery predicates match on them), short-lived
duty subscriptions joined one epoch ahead of the duty slot and dropped
after it passes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .topics import compute_subnet_for_attestation

SUBNETS_PER_NODE = 2  # spec `SUBNETS_PER_NODE`: long-lived subscriptions
EPOCHS_PER_SUBNET_SUBSCRIPTION = 256


def attnets_bitfield(subnets: set[int], count: int = 64) -> bytes:
    """The ENR `attnets` value: a fixed 8-byte little-endian bitfield."""
    out = bytearray(count // 8)
    for s in subnets:
        out[s // 8] |= 1 << (s % 8)
    return bytes(out)


def syncnets_bitfield(subnets: set[int], count: int = 4) -> bytes:
    out = bytearray(1)
    for s in subnets:
        out[0] |= 1 << (s % 8)
    return bytes(out)


def bitfield_to_subnets(raw: bytes) -> set[int]:
    return {
        i * 8 + j
        for i, byte in enumerate(raw)
        for j in range(8)
        if byte >> j & 1
    }


def long_lived_subnets(node_id: bytes, epoch: int, spec) -> set[int]:
    """Deterministic long-lived subnets from the node id + subscription
    period (attestation_subnets.rs compute_subscribed_subnets shape:
    id-prefix-derived, rotating every EPOCHS_PER_SUBNET_SUBSCRIPTION)."""
    prefix = int.from_bytes(node_id[:8], "big")
    period = epoch // EPOCHS_PER_SUBNET_SUBSCRIPTION
    return {
        (prefix + period + i) % spec.attestation_subnet_count
        for i in range(SUBNETS_PER_NODE)
    }


@dataclass
class Subscription:
    subnet_id: int
    slot: int  # the duty slot; unsubscribe after it passes


@dataclass
class AttestationSubnetService:
    """Tracks wanted subnets = long-lived ∪ duty-driven; the node diffs
    `wanted()` against its live topic set each epoch tick."""

    spec: object
    node_id: bytes = b"\x00" * 32
    _duty_subs: list[Subscription] = field(default_factory=list)

    def on_duties(self, duties, committees_per_slot: int) -> list[Subscription]:
        """Register duty-driven subscriptions (one per attester duty —
        validator_subscriptions in attestation_subnets.rs)."""
        added = []
        for duty in duties:
            subnet = compute_subnet_for_attestation(
                self.spec, duty.slot, duty.committee_index, committees_per_slot
            )
            sub = Subscription(subnet_id=subnet, slot=duty.slot)
            self._duty_subs.append(sub)
            added.append(sub)
        return added

    def tick(self, current_slot: int) -> None:
        """Expire duty subscriptions whose slot has passed."""
        self._duty_subs = [s for s in self._duty_subs if s.slot >= current_slot]

    def wanted(self, epoch: int) -> set[int]:
        return long_lived_subnets(self.node_id, epoch, self.spec) | {
            s.subnet_id for s in self._duty_subs
        }

    def enr_attnets(self, epoch: int) -> bytes:
        """Only LONG-LIVED subnets are advertised (duty subs churn too
        fast for discovery — same split as the reference)."""
        return attnets_bitfield(
            long_lived_subnets(self.node_id, epoch, self.spec),
            self.spec.attestation_subnet_count,
        )
