"""Ethereum Node Records (EIP-778) with the "v4" identity scheme.

The node identity format carried by discv5 and embedded in network configs
(reference: `beacon_node/lighthouse_network/src/discovery/enr.rs` — eth2
fork-digest field, attestation/sync-committee bitfield fields —
and `enr_ext.rs`).  A record is an RLP list

    [signature, seq, k1, v1, k2, v2, ...]

with keys sorted, signed by the node's secp256k1 key over
``keccak256(rlp([seq, k1, v1, ...]))``, and textual form
``enr:<base64url(rlp)>``.  Node id = keccak256(uncompressed pubkey x||y).
"""

from __future__ import annotations

import base64
from dataclasses import dataclass, field

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec, utils as asn1_utils

from ..crypto.keccak import keccak256
from . import rlp

MAX_ENR_SIZE = 300  # EIP-778 hard cap

# eth2-specific keys (enr.rs: ETH2_ENR_KEY, ATTESTATION_BITFIELD_ENR_KEY, ...)
ETH2_KEY = b"eth2"
ATTNETS_KEY = b"attnets"
SYNCNETS_KEY = b"syncnets"


def _pubkey_to_compressed(pub: ec.EllipticCurvePublicKey) -> bytes:
    return pub.public_bytes(
        serialization.Encoding.X962, serialization.PublicFormat.CompressedPoint
    )


def _pubkey_to_uncompressed_xy(pub: ec.EllipticCurvePublicKey) -> bytes:
    raw = pub.public_bytes(
        serialization.Encoding.X962, serialization.PublicFormat.UncompressedPoint
    )
    return raw[1:]  # strip 0x04


def node_id_of(pubkey_compressed: bytes) -> bytes:
    pub = ec.EllipticCurvePublicKey.from_encoded_point(
        ec.SECP256K1(), pubkey_compressed
    )
    return keccak256(_pubkey_to_uncompressed_xy(pub))


def _sig_to_raw64(der_sig: bytes) -> bytes:
    r, s = asn1_utils.decode_dss_signature(der_sig)
    # low-s normalization (the v4 scheme stores 64-byte r||s)
    n = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
    if s > n // 2:
        s = n - s
    return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def _raw64_to_der(sig: bytes) -> bytes:
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    return asn1_utils.encode_dss_signature(r, s)


def sign_keccak(key: ec.EllipticCurvePrivateKey, msg: bytes) -> bytes:
    """64-byte r||s ECDSA signature over keccak256(msg) (v4 scheme)."""
    digest = keccak256(msg)
    der = key.sign(digest, ec.ECDSA(asn1_utils.Prehashed(hashes.SHA256())))
    return _sig_to_raw64(der)


def verify_keccak(pubkey_compressed: bytes, msg: bytes, sig: bytes) -> bool:
    try:
        pub = ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256K1(), pubkey_compressed
        )
        pub.verify(
            _raw64_to_der(sig),
            keccak256(msg),
            ec.ECDSA(asn1_utils.Prehashed(hashes.SHA256())),
        )
        return True
    except Exception:
        return False


@dataclass
class Enr:
    """A decoded node record; ``kv`` holds raw value bytes per key."""

    seq: int = 1
    kv: dict = field(default_factory=dict)
    signature: bytes = b""

    # -- accessors ---------------------------------------------------------

    @property
    def pubkey(self) -> bytes | None:
        return self.kv.get(b"secp256k1")

    @property
    def node_id(self) -> bytes:
        pk = self.pubkey
        if pk is None:
            raise ValueError("ENR has no secp256k1 key")
        return node_id_of(pk)

    @property
    def ip4(self) -> str | None:
        raw = self.kv.get(b"ip")
        if raw is None or len(raw) != 4:
            return None
        return ".".join(str(b) for b in raw)

    @property
    def udp_port(self) -> int | None:
        raw = self.kv.get(b"udp")
        return rlp.decode_uint(raw) if raw is not None else None

    @property
    def quic_port(self) -> int | None:
        """The QUIC/UDP listening port (reference: `discovery/enr.rs`
        advertises libp2p-quic under the "quic" key)."""
        raw = self.kv.get(b"quic")
        return rlp.decode_uint(raw) if raw is not None else None

    @property
    def tcp_port(self) -> int | None:
        raw = self.kv.get(b"tcp")
        return rlp.decode_uint(raw) if raw is not None else None

    def udp_endpoint(self) -> tuple[str, int] | None:
        ip, port = self.ip4, self.udp_port
        if ip is None or port is None:
            return None
        return (ip, port)

    # -- codec -------------------------------------------------------------

    def _content(self) -> list:
        items: list = [rlp.encode_uint(self.seq)]
        for k in sorted(self.kv):
            items += [k, self.kv[k]]
        return items

    def signing_payload(self) -> bytes:
        return rlp.encode(self._content())

    def to_rlp(self) -> bytes:
        out = rlp.encode([self.signature] + self._content())
        if len(out) > MAX_ENR_SIZE:
            raise ValueError(f"ENR exceeds {MAX_ENR_SIZE} bytes")
        return out

    def to_text(self) -> str:
        return "enr:" + base64.urlsafe_b64encode(self.to_rlp()).rstrip(b"=").decode()

    def verify(self) -> bool:
        pk = self.pubkey
        if pk is None or self.kv.get(b"id") != b"v4":
            return False
        return verify_keccak(pk, self.signing_payload(), self.signature)

    @classmethod
    def from_rlp(cls, raw: bytes) -> "Enr":
        if len(raw) > MAX_ENR_SIZE:
            raise ValueError("oversized ENR")
        items = rlp.decode(raw)
        if not isinstance(items, list) or len(items) < 2 or len(items) % 2 != 0:
            raise ValueError("malformed ENR")
        sig, seq, *pairs = items
        kv = {}
        prev = None
        for i in range(0, len(pairs), 2):
            k, v = pairs[i], pairs[i + 1]
            if prev is not None and k <= prev:
                raise ValueError("ENR keys not sorted/unique")
            prev = k
            kv[k] = v
        rec = cls(seq=rlp.decode_uint(seq), kv=kv, signature=sig)
        if not rec.verify():
            raise ValueError("ENR signature invalid")
        return rec

    @classmethod
    def from_text(cls, text: str) -> "Enr":
        if not text.startswith("enr:"):
            raise ValueError("missing enr: prefix")
        b64 = text[4:]
        b64 += "=" * (-len(b64) % 4)
        return cls.from_rlp(base64.urlsafe_b64decode(b64))


def build_enr(
    key: ec.EllipticCurvePrivateKey,
    seq: int = 1,
    ip4: str | None = None,
    udp: int | None = None,
    tcp: int | None = None,
    quic: int | None = None,
    extra: dict | None = None,
) -> Enr:
    """Create and sign a record for ``key`` (v4 identity scheme)."""
    kv: dict = {b"id": b"v4", b"secp256k1": _pubkey_to_compressed(key.public_key())}
    if ip4 is not None:
        kv[b"ip"] = bytes(int(p) for p in ip4.split("."))
    if udp is not None:
        kv[b"udp"] = rlp.encode_uint(udp)
    if tcp is not None:
        kv[b"tcp"] = rlp.encode_uint(tcp)
    if quic is not None:
        kv[b"quic"] = rlp.encode_uint(quic)
    for k, v in (extra or {}).items():
        kv[k] = v
    rec = Enr(seq=seq, kv=kv)
    rec.signature = sign_keccak(key, rec.signing_payload())
    assert rec.verify()
    return rec
