"""yamux stream multiplexing over one secured connection.

The muxer of the reference's transport stack
(`lighthouse_network/src/service/utils.rs:39-48` — yamux upgrade above
noise).  Implements the yamux spec framing: 12-byte headers

    version(1)=0 | type(1) | flags(2 BE) | stream_id(4 BE) | length(4 BE)

types: 0 Data, 1 WindowUpdate, 2 Ping, 3 GoAway; flags: SYN=1 ACK=2
FIN=4 RST=8.  Dialer opens odd stream ids, listener even.  Receive
windows start at 256 KiB; consumed credit is returned with WindowUpdate
once half the window is drained.

The session pumps frames on a reader thread and hands bytes to Stream
objects with blocking reads — the synchronous analog of the reference's
polled muxer.
"""

from __future__ import annotations

import queue
import struct
import threading

TYPE_DATA = 0
TYPE_WINDOW = 1
TYPE_PING = 2
TYPE_GOAWAY = 3
FLAG_SYN = 1
FLAG_ACK = 2
FLAG_FIN = 4
FLAG_RST = 8

INITIAL_WINDOW = 256 * 1024


class YamuxError(Exception):
    pass


def _header(typ: int, flags: int, stream_id: int, length: int) -> bytes:
    return struct.pack(">BBHII", 0, typ, flags, stream_id, length)


class Stream:
    """One logical bidirectional stream."""

    def __init__(self, session: "Session", stream_id: int):
        self.session = session
        self.id = stream_id
        self._rx: queue.Queue[bytes | None] = queue.Queue()
        self._buf = b""
        self._recv_window = INITIAL_WINDOW
        self._send_window = INITIAL_WINDOW
        self._inflight = 0  # delivered-not-yet-consumed bytes
        self._window_cv = threading.Condition()
        self._closed_local = False
        self._closed_remote = False

    # -- write side --------------------------------------------------------

    def write(self, data: bytes, flags: int = 0,
              timeout: float = 30.0) -> None:
        """Write respecting the peer's receive window: blocks for
        WindowUpdate credit when the window is exhausted."""
        if self._closed_local:
            raise YamuxError(f"stream {self.id} closed")
        view = memoryview(data)
        while True:
            with self._window_cv:
                if self._send_window <= 0:
                    if not self._window_cv.wait(timeout):
                        raise YamuxError(
                            f"stream {self.id}: window starved for {timeout}s"
                        )
                    continue
                chunk = view[: self._send_window]
                self._send_window -= len(chunk)
            self.session._send_frame(TYPE_DATA, flags, self.id, bytes(chunk))
            view = view[len(chunk) :]
            if not len(view):
                return

    def _grant_credit(self, delta: int) -> None:
        with self._window_cv:
            self._send_window += delta
            self._window_cv.notify_all()

    def close(self) -> None:
        if not self._closed_local:
            self._closed_local = True
            self.session._send_frame(TYPE_DATA, FLAG_FIN, self.id, b"")
            self.session._maybe_gc(self)

    def reset(self) -> None:
        self._closed_local = True
        self.session._send_frame(TYPE_WINDOW, FLAG_RST, self.id, b"")
        self.session._maybe_gc(self)

    # -- read side ---------------------------------------------------------

    def read(self, n: int, timeout: float = 5.0) -> bytes:
        """Read EXACTLY n bytes (blocking); raises on EOF before n."""
        while len(self._buf) < n:
            chunk = self._pump(timeout)
            if chunk is None:
                raise YamuxError(f"stream {self.id}: EOF at {len(self._buf)}/{n}")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def read_until_eof(self, timeout: float = 5.0, limit: int = 1 << 24) -> bytes:
        while True:
            chunk = self._pump(timeout)  # drains queued data even after FIN
            if chunk is None:
                break
            self._buf += chunk
            if len(self._buf) > limit:
                raise YamuxError("stream body over limit")
        out, self._buf = self._buf, b""
        return out

    def read_available(self, timeout: float = 5.0) -> bytes:
        """At least one byte (unless EOF); whatever is buffered."""
        if not self._buf:
            chunk = self._pump(timeout)
            if chunk is not None:
                self._buf += chunk
        out, self._buf = self._buf, b""
        return out

    def _pump(self, timeout: float):
        """Dequeue one frame, returning credit AS data is consumed — a
        reader mid-way through a large read must keep feeding the peer
        window or transfers beyond one window deadlock."""
        if self._closed_remote and self._rx.empty():
            return None
        try:
            item = self._rx.get(timeout=timeout)
        except queue.Empty:
            raise YamuxError(f"stream {self.id}: read timeout") from None
        if item is not None:
            self._inflight -= len(item)
            self._return_credit(len(item))
        return item

    def _return_credit(self, n: int) -> None:
        self._recv_window -= n
        if self._recv_window <= INITIAL_WINDOW // 2:
            delta = INITIAL_WINDOW - self._recv_window
            self._recv_window = INITIAL_WINDOW
            self.session._send_frame(
                TYPE_WINDOW, 0, self.id, delta.to_bytes(4, "big"), raw_len=delta
            )

    # session-side delivery
    def _deliver(self, data: bytes) -> bool:
        """Queue received bytes; False = peer overran our advertised
        receive window (protocol violation — remote-controlled memory)."""
        self._inflight += len(data)
        if self._inflight > 2 * INITIAL_WINDOW:
            return False
        self._rx.put(data)
        return True

    def _remote_close(self) -> None:
        self._closed_remote = True
        self._rx.put(None)
        self.session._maybe_gc(self)


class Session:
    """One muxed connection; ``is_dialer`` fixes stream-id parity."""

    def __init__(self, send_fn, recv_fn, is_dialer: bool,
                 on_stream=None, on_close=None):
        self._send = send_fn  # (bytes) -> None, already secured
        self._recv = recv_fn  # () -> bytes (one noise frame) or b"" on EOF
        self._next_id = 1 if is_dialer else 2
        self.streams: dict[int, Stream] = {}
        self._accept_q: queue.Queue[Stream] = queue.Queue()
        self._on_stream = on_stream
        self._on_close = on_close
        self._lock = threading.Lock()
        self._wbuf = b""
        self._running = True
        self._thread = threading.Thread(target=self._read_loop, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        try:
            self._send(_header(TYPE_GOAWAY, 0, 0, 0))
        except Exception:
            pass

    # -- frame IO ----------------------------------------------------------

    def _send_frame(self, typ: int, flags: int, stream_id: int, body: bytes,
                    raw_len: int | None = None) -> None:
        with self._lock:
            if typ in (TYPE_WINDOW, TYPE_PING, TYPE_GOAWAY):
                # header-only frames: the length field carries the window
                # delta / ping opaque / goaway code, with no body
                self._send(_header(typ, flags, stream_id,
                                   raw_len if raw_len is not None else 0))
            else:
                self._send(_header(typ, flags, stream_id, len(body)) + body)

    def _maybe_gc(self, st: Stream) -> None:
        """Drop fully-closed streams from the table (long-lived sessions
        open one stream per req/resp; the table must not grow forever)."""
        if st._closed_local and st._closed_remote:
            self.streams.pop(st.id, None)

    def open_stream(self) -> Stream:
        with self._lock:
            sid = self._next_id
            self._next_id += 2
        st = Stream(self, sid)
        self.streams[sid] = st
        # SYN window update; delta 0 = both sides at the implicit 256 KiB
        self._send_frame(TYPE_WINDOW, FLAG_SYN, sid, b"", raw_len=0)
        return st

    def accept_stream(self, timeout: float = 5.0) -> Stream:
        try:
            return self._accept_q.get(timeout=timeout)
        except queue.Empty:
            raise YamuxError("accept timeout") from None

    # -- reader ------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        while len(self._wbuf) < n:
            frame = self._recv()
            if not frame:
                raise YamuxError("connection closed")
            self._wbuf += frame
        out, self._wbuf = self._wbuf[:n], self._wbuf[n:]
        return out

    def _read_loop(self) -> None:
        try:
            while self._running:
                hdr = self._read_exact(12)
                ver, typ, flags, sid, length = struct.unpack(">BBHII", hdr)
                if ver != 0:
                    raise YamuxError(f"bad yamux version {ver}")
                if typ == TYPE_DATA:
                    body = self._read_exact(length) if length else b""
                    self._handle_data(flags, sid, body)
                elif typ == TYPE_WINDOW:
                    self._handle_window(flags, sid, length)
                elif typ == TYPE_PING:
                    if flags & FLAG_SYN:
                        self._send_frame(TYPE_PING, FLAG_ACK, 0, b"",
                                         raw_len=length)
                elif typ == TYPE_GOAWAY:
                    break
        except Exception:
            pass
        finally:
            self._running = False
            for st in list(self.streams.values()):
                st._remote_close()
            if self._on_close:
                self._on_close()

    def _get_or_open(self, flags: int, sid: int) -> Stream | None:
        st = self.streams.get(sid)
        if st is None and flags & FLAG_SYN:
            st = Stream(self, sid)
            self.streams[sid] = st
            self._accept_q.put(st)
            if self._on_stream:
                self._on_stream(st)
        return st

    def _handle_data(self, flags: int, sid: int, body: bytes) -> None:
        st = self._get_or_open(flags, sid)
        if st is None:
            return
        if body and not st._deliver(body):
            # window overrun: reset the stream rather than buffer
            st.reset()
            st._remote_close()
            return
        if flags & (FLAG_FIN | FLAG_RST):
            st._remote_close()

    def _handle_window(self, flags: int, sid: int, delta: int) -> None:
        st = self._get_or_open(flags, sid)
        if st is None:
            return
        if delta and not flags & FLAG_RST:
            st._grant_credit(delta)
        if flags & (FLAG_FIN | FLAG_RST):
            st._remote_close()
