"""QUIC v1 transport (RFC 9000/9001/9002) for libp2p.

The reference's network service builds a TCP+QUIC transport pair
(`lighthouse_network/src/service/utils.rs:39-48`, quinn under the
libp2p-quic crate); this is the QUIC half, from the wire up, sharing
nothing with the TCP path but the stream API: QUIC natively provides
the secure channel (TLS 1.3, `tls13.py`) and stream multiplexing, so a
`QuicConnection` replaces noise+yamux wholesale — `libp2p.py` consumes
it through the same `open_stream`/`accept_stream` muxer surface and the
same `Stream.read/write/close/reset` contract as a yamux `Session`.

Layout of this module:
  - varint codec (RFC 9000 §16)
  - packet protection (RFC 9001 §5): HKDF-Expand-Label, Initial
    secrets from the client DCID, AES-128-GCM payload AEAD, AES-ECB
    header-protection masks — pinned to RFC 9001 Appendix A vectors
    in `tests/test_quic.py`
  - long/short header build+parse, packet-number encode/decode
    (RFC 9000 §17, A.2/A.3 sample algorithms re-derived)
  - frames (PADDING/PING/ACK/CRYPTO/STREAM/MAX_*/CLOSE/…, §19)
  - `QuicConnection`: the three packet-number spaces, CRYPTO flow
    into the TLS engine, ACK tracking, PTO retransmit (RFC 9002 §6),
    bidirectional streams with connection+stream flow control
  - `QuicEndpoint`: one UDP socket, DCID demux, dial/accept

Deliberate scope cuts (documented, not hidden): no connection
migration / NEW_CONNECTION_ID rotation, no 0-RTT, no Retry tokens,
no key update, v1 only.  None of these gate interop for a
lighthouse-style node mesh; all are additive later.
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import logging
import os
import secrets
import socket
import struct
import threading
import time
from collections import OrderedDict, deque

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
from cryptography.hazmat.primitives.ciphers.aead import AESGCM

log = logging.getLogger("quic")

QUIC_V1 = 0x00000001
# RFC 9001 §5.2: the v1 Initial salt (a protocol constant, like a DST).
INITIAL_SALT = bytes.fromhex("38762cf7f55934b34d179ae6a4c80cadccbb7f0a")

MAX_UDP_PAYLOAD = 1452  # conservative for loopback/ethernet
MIN_CLIENT_INITIAL = 1200  # RFC 9000 §8.1 anti-amplification pad


class QuicError(Exception):
    pass


# ---------------------------------------------------------------------------
# varints (RFC 9000 §16): 2-bit length prefix, big-endian
# ---------------------------------------------------------------------------

def enc_varint(v: int) -> bytes:
    if v < 0x40:
        return bytes([v])
    if v < 0x4000:
        return struct.pack(">H", v | 0x4000)
    if v < 0x4000_0000:
        return struct.pack(">I", v | 0x8000_0000)
    if v < 0x4000_0000_0000_0000:
        return struct.pack(">Q", v | 0xC000_0000_0000_0000)
    raise QuicError(f"varint too large: {v}")


def dec_varint(buf: bytes, pos: int) -> tuple[int, int]:
    if pos >= len(buf):
        raise QuicError("varint: truncated")
    first = buf[pos]
    ln = 1 << (first >> 6)
    if pos + ln > len(buf):
        raise QuicError("varint: truncated body")
    v = first & 0x3F
    for i in range(1, ln):
        v = (v << 8) | buf[pos + i]
    return v, pos + ln


# ---------------------------------------------------------------------------
# HKDF + packet protection (RFC 9001 §5)
# ---------------------------------------------------------------------------

def hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac_mod.new(salt, ikm, hashlib.sha256).digest()


def hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out = b""
    block = b""
    counter = 1
    while len(out) < length:
        block = hmac_mod.new(
            prk, block + info + bytes([counter]), hashlib.sha256
        ).digest()
        out += block
        counter += 1
    return out[:length]


def hkdf_expand_label(secret: bytes, label: str, context: bytes,
                      length: int) -> bytes:
    """RFC 8446 §7.1 HkdfLabel: uint16 length || "tls13 "+label || context."""
    full = b"tls13 " + label.encode()
    info = (struct.pack(">H", length) + bytes([len(full)]) + full
            + bytes([len(context)]) + context)
    return hkdf_expand(secret, info, length)


class DirectionKeys:
    """AEAD + header-protection keys for one direction at one level."""

    def __init__(self, secret: bytes):
        self.secret = secret
        self.key = hkdf_expand_label(secret, "quic key", b"", 16)
        self.iv = hkdf_expand_label(secret, "quic iv", b"", 12)
        self.hp = hkdf_expand_label(secret, "quic hp", b"", 16)
        self._aead = AESGCM(self.key)

    def _nonce(self, pn: int) -> bytes:
        return bytes(a ^ b for a, b in zip(self.iv, pn.to_bytes(12, "big")))

    def seal(self, pn: int, header: bytes, payload: bytes) -> bytes:
        return self._aead.encrypt(self._nonce(pn), payload, header)

    def open(self, pn: int, header: bytes, ciphertext: bytes) -> bytes:
        return self._aead.decrypt(self._nonce(pn), ciphertext, header)

    def hp_mask(self, sample: bytes) -> bytes:
        enc = Cipher(algorithms.AES(self.hp), modes.ECB()).encryptor()
        return enc.update(sample)[:5]


def initial_keys(client_dcid: bytes) -> tuple[DirectionKeys, DirectionKeys]:
    """(client_keys, server_keys) for the Initial space (RFC 9001 §5.2)."""
    initial_secret = hkdf_extract(INITIAL_SALT, client_dcid)
    client = hkdf_expand_label(initial_secret, "client in", b"", 32)
    server = hkdf_expand_label(initial_secret, "server in", b"", 32)
    return DirectionKeys(client), DirectionKeys(server)


# ---------------------------------------------------------------------------
# packet numbers (RFC 9000 §17.1, A.2/A.3)
# ---------------------------------------------------------------------------

def encode_pn(pn: int, largest_acked: int) -> bytes:
    """Smallest encoding whose window covers twice the unacked range."""
    num_unacked = pn + 1 if largest_acked < 0 else pn - largest_acked
    min_bits = num_unacked.bit_length() + 1
    nbytes = min(4, max(1, (min_bits + 7) // 8))
    return (pn & ((1 << (8 * nbytes)) - 1)).to_bytes(nbytes, "big")


def decode_pn(truncated: int, pn_nbits: int, largest_pn: int) -> int:
    expected = largest_pn + 1
    win = 1 << pn_nbits
    hwin = win // 2
    mask = win - 1
    candidate = (expected & ~mask) | truncated
    if candidate <= expected - hwin and candidate < (1 << 62) - win:
        return candidate + win
    if candidate > expected + hwin and candidate >= win:
        return candidate - win
    return candidate


# ---------------------------------------------------------------------------
# headers (RFC 9000 §17.2/17.3)
# ---------------------------------------------------------------------------

PKT_INITIAL = 0
PKT_0RTT = 1
PKT_HANDSHAKE = 2
PKT_RETRY = 3
PKT_1RTT = 4  # internal tag (short header)

LEVEL_INITIAL = 0
LEVEL_HANDSHAKE = 1
LEVEL_APP = 2

_LEVEL_FOR_TYPE = {PKT_INITIAL: LEVEL_INITIAL, PKT_HANDSHAKE: LEVEL_HANDSHAKE,
                   PKT_1RTT: LEVEL_APP}


class Packet:
    """One parsed (still protected) QUIC packet from a datagram."""

    __slots__ = ("ptype", "version", "dcid", "scid", "token",
                 "header_len", "pn_offset", "payload_end", "raw")

    def __init__(self):
        self.token = b""


def build_long_header(ptype: int, dcid: bytes, scid: bytes, pn_bytes: bytes,
                      payload_len: int, token: bytes = b"") -> bytes:
    first = 0xC0 | (ptype << 4) | (len(pn_bytes) - 1)
    hdr = bytearray([first])
    hdr += struct.pack(">I", QUIC_V1)
    hdr += bytes([len(dcid)]) + dcid
    hdr += bytes([len(scid)]) + scid
    if ptype == PKT_INITIAL:
        hdr += enc_varint(len(token)) + token
    hdr += enc_varint(payload_len + len(pn_bytes) + 16)  # +16 AEAD tag
    hdr += pn_bytes
    return bytes(hdr)


def build_short_header(dcid: bytes, pn_bytes: bytes,
                       key_phase: int = 0) -> bytes:
    first = 0x40 | (key_phase << 2) | (len(pn_bytes) - 1)
    return bytes([first]) + dcid + pn_bytes


def parse_packet(datagram: bytes, pos: int, local_cid_len: int) -> Packet:
    """Parse one (coalesced) packet's envelope; protection not yet removed.

    For short-header packets the DCID length is not self-describing —
    the endpoint supplies its own connection-id length.
    """
    pkt = Packet()
    pkt.raw = datagram
    if pos >= len(datagram):
        raise QuicError("empty packet")
    first = datagram[pos]
    if first & 0x80:  # long header
        if pos + 6 > len(datagram):
            raise QuicError("truncated long header")
        pkt.version = struct.unpack(">I", datagram[pos + 1:pos + 5])[0]
        p = pos + 5
        dlen = datagram[p]; p += 1
        pkt.dcid = datagram[p:p + dlen]; p += dlen
        slen = datagram[p]; p += 1
        pkt.scid = datagram[p:p + slen]; p += slen
        pkt.ptype = (first >> 4) & 0x03
        if pkt.version != QUIC_V1:
            raise QuicError(f"unsupported version {pkt.version:#x}")
        if pkt.ptype == PKT_INITIAL:
            tlen, p = dec_varint(datagram, p)
            pkt.token = datagram[p:p + tlen]; p += tlen
        elif pkt.ptype == PKT_RETRY:
            raise QuicError("retry not supported")
        length, p = dec_varint(datagram, p)
        pkt.pn_offset = p
        pkt.payload_end = p + length
        if pkt.payload_end > len(datagram):
            raise QuicError("packet length exceeds datagram")
    else:
        if not first & 0x40:
            raise QuicError("fixed bit clear")
        pkt.ptype = PKT_1RTT
        pkt.version = QUIC_V1
        p = pos + 1
        pkt.dcid = datagram[p:p + local_cid_len]
        p += local_cid_len
        pkt.scid = b""
        pkt.pn_offset = p
        pkt.payload_end = len(datagram)
    pkt.header_len = pos
    return pkt


def protect(keys: DirectionKeys, header: bytes, pn: int, pn_len: int,
            payload: bytes) -> bytes:
    """AEAD-seal then header-protect one packet (RFC 9001 §5.3-5.4)."""
    sealed = keys.seal(pn, header, payload)
    out = bytearray(header + sealed)
    pn_offset = len(header) - pn_len
    sample = bytes(out[pn_offset + 4:pn_offset + 20])
    mask = keys.hp_mask(sample)
    out[0] ^= mask[0] & (0x0F if out[0] & 0x80 else 0x1F)
    for i in range(pn_len):
        out[pn_offset + i] ^= mask[1 + i]
    return bytes(out)


def unprotect(keys: DirectionKeys, datagram: bytes, pkt: Packet,
              largest_pn: int) -> tuple[int, bytes]:
    """Remove header+packet protection; returns (pn, plaintext payload)."""
    buf = bytearray(datagram)
    po = pkt.pn_offset
    # minimum protected region: 4 pn-candidate bytes + 16-byte sample
    # (equivalently pn+payload+tag >= 20); shorter is garbage, not a crash
    if pkt.payload_end - po < 20:
        raise QuicError("packet too short for header-protection sample")
    sample = bytes(buf[po + 4:po + 20])
    mask = keys.hp_mask(sample)
    first = buf[pkt.header_len] ^ (mask[0] & (0x0F if buf[pkt.header_len] & 0x80
                                              else 0x1F))
    buf[pkt.header_len] = first
    pn_len = (first & 0x03) + 1
    for i in range(pn_len):
        buf[po + i] ^= mask[1 + i]
    truncated = int.from_bytes(bytes(buf[po:po + pn_len]), "big")
    pn = decode_pn(truncated, pn_len * 8, largest_pn)
    header = bytes(buf[pkt.header_len:po + pn_len])
    ciphertext = bytes(buf[po + pn_len:pkt.payload_end])
    try:
        plain = keys.open(pn, header, ciphertext)
    except Exception as exc:  # InvalidTag
        raise QuicError(f"AEAD open failed: {exc}") from exc
    return pn, plain


# ---------------------------------------------------------------------------
# transport parameters (RFC 9000 §18)
# ---------------------------------------------------------------------------

TP_ORIGINAL_DCID = 0x00
TP_MAX_IDLE_TIMEOUT = 0x01
TP_MAX_UDP_PAYLOAD = 0x03
TP_INITIAL_MAX_DATA = 0x04
TP_INITIAL_MAX_STREAM_DATA_BIDI_LOCAL = 0x05
TP_INITIAL_MAX_STREAM_DATA_BIDI_REMOTE = 0x06
TP_INITIAL_MAX_STREAM_DATA_UNI = 0x07
TP_INITIAL_MAX_STREAMS_BIDI = 0x08
TP_INITIAL_MAX_STREAMS_UNI = 0x09
TP_INITIAL_SCID = 0x0F

STREAM_WINDOW = 1 << 20  # per-stream flow-control window
CONN_WINDOW = 4 << 20    # connection-level window
MAX_INBOUND_STREAMS = 4096  # active-stream cap: remote-controlled memory


def encode_transport_params(params: dict[int, object]) -> bytes:
    out = b""
    for key, val in params.items():
        body = val if isinstance(val, bytes) else enc_varint(val)
        out += enc_varint(key) + enc_varint(len(body)) + body
    return out


def decode_transport_params(raw: bytes) -> dict[int, bytes]:
    out: dict[int, bytes] = {}
    pos = 0
    while pos < len(raw):
        key, pos = dec_varint(raw, pos)
        ln, pos = dec_varint(raw, pos)
        out[key] = raw[pos:pos + ln]
        pos += ln
    return out


def tp_int(params: dict[int, bytes], key: int, default: int) -> int:
    raw = params.get(key)
    if raw is None:
        return default
    return dec_varint(raw, 0)[0]


# ---------------------------------------------------------------------------
# frames (RFC 9000 §19)
# ---------------------------------------------------------------------------

F_PADDING = 0x00
F_PING = 0x01
F_ACK = 0x02
F_ACK_ECN = 0x03
F_RESET_STREAM = 0x04
F_STOP_SENDING = 0x05
F_CRYPTO = 0x06
F_NEW_TOKEN = 0x07
F_STREAM_BASE = 0x08  # ..0x0f: OFF=0x04 LEN=0x02 FIN=0x01
F_MAX_DATA = 0x10
F_MAX_STREAM_DATA = 0x11
F_MAX_STREAMS_BIDI = 0x12
F_MAX_STREAMS_UNI = 0x13
F_DATA_BLOCKED = 0x14
F_STREAM_DATA_BLOCKED = 0x15
F_STREAMS_BLOCKED_BIDI = 0x16
F_STREAMS_BLOCKED_UNI = 0x17
F_NEW_CONNECTION_ID = 0x18
F_RETIRE_CONNECTION_ID = 0x19
F_PATH_CHALLENGE = 0x1A
F_PATH_RESPONSE = 0x1B
F_CONNECTION_CLOSE = 0x1C
F_CONNECTION_CLOSE_APP = 0x1D
F_HANDSHAKE_DONE = 0x1E

# RFC 9000 §12.4 (table 3): frames only valid in 1-RTT packets.  STREAM
# (0x08..0x0f) is checked by range alongside this set.  An Initial/Handshake
# packet carrying one of these is a protocol violation — enforcing it keeps
# pre-handshake-authentication packets from touching stream/flow-control
# state (or faking handshake confirmation).
_APP_ONLY_FRAMES = frozenset({
    F_RESET_STREAM, F_STOP_SENDING, F_NEW_TOKEN,
    F_MAX_DATA, F_MAX_STREAM_DATA, F_MAX_STREAMS_BIDI, F_MAX_STREAMS_UNI,
    F_DATA_BLOCKED, F_STREAM_DATA_BLOCKED,
    F_STREAMS_BLOCKED_BIDI, F_STREAMS_BLOCKED_UNI,
    F_NEW_CONNECTION_ID, F_RETIRE_CONNECTION_ID,
    F_PATH_CHALLENGE, F_PATH_RESPONSE,
    F_CONNECTION_CLOSE_APP, F_HANDSHAKE_DONE,
})


def _enc_ack_frame(ranges: list[list[int]], ack_delay_us: int = 0) -> bytes:
    """ranges: sorted descending, non-overlapping [lo, hi] pairs."""
    largest = ranges[0][1]
    out = bytearray(enc_varint(F_ACK))
    out += enc_varint(largest)
    out += enc_varint(ack_delay_us >> 3)  # default ack_delay_exponent
    out += enc_varint(len(ranges) - 1)
    out += enc_varint(ranges[0][1] - ranges[0][0])
    prev_lo = ranges[0][0]
    for lo, hi in ranges[1:]:
        out += enc_varint(prev_lo - hi - 2)  # gap
        out += enc_varint(hi - lo)
        prev_lo = lo
    return bytes(out)


class _RecvState:
    """Packet-number tracking for one space's receive side."""

    def __init__(self):
        self.ranges: list[list[int]] = []  # [lo, hi] descending
        self.largest = -1
        self.ack_pending = False
        self.unacked_eliciting = 0     # ack-eliciting packets since last ACK
        self.oldest_unacked: float | None = None

    def register(self, pn: int) -> bool:
        """Record pn; returns False when it is a duplicate."""
        self.largest = max(self.largest, pn)
        for rng in self.ranges:
            if rng[0] - 1 <= pn <= rng[1] + 1:
                if rng[0] <= pn <= rng[1]:
                    return False
                if pn == rng[1] + 1:
                    rng[1] = pn
                else:
                    rng[0] = pn
                self._merge()
                return True
        self.ranges.append([pn, pn])
        self.ranges.sort(key=lambda r: -r[1])
        del self.ranges[32:]  # bound state
        return True

    def _merge(self) -> None:
        self.ranges.sort(key=lambda r: -r[1])
        merged: list[list[int]] = []
        for rng in self.ranges:
            if merged and rng[1] >= merged[-1][0] - 1:
                merged[-1][0] = min(merged[-1][0], rng[0])
            else:
                merged.append(rng)
        self.ranges = merged


class _SentPacket:
    __slots__ = ("pn", "time", "ack_eliciting", "frames", "size")

    def __init__(self, pn, now, ack_eliciting, frames, size):
        self.pn = pn
        self.time = now
        self.ack_eliciting = ack_eliciting
        self.frames = frames  # retransmittable descriptors
        self.size = size


class _Space:
    """One packet-number space (Initial / Handshake / 1-RTT)."""

    def __init__(self):
        self.next_pn = 0
        self.largest_acked = -1
        self.recv = _RecvState()
        self.sent: dict[int, _SentPacket] = {}
        # CRYPTO send: queued (offset, bytes); offset counter
        self.crypto_offset = 0
        self.crypto_pending: deque[tuple[int, bytes]] = deque()
        # CRYPTO recv reassembly
        self.crypto_frags: dict[int, bytes] = {}
        self.crypto_delivered = 0
        self.inflight = 0  # bytes of unacked ack-eliciting packets


class QuicStreamError(QuicError):
    pass


class QuicStream:
    """One bidirectional QUIC stream with the yamux `Stream` contract:
    exact-n blocking reads, EOF-terminated bodies, write-side FIN via
    ``close()``, abortive ``reset()`` — so `libp2p.py` treats a QUIC
    connection exactly like a yamux session (`yamux.py:45`)."""

    def __init__(self, conn: "QuicConnection", stream_id: int):
        self.conn = conn
        self.id = stream_id
        self._rx: deque[bytes] = deque()
        self._rx_frags: dict[int, bytes] = {}
        self._rx_delivered = 0   # contiguous bytes handed to _rx
        self._rx_consumed = 0    # bytes the application has read
        self._rx_limit = STREAM_WINDOW  # what we advertised
        self._rx_fin: int | None = None  # final size once FIN seen
        self._rx_highest = 0     # highest received offset (flow control)
        self._reset_err: int | None = None
        self._buf = b""
        self._send_offset = 0
        self._send_limit = STREAM_WINDOW  # peer's advertised limit
        self._closed_local = False
        self._closed_remote = False

    # -- write side --------------------------------------------------------

    def write(self, data: bytes, flags: int = 0, timeout: float = 30.0) -> None:
        if self._closed_local:
            raise QuicStreamError(f"stream {self.id} closed")
        conn = self.conn
        view = memoryview(data)
        deadline = time.monotonic() + timeout
        while len(view):
            with conn._cv:
                if conn._closed:
                    raise QuicStreamError("connection closed")
                allowed = min(
                    self._send_limit - self._send_offset,
                    conn._send_max_data - conn._send_data_total,
                )
                if allowed <= 0:
                    if not conn._cv.wait(deadline - time.monotonic()):
                        raise QuicStreamError(
                            f"stream {self.id}: window starved for {timeout}s")
                    continue
                chunk = bytes(view[:allowed])
                conn._queue_stream(self.id, self._send_offset, chunk, False)
                self._send_offset += len(chunk)
                conn._send_data_total += len(chunk)
            conn._flush()
            view = view[len(chunk):]

    def close(self) -> None:
        if self._closed_local:
            return
        self._closed_local = True
        conn = self.conn
        with conn._cv:
            conn._queue_stream(self.id, self._send_offset, b"", True)
            conn._maybe_gc_stream(self)
        conn._flush()

    def reset(self) -> None:
        self._closed_local = True
        conn = self.conn
        with conn._cv:
            conn._queue_frame(
                LEVEL_APP,
                ("raw", enc_varint(F_RESET_STREAM) + enc_varint(self.id)
                 + enc_varint(0) + enc_varint(self._send_offset)))
            conn._maybe_gc_stream(self)
        conn._flush()

    # -- read side ---------------------------------------------------------

    def _pump(self, timeout: float):
        conn = self.conn
        deadline = time.monotonic() + timeout
        chunk = None
        with conn._cv:
            while True:
                if self._rx:
                    chunk = self._rx.popleft()
                    self._rx_consumed += len(chunk)
                    conn._credit_consumed(self, len(chunk))
                    break
                if self._reset_err is not None:
                    raise QuicStreamError(
                        f"stream {self.id} reset by peer ({self._reset_err})")
                if self._closed_remote:
                    return None
                if conn._closed:
                    raise QuicStreamError("connection closed")
                if not conn._cv.wait(deadline - time.monotonic()):
                    raise QuicStreamError(f"stream {self.id}: read timeout")
        # outside the lock: push any MAX_DATA/MAX_STREAM_DATA updates the
        # consumption queued — a blocked peer only unblocks when they SEND
        conn._flush()
        return chunk

    def read(self, n: int, timeout: float = 5.0) -> bytes:
        while len(self._buf) < n:
            chunk = self._pump(timeout)
            if chunk is None:
                raise QuicStreamError(
                    f"stream {self.id}: EOF at {len(self._buf)}/{n}")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def read_until_eof(self, timeout: float = 5.0,
                       limit: int = 1 << 24) -> bytes:
        while True:
            chunk = self._pump(timeout)
            if chunk is None:
                break
            self._buf += chunk
            if len(self._buf) > limit:
                raise QuicStreamError("stream body over limit")
        out, self._buf = self._buf, b""
        return out

    def read_available(self, timeout: float = 5.0) -> bytes:
        if not self._buf:
            chunk = self._pump(timeout)
            if chunk is not None:
                self._buf += chunk
        out, self._buf = self._buf, b""
        return out

    # -- connection-side delivery (conn lock held) -------------------------

    def _on_stream_frame(self, offset: int, data: bytes, fin: bool) -> None:
        if fin:
            self._rx_fin = offset + len(data)
        if offset + len(data) > self._rx_limit:
            raise QuicError(f"stream {self.id}: flow-control overrun")
        if data and offset + len(data) > self._rx_delivered:
            self._rx_frags[offset] = data
            # drain contiguous prefix
            while True:
                for off, frag in list(self._rx_frags.items()):
                    if off <= self._rx_delivered < off + len(frag):
                        self._rx.append(frag[self._rx_delivered - off:])
                        self._rx_delivered = off + len(frag)
                        del self._rx_frags[off]
                        break
                    if off + len(frag) <= self._rx_delivered:
                        del self._rx_frags[off]
                        break
                else:
                    break
        if self._rx_fin is not None and self._rx_delivered == self._rx_fin:
            self._closed_remote = True

    def _on_reset(self, err: int) -> None:
        self._reset_err = err
        self._closed_remote = True


# ---------------------------------------------------------------------------
# connection
# ---------------------------------------------------------------------------

CID_LEN = 8
PTO_INITIAL = 0.4  # seconds; doubles per retry
PTO_MAX_RETRIES = 8
IDLE_TIMEOUT = 30.0
# Fixed congestion window, in bytes: bounds the burst a bulk write can
# blast into a UDP socket (loopback loss at unbounded bursts is near
# total); ACK arrival re-opens the window via the post-datagram flush.
CWND_BYTES = 1 << 21
# Post-handshake datagram ceiling when the peer's max_udp_payload_size
# allows it: QUIC's own PMTU signal.  16K datagrams cut the per-packet
# Python+AEAD overhead 12x on loopback/jumbo paths; 1452 remains the
# conservative floor for handshake flights and modest peers.
BIG_UDP_PAYLOAD = 1 << 14


class QuicConnection:
    """One QUIC connection: handshake, spaces, streams, recovery.

    Muxer surface (`open_stream`/`accept_stream`/`stop`) matches
    `yamux.Session` so `libp2p.Connection` drives either transparently.
    """

    def __init__(self, endpoint: "QuicEndpoint", peer_addr, is_client: bool,
                 original_dcid: bytes | None = None):
        from . import tls13 as _tls  # late import: tls13 imports our hkdf

        self.endpoint = endpoint
        self.peer_addr = peer_addr
        self.is_client = is_client
        self._cv = threading.Condition()
        self._closed = False
        self.close_reason: str | None = None

        self.local_cid = secrets.token_bytes(CID_LEN)
        if is_client:
            self.original_dcid = secrets.token_bytes(CID_LEN)
            self.peer_cid = self.original_dcid  # until ServerHello arrives
        else:
            self.original_dcid = original_dcid
            self.peer_cid = None  # learned from the client's SCID

        self.spaces = {lvl: _Space() for lvl in
                       (LEVEL_INITIAL, LEVEL_HANDSHAKE, LEVEL_APP)}
        ckeys, skeys = initial_keys(self.original_dcid)
        if is_client:
            self.send_keys = {LEVEL_INITIAL: ckeys}
            self.recv_keys = {LEVEL_INITIAL: skeys}
        else:
            self.send_keys = {LEVEL_INITIAL: skeys}
            self.recv_keys = {LEVEL_INITIAL: ckeys}

        tp = {
            TP_MAX_IDLE_TIMEOUT: int(IDLE_TIMEOUT * 1000),
            TP_MAX_UDP_PAYLOAD: 65527,
            TP_INITIAL_MAX_DATA: CONN_WINDOW,
            TP_INITIAL_MAX_STREAM_DATA_BIDI_LOCAL: STREAM_WINDOW,
            TP_INITIAL_MAX_STREAM_DATA_BIDI_REMOTE: STREAM_WINDOW,
            TP_INITIAL_MAX_STREAM_DATA_UNI: STREAM_WINDOW,
            TP_INITIAL_MAX_STREAMS_BIDI: 1 << 40,
            TP_INITIAL_MAX_STREAMS_UNI: 0,
            TP_INITIAL_SCID: self.local_cid,
        }
        if not is_client:
            tp[TP_ORIGINAL_DCID] = self.original_dcid
        self.tls = _tls.TlsEngine(
            "client" if is_client else "server",
            endpoint.identity_key, encode_transport_params(tp),
            cert=getattr(endpoint, "cert", None))

        self.handshake_complete = threading.Event()
        self.handshake_confirmed = False
        self._handshake_done_queued = False
        self.remote_peer_id: bytes | None = None

        # streams; _dead holds tombstoned ids so late retransmits for a
        # collected stream don't resurrect it as a fresh inbound stream
        self.streams: dict[int, QuicStream] = {}
        self._dead_streams: "OrderedDict[int, bool]" = OrderedDict()
        self._next_stream = 0 if is_client else 1
        self._accept_q: deque[QuicStream] = deque()
        self._peer_tp: dict[int, bytes] | None = None

        # flow control: what the peer lets us send / what we let them.
        # per-stream initial limits come from the peer's transport params
        # at handshake completion (RFC 9000 section 18.2): _ours applies
        # to streams WE initiate (their ..._bidi_remote), _theirs to
        # streams THEY initiate (their ..._bidi_local)
        self._peer_sd_ours = STREAM_WINDOW
        self._peer_sd_theirs = STREAM_WINDOW
        self._send_max_data = CONN_WINDOW
        self._send_data_total = 0
        self._recv_max_data = CONN_WINDOW
        self._recv_data_total = 0
        self._recv_consumed_total = 0

        # frame queues: level -> deque of descriptors
        #   ("raw", bytes)                      control, retransmit verbatim
        #   ("stream", sid, offset, data, fin)
        self._pending: dict[int, deque] = {
            LEVEL_INITIAL: deque(), LEVEL_HANDSHAKE: deque(),
            LEVEL_APP: deque()}
        self._undecryptable: list[tuple[Packet, bytes]] = []
        # levels whose keys were discarded (RFC 9001 §4.9): packets there
        # are DROPPED, not parked — the keys are never coming back
        self._discarded_levels: set[int] = set()
        self._pto_count = 0
        self._max_payload = MAX_UDP_PAYLOAD
        self._last_rx = time.monotonic()
        self._last_tx = time.monotonic()
        self._amp_budget = 0  # server: 3x bytes received pre-validation
        self._addr_validated = is_client

        if is_client:
            # queue the first flight; dial() flushes AFTER registering the
            # connection for demux, else a same-host server can reply
            # before we are routable and the whole flight rides one PTO
            self.tls.start()
            with self._cv:
                self._drive_tls_locked()

    # -- muxer surface (yamux.Session contract) ---------------------------

    # callback-driven inbound streams, as yamux.Session exposes them:
    # libp2p sets these then calls start()
    _on_stream = None
    _on_close = None

    def start(self) -> None:
        threading.Thread(target=self._stream_accept_loop,
                         name=f"quic-streams-{self.local_cid.hex()[:6]}",
                         daemon=True).start()

    def _stream_accept_loop(self) -> None:
        while True:
            try:
                st = self.accept_stream(timeout=30.0)
            except QuicError:
                if self._closed:
                    cb, self._on_close = self._on_close, None
                    if cb:
                        cb()
                    return
                continue  # idle window with no inbound streams
            if self._on_stream is not None:
                self._on_stream(st)

    def open_stream(self) -> QuicStream:
        with self._cv:
            if self._closed:
                raise QuicError("connection closed")
            sid = self._next_stream
            self._next_stream += 4
            st = QuicStream(self, sid)
            st._send_limit = self._peer_sd_ours
            self.streams[sid] = st
            return st

    def accept_stream(self, timeout: float = 5.0) -> QuicStream:
        deadline = time.monotonic() + timeout
        with self._cv:
            while not self._accept_q:
                if self._closed:
                    raise QuicError("connection closed")
                if not self._cv.wait(deadline - time.monotonic()):
                    raise QuicError("accept_stream timeout")
            return self._accept_q.popleft()

    def stop(self) -> None:
        self.close("closed by application")

    def close(self, reason: str = "", error_code: int = 0) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self.close_reason = reason
            # highest level with live send keys (Initial/Handshake may be
            # discarded per RFC 9001 §4.9 — a CONNECTION_CLOSE can only
            # ride a level both sides still hold keys for)
            level = next((lv for lv in (LEVEL_APP, LEVEL_HANDSHAKE,
                                        LEVEL_INITIAL)
                          if lv in self.send_keys), None)
            frame = (enc_varint(F_CONNECTION_CLOSE) + enc_varint(error_code)
                     + enc_varint(0) + enc_varint(len(reason))
                     + reason.encode())
            if level is not None:
                try:
                    self._send_one(level, [frame], ack_eliciting=False)
                except OSError:
                    pass
            self._cv.notify_all()
        self.endpoint._forget(self)

    # -- TLS plumbing ------------------------------------------------------

    def _drive_tls_locked(self) -> None:
        for level, msg in self.tls.take_output():
            space = self.spaces[level]
            self._pending[level].append(
                ("crypto", space.crypto_offset, msg))
            space.crypto_offset += len(msg)
        for level, (c_secret, s_secret) in self.tls.secrets.items():
            mine, theirs = ((c_secret, s_secret) if self.is_client
                            else (s_secret, c_secret))
            if level not in self.send_keys:
                self.send_keys[level] = DirectionKeys(mine)
            # RFC 9001 section 5.7: the server must not process 1-RTT
            # data before the client proves its identity — installing
            # the receive keys only at handshake completion parks early
            # stream data in the (bounded) undecryptable buffer instead
            # of committing flow-control memory to unauthenticated peers
            if level not in self.recv_keys:
                if (level == LEVEL_APP and not self.is_client
                        and not self.tls.complete):
                    continue
                self.recv_keys[level] = DirectionKeys(theirs)
        if self.tls.complete and not self.handshake_complete.is_set():
            self.remote_peer_id = self.tls.peer_id
            self._peer_tp = decode_transport_params(
                self.tls.peer_transport_params)
            self._validate_peer_tp()
            self._send_max_data = tp_int(
                self._peer_tp, TP_INITIAL_MAX_DATA, 0)
            self._peer_sd_ours = tp_int(
                self._peer_tp,
                TP_INITIAL_MAX_STREAM_DATA_BIDI_REMOTE, 0)
            self._peer_sd_theirs = tp_int(
                self._peer_tp,
                TP_INITIAL_MAX_STREAM_DATA_BIDI_LOCAL, 0)
            # never exceed what the peer advertised (RFC 9000 section
            # 18.2 MUST NOT); 1200 is the protocol floor, BIG the cap
            self._max_payload = min(
                max(1200,
                    tp_int(self._peer_tp, TP_MAX_UDP_PAYLOAD, MAX_UDP_PAYLOAD)),
                BIG_UDP_PAYLOAD)
            if not self.is_client and not self._handshake_done_queued:
                self._handshake_done_queued = True
                self._pending[LEVEL_APP].append(
                    ("raw", enc_varint(F_HANDSHAKE_DONE)))
                self.handshake_confirmed = True
                # RFC 9001 §4.9.2: the server confirms at handshake
                # completion and retires its Handshake keys (the final
                # ACK for the client's Finished goes out first)
                self._discard_keys(LEVEL_HANDSHAKE)
            self.handshake_complete.set()
            self._cv.notify_all()

    def _discard_keys(self, level: int) -> None:
        """Retire an encryption level (RFC 9001 §4.9): keys, loss-recovery
        state and queued frames all go — nothing at this level will ever
        be sent or processed again.  Lock held.

        Before dropping the send keys, flush one final ACK for anything
        received at this level: the peer may not have confirmed yet (e.g.
        its Finished is un-ACKed) and without it would burn a PTO
        retransmitting into our discarded keys.
        """
        if level in self._discarded_levels:
            return
        self._discarded_levels.add(level)
        space = self.spaces[level]
        if level in self.send_keys and space.recv.ranges:
            try:
                self._send_one(level, [_enc_ack_frame(space.recv.ranges)],
                               ack_eliciting=False)
            except OSError:
                pass
        self.send_keys.pop(level, None)
        self.recv_keys.pop(level, None)
        self._pending[level].clear()
        space.sent.clear()
        space.inflight = 0
        space.recv.ack_pending = False
        space.recv.unacked_eliciting = 0
        space.recv.oldest_unacked = None
        # parked packets at this level are undecryptable forever: free
        # their slots for levels that can still progress
        self._undecryptable = [
            p for p in self._undecryptable
            if _LEVEL_FOR_TYPE.get(p.ptype) != level
        ]

    def _validate_peer_tp(self) -> None:
        peer_scid = self._peer_tp.get(TP_INITIAL_SCID)
        if peer_scid != self.peer_cid:
            raise QuicError("transport params: initial_scid mismatch")
        if self.is_client:
            odcid = self._peer_tp.get(TP_ORIGINAL_DCID)
            if odcid != self.original_dcid:
                raise QuicError(
                    "transport params: original_destination_cid mismatch")

    # -- inbound -----------------------------------------------------------

    def handle_datagram(self, datagram: bytes) -> None:
        with self._cv:
            if self._closed:
                return
            self._last_rx = time.monotonic()
            if not self._addr_validated:
                self._amp_budget += 3 * len(datagram)
            pos = 0
            while pos < len(datagram):
                if datagram[pos] == 0:  # trailing padding at datagram level
                    pos += 1
                    continue
                try:
                    pkt = parse_packet(datagram, pos, CID_LEN)
                except QuicError as exc:
                    log.debug("drop undecodable packet: %s", exc)
                    return
                try:
                    self._handle_packet(pkt, datagram)
                except QuicError as exc:
                    # a protocol violation inside a decrypted packet is
                    # fatal to the connection, not just this datagram
                    log.warning("protocol violation: %s", exc)
                    self._cv.release()
                    try:
                        self.close(f"protocol violation: {exc}",
                                   error_code=0x03)
                    finally:
                        self._cv.acquire()
                    return
                except Exception as exc:  # noqa: BLE001
                    # malformed input escaping as ValueError/IndexError
                    # (cert parsing, varint truncation, ...) must close
                    # the connection, not zombie its handshake slot with
                    # the rx thread's exception swallowed
                    log.warning("internal error on packet: %r", exc)
                    self._cv.release()
                    try:
                        self.close(f"internal error: {exc!r}",
                                   error_code=0x01)
                    finally:
                        self._cv.acquire()
                    return
                pos = pkt.payload_end
            try:
                self._drive_tls_locked()
            except Exception as exc:
                log.warning("TLS failure: %s", exc)
                self._cv.release()
                try:
                    self.close(f"tls: {exc}", error_code=0x0128)
                finally:
                    self._cv.acquire()
                return
        self._flush()

    def _handle_packet(self, pkt: Packet, datagram: bytes) -> None:
        level = _LEVEL_FOR_TYPE.get(pkt.ptype)
        if level is None:
            return  # 0-RTT / Retry: not used by this stack
        if level in self._discarded_levels:
            return  # keys retired (RFC 9001 §4.9): drop, don't park
        keys = self.recv_keys.get(level)
        if keys is None:
            if len(self._undecryptable) < 8:
                self._undecryptable.append(pkt)
            return
        space = self.spaces[level]
        try:
            pn, plain = unprotect(keys, datagram, pkt, space.recv.largest)
        except QuicError as exc:
            log.debug("drop packet (level %d): %s", level, exc)
            return
        if not space.recv.register(pn):
            return  # duplicate
        if self.peer_cid is None or (pkt.ptype != PKT_1RTT
                                     and pkt.scid != self.peer_cid):
            # server learns the client SCID; client re-targets to the
            # server's chosen SCID on first response
            self.peer_cid = pkt.scid
        if level == LEVEL_HANDSHAKE and not self._addr_validated:
            self._addr_validated = True  # RFC 9001 §4.9: address proven
        self._process_frames(level, plain)
        if level == LEVEL_HANDSHAKE:
            # RFC 9001 §4.9.1: a successfully processed Handshake packet
            # proves the peer is past the Initial exchange on both ends —
            # Initial keys (and any Initial retransmission state) retire
            self._discard_keys(LEVEL_INITIAL)

    def _process_frames(self, level: int, plain: bytes) -> None:
        space = self.spaces[level]
        pos = 0
        ack_eliciting = False
        while pos < len(plain):
            ftype, pos = dec_varint(plain, pos)
            if level != LEVEL_APP and (
                    F_STREAM_BASE <= ftype <= 0x0F
                    or ftype in _APP_ONLY_FRAMES):
                raise QuicError(
                    f"frame type {ftype:#x} forbidden at encryption "
                    f"level {level} (RFC 9000 §12.4)")
            if ftype == F_PADDING:
                continue
            if ftype == F_PING:
                ack_eliciting = True
                continue
            if ftype in (F_ACK, F_ACK_ECN):
                pos = self._on_ack(space, plain, pos, ftype == F_ACK_ECN)
                continue
            ack_eliciting = True
            if ftype == F_CRYPTO:
                off, pos = dec_varint(plain, pos)
                ln, pos = dec_varint(plain, pos)
                self._on_crypto(space, level, off, plain[pos:pos + ln])
                pos += ln
            elif F_STREAM_BASE <= ftype <= 0x0F:
                sid, pos = dec_varint(plain, pos)
                off = 0
                if ftype & 0x04:
                    off, pos = dec_varint(plain, pos)
                if ftype & 0x02:
                    ln, pos = dec_varint(plain, pos)
                else:
                    ln = len(plain) - pos
                self._handle_stream_frame(sid, off, plain[pos:pos + ln],
                                           bool(ftype & 0x01))
                pos += ln
            elif ftype == F_MAX_DATA:
                v, pos = dec_varint(plain, pos)
                if v > self._send_max_data:
                    self._send_max_data = v
                    self._cv.notify_all()
            elif ftype == F_MAX_STREAM_DATA:
                sid, pos = dec_varint(plain, pos)
                v, pos = dec_varint(plain, pos)
                st = self.streams.get(sid)
                if st and v > st._send_limit:
                    st._send_limit = v
                    self._cv.notify_all()
            elif ftype in (F_MAX_STREAMS_BIDI, F_MAX_STREAMS_UNI):
                _, pos = dec_varint(plain, pos)
            elif ftype == F_RESET_STREAM:
                sid, pos = dec_varint(plain, pos)
                err, pos = dec_varint(plain, pos)
                _final, pos = dec_varint(plain, pos)
                st = self.streams.get(sid)
                if st:
                    st._on_reset(err)
                    self._maybe_gc_stream(st)
                    self._cv.notify_all()
            elif ftype == F_STOP_SENDING:
                sid, pos = dec_varint(plain, pos)
                err, pos = dec_varint(plain, pos)
                st = self.streams.get(sid)
                if st and not st._closed_local:
                    st._closed_local = True
                    self._queue_frame(LEVEL_APP, ("raw",
                        enc_varint(F_RESET_STREAM) + enc_varint(sid)
                        + enc_varint(err) + enc_varint(st._send_offset)))
            elif ftype in (F_DATA_BLOCKED, F_STREAMS_BLOCKED_BIDI,
                           F_STREAMS_BLOCKED_UNI, F_RETIRE_CONNECTION_ID):
                _, pos = dec_varint(plain, pos)
            elif ftype == F_STREAM_DATA_BLOCKED:
                _, pos = dec_varint(plain, pos)
                _, pos = dec_varint(plain, pos)
            elif ftype == F_NEW_CONNECTION_ID:
                _, pos = dec_varint(plain, pos)   # sequence
                _, pos = dec_varint(plain, pos)   # retire prior to
                ln = plain[pos]; pos += 1 + ln + 16  # cid + reset token
            elif ftype == F_NEW_TOKEN:
                ln, pos = dec_varint(plain, pos)
                pos += ln
            elif ftype == F_PATH_CHALLENGE:
                data = plain[pos:pos + 8]; pos += 8
                self._queue_frame(level, ("raw",
                    enc_varint(F_PATH_RESPONSE) + data))
            elif ftype == F_PATH_RESPONSE:
                pos += 8
            elif ftype in (F_CONNECTION_CLOSE, F_CONNECTION_CLOSE_APP):
                err, pos = dec_varint(plain, pos)
                if ftype == F_CONNECTION_CLOSE:
                    _, pos = dec_varint(plain, pos)
                rlen, pos = dec_varint(plain, pos)
                reason = plain[pos:pos + rlen].decode("utf-8", "replace")
                pos += rlen
                self._closed = True
                self.close_reason = f"peer closed ({err:#x}): {reason}"
                self._cv.notify_all()
                self.endpoint._forget(self)
                return
            elif ftype == F_HANDSHAKE_DONE:
                if not self.is_client:
                    # only the server sends HANDSHAKE_DONE (RFC 9000 §19.20)
                    raise QuicError("client sent HANDSHAKE_DONE")
                self.handshake_confirmed = True
                # RFC 9001 §4.9.2: handshake confirmation retires the
                # Handshake keys on the client
                self._discard_keys(LEVEL_HANDSHAKE)
            else:
                raise QuicError(f"unknown frame type {ftype:#x}")
        if ack_eliciting:
            rs = space.recv
            rs.unacked_eliciting += 1
            if rs.oldest_unacked is None:
                rs.oldest_unacked = time.monotonic()
            # RFC 9000 section 13.2.2: ack every 2nd ack-eliciting packet;
            # handshake levels ack immediately (latency over overhead)
            if level != LEVEL_APP or rs.unacked_eliciting >= 2:
                rs.ack_pending = True

    def _on_ack(self, space: _Space, plain: bytes, pos: int,
                ecn: bool) -> int:
        largest, pos = dec_varint(plain, pos)
        if largest >= space.next_pn:
            # RFC 9000 §13.1: acknowledging a packet number we never sent
            # is a protocol violation, not a no-op — a forged/corrupt ACK
            # must not poison largest_acked (it would mark every genuine
            # in-flight packet "lost" via the packet-threshold rule)
            raise QuicError(
                f"ACK for unsent packet number {largest} "
                f"(next_pn {space.next_pn})")
        _delay, pos = dec_varint(plain, pos)
        nranges, pos = dec_varint(plain, pos)
        first, pos = dec_varint(plain, pos)
        acked = [(largest - first, largest)]
        lo = largest - first
        for _ in range(nranges):
            gap, pos = dec_varint(plain, pos)
            rlen, pos = dec_varint(plain, pos)
            hi = lo - gap - 2
            acked.append((hi - rlen, hi))
            lo = hi - rlen
        if ecn:
            for _ in range(3):
                _, pos = dec_varint(plain, pos)
        newly = False
        for alo, ahi in acked:
            for pn in [p for p in space.sent if alo <= p <= ahi]:
                space.inflight -= space.sent[pn].size
                del space.sent[pn]
                newly = True
        space.largest_acked = max(space.largest_acked, largest)
        if newly:
            self._pto_count = 0
        # packet-threshold loss: 3 packets past a later-sent acked one
        lost = [p for p in space.sent if p <= space.largest_acked - 3]
        for pn in lost:
            self._requeue(space, pn)
        return pos

    def _on_crypto(self, space: _Space, level: int, off: int,
                   data: bytes) -> None:
        if off + len(data) <= space.crypto_delivered:
            return
        space.crypto_frags[off] = data
        # legitimate TLS flights are a few KB; an attacker spraying
        # widely-spaced CRYPTO offsets must not grow this without bound
        if (len(space.crypto_frags) > 64
                or sum(len(v) for v in space.crypto_frags.values()) > (1 << 18)):
            raise QuicError("CRYPTO reassembly buffer overflow")
        progressed = True
        while progressed:
            progressed = False
            for frag_off, frag in list(space.crypto_frags.items()):
                if frag_off <= space.crypto_delivered < frag_off + len(frag):
                    self.tls.on_data(
                        level, frag[space.crypto_delivered - frag_off:])
                    space.crypto_delivered = frag_off + len(frag)
                    del space.crypto_frags[frag_off]
                    progressed = True
                elif frag_off + len(frag) <= space.crypto_delivered:
                    del space.crypto_frags[frag_off]

    def _handle_stream_frame(self, sid: int, off: int, data: bytes,
                             fin: bool) -> None:
        st = self.streams.get(sid)
        if st is None:
            if sid in self._dead_streams:
                return  # late retransmit for a collected stream
            locally_initiated = (sid % 4 == 0) == self.is_client
            if locally_initiated:
                return  # data for a stream we never opened / already gc'd
            if len(self.streams) >= MAX_INBOUND_STREAMS:
                raise QuicError("inbound stream cap exceeded")
            st = QuicStream(self, sid)
            st._send_limit = self._peer_sd_theirs
            self.streams[sid] = st
            self._accept_q.append(st)
        # connection-level flow control counts the HIGHEST received
        # offset per stream (RFC 9000 section 4.1), so retransmits and
        # reordering don't inflate the total
        new_high = off + len(data)
        if new_high > st._rx_highest:
            self._recv_data_total += new_high - st._rx_highest
            st._rx_highest = new_high
            if self._recv_data_total > self._recv_max_data:
                raise QuicError("connection flow-control overrun")
        st._on_stream_frame(off, data, fin)
        if st._closed_remote:
            self._maybe_gc_stream(st)
        self._cv.notify_all()

    def _maybe_gc_stream(self, st: QuicStream) -> None:
        """Lock held.  Long-lived connections open one stream per
        req/resp; fully-closed streams leave the table (the stream object
        itself stays readable — buffered data lives on it, not here)."""
        if not (st._closed_local and st._closed_remote):
            return
        if self.streams.pop(st.id, None) is not None:
            self._dead_streams[st.id] = True
            while len(self._dead_streams) > 8192:
                self._dead_streams.popitem(last=False)

    def _credit_consumed(self, st: QuicStream, n: int) -> None:
        """Called under lock as the app consumes bytes: slide windows."""
        self._recv_consumed_total += n
        if st._rx_limit - st._rx_consumed < STREAM_WINDOW // 2:
            st._rx_limit = st._rx_consumed + STREAM_WINDOW
            self._pending[LEVEL_APP].append(("raw",
                enc_varint(F_MAX_STREAM_DATA) + enc_varint(st.id)
                + enc_varint(st._rx_limit)))
        if self._recv_max_data - self._recv_consumed_total < CONN_WINDOW // 2:
            self._recv_max_data = self._recv_consumed_total + CONN_WINDOW
            self._pending[LEVEL_APP].append(("raw",
                enc_varint(F_MAX_DATA) + enc_varint(self._recv_max_data)))
        self.endpoint._wake()

    # -- outbound ----------------------------------------------------------

    def _queue_stream(self, sid: int, offset: int, data: bytes,
                      fin: bool) -> None:
        self._pending[LEVEL_APP].append(("stream", sid, offset, data, fin))

    def _queue_frame(self, level: int, desc) -> None:
        self._pending[level].append(desc)

    def _flush(self) -> None:
        with self._cv:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if self._closed:
            return
        budget = None if self._addr_validated else self._amp_budget
        for level in (LEVEL_INITIAL, LEVEL_HANDSHAKE, LEVEL_APP):
            if level not in self.send_keys:
                continue
            space = self.spaces[level]
            while (self._pending[level] or space.recv.ack_pending):
                if (level == LEVEL_APP
                        and space.inflight >= CWND_BYTES
                        and not space.recv.ack_pending):
                    break  # congestion window full; ACKs re-open it
                frames: list[bytes] = []
                descs: list = []
                size = 0
                if space.recv.ack_pending and space.recv.ranges:
                    frames.append(_enc_ack_frame(space.recv.ranges))
                    size += len(frames[-1])
                    space.recv.ack_pending = False
                    space.recv.unacked_eliciting = 0
                    space.recv.oldest_unacked = None
                max_payload = (self._max_payload if level == LEVEL_APP
                               else MAX_UDP_PAYLOAD) - 64
                while self._pending[level] and size < max_payload:
                    desc = self._pending[level].popleft()
                    if desc[0] == "raw":
                        frames.append(desc[1])
                        descs.append(desc)
                        size += len(desc[1])
                    elif desc[0] == "crypto":
                        _, off, data = desc
                        room = max_payload - size - 16
                        if room < 32:
                            self._pending[level].appendleft(desc)
                            break
                        # memoryview keeps the unsent remainder O(1): a
                        # queued 1 MB chunk must not be re-copied per packet
                        view = memoryview(data)
                        take = bytes(view[:room])
                        rest = view[room:]
                        if len(rest):
                            self._pending[level].appendleft(
                                ("crypto", off + len(take), rest))
                        frame = (enc_varint(F_CRYPTO) + enc_varint(off)
                                 + enc_varint(len(take)) + take)
                        frames.append(frame)
                        descs.append(("crypto", off, take))
                        size += len(frame)
                    elif desc[0] == "stream":
                        _, sid, off, data, fin = desc
                        room = max_payload - size - 20
                        if room < 64 and len(data):
                            self._pending[level].appendleft(desc)
                            break
                        view = memoryview(data)
                        take = bytes(view[:room])
                        rest = view[room:]
                        if len(rest):
                            self._pending[level].appendleft(
                                ("stream", sid, off + len(take), rest, fin))
                            fin_now = False
                        else:
                            fin_now = fin
                        frame = (enc_varint(F_STREAM_BASE | 0x04 | 0x02
                                            | (0x01 if fin_now else 0))
                                 + enc_varint(sid) + enc_varint(off)
                                 + enc_varint(len(take)) + take)
                        frames.append(frame)
                        descs.append(("stream", sid, off, take, fin_now))
                        size += len(frame)
                if not frames:
                    break
                sent = self._send_one(level, frames, ack_eliciting=bool(descs)
                                      or any(f[0] == F_PING for f in frames),
                                      descs=descs)
                if budget is not None:
                    budget -= sent
                    self._amp_budget = max(0, budget)
                    if budget <= 0:
                        return  # anti-amplification: wait for more rx

    def _send_one(self, level: int, frames: list[bytes],
                  ack_eliciting: bool, descs: list | None = None) -> int:
        """Assemble, protect and transmit ONE packet; returns bytes sent."""
        space = self.spaces[level]
        pn = space.next_pn
        space.next_pn += 1
        pn_bytes = encode_pn(pn, space.largest_acked)
        payload = b"".join(frames)
        # sample for header protection needs >= 4 bytes of pn+payload
        while len(pn_bytes) + len(payload) < 4:
            payload += b"\x00"
        dcid = self.peer_cid if self.peer_cid is not None else b""
        if level == LEVEL_APP:
            header = build_short_header(dcid, pn_bytes)
        else:
            ptype = PKT_INITIAL if level == LEVEL_INITIAL else PKT_HANDSHAKE
            # a client Initial datagram must be >= 1200 bytes (RFC 9000
            # §14.1): pad the packet payload itself
            if ptype == PKT_INITIAL and self.is_client:
                # datagram = header(<=30 for 8-byte cids) + payload + tag;
                # pad so the total clears 1200 for any pn length
                target = MIN_CLIENT_INITIAL - 26 - len(pn_bytes) - 16
                if len(payload) < target:
                    payload += b"\x00" * (target - len(payload))
            header = build_long_header(ptype, dcid, self.local_cid,
                                       pn_bytes, len(payload))
        datagram = protect(self.send_keys[level], header, pn,
                           len(pn_bytes), payload)
        self._last_tx = time.monotonic()
        if ack_eliciting:
            space.sent[pn] = _SentPacket(pn, time.monotonic(), True,
                                         descs or [], len(datagram))
            space.inflight += len(datagram)
        self.endpoint._transmit(datagram, self.peer_addr)
        return len(datagram)

    def _requeue(self, space: _Space, pn: int) -> None:
        """Move a lost packet's retransmittable content back to pending."""
        rec = space.sent.pop(pn, None)
        if rec is None:
            return
        space.inflight -= rec.size
        level = next(l for l, s in self.spaces.items() if s is space)
        for desc in rec.frames:
            self._pending[level].append(desc)

    # -- timers ------------------------------------------------------------

    def on_tick(self, now: float) -> None:
        flush = False
        with self._cv:
            if self._closed:
                return
            if now - self._last_rx > IDLE_TIMEOUT:
                self._cv.release()
                try:
                    self.close("idle timeout")
                finally:
                    self._cv.acquire()
                return
            # keepalive: a quiet-but-healthy connection (stable gossip
            # mesh, no RPC) must not idle out — PING well inside the
            # timeout; the peer's ACK refreshes both sides' last_rx
            if (self.handshake_complete.is_set()
                    and LEVEL_APP in self.send_keys
                    and now - max(self._last_rx, self._last_tx)
                        > IDLE_TIMEOUT / 3):
                self._pending[LEVEL_APP].append(
                    ("raw", enc_varint(F_PING)))
                flush = True
            for space in self.spaces.values():
                rs = space.recv
                if (rs.unacked_eliciting > 0 and rs.oldest_unacked is not None
                        and now - rs.oldest_unacked > 0.025):
                    rs.ack_pending = True
                    flush = True
            pto = PTO_INITIAL * (2 ** min(self._pto_count, 6))
            for level, space in self.spaces.items():
                if not space.sent:
                    continue
                # time-threshold loss (RFC 9002 section 6.1): a packet
                # sent well before one the peer has acked is lost
                if space.largest_acked >= 0:
                    lost = [pn for pn, rec in space.sent.items()
                            if pn < space.largest_acked
                            and now - rec.time > 0.12]
                    for pn in lost:
                        self._requeue(space, pn)
                    if lost:
                        flush = True
                if not space.sent:
                    continue
                oldest = min(rec.time for rec in space.sent.values())
                if now - oldest > pto:
                    self._pto_count += 1
                    if self._pto_count > PTO_MAX_RETRIES:
                        self._cv.release()
                        try:
                            self.close("handshake/transfer timed out (PTO)")
                        finally:
                            self._cv.acquire()
                        return
                    for pn in list(space.sent):
                        self._requeue(space, pn)
                    flush = True
            # retry packets parked for missing keys
            if self._undecryptable and any(
                    _LEVEL_FOR_TYPE.get(p.ptype) in self.recv_keys
                    for p in self._undecryptable):
                parked, self._undecryptable = self._undecryptable, []
                try:
                    for pkt in parked:
                        self._handle_packet(pkt, pkt.raw)
                    self._drive_tls_locked()
                except Exception as exc:  # noqa: BLE001
                    violation = isinstance(exc, QuicError)
                    log.warning("parked replay failed (%s): %r",
                                "violation" if violation else "internal",
                                exc)
                    self._cv.release()
                    try:
                        self.close(
                            f"protocol violation: {exc}" if violation
                            else f"internal error: {exc!r}",
                            error_code=0x03 if violation else 0x01)
                    finally:
                        self._cv.acquire()
                    return
                flush = True
        if flush:
            self._flush()


# ---------------------------------------------------------------------------
# endpoint
# ---------------------------------------------------------------------------

class QuicEndpoint:
    """One UDP socket carrying many QUIC connections (client and server).

    The reference's QUIC listener is one quinn endpoint per node
    (`lighthouse_network/src/service/utils.rs:39-48`); same shape here:
    ``dial()`` and ``accept()`` both hand back handshake-complete
    `QuicConnection`s whose `remote_peer_id` is the TLS-authenticated
    libp2p identity.
    """

    MAX_PENDING_HANDSHAKES = 64
    MAX_CONNECTIONS = 1024

    def __init__(self, identity_key, ip: str = "127.0.0.1", port: int = 0):
        from . import tls13 as _tls

        self.identity_key = identity_key
        # one certificate per endpoint (it binds only the static identity
        # key) — per-handshake keygen+signing would hand an unauthenticated
        # Initial flood ~1ms of our CPU per 1200-byte datagram
        self.cert = _tls.make_libp2p_cert(identity_key)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        for opt in (socket.SO_RCVBUF, socket.SO_SNDBUF):
            try:
                self.sock.setsockopt(socket.SOL_SOCKET, opt, 1 << 22)
            except OSError:
                pass
        self.sock.bind((ip, port))
        self.sock.settimeout(0.05)
        self.ip, self.port = self.sock.getsockname()
        self._conns: dict[bytes, QuicConnection] = {}
        self._lock = threading.Lock()
        self._accept_q: deque[QuicConnection] = deque()
        self._accept_cv = threading.Condition(self._lock)
        self._stopped = False
        self._rx_thread = threading.Thread(
            target=self._rx_loop, name=f"quic-rx-{self.port}", daemon=True)
        self._tick_thread = threading.Thread(
            target=self._tick_loop, name=f"quic-tick-{self.port}", daemon=True)
        self._rx_thread.start()
        self._tick_thread.start()

    # -- wiring ------------------------------------------------------------

    def _transmit(self, datagram: bytes, addr) -> None:
        try:
            self.sock.sendto(datagram, addr)
        except OSError as exc:
            log.debug("sendto %s failed: %s", addr, exc)

    def _wake(self) -> None:
        pass  # sends are synchronous; nothing to wake

    def _forget(self, conn: QuicConnection) -> None:
        with self._lock:
            for cid in [c for c, v in self._conns.items() if v is conn]:
                del self._conns[cid]

    def _rx_loop(self) -> None:
        while not self._stopped:
            try:
                datagram, addr = self.sock.recvfrom(65535)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                self._dispatch(datagram, addr)
            except Exception as exc:
                log.warning("datagram dispatch failed: %s", exc)

    def _dispatch(self, datagram: bytes, addr) -> None:
        if not datagram:
            return
        first = datagram[0]
        if first & 0x80:
            if len(datagram) < 7:
                return
            dlen = datagram[5]
            dcid = datagram[6:6 + dlen]
        else:
            dcid = datagram[1:1 + CID_LEN]
        with self._lock:
            conn = self._conns.get(dcid)
            if conn is None and first & 0x80 and ((first >> 4) & 3) == PKT_INITIAL:
                if len(datagram) < MIN_CLIENT_INITIAL:
                    return  # RFC 9000 §14.1: drop small client Initials
                live = set(self._conns.values())
                pending = sum(1 for c in live
                              if not c.handshake_complete.is_set())
                if (pending >= self.MAX_PENDING_HANDSHAKES
                        or len(live) >= self.MAX_CONNECTIONS):
                    return  # unauthenticated flood: shed load, no state
                conn = QuicConnection(self, addr, is_client=False,
                                      original_dcid=dcid)
                self._conns[dcid] = conn
                self._conns[conn.local_cid] = conn
                threading.Thread(target=self._await_accept, args=(conn,),
                                 daemon=True).start()
        if conn is not None:
            conn.handle_datagram(datagram)

    def _await_accept(self, conn: QuicConnection) -> None:
        if conn.handshake_complete.wait(timeout=15.0):
            with self._lock:
                self._accept_q.append(conn)
                self._accept_cv.notify_all()
        else:
            conn.close("handshake timeout")

    def _tick_loop(self) -> None:
        while not self._stopped:
            time.sleep(0.05)
            now = time.monotonic()
            with self._lock:
                conns = list(set(self._conns.values()))
            for conn in conns:
                try:
                    conn.on_tick(now)
                except Exception as exc:
                    log.warning("tick failed: %s", exc)

    # -- public ------------------------------------------------------------

    def dial(self, ip: str, port: int, timeout: float = 10.0,
             expected_peer_id: bytes | None = None) -> QuicConnection:
        conn = QuicConnection(self, (ip, port), is_client=True)
        with self._lock:
            self._conns[conn.local_cid] = conn
        conn._flush()
        if not conn.handshake_complete.wait(timeout):
            conn.close("dial handshake timeout")
            raise QuicError(f"QUIC dial {ip}:{port}: handshake timeout "
                            f"({conn.close_reason})")
        if (expected_peer_id is not None
                and conn.remote_peer_id != expected_peer_id):
            conn.close("peer identity mismatch")
            raise QuicError(
                f"remote proved identity {conn.remote_peer_id.hex()[:8]}, "
                f"expected {expected_peer_id.hex()[:8]}")
        return conn

    def accept(self, timeout: float = 10.0) -> QuicConnection:
        deadline = time.monotonic() + timeout
        with self._accept_cv:
            while not self._accept_q:
                if self._stopped:
                    raise QuicError("endpoint stopped")
                if not self._accept_cv.wait(deadline - time.monotonic()):
                    raise QuicError("accept timeout")
            return self._accept_q.popleft()

    def stop(self) -> None:
        self._stopped = True
        with self._lock:
            conns = list(set(self._conns.values()))
        for conn in conns:
            conn.close("endpoint shutdown")
        try:
            self.sock.close()
        except OSError:
            pass
