"""Minimal TLS 1.3 (RFC 8446) handshake engine for QUIC, with libp2p certs.

QUIC replaces the TLS record layer with CRYPTO frames under its own
packet protection (RFC 9001 §4), so this engine never encrypts a byte:
it consumes plaintext handshake messages per encryption level, emits
plaintext handshake messages per level, and surfaces traffic SECRETS —
`quic.py` turns those into packet-protection keys.  That one design
fact is why a complete, mutually-authenticated TLS 1.3 fits in this
file: no records, no compat ChangeCipherSpec, no resumption/0-RTT, one
suite (TLS_AES_128_GCM_SHA256), one group (x25519), one signature
algorithm (ecdsa_secp256r1_sha256 for the certificate key).

libp2p identity (libp2p TLS spec, as rust-libp2p's `libp2p-tls` does for
the reference's QUIC transport): each side presents a self-signed X.509
certificate over a throwaway P-256 key carrying the critical extension
1.3.6.1.4.1.53594.1.1 = SignedKey{ identity-pubkey-protobuf, secp256k1
signature over "libp2p-tls-handshake:" || SPKI(cert key) }.  Mutual
authentication is mandatory: the server sends CertificateRequest and the
client responds with its own certificate chain.  Peer identity comes out
of the handshake as a libp2p peer id — the same id `noise.py` proves on
the TCP path, derived from the same secp256k1 node key.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac as hmac_mod
import os
import struct

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.x509.oid import NameOID

from .noise import (
    marshal_identity_pubkey,
    peer_id_from_pubkey,
    unmarshal_identity_pubkey,
)
from .quic import QuicError, hkdf_expand_label, hkdf_extract

LEVEL_INITIAL = 0
LEVEL_HANDSHAKE = 1
LEVEL_APP = 2

TLS_AES_128_GCM_SHA256 = 0x1301
GROUP_X25519 = 0x001D
SIG_ECDSA_P256_SHA256 = 0x0403
ALPN_LIBP2P = b"libp2p"

LIBP2P_CERT_OID = x509.ObjectIdentifier("1.3.6.1.4.1.53594.1.1")
LIBP2P_CERT_PREFIX = b"libp2p-tls-handshake:"

HT_CLIENT_HELLO = 1
HT_SERVER_HELLO = 2
HT_NEW_SESSION_TICKET = 4
HT_ENCRYPTED_EXTENSIONS = 8
HT_CERTIFICATE = 11
HT_CERTIFICATE_REQUEST = 13
HT_CERTIFICATE_VERIFY = 15
HT_FINISHED = 20
HT_KEY_UPDATE = 24

EXT_SERVER_NAME = 0x0000
EXT_SUPPORTED_GROUPS = 0x000A
EXT_SIGNATURE_ALGORITHMS = 0x000D
EXT_ALPN = 0x0010
EXT_SUPPORTED_VERSIONS = 0x002B
EXT_KEY_SHARE = 0x0033
EXT_QUIC_TRANSPORT_PARAMS = 0x0039

TLS13 = 0x0304


class TlsError(QuicError):
    """TLS failures subclass QuicError so the connection's per-packet
    error handling treats a failed handshake exactly like any other
    protocol violation: CONNECTION_CLOSE, teardown, crisp dial error."""


# ---------------------------------------------------------------------------
# vector helpers
# ---------------------------------------------------------------------------

def _v8(data: bytes) -> bytes:
    return bytes([len(data)]) + data


def _v16(data: bytes) -> bytes:
    return struct.pack(">H", len(data)) + data


def _v24(data: bytes) -> bytes:
    return len(data).to_bytes(3, "big") + data


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def u8(self) -> int:
        return self.bytes(1)[0]

    def u16(self) -> int:
        return struct.unpack(">H", self.bytes(2))[0]

    def u24(self) -> int:
        return int.from_bytes(self.bytes(3), "big")

    def bytes(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise TlsError("truncated")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def vec8(self) -> bytes:
        return self.bytes(self.u8())

    def vec16(self) -> bytes:
        return self.bytes(self.u16())

    def vec24(self) -> bytes:
        return self.bytes(self.u24())

    def done(self) -> bool:
        return self.pos >= len(self.data)


def _ext(etype: int, data: bytes) -> bytes:
    return struct.pack(">H", etype) + _v16(data)


def _parse_extensions(data: bytes) -> dict[int, bytes]:
    r = _Reader(data)
    out: dict[int, bytes] = {}
    while not r.done():
        etype = r.u16()
        out[etype] = r.vec16()
    return out


def _msg(htype: int, body: bytes) -> bytes:
    return bytes([htype]) + _v24(body)


# ---------------------------------------------------------------------------
# libp2p certificates
# ---------------------------------------------------------------------------

def _der_octet_string(data: bytes) -> bytes:
    return b"\x04" + _der_len(len(data)) + data


def _der_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _der_seq(inner: bytes) -> bytes:
    return b"\x30" + _der_len(len(inner)) + inner


def _der_read_tlv(data: bytes, pos: int) -> tuple[int, bytes, int]:
    tag = data[pos]
    ln = data[pos + 1]
    pos += 2
    if ln & 0x80:
        nb = ln & 0x7F
        ln = int.from_bytes(data[pos:pos + nb], "big")
        pos += nb
    return tag, data[pos:pos + ln], pos + ln


def make_libp2p_cert(
    identity_key: ec.EllipticCurvePrivateKey,
    not_before: datetime.datetime | None = None,
    not_after: datetime.datetime | None = None,
) -> tuple[bytes, ec.EllipticCurvePrivateKey]:
    """Self-signed P-256 certificate binding the secp256k1 libp2p identity.

    Returns (certificate DER, certificate private key).  ``not_before`` /
    ``not_after`` override the default window (now-1h .. now+3650d) —
    used by the clock-skew regression tests.
    """
    cert_key = ec.generate_private_key(ec.SECP256R1())
    spki = cert_key.public_key().public_bytes(
        serialization.Encoding.DER,
        serialization.PublicFormat.SubjectPublicKeyInfo,
    )
    identity_sig = identity_key.sign(
        LIBP2P_CERT_PREFIX + spki, ec.ECDSA(hashes.SHA256())
    )
    identity_pub = identity_key.public_key().public_bytes(
        serialization.Encoding.X962,
        serialization.PublicFormat.CompressedPoint,
    )
    signed_key = _der_seq(
        _der_octet_string(marshal_identity_pubkey(identity_pub))
        + _der_octet_string(identity_sig)
    )
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "lighthouse-tpu")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(cert_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(not_before or now - datetime.timedelta(hours=1))
        .not_valid_after(not_after or now + datetime.timedelta(days=3650))
        .add_extension(
            x509.UnrecognizedExtension(LIBP2P_CERT_OID, signed_key),
            critical=True,
        )
        .sign(cert_key, hashes.SHA256())
    )
    return cert.public_bytes(serialization.Encoding.DER), cert_key


# Clock-skew tolerance on the certificate validity window.  The libp2p TLS
# spec deliberately de-emphasizes X.509 validity (identity comes from the
# SignedKey extension, not a CA chain), so a strict `not_before <= now`
# check only manufactures handshake failures against peers with skewed
# clocks — spec-conformant implementations tolerate skew.
CERT_VALIDITY_SKEW = datetime.timedelta(hours=2)


def verify_libp2p_cert(cert_der: bytes) -> tuple[bytes, ec.EllipticCurvePublicKey]:
    """Validate the libp2p extension; returns (peer_id, cert public key).

    The cert public key is what CertificateVerify must be checked
    against; the peer id is the authenticated libp2p identity.

    Checks (libp2p TLS spec): the certificate's own self-signature (it is
    self-signed — a cert whose signature does not verify under its own
    public key is structurally invalid even though impersonation is
    independently blocked by CertificateVerify + the SignedKey identity
    signature), the skew-tolerant validity window, and the SignedKey
    extension's identity signature over the cert public key.
    """
    cert = x509.load_der_x509_certificate(cert_der)
    try:
        cert.public_key().verify(
            cert.signature,
            cert.tbs_certificate_bytes,
            ec.ECDSA(cert.signature_hash_algorithm),
        )
    except Exception:
        raise TlsError("certificate self-signature invalid") from None
    now = datetime.datetime.now(datetime.timezone.utc)
    if not (
        cert.not_valid_before_utc - CERT_VALIDITY_SKEW
        <= now
        <= cert.not_valid_after_utc + CERT_VALIDITY_SKEW
    ):
        raise TlsError("certificate outside validity window")
    try:
        ext = cert.extensions.get_extension_for_oid(LIBP2P_CERT_OID)
    except x509.ExtensionNotFound:
        raise TlsError("missing libp2p certificate extension") from None
    raw = ext.value.public_bytes() if hasattr(ext.value, "public_bytes") else ext.value.value
    tag, seq, _ = _der_read_tlv(raw, 0)
    if tag != 0x30:
        raise TlsError("libp2p extension: not a SEQUENCE")
    tag, pub_pb, nxt = _der_read_tlv(seq, 0)
    if tag != 0x04:
        raise TlsError("libp2p extension: bad publicKey")
    tag, identity_sig, _ = _der_read_tlv(seq, nxt)
    if tag != 0x04:
        raise TlsError("libp2p extension: bad signature")
    identity_pub_compressed = unmarshal_identity_pubkey(pub_pb)
    identity_pub = ec.EllipticCurvePublicKey.from_encoded_point(
        ec.SECP256K1(), identity_pub_compressed
    )
    spki = cert.public_key().public_bytes(
        serialization.Encoding.DER,
        serialization.PublicFormat.SubjectPublicKeyInfo,
    )
    try:
        identity_pub.verify(
            identity_sig,
            LIBP2P_CERT_PREFIX + spki,
            ec.ECDSA(hashes.SHA256()),
        )
    except Exception:
        raise TlsError("libp2p identity signature invalid") from None
    return peer_id_from_pubkey(identity_pub_compressed), cert.public_key()


# ---------------------------------------------------------------------------
# key schedule (RFC 8446 §7.1)
# ---------------------------------------------------------------------------

_ZEROS = b"\x00" * 32
_EMPTY_HASH = hashlib.sha256(b"").digest()


def _derive_secret(secret: bytes, label: str, transcript_hash: bytes) -> bytes:
    return hkdf_expand_label(secret, label, transcript_hash, 32)


def _finished_mac(traffic_secret: bytes, transcript_hash: bytes) -> bytes:
    fk = hkdf_expand_label(traffic_secret, "finished", b"", 32)
    return hmac_mod.new(fk, transcript_hash, hashlib.sha256).digest()


_CV_SERVER = b" " * 64 + b"TLS 1.3, server CertificateVerify" + b"\x00"
_CV_CLIENT = b" " * 64 + b"TLS 1.3, client CertificateVerify" + b"\x00"


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class TlsEngine:
    """One QUIC-TLS handshake, client or server.

    Drive with ``start()`` (client only) and ``on_data(level, bytes)``
    (reassembled CRYPTO stream data); collect plaintext output with
    ``take_output() -> [(level, bytes)...]``.  ``secrets`` fills in as
    epochs become available: ``{LEVEL_HANDSHAKE: (client, server),
    LEVEL_APP: (client, server)}``.  ``complete`` flips after Finished
    verifies in both directions; then ``peer_id``/``alpn``/
    ``peer_transport_params`` are authenticated facts.
    """

    def __init__(self, role: str, identity_key: ec.EllipticCurvePrivateKey,
                 transport_params: bytes, alpn: bytes = ALPN_LIBP2P,
                 cert: tuple[bytes, ec.EllipticCurvePrivateKey] | None = None):
        assert role in ("client", "server")
        self.role = role
        self.identity_key = identity_key
        self.transport_params = transport_params
        self.alpn = alpn

        # the certificate binds only the static identity key, so an
        # endpoint generates it once and reuses it for every handshake
        # (per-dial keygen+signing would also amplify Initial-flood DoS)
        if cert is not None:
            self.cert_der, self.cert_key = cert
        else:
            self.cert_der, self.cert_key = make_libp2p_cert(identity_key)
        self._eph = X25519PrivateKey.generate()
        self._transcript = hashlib.sha256()
        self._out: list[tuple[int, bytes]] = []
        self._buf: dict[int, bytearray] = {
            LEVEL_INITIAL: bytearray(),
            LEVEL_HANDSHAKE: bytearray(),
            LEVEL_APP: bytearray(),
        }

        self.secrets: dict[int, tuple[bytes, bytes]] = {}
        self.complete = False
        self.peer_id: bytes | None = None
        self.peer_transport_params: bytes | None = None
        self.negotiated_alpn: bytes | None = None

        self._hs_secret: bytes | None = None
        self._master: bytes | None = None
        self._client_hs: bytes | None = None
        self._server_hs: bytes | None = None
        self._peer_cert_pub: ec.EllipticCurvePublicKey | None = None
        self._server_fin_transcript: bytes | None = None
        self._client_random = os.urandom(32)
        # message sequencing: what we expect next from the peer
        if role == "client":
            self._expect = [HT_SERVER_HELLO, HT_ENCRYPTED_EXTENSIONS,
                            HT_CERTIFICATE_REQUEST, HT_CERTIFICATE,
                            HT_CERTIFICATE_VERIFY, HT_FINISHED]
        else:
            self._expect = [HT_CLIENT_HELLO, HT_CERTIFICATE,
                            HT_CERTIFICATE_VERIFY, HT_FINISHED]

    # -- helpers ----------------------------------------------------------

    def _send(self, level: int, htype: int, body: bytes) -> None:
        raw = _msg(htype, body)
        self._transcript.update(raw)
        self._out.append((level, raw))

    def _th(self) -> bytes:
        return self._transcript.copy().digest()

    def take_output(self) -> list[tuple[int, bytes]]:
        out, self._out = self._out, []
        return out

    # -- client start -----------------------------------------------------

    def start(self) -> None:
        if self.role != "client":
            return
        pub = self._eph.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        exts = b"".join([
            _ext(EXT_SUPPORTED_VERSIONS, _v8(struct.pack(">H", TLS13))),
            _ext(EXT_SUPPORTED_GROUPS,
                 _v16(struct.pack(">H", GROUP_X25519))),
            _ext(EXT_SIGNATURE_ALGORITHMS,
                 _v16(struct.pack(">H", SIG_ECDSA_P256_SHA256))),
            _ext(EXT_KEY_SHARE,
                 _v16(struct.pack(">H", GROUP_X25519) + _v16(pub))),
            _ext(EXT_ALPN, _v16(_v8(self.alpn))),
            _ext(EXT_QUIC_TRANSPORT_PARAMS, self.transport_params),
        ])
        body = (struct.pack(">H", 0x0303) + self._client_random
                + _v8(b"")  # legacy_session_id: empty under QUIC
                + _v16(struct.pack(">H", TLS_AES_128_GCM_SHA256))
                + _v8(b"\x00")  # null compression
                + _v16(exts))
        self._send(LEVEL_INITIAL, HT_CLIENT_HELLO, body)

    # -- inbound data -----------------------------------------------------

    def on_data(self, level: int, data: bytes) -> None:
        buf = self._buf[level]
        buf += data
        while len(buf) >= 4:
            htype = buf[0]
            blen = int.from_bytes(bytes(buf[1:4]), "big")
            if len(buf) < 4 + blen:
                return
            raw = bytes(buf[:4 + blen])
            del buf[:4 + blen]
            self._handle(level, htype, raw)

    def _handle(self, level: int, htype: int, raw: bytes) -> None:
        body = raw[4:]
        if htype in (HT_NEW_SESSION_TICKET,):
            return  # tolerated, ignored (no resumption)
        if htype == HT_KEY_UPDATE:
            raise TlsError("key_update not supported")
        if not self._expect or htype != self._expect[0]:
            raise TlsError(
                f"unexpected handshake message {htype} "
                f"(wanted {self._expect[:1]})")
        self._expect.pop(0)
        handler = {
            HT_CLIENT_HELLO: self._on_client_hello,
            HT_SERVER_HELLO: self._on_server_hello,
            HT_ENCRYPTED_EXTENSIONS: self._on_encrypted_extensions,
            HT_CERTIFICATE_REQUEST: self._on_certificate_request,
            HT_CERTIFICATE: self._on_certificate,
            HT_CERTIFICATE_VERIFY: self._on_certificate_verify,
            HT_FINISHED: self._on_finished,
        }[htype]
        handler(body, raw)

    # -- key schedule -----------------------------------------------------

    def _install_handshake(self, shared: bytes) -> None:
        early = hkdf_extract(_ZEROS, _ZEROS)
        derived = _derive_secret(early, "derived", _EMPTY_HASH)
        self._hs_secret = hkdf_extract(derived, shared)
        th = self._th()  # CH..SH
        self._client_hs = _derive_secret(self._hs_secret, "c hs traffic", th)
        self._server_hs = _derive_secret(self._hs_secret, "s hs traffic", th)
        self.secrets[LEVEL_HANDSHAKE] = (self._client_hs, self._server_hs)
        derived2 = _derive_secret(self._hs_secret, "derived", _EMPTY_HASH)
        self._master = hkdf_extract(derived2, _ZEROS)

    def _install_app(self, th_server_fin: bytes) -> None:
        c_ap = _derive_secret(self._master, "c ap traffic", th_server_fin)
        s_ap = _derive_secret(self._master, "s ap traffic", th_server_fin)
        self.secrets[LEVEL_APP] = (c_ap, s_ap)

    # -- server side ------------------------------------------------------

    def _on_client_hello(self, body: bytes, raw: bytes) -> None:
        self._transcript.update(raw)
        r = _Reader(body)
        if r.u16() != 0x0303:
            raise TlsError("bad legacy_version")
        r.bytes(32)  # client random
        session_id = r.vec8()
        suites = r.vec16()
        if struct.pack(">H", TLS_AES_128_GCM_SHA256) not in [
            suites[i:i + 2] for i in range(0, len(suites), 2)
        ]:
            raise TlsError("no common cipher suite")
        r.vec8()  # compression
        exts = _parse_extensions(r.vec16())
        sv = exts.get(EXT_SUPPORTED_VERSIONS, b"")
        versions = [sv[i:i + 2] for i in range(1, len(sv) - 1, 2)]
        if struct.pack(">H", TLS13) not in versions:
            raise TlsError("peer does not offer TLS 1.3")
        peer_share = None
        ks = _Reader(exts.get(EXT_KEY_SHARE, b""))
        for entry in [ks.vec16()] if exts.get(EXT_KEY_SHARE) else []:
            er = _Reader(entry)
            while not er.done():
                group = er.u16()
                share = er.vec16()
                if group == GROUP_X25519:
                    peer_share = share
        if peer_share is None:
            raise TlsError("no x25519 key share (HelloRetry unsupported)")
        alpn_ext = exts.get(EXT_ALPN)
        if alpn_ext is None:
            # RFC 9001 section 8.1: ALPN is mandatory over QUIC, and
            # libp2p-tls requires "libp2p" specifically
            raise TlsError("client omitted ALPN")
        ar = _Reader(alpn_ext)
        protos = _Reader(ar.vec16())
        offered = []
        while not protos.done():
            offered.append(protos.vec8())
        if self.alpn not in offered:
            raise TlsError("no common ALPN protocol")
        self.negotiated_alpn = self.alpn
        qtp = exts.get(EXT_QUIC_TRANSPORT_PARAMS)
        if qtp is None:
            raise TlsError("client omitted quic_transport_parameters")
        self.peer_transport_params = qtp

        # ServerHello
        my_pub = self._eph.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        sh_exts = b"".join([
            _ext(EXT_SUPPORTED_VERSIONS, struct.pack(">H", TLS13)),
            _ext(EXT_KEY_SHARE,
                 struct.pack(">H", GROUP_X25519) + _v16(my_pub)),
        ])
        sh = (struct.pack(">H", 0x0303) + os.urandom(32) + _v8(session_id)
              + struct.pack(">H", TLS_AES_128_GCM_SHA256) + b"\x00"
              + _v16(sh_exts))
        self._send(LEVEL_INITIAL, HT_SERVER_HELLO, sh)

        shared = self._eph.exchange(
            X25519PublicKey.from_public_bytes(peer_share))
        self._install_handshake(shared)

        # EncryptedExtensions
        ee_exts = b"".join([
            _ext(EXT_ALPN, _v16(_v8(self.alpn))),
            _ext(EXT_QUIC_TRANSPORT_PARAMS, self.transport_params),
        ])
        self._send(LEVEL_HANDSHAKE, HT_ENCRYPTED_EXTENSIONS, _v16(ee_exts))
        # CertificateRequest (mutual auth is mandatory in libp2p)
        cr_exts = _ext(EXT_SIGNATURE_ALGORITHMS,
                       _v16(struct.pack(">H", SIG_ECDSA_P256_SHA256)))
        self._send(LEVEL_HANDSHAKE, HT_CERTIFICATE_REQUEST,
                   _v8(b"") + _v16(cr_exts))
        self._send_certificate()
        self._send_certificate_verify(_CV_SERVER)
        # server Finished
        fin = _finished_mac(self._server_hs, self._th())
        self._send(LEVEL_HANDSHAKE, HT_FINISHED, fin)
        self._server_fin_transcript = self._th()  # CH..server Fin
        self._install_app(self._server_fin_transcript)

    # -- client side ------------------------------------------------------

    def _on_server_hello(self, body: bytes, raw: bytes) -> None:
        self._transcript.update(raw)
        r = _Reader(body)
        if r.u16() != 0x0303:
            raise TlsError("bad legacy_version")
        r.bytes(32)
        r.vec8()  # session id echo
        if r.u16() != TLS_AES_128_GCM_SHA256:
            raise TlsError("server picked unknown suite")
        if r.u8() != 0:
            raise TlsError("nonzero compression")
        exts = _parse_extensions(r.vec16())
        if exts.get(EXT_SUPPORTED_VERSIONS) != struct.pack(">H", TLS13):
            raise TlsError("server did not select TLS 1.3")
        ksr = _Reader(exts.get(EXT_KEY_SHARE, b""))
        if ksr.u16() != GROUP_X25519:
            raise TlsError("server key share not x25519")
        peer_share = ksr.vec16()
        shared = self._eph.exchange(
            X25519PublicKey.from_public_bytes(peer_share))
        self._install_handshake(shared)

    def _on_encrypted_extensions(self, body: bytes, raw: bytes) -> None:
        self._transcript.update(raw)
        exts = _parse_extensions(_Reader(body).vec16())
        alpn_ext = exts.get(EXT_ALPN)
        if alpn_ext is None:
            raise TlsError("server omitted ALPN")
        ar = _Reader(alpn_ext)
        lr = _Reader(ar.vec16())
        self.negotiated_alpn = lr.vec8()
        if self.negotiated_alpn != self.alpn:
            raise TlsError("server picked foreign ALPN")
        qtp = exts.get(EXT_QUIC_TRANSPORT_PARAMS)
        if qtp is None:
            raise TlsError("server omitted quic_transport_parameters")
        self.peer_transport_params = qtp

    def _on_certificate_request(self, body: bytes, raw: bytes) -> None:
        self._transcript.update(raw)
        # context must be echoed; we only ever see the empty context
        if _Reader(body).vec8() != b"":
            raise TlsError("nonempty certificate_request_context")

    # -- shared: certificates and finished --------------------------------

    def _send_certificate(self) -> None:
        entry = _v24(self.cert_der) + _v16(b"")
        self._send(LEVEL_HANDSHAKE, HT_CERTIFICATE, _v8(b"") + _v24(entry))

    def _send_certificate_verify(self, context: bytes) -> None:
        content = context + self._th()
        sig = self.cert_key.sign(content, ec.ECDSA(hashes.SHA256()))
        self._send(LEVEL_HANDSHAKE, HT_CERTIFICATE_VERIFY,
                   struct.pack(">H", SIG_ECDSA_P256_SHA256) + _v16(sig))

    def _on_certificate(self, body: bytes, raw: bytes) -> None:
        self._transcript.update(raw)
        r = _Reader(body)
        if r.vec8() != b"":
            raise TlsError("nonempty certificate context")
        entries = _Reader(r.vec24())
        cert_der = entries.vec24()
        entries.vec16()  # per-entry extensions
        self.peer_id, self._peer_cert_pub = verify_libp2p_cert(cert_der)

    def _on_certificate_verify(self, body: bytes, raw: bytes) -> None:
        # signature covers the transcript UP TO (not including) this message
        th = self._th()
        self._transcript.update(raw)
        r = _Reader(body)
        if r.u16() != SIG_ECDSA_P256_SHA256:
            raise TlsError("unsupported CertificateVerify algorithm")
        sig = r.vec16()
        context = _CV_SERVER if self.role == "client" else _CV_CLIENT
        try:
            self._peer_cert_pub.verify(
                sig, context + th, ec.ECDSA(hashes.SHA256()))
        except Exception:
            raise TlsError("CertificateVerify signature invalid") from None

    def _on_finished(self, body: bytes, raw: bytes) -> None:
        th = self._th()
        peer_hs = self._server_hs if self.role == "client" else self._client_hs
        expect = _finished_mac(peer_hs, th)
        if not hmac_mod.compare_digest(body, expect):
            raise TlsError("Finished verify_data mismatch")
        self._transcript.update(raw)
        if self.role == "client":
            # CH..server Fin fixes the application secrets
            self._server_fin_transcript = self._th()
            self._install_app(self._server_fin_transcript)
            self._send_certificate()
            self._send_certificate_verify(_CV_CLIENT)
            fin = _finished_mac(self._client_hs, self._th())
            self._send(LEVEL_HANDSHAKE, HT_FINISHED, fin)
            self.complete = True
        else:
            self.complete = True
