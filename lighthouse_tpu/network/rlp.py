"""Recursive Length Prefix (RLP) codec.

The serialization under ENRs and every discv5 message body (reference:
discv5/enr crates pulled in by `beacon_node/lighthouse_network`, e.g.
`src/discovery/enr.rs`).  Items are ``bytes`` or (nested) lists of items;
integers are encoded big-endian with no leading zeros per the Ethereum
convention (0 encodes as the empty byte string).
"""

from __future__ import annotations

Item = "bytes | int | list"


def encode_uint(n: int) -> bytes:
    if n == 0:
        return b""
    return n.to_bytes((n.bit_length() + 7) // 8, "big")


def decode_uint(b: bytes) -> int:
    return int.from_bytes(b, "big")


def _encode_length(length: int, base: int) -> bytes:
    if length < 56:
        return bytes([base + length])
    ln = encode_uint(length)
    return bytes([base + 55 + len(ln)]) + ln


def encode(item) -> bytes:
    if isinstance(item, int):
        item = encode_uint(item)
    if isinstance(item, (bytes, bytearray)):
        item = bytes(item)
        if len(item) == 1 and item[0] < 0x80:
            return item
        return _encode_length(len(item), 0x80) + item
    if isinstance(item, (list, tuple)):
        payload = b"".join(encode(x) for x in item)
        return _encode_length(len(payload), 0xC0) + payload
    raise TypeError(f"cannot RLP-encode {type(item)}")


def _decode_at(data: bytes, pos: int):
    """-> (item, next_pos).  Strings decode to bytes, lists to list."""
    if pos >= len(data):
        raise ValueError("RLP: truncated input")
    b0 = data[pos]
    if b0 < 0x80:
        return data[pos : pos + 1], pos + 1
    if b0 < 0xB8:  # short string
        n = b0 - 0x80
        end = pos + 1 + n
        s = data[pos + 1 : end]
        if len(s) != n:
            raise ValueError("RLP: truncated string")
        if n == 1 and s[0] < 0x80:
            raise ValueError("RLP: non-canonical single byte")
        return s, end
    if b0 < 0xC0:  # long string
        ll = b0 - 0xB7
        n = decode_uint(data[pos + 1 : pos + 1 + ll])
        if ll > 1 and data[pos + 1] == 0 or n < 56:
            raise ValueError("RLP: non-canonical length")
        end = pos + 1 + ll + n
        s = data[pos + 1 + ll : end]
        if len(s) != n:
            raise ValueError("RLP: truncated string")
        return s, end
    if b0 < 0xF8:  # short list
        n = b0 - 0xC0
        end = pos + 1 + n
        if end > len(data):
            raise ValueError("RLP: truncated list")
        return _decode_list(data, pos + 1, end), end
    ll = b0 - 0xF7
    n = decode_uint(data[pos + 1 : pos + 1 + ll])
    if ll > 1 and data[pos + 1] == 0 or n < 56:
        raise ValueError("RLP: non-canonical length")
    end = pos + 1 + ll + n
    if end > len(data):
        raise ValueError("RLP: truncated list")
    return _decode_list(data, pos + 1 + ll, end), end


def _decode_list(data: bytes, start: int, end: int) -> list:
    out, pos = [], start
    while pos < end:
        item, pos = _decode_at(data, pos)
        out.append(item)
    if pos != end:
        raise ValueError("RLP: list payload overrun")
    return out


def decode(data: bytes):
    item, pos = _decode_at(bytes(data), 0)
    if pos != len(data):
        raise ValueError("RLP: trailing bytes")
    return item
