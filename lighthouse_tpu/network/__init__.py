"""Networking layer — twin of beacon_node/lighthouse_network + network +
http_api + common/eth2 (gossip, req/resp, Beacon-API server/client)."""

from . import gossip, rpc, snappy, topics  # noqa: F401
from .api import BeaconApiClient, BeaconApiServer  # noqa: F401
