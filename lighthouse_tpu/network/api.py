"""Beacon-API HTTP server + typed client (stdlib only).

Twin of beacon_node/http_api (warp server, src/lib.rs:319 `serve`; 18,827
LoC there — the subset here covers the endpoints the implemented layers
serve) + common/eth2 (the typed client, src/lib.rs:1-5) + http_metrics (the
Prometheus scrape endpoint, mounted at /metrics).

JSON mapping follows the beacon-APIs conventions: uints as decimal strings,
roots/signatures as 0x-hex, containers as objects keyed by field name.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..consensus.ssz import (
    BOOLEAN,
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    SSZList,
    UintN,
    Vector,
    _ContainerField,
)
from ..utils import render as render_metrics

VERSION = "lighthouse-tpu/0.3.0"


# ---------------------------------------------------------------------------
# container <-> Beacon-API JSON
# ---------------------------------------------------------------------------


def to_json(type_or_cls, value):
    if isinstance(type_or_cls, type) and issubclass(type_or_cls, Container):
        return {
            f: to_json(t, getattr(value, f))
            for f, t in type_or_cls._fields.items()
        }
    if isinstance(type_or_cls, _ContainerField):
        return to_json(type_or_cls.cls, value)
    if isinstance(type_or_cls, UintN):
        return str(int(value))
    if isinstance(type_or_cls, type(BOOLEAN)):
        return bool(value)
    if isinstance(type_or_cls, (ByteVector, ByteList)):
        return "0x" + bytes(value).hex()
    if isinstance(type_or_cls, (Bitvector, Bitlist)):
        return "0x" + type_or_cls.serialize(value).hex()
    if isinstance(type_or_cls, (Vector, SSZList)):
        return [to_json(type_or_cls.elem, v) for v in value]
    raise TypeError(f"unmapped type {type_or_cls!r}")


def from_json(type_or_cls, data):
    if isinstance(type_or_cls, type) and issubclass(type_or_cls, Container):
        return type_or_cls(
            **{
                f: from_json(t, data[f])
                for f, t in type_or_cls._fields.items()
            }
        )
    if isinstance(type_or_cls, _ContainerField):
        return from_json(type_or_cls.cls, data)
    if isinstance(type_or_cls, UintN):
        return int(data)
    if isinstance(type_or_cls, type(BOOLEAN)):
        return bool(data)
    if isinstance(type_or_cls, (ByteVector, ByteList)):
        return bytes.fromhex(data[2:])
    if isinstance(type_or_cls, (Bitvector, Bitlist)):
        return type_or_cls.deserialize(bytes.fromhex(data[2:]))
    if isinstance(type_or_cls, (Vector, SSZList)):
        return [from_json(type_or_cls.elem, v) for v in data]
    raise TypeError(f"unmapped type {type_or_cls!r}")


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class BeaconApiServer:
    """Routes Beacon-API requests onto a BeaconChain (+ optional VC duties
    helpers).  `task_spawner.rs` in the reference pushes blocking work onto
    beacon_processor queues; here handlers run on the HTTP thread pool and
    heavy verification still flows through the chain's normal pipelines."""

    def __init__(self, chain, host: str = "127.0.0.1", port: int = 0,
                 node=None):
        self.chain = chain
        # optional BeaconNode back-reference: enables node/peers endpoints
        self.node = node
        # per-route hit counts (http_metrics analog; also lets the soak
        # tests assert the remote VC never touches the debug endpoints).
        # Numeric path segments (slots/epochs/ids) normalize to {n} so a
        # long soak doesn't grow one key per slot; the lock is for
        # ThreadingHTTPServer's concurrent handlers.
        self.request_counts: dict[str, int] = {}
        self._count_lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, payload, raw: bytes | None = None,
                      content_type: str = "application/json"):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.end_headers()
                if raw is not None:
                    self.wfile.write(raw)
                else:
                    self.wfile.write(json.dumps(payload).encode())

            def do_GET(self):
                try:
                    outer._get(self)
                except KeyError as e:
                    self._send(404, {"code": 404, "message": str(e)})
                except ValueError as e:  # malformed query/params = client error
                    self._send(400, {"code": 400, "message": str(e)})
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"code": 500, "message": repr(e)})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    outer._post(self, body)
                except ValueError as e:
                    self._send(400, {"code": 400, "message": str(e)})
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"code": 500, "message": repr(e)})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    # ----------------------------------------------------------- routing

    def _count(self, path: str) -> None:
        route = "/".join(
            "{n}" if seg.isdigit() or seg.startswith("0x") else seg
            for seg in path.split("/")
        )
        with self._count_lock:
            self.request_counts[route] = self.request_counts.get(route, 0) + 1

    def _get(self, h) -> None:
        path = h.path.split("?")[0].rstrip("/")
        self._count(path)
        chain = self.chain
        if path == "/eth/v1/node/health":
            h._send(200, {})
            return
        if path == "/eth/v1/node/version":
            h._send(200, {"data": {"version": VERSION}})
            return
        if path == "/eth/v1/node/syncing":
            head = chain.head_state()
            cur = (
                chain.slot_clock.current_slot()
                if chain.slot_clock
                else int(head.slot)
            )
            distance = max(0, cur - int(head.slot))
            h._send(
                200,
                {
                    "data": {
                        "head_slot": str(int(head.slot)),
                        "sync_distance": str(distance),
                        "is_syncing": distance > 1,
                        "is_optimistic": False,
                        "el_offline": True,
                    }
                },
            )
            return
        if path == "/eth/v1/beacon/genesis":
            st = chain.head_state()
            h._send(
                200,
                {
                    "data": {
                        "genesis_time": str(int(st.genesis_time)),
                        "genesis_validators_root": "0x"
                        + bytes(st.genesis_validators_root).hex(),
                        "genesis_fork_version": "0x"
                        + bytes(chain.spec.genesis_fork_version).hex(),
                    }
                },
            )
            return
        if path.startswith("/eth/v1/beacon/states/") and path.endswith("/root"):
            state = self._resolve_state(path.split("/")[5])
            h._send(200, {"data": {"root": "0x" + state.root().hex()}})
            return
        if path.startswith("/eth/v1/beacon/states/") and path.endswith(
            "/finality_checkpoints"
        ):
            state = self._resolve_state(path.split("/")[5])

            def cp(c):
                return {"epoch": str(int(c.epoch)), "root": "0x" + bytes(c.root).hex()}

            h._send(
                200,
                {
                    "data": {
                        "previous_justified": cp(state.previous_justified_checkpoint),
                        "current_justified": cp(state.current_justified_checkpoint),
                        "finalized": cp(state.finalized_checkpoint),
                    }
                },
            )
            return
        if path.startswith("/eth/v1/beacon/states/") and path.endswith(
            "/validators"
        ):
            state = self._resolve_state(path.split("/")[5])
            current = int(state.slot) // chain.preset.slots_per_epoch
            out = []
            for i, v in enumerate(state.validators):
                active = v.activation_epoch <= current < v.exit_epoch
                if v.slashed:
                    status = "active_slashed" if active else "exited_slashed"
                elif active:
                    status = "active_ongoing"
                elif v.activation_epoch > current:
                    status = "pending_queued"
                else:
                    status = "exited_unslashed"
                out.append(
                    {
                        "index": str(i),
                        "balance": str(int(state.balances[i])),
                        "status": status,
                        "validator": {
                            "pubkey": "0x" + bytes(v.pubkey).hex(),
                            "effective_balance": str(int(v.effective_balance)),
                            "slashed": bool(v.slashed),
                            "activation_epoch": str(int(v.activation_epoch)),
                            "exit_epoch": str(int(v.exit_epoch)),
                        },
                    }
                )
            h._send(200, {"data": out})
            return
        if path.startswith("/eth/v1/beacon/headers"):
            root = self._resolve_block_root(path.split("/")[-1])
            if root == chain.genesis_block_root:
                # the anchor is a header, not a stored SignedBeaconBlock
                state = chain.state_for_block(root)
                hdr = state.latest_block_header.copy()
                if bytes(hdr.state_root) == bytes(32):
                    hdr.state_root = state.root()
                h._send(
                    200,
                    {
                        "data": {
                            "root": "0x" + root.hex(),
                            "canonical": True,
                            "header": {
                                "message": {
                                    "slot": str(int(hdr.slot)),
                                    "proposer_index": str(
                                        int(hdr.proposer_index)
                                    ),
                                    "parent_root": "0x"
                                    + bytes(hdr.parent_root).hex(),
                                    "state_root": "0x"
                                    + bytes(hdr.state_root).hex(),
                                    "body_root": "0x"
                                    + bytes(hdr.body_root).hex(),
                                },
                                "signature": "0x" + "00" * 96,
                            },
                        }
                    },
                )
                return
            blk = chain.store.get_block(
                root, chain.types.SignedBeaconBlock_BY_FORK[chain.fork_name]
            )
            if blk is None:
                raise KeyError("block not found")
            msg = blk.message
            h._send(
                200,
                {
                    "data": {
                        "root": "0x" + root.hex(),
                        "canonical": True,
                        "header": {
                            "message": {
                                "slot": str(int(msg.slot)),
                                "proposer_index": str(int(msg.proposer_index)),
                                "parent_root": "0x" + bytes(msg.parent_root).hex(),
                                "state_root": "0x" + bytes(msg.state_root).hex(),
                                "body_root": "0x"
                                + type(msg)._fields["body"].hash_tree_root(msg.body).hex(),
                            },
                            "signature": "0x" + bytes(blk.signature).hex(),
                        },
                    }
                },
            )
            return
        if path.startswith("/eth/v2/beacon/blocks/"):
            root = self._resolve_block_root(path.split("/")[-1])
            blk = chain.store.get_block(
                root, chain.types.SignedBeaconBlock_BY_FORK[chain.fork_name]
            )
            if blk is None:
                raise KeyError("block not found")
            h._send(
                200,
                {
                    "version": chain.fork_name,
                    "data": to_json(type(blk), blk),
                },
            )
            return
        if path.startswith("/eth/v1/validator/duties/proposer/"):
            epoch = int(path.split("/")[-1])
            from ..consensus import committees as cm

            state = chain.head_state()
            duties = []
            preset = chain.preset
            for slot in range(
                max(epoch * preset.slots_per_epoch, int(state.slot), 1),
                (epoch + 1) * preset.slots_per_epoch,
            ):
                vi = cm.get_beacon_proposer_index(state, slot, preset)
                duties.append(
                    {
                        "pubkey": "0x" + bytes(state.validators[vi].pubkey).hex(),
                        "validator_index": str(vi),
                        "slot": str(slot),
                    }
                )
            h._send(
                200,
                {
                    "data": duties,
                    "dependent_root": "0x"
                    + self._dependent_root(state, epoch, attester=False).hex(),
                },
            )
            return
        if path.startswith("/eth/v1/validator/duties/attester/"):
            # GET variant (the reference serves POST with index filters;
            # the GET form returns all indices' duties for the epoch)
            from ..consensus import committees as cm

            epoch = int(path.split("/")[-1])
            state = chain.head_state()
            cache = chain.committee_cache(state, epoch)
            duties = []
            for slot, index, committee in cm.iter_epoch_committees(
                cache, epoch, chain.preset
            ):
                for pos, vi in enumerate(committee):
                    duties.append(
                        {
                            "pubkey": "0x"
                            + bytes(state.validators[int(vi)].pubkey).hex(),
                            "validator_index": str(int(vi)),
                            "committee_index": str(index),
                            "committee_length": str(len(committee)),
                            "validator_committee_index": str(pos),
                            "slot": str(slot),
                        }
                    )
            h._send(
                200,
                {
                    "data": duties,
                    "dependent_root": "0x"
                    + self._dependent_root(state, epoch, attester=True).hex(),
                },
            )
            return
        if path.startswith("/eth/v3/validator/blocks/"):
            # produce_block.rs over the wire: the VC supplies only the
            # randao reveal; the BN advances the head state, max-cover
            # packs the op pool, and returns the UNSIGNED block (v3 says
            # blinded-or-full; we always serve full + a zero consensus
            # value — no local bid comparison data at this endpoint).
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(h.path).query)
            slot = int(path.split("/")[-1])
            reveal = q.get("randao_reveal", [None])[0]
            if reveal is None:
                raise ValueError("randao_reveal is required")
            graffiti = bytes.fromhex(
                q.get("graffiti", ["0x"])[0].removeprefix("0x")
            )
            block, fork_now = chain.produce_unsigned_block(
                slot, bytes.fromhex(reveal.removeprefix("0x")), graffiti
            )
            h._send(
                200,
                {
                    "version": fork_now,
                    "execution_payload_blinded": False,
                    "execution_payload_value": "0",
                    "consensus_block_value": "0",
                    "data": to_json(type(block), block),
                },
            )
            return
        if path == "/eth/v1/validator/attestation_data":
            # the BN-side attestation template (the VC no longer needs
            # the state: validator/attestation_data in http_api/src/
            # lib.rs) — the chain owns the single shared derivation
            from urllib.parse import parse_qs, urlparse

            from ..consensus.containers import AttestationData

            q = parse_qs(urlparse(h.path).query)
            if "slot" not in q or "committee_index" not in q:
                raise ValueError("slot and committee_index are required")
            data = chain.attestation_data_for(
                int(q["slot"][0]), int(q["committee_index"][0])
            )
            h._send(200, {"data": to_json(AttestationData, data)})
            return
        if path == "/eth/v1/validator/aggregate_attestation":
            from urllib.parse import parse_qs, urlparse

            from ..consensus.containers import Attestation

            q = parse_qs(urlparse(h.path).query)
            if "attestation_data_root" not in q:
                raise ValueError("attestation_data_root is required")
            root = bytes.fromhex(
                q["attestation_data_root"][0].removeprefix("0x")
            )
            agg = chain.naive_pool.get_aggregate(root)
            if agg is None:
                raise KeyError("no aggregate known for that data root")
            h._send(200, {"data": to_json(Attestation, agg)})
            return
        if path == "/eth/v1/config/spec":
            import dataclasses

            spec = chain.spec
            flat = {}
            for f in dataclasses.fields(spec):
                v = getattr(spec, f.name)
                if isinstance(v, bytes):
                    flat[f.name.upper()] = "0x" + v.hex()
                elif isinstance(v, int):
                    flat[f.name.upper()] = str(v)
            for f in dataclasses.fields(spec.preset):
                v = getattr(spec.preset, f.name)
                if isinstance(v, int):
                    flat[f.name.upper()] = str(v)
            h._send(200, {"data": flat})
            return
        if path.startswith("/eth/v2/debug/beacon/states/"):
            state = self._resolve_state(path.split("/")[-1])
            h._send(200, None, raw=state.encode(),
                    content_type="application/octet-stream")
            return
        if path == "/metrics":
            h._send(200, None, raw=render_metrics().encode(),
                    content_type="text/plain; version=0.0.4")
            return
        if path == "/eth/v1/events":
            self._serve_sse(h)
            return
        if path == "/eth/v1/node/identity":
            node = self.node
            peer_id = (
                "0x" + node.host.peer_id.hex() if node is not None else "0x"
            )
            enr = ""
            if node is not None and node.discovery is not None:
                enr = node.discovery.enr.to_text()
            h._send(200, {"data": {
                "peer_id": peer_id,
                "enr": enr,
                "p2p_addresses": [],
                "discovery_addresses": [],
                "metadata": {"seq_number": "1", "attnets": "0x" + "00" * 8},
            }})
            return
        if path == "/eth/v1/node/peers":
            h._send(200, {"data": self._peers_json(),
                          "meta": {"count": len(self._peers_json())}})
            return
        if path == "/eth/v1/node/peer_count":
            peers = self._peers_json()
            connected = sum(1 for p in peers if p["state"] == "connected")
            h._send(200, {"data": {
                "disconnected": str(len(peers) - connected),
                "connecting": "0",
                "connected": str(connected),
                "disconnecting": "0",
            }})
            return
        if path == "/eth/v1/beacon/pool/voluntary_exits":
            from ..consensus.containers import SignedVoluntaryExit

            h._send(200, {"data": [
                to_json(SignedVoluntaryExit, e)
                for e in chain.op_pool.voluntary_exits.values()
            ]})
            return
        if path == "/eth/v1/beacon/pool/attester_slashings":
            from ..consensus.containers import AttesterSlashing

            h._send(200, {"data": [
                to_json(AttesterSlashing, s)
                for s in chain.op_pool.attester_slashings
            ]})
            return
        if path == "/eth/v1/beacon/pool/proposer_slashings":
            from ..consensus.containers import ProposerSlashing

            h._send(200, {"data": [
                to_json(ProposerSlashing, s)
                for s in chain.op_pool.proposer_slashings.values()
            ]})
            return
        if path.startswith("/eth/v1/beacon/blob_sidecars/"):
            root = self._resolve_block_root(path.split("/")[-1])
            sidecars = chain.store.get_blobs(
                root, chain.preset.max_blobs_per_block
            )
            h._send(200, {"data": [
                to_json(type(sc), sc) for sc in sidecars
            ]})
            return
        if path.startswith("/eth/v1/beacon/rewards/blocks/"):
            root = self._resolve_block_root(path.split("/")[-1])
            blk = chain.store.get_block(
                root, self._block_cls_for_root(root)
            )
            post = chain.state_for_block(root)
            if blk is None or post is None:
                raise KeyError("block/state not held")
            parent = chain.state_for_block(bytes(blk.message.parent_root))
            proposer = int(blk.message.proposer_index)
            # total = proposer balance delta across the block (covers
            # attestation-inclusion + sync-aggregate + slashing rewards;
            # the reference splits components — this reports the sum in
            # `total` with attestations as the dominant attribution)
            total = 0
            if parent is not None and proposer < len(parent.balances):
                total = int(post.balances[proposer]) - int(
                    parent.balances[proposer]
                )
            h._send(200, {"execution_optimistic": False, "finalized": False,
                          "data": {
                              "proposer_index": str(proposer),
                              "total": str(total),
                              "attestations": str(total),
                              "sync_aggregate": "0",
                              "proposer_slashings": "0",
                              "attester_slashings": "0",
                          }})
            return
        if path.startswith("/eth/v1/beacon/light_client/bootstrap/"):
            from ..consensus.light_client import build_bootstrap

            root = self._resolve_block_root(path.split("/")[-1])
            state = chain.state_for_block(root)
            blk = chain.store.get_block(root, self._block_cls_for_root(root))
            if state is None or blk is None:
                raise KeyError("bootstrap state not held")
            from ..consensus.containers import BeaconBlockHeader

            msg = blk.message
            header = BeaconBlockHeader(
                slot=int(msg.slot),
                proposer_index=int(msg.proposer_index),
                parent_root=bytes(msg.parent_root),
                state_root=bytes(msg.state_root),
                body_root=type(msg)._fields["body"].hash_tree_root(msg.body),
            )
            bootstrap = build_bootstrap(state, header, chain.types)
            h._send(200, {"version": chain.fork_name,
                          "data": to_json(type(bootstrap), bootstrap)})
            return
        raise KeyError(f"no route {path}")

    def _block_cls_for_root(self, root: bytes):
        """Decode a STORED block with the fork class of its own slot (not
        the chain's active fork) — a node that crossed a fork boundary
        must still decode pre-fork history (round-3 weak item 5)."""
        chain = self.chain
        blk_state = chain.state_for_block(root)
        if blk_state is not None:
            from ..consensus.state_processing.forks import state_fork_name

            return chain.types.SignedBeaconBlock_BY_FORK[
                state_fork_name(blk_state)
            ]
        return chain.types.SignedBeaconBlock_BY_FORK[chain.fork_name]

    def _peers_json(self) -> list:
        node = self.node
        if node is None:
            return []
        out = []
        pm = node.host.peer_manager
        connected = {pid.hex() for pid in node.host.connections}
        for pid_hex, rec in pm.peers.items():
            state = "connected" if pid_hex in connected else "disconnected"
            out.append({
                "peer_id": "0x" + pid_hex,
                "state": state,
                "direction": "outbound",
                "score": round(rec.score(), 3),
                "banned": rec.banned,
            })
        return out

    def _serve_sse(self, h) -> None:
        """`/eth/v1/events?topics=head,block,...` — the SSE stream
        (events.rs), one `event:`/`data:` pair per chain milestone."""
        from urllib.parse import parse_qs, urlparse

        q = parse_qs(urlparse(h.path).query)
        topics = set(
            t for raw in q.get("topics", []) for t in raw.split(",")
        ) or None
        sub = self.chain.events.subscribe()
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.end_headers()
        import queue as _q

        try:
            while True:
                try:
                    kind, data = sub.get(timeout=1.0)
                except _q.Empty:
                    h.wfile.write(b": keepalive\n\n")  # comment ping
                    h.wfile.flush()
                    continue
                if topics is not None and kind not in topics:
                    continue
                payload = (
                    f"event: {kind}\ndata: {json.dumps(data)}\n\n".encode()
                )
                h.wfile.write(payload)
                h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # client went away
        finally:
            self.chain.events.unsubscribe(sub)

    def _post(self, h, body: bytes) -> None:
        path = h.path.rstrip("/")
        self._count(path)
        chain = self.chain
        if path in ("/eth/v1/beacon/blocks", "/eth/v2/beacon/blocks"):
            ctype = h.headers.get("Content-Type", "application/json")
            cls = chain.types.SignedBeaconBlock_BY_FORK[chain.fork_name]
            if "octet-stream" in ctype:
                signed = cls.deserialize_value(body)
            else:
                signed = from_json(cls, json.loads(body))
            try:
                chain.process_block(signed)
            except Exception as e:
                raise ValueError(f"block rejected: {e}") from None
            h._send(200, {})
            return
        if path == "/eth/v1/beacon/pool/attestations":
            from ..consensus.containers import Attestation

            payload = json.loads(body)
            failures = []
            for i, item in enumerate(payload):
                att = from_json(Attestation, item)
                try:
                    # the pool endpoint receives UNAGGREGATED attestations
                    # from VCs (http_api/src/lib.rs attestation publish):
                    # single-bit ones ride the unaggregated ladder into
                    # the naive pool so the BN can serve them back via
                    # aggregate_attestation; merged ones take the
                    # aggregate pipeline
                    bits = [bool(b) for b in att.aggregation_bits]
                    if sum(bits) == 1:
                        chain.process_unaggregated_attestation(att)
                        if self.node is not None:
                            from ..network.topics import (
                                compute_subnet_for_attestation,
                            )

                            cache = chain.committee_cache(
                                chain.head_state(),
                                int(att.data.slot)
                                // chain.preset.slots_per_epoch,
                            )
                            subnet = compute_subnet_for_attestation(
                                chain.spec, int(att.data.slot),
                                int(att.data.index),
                                cache.committees_per_slot,
                            )
                            self.node.publish_attestation_single(subnet, att)
                    else:
                        chain.process_attestation(att)
                except Exception as e:  # collect per-index failures
                    failures.append({"index": i, "message": str(e)})
            if failures:
                h._send(400, {"code": 400, "message": "some attestations failed",
                              "failures": failures})
            else:
                h._send(200, {})
            return
        if path == "/eth/v1/beacon/pool/voluntary_exits":
            from ..consensus.containers import SignedVoluntaryExit
            from ..consensus.state_processing import signature_sets as sets_mod

            signed = from_json(SignedVoluntaryExit, json.loads(body))
            state = chain.head_state()
            s = sets_mod.exit_signature_set(
                state, chain.get_pubkey, signed, chain.spec
            )
            if not s.verify():
                raise ValueError("exit signature invalid")
            chain.op_pool.insert_voluntary_exit(signed)
            chain.events.emit("voluntary_exit", {
                "message": {
                    "epoch": str(int(signed.message.epoch)),
                    "validator_index": str(int(signed.message.validator_index)),
                },
            })
            h._send(200, {})
            return
        if path == "/eth/v1/beacon/pool/attester_slashings":
            from ..consensus.containers import AttesterSlashing

            slashing = from_json(AttesterSlashing, json.loads(body))
            chain.op_pool.insert_attester_slashing(slashing)
            h._send(200, {})
            return
        if path == "/eth/v1/beacon/pool/proposer_slashings":
            from ..consensus.containers import ProposerSlashing

            slashing = from_json(ProposerSlashing, json.loads(body))
            chain.op_pool.insert_proposer_slashing(slashing)
            h._send(200, {})
            return
        if path == "/eth/v1/beacon/pool/sync_committees":
            from ..beacon.sync_committee import subnets_for_validator

            payload = json.loads(body)
            state = chain.head_state()
            failures = []
            for i, item in enumerate(payload):
                msg = from_json(chain.types.SyncCommitteeMessage, item)
                subnets = subnets_for_validator(
                    state, int(msg.validator_index), chain.spec
                )
                try:
                    if not subnets:
                        raise ValueError("not in the sync committee")
                    chain.process_sync_committee_message(
                        msg, next(iter(subnets))
                    )
                except Exception as e:  # noqa: BLE001
                    failures.append({"index": i, "message": str(e)})
            if failures:
                h._send(400, {"code": 400, "message": "some messages failed",
                              "failures": failures})
            else:
                h._send(200, {})
            return
        if path == "/eth/v1/beacon/pool/bls_to_execution_changes":
            from ..consensus.containers import SignedBLSToExecutionChange
            from ..consensus.state_processing import signature_sets as sets_mod

            payload = json.loads(body)
            state = chain.head_state()
            for item in payload:
                signed = from_json(SignedBLSToExecutionChange, item)
                s = sets_mod.bls_execution_change_signature_set(
                    state, signed, chain.spec
                )
                if not s.verify():
                    raise ValueError("bls-to-execution-change signature invalid")
                chain.op_pool.bls_changes[
                    int(signed.message.validator_index)
                ] = signed
            h._send(200, {})
            return
        if path.startswith("/eth/v1/validator/liveness/"):
            # lighthouse's liveness endpoint (doppelganger_service.rs polls
            # it): a validator is live in an epoch if the chain saw any
            # participation flag for it in that epoch's participation list
            epoch = int(path.split("/")[-1])
            indices = [int(x) for x in json.loads(body)]
            state = chain.head_state()
            current = int(state.slot) // chain.preset.slots_per_epoch
            if epoch == current:
                participation = list(state.current_epoch_participation)
            elif epoch == current - 1:
                participation = list(state.previous_epoch_participation)
            else:
                participation = []
            out = []
            for i in indices:
                live = i < len(participation) and participation[i] != 0
                out.append({"index": str(i), "is_live": bool(live)})
            h._send(200, {"data": out})
            return
        if path.startswith("/eth/v1/validator/duties/attester/"):
            # POST variant — the reference's VC<->BN duties contract
            # (validator/duties/attester in http_api/src/lib.rs:319): the
            # VC sends its indices, the BN shuffles server-side.  This is
            # what lets the remote VC drop the O(state) debug fetch.
            from ..consensus import committees as cm

            epoch = int(path.split("/")[-1])
            want = {int(x) for x in json.loads(body)}
            state = chain.head_state()
            cache = chain.committee_cache(state, epoch)
            per_slot = cache.committees_per_slot
            duties = []
            for slot, index, committee in cm.iter_epoch_committees(
                cache, epoch, chain.preset
            ):
                for pos, vi in enumerate(committee):
                    if int(vi) not in want:
                        continue
                    duties.append(
                        {
                            "pubkey": "0x"
                            + bytes(state.validators[int(vi)].pubkey).hex(),
                            "validator_index": str(int(vi)),
                            "committee_index": str(index),
                            "committee_length": str(len(committee)),
                            "committees_at_slot": str(per_slot),
                            "validator_committee_index": str(pos),
                            "slot": str(slot),
                        }
                    )
            h._send(
                200,
                {
                    "data": duties,
                    "dependent_root": "0x"
                    + self._dependent_root(state, epoch, attester=True).hex(),
                    "execution_optimistic": False,
                },
            )
            return
        if path == "/eth/v1/validator/aggregate_and_proofs":
            # publish_aggregate_and_proofs (publish_blocks.rs sibling):
            # verify the envelope exactly like the gossip path (selection
            # proof + outer signature; the indexed attestation inside is
            # checked by process_attestation), import, then re-gossip.
            from ..consensus.containers import SignedAggregateAndProof
            from ..consensus.state_processing import signature_sets as sets_mod
            from ..crypto.bls import api as bls

            payload = json.loads(body)
            failures = []
            state = chain.head_state()
            for i, item in enumerate(payload):
                signed = from_json(SignedAggregateAndProof, item)
                try:
                    envelope = [
                        sets_mod.selection_proof_signature_set(
                            state, chain.get_pubkey,
                            int(signed.message.aggregator_index),
                            int(signed.message.aggregate.data.slot),
                            bytes(signed.message.selection_proof),
                            chain.preset,
                        ),
                        sets_mod.aggregate_and_proof_signature_set(
                            state, chain.get_pubkey, signed, chain.preset
                        ),
                    ]
                    if not bls.verify_signature_sets(envelope):
                        raise ValueError("aggregate envelope invalid")
                    chain.process_attestation(signed.message.aggregate)
                    if self.node is not None:
                        self.node.publish_aggregate(signed)
                except Exception as e:  # noqa: BLE001
                    failures.append({"index": i, "message": str(e)})
            if failures:
                h._send(400, {"code": 400,
                              "message": "some aggregates failed",
                              "failures": failures})
            else:
                h._send(200, {})
            return
        if path == "/eth/v1/validator/beacon_committee_subscriptions":
            # subscribe_to_subnets: route duty subscriptions into the
            # attestation-subnet service so the BN joins/aggregates on the
            # right subnets (validator/beacon_committee_subscriptions).
            payload = json.loads(body)
            if self.node is not None and payload:
                from ..validator.client import Duty

                # committees_at_slot feeds the subnet derivation and may
                # differ across items (epochs in one batch): group by it
                # rather than flattening to one global value
                by_count: dict[int, list] = {}
                for item in payload:
                    by_count.setdefault(
                        int(item["committees_at_slot"]), []
                    ).append(
                        Duty(
                            validator_index=int(item["validator_index"]),
                            slot=int(item["slot"]),
                            committee_index=int(item["committee_index"]),
                            committee_position=0,
                            committee_size=0,
                        )
                    )
                for per_slot, duties in by_count.items():
                    self.node.subscribe_committee_duties(duties, per_slot)
            h._send(200, {})
            return
        if path.startswith("/eth/v1/validator/duties/sync/"):
            from ..beacon.sync_committee import sync_committee_indices

            state = chain.head_state()
            want = {int(x) for x in json.loads(body)} if body else None
            indices = sync_committee_indices(state)
            duties = []
            for vi in sorted(set(indices)):
                if want is not None and vi not in want:
                    continue
                duties.append({
                    "pubkey": "0x" + bytes(state.validators[vi].pubkey).hex(),
                    "validator_index": str(vi),
                    "validator_sync_committee_indices": [
                        str(pos) for pos, holder in enumerate(indices)
                        if holder == vi
                    ],
                })
            h._send(200, {"data": duties, "execution_optimistic": False})
            return
        raise KeyError(f"no route {path}")

    # ----------------------------------------------------------- helpers

    def _dependent_root(self, state, epoch: int, attester: bool) -> bytes:
        """The shuffling-decision anchor duties depend on (duties_service
        .rs contract): the last block before epoch-1 (attester) / the
        epoch (proposer).  Stable across head changes WITHIN an epoch —
        a VC caching duties on it must not see churn every slot."""
        chain = self.chain
        spe = chain.preset.slots_per_epoch
        anchor = (epoch - (1 if attester else 0)) * spe - 1
        if anchor < 0 or int(state.slot) == 0:
            return chain.head_root
        if anchor >= int(state.slot):
            return chain.head_root
        if int(state.slot) - anchor > chain.preset.slots_per_historical_root:
            return chain.head_root
        return bytes(
            state.block_roots[anchor % chain.preset.slots_per_historical_root]
        )

    def _resolve_state(self, state_id: str):
        chain = self.chain
        if state_id == "head":
            return chain.head_state()
        if state_id in ("justified", "finalized"):
            cp = (
                chain.fork_choice.justified_checkpoint
                if state_id == "justified"
                else chain.fork_choice.finalized_checkpoint
            )
            st = chain.state_for_block(cp[1])
            if st is None:
                raise KeyError(f"{state_id} state not held")
            return st
        if state_id.startswith("0x"):
            # decode with the chain's ACTIVE fork class — the store's
            # default (base) would mis-deserialize post-altair states
            st = chain.store.get_state(
                bytes.fromhex(state_id[2:]),
                state_cls=chain.types.BeaconState_BY_FORK[chain.fork_name],
            )
            if st is None:
                raise KeyError("state not found")
            return st
        raise KeyError(f"unsupported state id {state_id}")

    def _resolve_block_root(self, block_id: str) -> bytes:
        if block_id == "head":
            return self.chain.head_root
        if block_id == "finalized":
            root = self.chain.fork_choice.finalized_checkpoint[1]
            if root == self.chain.genesis_block_root or root in self.chain._states:
                return root
            return root
        if block_id == "genesis":
            return self.chain.genesis_block_root
        if block_id.startswith("0x"):
            return bytes.fromhex(block_id[2:])
        if block_id.isdigit():
            # slot id: resolved through the head state's block_roots ring
            # (a skipped slot yields the last block at or before it, the
            # ring's semantics; consumers dedupe by root)
            slot = int(block_id)
            chain = self.chain
            state = chain.head_state()
            head_slot = int(state.slot)
            sphr = chain.preset.slots_per_historical_root
            if slot == head_slot:
                return chain.head_root
            if 0 <= slot < head_slot and head_slot - slot <= sphr:
                return bytes(state.block_roots[slot % sphr])
            raise KeyError(f"slot {block_id} outside the historical window")
        raise KeyError(f"unsupported block id {block_id}")

    # ----------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="beacon-api"
        )
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class BeaconApiClient:
    """Typed client (common/eth2's BeaconNodeHttpClient shape)."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base = base_url.rstrip("/")
        self.timeout = timeout

    def _get(self, path: str) -> dict:
        with urllib.request.urlopen(self.base + path, timeout=self.timeout) as r:
            return json.loads(r.read())

    def _post(self, path: str, payload, ssz: bytes | None = None) -> dict:
        if ssz is not None:
            req = urllib.request.Request(
                self.base + path, data=ssz,
                headers={"Content-Type": "application/octet-stream"},
            )
        else:
            req = urllib.request.Request(
                self.base + path, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read() or b"{}")

    def node_version(self) -> str:
        return self._get("/eth/v1/node/version")["data"]["version"]

    def node_syncing(self) -> dict:
        return self._get("/eth/v1/node/syncing")["data"]

    def genesis(self) -> dict:
        return self._get("/eth/v1/beacon/genesis")["data"]

    def state_root(self, state_id: str = "head") -> bytes:
        d = self._get(f"/eth/v1/beacon/states/{state_id}/root")
        return bytes.fromhex(d["data"]["root"][2:])

    def finality_checkpoints(self, state_id: str = "head") -> dict:
        return self._get(
            f"/eth/v1/beacon/states/{state_id}/finality_checkpoints"
        )["data"]

    def block_header(self, block_id: str = "head") -> dict:
        return self._get(f"/eth/v1/beacon/headers/{block_id}")["data"]

    def get_block_json(self, block_id: str = "head") -> dict:
        return self._get(f"/eth/v2/beacon/blocks/{block_id}")

    def get_state_ssz(self, state_id: str = "finalized") -> bytes:
        with urllib.request.urlopen(
            self.base + f"/eth/v2/debug/beacon/states/{state_id}",
            timeout=self.timeout,
        ) as r:
            return r.read()

    def validators(self, state_id: str = "head") -> list[dict]:
        return self._get(f"/eth/v1/beacon/states/{state_id}/validators")["data"]

    def attester_duties(self, epoch: int) -> list[dict]:
        return self._get(f"/eth/v1/validator/duties/attester/{epoch}")["data"]

    def proposer_duties(self, epoch: int) -> list[dict]:
        return self._get(f"/eth/v1/validator/duties/proposer/{epoch}")["data"]

    def attester_duties_post(self, epoch: int, indices: list[int]) -> dict:
        """POST duties contract (the production VC<->BN path): returns the
        full response so callers can key caches on dependent_root."""
        return self._post(
            f"/eth/v1/validator/duties/attester/{epoch}",
            [str(i) for i in indices],
        )

    def attestation_data(self, slot: int, committee_index: int) -> dict:
        return self._get(
            f"/eth/v1/validator/attestation_data?slot={slot}"
            f"&committee_index={committee_index}"
        )["data"]

    def aggregate_attestation(self, slot: int, data_root: bytes) -> dict:
        return self._get(
            f"/eth/v1/validator/aggregate_attestation?slot={slot}"
            f"&attestation_data_root=0x{data_root.hex()}"
        )["data"]

    def publish_aggregate_and_proofs(self, signed_aggregates) -> None:
        self._post(
            "/eth/v1/validator/aggregate_and_proofs",
            [to_json(type(s), s) for s in signed_aggregates],
        )

    def subscribe_beacon_committees(self, subscriptions: list[dict]) -> None:
        self._post(
            "/eth/v1/validator/beacon_committee_subscriptions", subscriptions
        )

    def produce_block_v3(
        self, slot: int, randao_reveal: bytes, graffiti: bytes = b""
    ) -> dict:
        """Full v3 production response: {version, data: unsigned block}."""
        return self._get(
            f"/eth/v3/validator/blocks/{slot}"
            f"?randao_reveal=0x{randao_reveal.hex()}"
            f"&graffiti=0x{graffiti.hex()}"
        )

    def spec(self) -> dict:
        return self._get("/eth/v1/config/spec")["data"]

    def publish_block_ssz(self, signed_block) -> None:
        self._post("/eth/v1/beacon/blocks", None, ssz=signed_block.encode())

    def publish_attestations(self, attestations) -> None:
        from ..consensus.containers import Attestation

        self._post(
            "/eth/v1/beacon/pool/attestations",
            [to_json(Attestation, a) for a in attestations],
        )

    def metrics(self) -> str:
        with urllib.request.urlopen(
            self.base + "/metrics", timeout=self.timeout
        ) as r:
            return r.read().decode()

    # --- round-4 breadth --------------------------------------------------

    def node_peers(self) -> list[dict]:
        return self._get("/eth/v1/node/peers")["data"]

    def node_identity(self) -> dict:
        return self._get("/eth/v1/node/identity")["data"]

    def pool_voluntary_exits(self) -> list[dict]:
        return self._get("/eth/v1/beacon/pool/voluntary_exits")["data"]

    def submit_voluntary_exit(self, signed_exit) -> None:
        from ..consensus.containers import SignedVoluntaryExit

        self._post(
            "/eth/v1/beacon/pool/voluntary_exits",
            to_json(SignedVoluntaryExit, signed_exit),
        )

    def submit_sync_messages(self, messages, msg_cls) -> None:
        self._post(
            "/eth/v1/beacon/pool/sync_committees",
            [to_json(msg_cls, m) for m in messages],
        )

    def sync_duties(self, epoch: int, indices: list[int]) -> list[dict]:
        return self._post(
            f"/eth/v1/validator/duties/sync/{epoch}", [str(i) for i in indices]
        )["data"]

    def blob_sidecars(self, block_id: str = "head") -> list[dict]:
        return self._get(f"/eth/v1/beacon/blob_sidecars/{block_id}")["data"]

    def block_rewards(self, block_id: str = "head") -> dict:
        return self._get(f"/eth/v1/beacon/rewards/blocks/{block_id}")["data"]

    def light_client_bootstrap(self, block_root: bytes) -> dict:
        return self._get(
            f"/eth/v1/beacon/light_client/bootstrap/0x{block_root.hex()}"
        )

    def validator_liveness(self, epoch: int, indices: list[int]) -> list[dict]:
        return self._post(
            f"/eth/v1/validator/liveness/{epoch}", [str(i) for i in indices]
        )["data"]

    def stream_events(self, topics: list[str] | None = None,
                      timeout: float | None = None):
        """Generator over `/eth/v1/events` SSE: yields (event, data) —
        the VC's push-based head-following mode (events.rs consumer)."""
        q = "?topics=" + ",".join(topics) if topics else ""
        req = urllib.request.Request(self.base + "/eth/v1/events" + q)
        with urllib.request.urlopen(
            req, timeout=timeout or self.timeout
        ) as r:
            event = None
            while True:
                line = r.readline()
                if not line:
                    return
                line = line.decode().rstrip("\n")
                if line.startswith("event: "):
                    event = line[len("event: "):]
                elif line.startswith("data: ") and event is not None:
                    yield event, json.loads(line[len("data: "):])
                    event = None
