"""Node Discovery Protocol v5 (discv5 v5.1) over UDP.

Peer discovery for the beacon node and the standalone boot node —
the role the `discv5` crate plays for the reference
(`beacon_node/lighthouse_network/src/discovery/mod.rs:3`,
`boot_node/src/server.rs`).  Implements the wire protocol from the
devp2p discv5-wire spec:

* packet masking: AES-128-CTR keyed by the destination node-id prefix,
* three packet flavors — ordinary message, WHOAREYOU, handshake,
* session keys from an ECDH(secp256k1) + HKDF-SHA256 handshake bound to
  the WHOAREYOU challenge, messages sealed with AES-128-GCM,
* PING/PONG/FINDNODE/NODES/TALKREQ/TALKRESP message bodies (RLP),
* a 256-bucket XOR routing table and iterative lookups
  (`discovery/mod.rs` find-node queries, subnet predicates applied by
  the caller), and
* `BootNode` — the answer-only server of `boot_node/src/server.rs`.

Host-side networking only; nothing here touches the device.
"""

from __future__ import annotations

import secrets
import selectors
import socket
import threading
import time
from dataclasses import dataclass, field

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec, utils as asn1_utils
from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
from cryptography.hazmat.primitives.ciphers.aead import AESGCM
from cryptography.hazmat.primitives.kdf.hkdf import HKDF

from ..utils.logging import get_logger
from . import rlp
from .enr import Enr, _raw64_to_der, _sig_to_raw64, build_enr

log = get_logger("discv5")

PROTOCOL_ID = b"discv5"
VERSION = b"\x00\x01"
FLAG_MESSAGE = 0
FLAG_WHOAREYOU = 1
FLAG_HANDSHAKE = 2

ID_SIGNATURE_TEXT = b"discovery v5 identity proof"
KDF_INFO_TEXT = b"discovery v5 key agreement"

MSG_PING = 0x01
MSG_PONG = 0x02
MSG_FINDNODE = 0x03
MSG_NODES = 0x04
MSG_TALKREQ = 0x05
MSG_TALKRESP = 0x06

BUCKET_SIZE = 16  # spec k
LOOKUP_ALPHA = 3
REQUEST_TIMEOUT = 1.0
MAX_NODES_PER_MSG = 4  # ENRs per NODES response (fits one UDP datagram)

# secp256k1 curve params for the compressed-point ECDH the spec requires
_P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F


def _pt_decompress(comp: bytes) -> tuple[int, int]:
    x = int.from_bytes(comp[1:], "big")
    y2 = (pow(x, 3, _P) + 7) % _P
    y = pow(y2, (_P + 1) // 4, _P)
    if (y & 1) != (comp[0] & 1):
        y = _P - y
    return x, y


def _pt_mul(k: int, pt: tuple[int, int]) -> tuple[int, int]:
    """Affine double-and-add (handshake-rate only, not a hot path)."""
    rx, ry, present = 0, 0, False
    ax, ay = pt
    while k:
        if k & 1:
            if not present:
                rx, ry, present = ax, ay, True
            elif rx == ax:
                if (ry + ay) % _P == 0:
                    present = False
                else:
                    lam = (3 * ax * ax) * pow(2 * ay, -1, _P) % _P
                    nx = (lam * lam - 2 * ax) % _P
                    rx, ry = nx, (lam * (ax - nx) - ay) % _P
            else:
                lam = (ay - ry) * pow(ax - rx, -1, _P) % _P
                nx = (lam * lam - rx - ax) % _P
                rx, ry = nx, (lam * (rx - nx) - ry) % _P
        # double the addend
        lam = (3 * ax * ax) * pow(2 * ay, -1, _P) % _P
        nx = (lam * lam - 2 * ax) % _P
        ax, ay = nx, (lam * (ax - nx) - ay) % _P
        k >>= 1
    if not present:
        raise ValueError("ECDH with zero scalar")
    return rx, ry


def _ecdh_compressed(priv: ec.EllipticCurvePrivateKey, pub_comp: bytes) -> bytes:
    """discv5 ecdh(): the COMPRESSED shared point (33 bytes), not just x."""
    k = priv.private_numbers().private_value
    x, y = _pt_mul(k, _pt_decompress(pub_comp))
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def _compressed_pub(key: ec.EllipticCurvePrivateKey) -> bytes:
    return key.public_key().public_bytes(
        serialization.Encoding.X962, serialization.PublicFormat.CompressedPoint
    )


def log2_distance(a: bytes, b: bytes) -> int:
    """XOR log-distance in [0, 256]; 0 iff a == b."""
    d = int.from_bytes(a, "big") ^ int.from_bytes(b, "big")
    return d.bit_length()


# ---------------------------------------------------------------------------
# Packet codec
# ---------------------------------------------------------------------------


def _ctr_mask(dest_id: bytes, iv: bytes, data: bytes) -> bytes:
    c = Cipher(algorithms.AES(dest_id[:16]), modes.CTR(iv)).encryptor()
    return c.update(data) + c.finalize()


def _header(flag: int, nonce: bytes, authdata: bytes) -> bytes:
    return (
        PROTOCOL_ID
        + VERSION
        + bytes([flag])
        + nonce
        + len(authdata).to_bytes(2, "big")
        + authdata
    )


def encode_packet(
    dest_id: bytes, flag: int, nonce: bytes, authdata: bytes, message_ct: bytes
) -> bytes:
    iv = secrets.token_bytes(16)
    header = _header(flag, nonce, authdata)
    return iv + _ctr_mask(dest_id, iv, header) + message_ct


def decode_packet(local_id: bytes, datagram: bytes):
    """-> (flag, nonce, authdata, header_bytes, masking_iv, message_ct)."""
    if len(datagram) < 16 + 23:
        raise ValueError("short packet")
    iv, rest = datagram[:16], datagram[16:]
    # unmask the static header first to learn authdata-size
    static = _ctr_mask(local_id, iv, rest[:23])
    if static[:6] != PROTOCOL_ID or static[6:8] != VERSION:
        raise ValueError("bad protocol id")
    flag = static[8]
    nonce = static[9:21]
    authdata_size = int.from_bytes(static[21:23], "big")
    full = _ctr_mask(local_id, iv, rest[: 23 + authdata_size])
    if len(full) < 23 + authdata_size:
        raise ValueError("truncated authdata")
    authdata = full[23:]
    message_ct = rest[23 + authdata_size :]
    return flag, nonce, authdata, full, iv, message_ct


def derive_keys(
    secret: bytes, challenge_data: bytes, initiator_id: bytes, recipient_id: bytes
) -> tuple[bytes, bytes]:
    """HKDF-SHA256 -> (initiator_key, recipient_key), 16 bytes each."""
    okm = HKDF(
        algorithm=hashes.SHA256(),
        length=32,
        salt=challenge_data,
        info=KDF_INFO_TEXT + initiator_id + recipient_id,
    ).derive(secret)
    return okm[:16], okm[16:]


def id_sign(
    key: ec.EllipticCurvePrivateKey,
    challenge_data: bytes,
    eph_pubkey: bytes,
    dest_id: bytes,
) -> bytes:
    digest = hashes.Hash(hashes.SHA256())
    digest.update(ID_SIGNATURE_TEXT + challenge_data + eph_pubkey + dest_id)
    der = key.sign(digest.finalize(), ec.ECDSA(asn1_utils.Prehashed(hashes.SHA256())))
    return _sig_to_raw64(der)


def id_verify(
    static_pubkey: bytes,
    sig: bytes,
    challenge_data: bytes,
    eph_pubkey: bytes,
    dest_id: bytes,
) -> bool:
    try:
        pub = ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256K1(), static_pubkey
        )
        der = _raw64_to_der(sig)
        digest = hashes.Hash(hashes.SHA256())
        digest.update(ID_SIGNATURE_TEXT + challenge_data + eph_pubkey + dest_id)
        pub.verify(
            der, digest.finalize(), ec.ECDSA(asn1_utils.Prehashed(hashes.SHA256()))
        )
        return True
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Messages
# ---------------------------------------------------------------------------


def encode_message(msg_type: int, fields: list) -> bytes:
    return bytes([msg_type]) + rlp.encode(fields)


def decode_message(data: bytes) -> tuple[int, list]:
    if not data:
        raise ValueError("empty message")
    body = rlp.decode(data[1:])
    if not isinstance(body, list):
        raise ValueError("message body not a list")
    return data[0], body


def _ip_bytes(ip: str) -> bytes:
    return bytes(int(p) for p in ip.split("."))


def ping(req_id: bytes, enr_seq: int) -> bytes:
    return encode_message(MSG_PING, [req_id, enr_seq])


def pong(req_id: bytes, enr_seq: int, ip: str, port: int) -> bytes:
    return encode_message(MSG_PONG, [req_id, enr_seq, _ip_bytes(ip), port])


def findnode(req_id: bytes, distances: list[int]) -> bytes:
    return encode_message(MSG_FINDNODE, [req_id, [d for d in distances]])


def nodes(req_id: bytes, total: int, enrs: list[Enr]) -> bytes:
    # each record embeds as its RLP *list* structure, not as a byte blob
    return encode_message(
        MSG_NODES, [req_id, total, [rlp.decode(e.to_rlp()) for e in enrs]]
    )


# ---------------------------------------------------------------------------
# Routing table
# ---------------------------------------------------------------------------


class KBuckets:
    """256 XOR-distance buckets of size k=16, LRU within a bucket."""

    def __init__(self, local_id: bytes):
        self.local_id = local_id
        self.buckets: list[list[Enr]] = [[] for _ in range(257)]
        self.lock = threading.Lock()

    def insert(self, enr: Enr) -> bool:
        nid = enr.node_id
        d = log2_distance(self.local_id, nid)
        if d == 0:
            return False
        with self.lock:
            bucket = self.buckets[d]
            for i, existing in enumerate(bucket):
                if existing.node_id == nid:
                    if enr.seq >= existing.seq:
                        bucket.pop(i)
                        bucket.append(enr)
                    return True
            if len(bucket) >= BUCKET_SIZE:
                bucket.pop(0)  # evict least-recently seen
            bucket.append(enr)
            return True

    def at_distance(self, d: int, limit: int = BUCKET_SIZE) -> list[Enr]:
        if not 0 <= d <= 256:
            return []
        with self.lock:
            return list(self.buckets[d][-limit:]) if d else []

    def closest(self, target_id: bytes, limit: int = BUCKET_SIZE) -> list[Enr]:
        with self.lock:
            allnodes = [e for b in self.buckets for e in b]
        allnodes.sort(key=lambda e: log2_distance(target_id, e.node_id))
        return allnodes[:limit]

    def __len__(self):
        with self.lock:
            return sum(len(b) for b in self.buckets)


# ---------------------------------------------------------------------------
# Sessions
# ---------------------------------------------------------------------------


@dataclass
class Session:
    send_key: bytes
    recv_key: bytes
    created: float = field(default_factory=time.monotonic)


@dataclass
class _Challenge:
    """Outstanding WHOAREYOU we issued (keyed by peer addr)."""

    challenge_data: bytes
    nonce: bytes  # the nonce of the packet that triggered it
    created: float = field(default_factory=time.monotonic)


@dataclass
class _PendingSend:
    """Message stashed until the handshake completes."""

    msg_plain: bytes
    created: float = field(default_factory=time.monotonic)


# ---------------------------------------------------------------------------
# Service
# ---------------------------------------------------------------------------


class Discv5Service:
    """A full discv5 node: socket loop, sessions, routing table, lookups.

    Mirrors the role of lighthouse_network's Discovery behaviour
    (`src/discovery/mod.rs`): maintain a table of ENRs, answer
    PING/FINDNODE, and run iterative lookups to harvest peers.  The
    caller filters harvested ENRs (e.g. by eth2 fork digest / attnets —
    `subnet_predicate.rs`).
    """

    def __init__(
        self,
        key: ec.EllipticCurvePrivateKey | None = None,
        ip: str = "127.0.0.1",
        port: int = 0,
        enr_extra: dict | None = None,
    ):
        self.key = key or ec.generate_private_key(ec.SECP256K1())
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((ip, port))
        self.port = self.sock.getsockname()[1]
        self.enr = build_enr(self.key, seq=1, ip4=ip, udp=self.port, extra=enr_extra)
        self.node_id = self.enr.node_id
        self.table = KBuckets(self.node_id)
        self.sessions: dict[bytes, Session] = {}
        self.known_enrs: dict[bytes, Enr] = {}  # node-id -> freshest record
        self.addr_of: dict[bytes, tuple[str, int]] = {}
        # nonces of recently-sent message packets per peer: a WHOAREYOU
        # must echo one of them, else an off-path attacker could forge
        # session resets from arbitrary addresses (ADVICE r3; spec 7.2)
        self._sent_nonces: dict[bytes, list[bytes]] = {}
        self._challenges: dict[tuple[str, int], _Challenge] = {}
        self._pending: dict[bytes, list[_PendingSend]] = {}
        self._requests: dict[bytes, dict] = {}  # req-id -> waiter state
        self._lock = threading.Lock()
        self._running = False
        self._thread: threading.Thread | None = None
        self.talk_handlers: dict[bytes, callable] = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._running = True
        self._thread = threading.Thread(
            target=self._recv_loop, name=f"discv5-{self.port}", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._running = False
        try:
            # unblock the selector with a self-send
            self.sock.sendto(b"", ("127.0.0.1", self.port))
        except OSError:
            pass
        if self._thread:
            self._thread.join(timeout=2.0)
        self.sock.close()

    # -- low-level send ----------------------------------------------------

    def _seal_and_send(self, dest: Enr, msg_plain: bytes):
        """Send under an existing session, or kick off a handshake."""
        nid = dest.node_id
        addr = dest.udp_endpoint() or self.addr_of.get(nid)
        if addr is None:
            return
        self.addr_of[nid] = addr
        sess = self.sessions.get(nid)
        nonce = secrets.token_bytes(12)
        self._record_sent_nonce(nid, nonce)
        if sess is not None:
            authdata = self.node_id
            header = _header(FLAG_MESSAGE, nonce, authdata)
            iv = secrets.token_bytes(16)
            ct = AESGCM(sess.send_key).encrypt(nonce, msg_plain, iv + header)
            self.sock.sendto(iv + _ctr_mask(nid, iv, header) + ct, addr)
            return
        # No session: send a random-content message packet to elicit
        # WHOAREYOU (spec: the initiator cannot encrypt yet), park the real
        # message for the handshake completion.
        with self._lock:
            self._pending.setdefault(nid, []).append(_PendingSend(msg_plain))
        authdata = self.node_id
        self.sock.sendto(
            encode_packet(nid, FLAG_MESSAGE, nonce, authdata, secrets.token_bytes(20)),
            addr,
        )

    # -- receive path ------------------------------------------------------

    def _recv_loop(self):
        sel = selectors.DefaultSelector()
        sel.register(self.sock, selectors.EVENT_READ)
        while self._running:
            if not sel.select(timeout=0.2):
                continue
            try:
                datagram, addr = self.sock.recvfrom(2048)
            except OSError:
                break
            if not datagram:
                continue
            try:
                self._handle_datagram(datagram, addr)
            except Exception as exc:  # noqa: BLE001 — drop malformed traffic
                log.debug("discv5 drop from %s: %s", addr, exc)
        sel.close()

    def _handle_datagram(self, datagram: bytes, addr):
        flag, nonce, authdata, header, iv, message_ct = decode_packet(
            self.node_id, datagram
        )
        if flag == FLAG_WHOAREYOU:
            self._on_whoareyou(nonce, authdata, header, iv, addr)
        elif flag == FLAG_MESSAGE:
            self._on_message(nonce, authdata, header, iv, message_ct, addr)
        elif flag == FLAG_HANDSHAKE:
            self._on_handshake(nonce, authdata, header, iv, message_ct, addr)

    def _on_message(self, nonce, authdata, header, iv, message_ct, addr):
        if len(authdata) != 32:
            raise ValueError("bad ordinary authdata")
        src_id = authdata
        sess = self.sessions.get(src_id)
        if sess is not None:
            try:
                plain = AESGCM(sess.recv_key).decrypt(nonce, message_ct, iv + header)
            except Exception:
                del self.sessions[src_id]  # stale keys: fall through
                plain = None
            if plain is not None:
                self.addr_of[src_id] = addr
                self._dispatch(src_id, addr, plain)
                return
        # Unreadable: challenge the sender (spec: respond WHOAREYOU).
        known = self.known_enrs.get(src_id)
        id_nonce = secrets.token_bytes(16)
        enr_seq = known.seq if known else 0
        authdata_w = id_nonce + enr_seq.to_bytes(8, "big")
        iv2 = secrets.token_bytes(16)
        header_w = _header(FLAG_WHOAREYOU, nonce, authdata_w)
        self._challenges[addr] = _Challenge(iv2 + header_w, nonce)
        self.sock.sendto(iv2 + _ctr_mask(src_id, iv2, header_w), addr)

    def _record_sent_nonce(self, nid: bytes, nonce: bytes) -> None:
        lst = self._sent_nonces.setdefault(nid, [])
        lst.append(nonce)
        if len(lst) > 32:
            del lst[: len(lst) - 32]

    def _on_whoareyou(self, nonce, authdata, header, iv, addr):
        if len(authdata) != 24:
            raise ValueError("bad WHOAREYOU authdata")
        enr_seq = int.from_bytes(authdata[16:], "big")
        # find who we were talking to at this address
        nid = next((n for n, a in self.addr_of.items() if a == addr), None)
        if nid is None:
            return
        if nonce not in self._sent_nonces.get(nid, []):
            # the echoed nonce must belong to a packet WE actually sent;
            # anything else is a forgeable session-reset attempt — drop
            return
        dest = self.known_enrs.get(nid)
        if dest is None:
            return
        challenge_data = iv + header
        eph = ec.generate_private_key(ec.SECP256K1())
        eph_pub = _compressed_pub(eph)
        secret = _ecdh_compressed(eph, dest.pubkey)
        send_key, recv_key = derive_keys(secret, challenge_data, self.node_id, nid)
        self.sessions[nid] = Session(send_key, recv_key)
        sig = id_sign(self.key, challenge_data, eph_pub, nid)
        record = b"" if enr_seq >= self.enr.seq else self.enr.to_rlp()
        authdata_h = (
            self.node_id + bytes([len(sig)]) + bytes([len(eph_pub)])
            + sig + eph_pub + record
        )
        with self._lock:
            queued = self._pending.pop(nid, [])
        if not queued:
            queued = [_PendingSend(ping(secrets.token_bytes(8), self.enr.seq))]
        first, rest = queued[0], queued[1:]
        new_nonce = secrets.token_bytes(12)
        self._record_sent_nonce(nid, new_nonce)
        header_h = _header(FLAG_HANDSHAKE, new_nonce, authdata_h)
        iv2 = secrets.token_bytes(16)
        ct = AESGCM(send_key).encrypt(new_nonce, first.msg_plain, iv2 + header_h)
        self.sock.sendto(iv2 + _ctr_mask(nid, iv2, header_h) + ct, addr)
        for p in rest:  # session is up now; send the remainder normally
            if (e := self.known_enrs.get(nid)) is not None:
                self._seal_and_send(e, p.msg_plain)

    def _on_handshake(self, nonce, authdata, header, iv, message_ct, addr):
        if len(authdata) < 34:
            raise ValueError("short handshake authdata")
        src_id = authdata[:32]
        sig_size, eph_size = authdata[32], authdata[33]
        sig = authdata[34 : 34 + sig_size]
        eph_pub = authdata[34 + sig_size : 34 + sig_size + eph_size]
        record_rlp = authdata[34 + sig_size + eph_size :]
        chal = self._challenges.pop(addr, None)
        if chal is None:
            raise ValueError("handshake without challenge")
        if record_rlp:
            rec = Enr.from_rlp(record_rlp)
            if rec.node_id != src_id:
                raise ValueError("handshake record id mismatch")
            self.known_enrs[src_id] = rec
            self.table.insert(rec)
        known = self.known_enrs.get(src_id)
        if known is None or known.pubkey is None:
            raise ValueError("no record for handshake peer")
        if not id_verify(known.pubkey, sig, chal.challenge_data, eph_pub, self.node_id):
            raise ValueError("bad id signature")
        secret = _ecdh_compressed(self.key, eph_pub)
        # peer is the initiator: their send key is our recv key
        their_send, our_send = derive_keys(
            secret, chal.challenge_data, src_id, self.node_id
        )
        sess = Session(our_send, their_send)
        self.sessions[src_id] = sess
        self.addr_of[src_id] = addr
        plain = AESGCM(sess.recv_key).decrypt(nonce, message_ct, iv + header)
        self._dispatch(src_id, addr, plain)

    # -- message dispatch --------------------------------------------------

    def _dispatch(self, src_id: bytes, addr, plain: bytes):
        msg_type, body = decode_message(plain)
        if msg_type == MSG_PING:
            req_id, enr_seq = body[0], rlp.decode_uint(body[1])
            known = self.known_enrs.get(src_id)
            if known is not None and enr_seq > known.seq:
                self._request_enr_refresh(src_id)
            self._send_to_id(src_id, pong(req_id, self.enr.seq, addr[0], addr[1]))
        elif msg_type == MSG_PONG:
            self._complete(body[0], ("pong", body))
        elif msg_type == MSG_FINDNODE:
            req_id, distances = body[0], [rlp.decode_uint(d) for d in body[1]]
            found: list[Enr] = []
            for d in distances:
                if d == 0:
                    found.append(self.enr)
                else:
                    found.extend(self.table.at_distance(d))
            found = found[: 3 * BUCKET_SIZE]
            chunks = [
                found[i : i + MAX_NODES_PER_MSG]
                for i in range(0, len(found), MAX_NODES_PER_MSG)
            ] or [[]]
            for chunk in chunks:
                self._send_to_id(src_id, nodes(req_id, len(chunks), chunk))
        elif msg_type == MSG_NODES:
            req_id, total = body[0], rlp.decode_uint(body[1])
            recs = []
            for item in body[2]:
                try:
                    rec = Enr.from_rlp(rlp.encode(item))
                except ValueError:
                    continue
                recs.append(rec)
                known = self.known_enrs.get(rec.node_id)
                if known is None or rec.seq >= known.seq:
                    self.known_enrs[rec.node_id] = rec
            self._accumulate_nodes(req_id, total, recs)
        elif msg_type == MSG_TALKREQ:
            req_id, protocol, request = body[0], body[1], body[2]
            handler = self.talk_handlers.get(protocol)
            resp = handler(src_id, request) if handler else b""
            self._send_to_id(
                src_id, encode_message(MSG_TALKRESP, [req_id, resp])
            )
        elif msg_type == MSG_TALKRESP:
            self._complete(body[0], ("talkresp", body))

    def _send_to_id(self, nid: bytes, msg_plain: bytes):
        enr = self.known_enrs.get(nid)
        if enr is not None:
            self._seal_and_send(enr, msg_plain)

    def _request_enr_refresh(self, nid: bytes):
        # fire-and-forget: the MSG_NODES handler records any returned
        # record into known_enrs without needing a registered waiter
        self._send_to_id(nid, findnode(secrets.token_bytes(8), [0]))

    # -- request/response plumbing ----------------------------------------

    def _complete(self, req_id: bytes, result):
        with self._lock:
            st = self._requests.get(bytes(req_id))
        if st is None:
            return
        st["result"] = result
        st["event"].set()

    def _accumulate_nodes(self, req_id: bytes, total: int, recs: list[Enr]):
        with self._lock:
            st = self._requests.get(bytes(req_id))
        if st is None:
            return
        st["nodes"].extend(recs)
        st["total"] = total
        st["got"] = st.get("got", 0) + 1
        if st["got"] >= total:
            st["event"].set()

    def _request(self, dest: Enr, msg_builder, timeout=REQUEST_TIMEOUT):
        req_id = secrets.token_bytes(8)
        st = {"event": threading.Event(), "nodes": [], "total": None}
        with self._lock:
            self._requests[req_id] = st
        self.known_enrs.setdefault(dest.node_id, dest)
        self._seal_and_send(dest, msg_builder(req_id))
        st["event"].wait(timeout)
        with self._lock:
            self._requests.pop(req_id, None)
        return st

    # -- public API --------------------------------------------------------

    def ping(self, dest: Enr, timeout=REQUEST_TIMEOUT) -> bool:
        st = self._request(dest, lambda rid: ping(rid, self.enr.seq), timeout)
        ok = "result" in st
        if ok:
            self.table.insert(dest)
        return ok

    def find_node(
        self, dest: Enr, distances: list[int], timeout=REQUEST_TIMEOUT
    ) -> list[Enr]:
        st = self._request(dest, lambda rid: findnode(rid, distances), timeout)
        return st["nodes"]

    def talk_req(
        self, dest: Enr, protocol: bytes, request: bytes, timeout=REQUEST_TIMEOUT
    ) -> bytes | None:
        st = self._request(
            dest,
            lambda rid: encode_message(MSG_TALKREQ, [rid, protocol, request]),
            timeout,
        )
        res = st.get("result")
        return bytes(res[1][1]) if res else None

    def bootstrap(self, boot_enrs: list[Enr]):
        for e in boot_enrs:
            self.known_enrs[e.node_id] = e
            if self.ping(e):
                self.table.insert(e)

    def _query_peer(self, peer: Enr, target: bytes) -> list[Enr]:
        """FINDNODE ``peer`` for nodes near ``target``, widening the
        distance window until something comes back (random node ids
        cluster at high log-distances, so a fixed d±1 window misses)."""
        d = log2_distance(target, peer.node_id) or 256
        ordered, lo, hi = [d], d, d
        while lo > 1 or hi < 256:
            if hi < 256:
                hi += 1
                ordered.append(hi)
            if lo > 1:
                lo -= 1
                ordered.append(lo)
        found: list[Enr] = []
        for i in range(0, min(len(ordered), 32), 8):
            found = self.find_node(peer, ordered[i : i + 8])
            if found:
                break
        return found

    def lookup(self, target_id: bytes | None = None, rounds: int = 3) -> list[Enr]:
        """Iterative FINDNODE toward ``target_id`` (default: self — the
        table-refresh lookup discovery runs continuously)."""
        target = target_id or self.node_id
        seen: set[bytes] = {self.node_id}
        results: dict[bytes, Enr] = {}
        frontier = self.table.closest(target, LOOKUP_ALPHA) or list(
            self.known_enrs.values()
        )
        for _ in range(rounds):
            nxt: list[Enr] = []
            for peer in frontier[:LOOKUP_ALPHA]:
                if peer.node_id in seen:
                    continue
                seen.add(peer.node_id)
                for rec in self._query_peer(peer, target):
                    if rec.node_id not in results and rec.node_id != self.node_id:
                        results[rec.node_id] = rec
                        self.table.insert(rec)
                        nxt.append(rec)
            if not nxt:
                break
            nxt.sort(key=lambda e: log2_distance(target, e.node_id))
            frontier = nxt
        return list(results.values())


class BootNode:
    """Answer-only discv5 server (boot_node/src/server.rs): maintains a
    table from inbound traffic and serves FINDNODE, never dials out."""

    def __init__(self, ip: str = "127.0.0.1", port: int = 0, key=None):
        self.service = Discv5Service(key=key, ip=ip, port=port)

    @property
    def enr(self) -> Enr:
        return self.service.enr

    def start(self):
        self.service.start()

    def stop(self):
        self.service.stop()
