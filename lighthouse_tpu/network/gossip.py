"""Gossip layer: message IDs, peer scoring, and an in-process mesh router.

Twin of the vendored gossipsub fork + peer manager (SURVEY §2.4): spec
message-id derivation (sha256 over a domain + topic + payload, first 20
bytes), duplicate suppression cache (the mcache/seen-cache), per-peer
behavioral scoring with ban thresholds (peer_manager/peerdb.rs shape), and
a GossipRouter that floods to mesh peers — the transport for the in-process
multi-node simulator (testing/simulator analog), where libp2p's wire layer
is out of scope but the BEHAVIOR (dedup, scoring, topic fanout, validation
callbacks) is the part the consensus stack depends on.
"""

from __future__ import annotations

import time
from collections import OrderedDict, defaultdict
from typing import Callable

from ..ops import sha256
from . import snappy

MESSAGE_DOMAIN_VALID_SNAPPY = b"\x01\x00\x00\x00"
MESSAGE_DOMAIN_INVALID_SNAPPY = b"\x00\x00\x00\x00"


def message_id(topic: str, compressed_payload: bytes) -> bytes:
    """Spec compute_message_id (altair+ form: domain + topic len + topic +
    decompressed data, first 20 bytes of sha256)."""
    try:
        data = snappy.decompress_block(compressed_payload)
        domain = MESSAGE_DOMAIN_VALID_SNAPPY
    except snappy.SnappyError:
        data = compressed_payload
        domain = MESSAGE_DOMAIN_INVALID_SNAPPY
    t = topic.encode()
    return sha256(domain + len(t).to_bytes(8, "little") + t + data)[:20]


class SeenCache:
    """Bounded LRU of seen message ids (duplicate suppression)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._d: OrderedDict[bytes, float] = OrderedDict()

    def observe(self, mid: bytes) -> bool:
        """True if NEW."""
        if mid in self._d:
            self._d.move_to_end(mid)
            return False
        self._d[mid] = time.monotonic()
        if len(self._d) > self.capacity:
            self._d.popitem(last=False)
        return True

    def contains(self, mid: bytes) -> bool:
        """Non-mutating membership probe (IHAVE filtering)."""
        return mid in self._d


# peer scoring: the full decay/ban-expiry/per-topic model lives in
# peer_manager.py (peerdb.rs + gossipsub_scoring_parameters.rs twin);
# re-exported here for the in-process router + older call sites.
from .peer_manager import (  # noqa: E402,F401
    BAN_THRESHOLD,
    GREYLIST_THRESHOLD,
    PeerManager,
)


class GossipNode:
    """One node's gossip endpoint: subscribe with a validator callback,
    publish to the mesh.  Validation outcomes mirror the reference's
    MessageAcceptance {Accept, Ignore, Reject}: Reject penalizes the
    forwarding peer."""

    def __init__(self, node_id: str, router: "GossipRouter"):
        self.node_id = node_id
        self.router = router
        self.handlers: dict[str, Callable[[bytes, str], str]] = {}
        self.seen = SeenCache()
        self.peer_manager = PeerManager()
        self.received: list[tuple[str, bytes]] = []

    def subscribe(self, topic: str, handler: Callable[[bytes, str], str]) -> None:
        self.handlers[topic] = handler
        self.router.register(topic, self)

    def publish(self, topic: str, payload: bytes) -> bytes:
        compressed = snappy.compress_block(payload)
        mid = message_id(topic, compressed)
        self.seen.observe(mid)
        self.router.route(topic, compressed, origin=self.node_id)
        return mid

    def deliver(self, topic: str, compressed: bytes, from_peer: str) -> None:
        mid = message_id(topic, compressed)
        if not self.seen.observe(mid):
            return  # duplicate
        handler = self.handlers.get(topic)
        if handler is None:
            return
        try:
            payload = snappy.decompress_block(compressed)
        except snappy.SnappyError:
            # invalid-snappy gossip: reject + penalize (the reason the
            # MESSAGE_DOMAIN_INVALID_SNAPPY id domain exists)
            self.peer_manager.report(from_peer, -10.0, "invalid snappy")
            return
        outcome = handler(payload, from_peer)
        if outcome == "accept":
            self.received.append((topic, payload))
            # forward to the rest of the mesh (flood publish)
            self.router.route(topic, compressed, origin=self.node_id)
        elif outcome == "reject":
            self.peer_manager.report(from_peer, -10.0, "invalid gossip")


class GossipRouter:
    """In-process full-mesh router for the multi-node simulator.

    ``injector``: optional FaultInjector consulted once per *delivery* at
    the ``gossip.route`` site — a raising kind (``drop``) makes the
    message vanish on the wire to that one peer (lossy network), a
    mutating kind (``corrupt``) hands the peer flipped bytes (which then
    fail snappy/SSZ validation and penalize the forwarder, exactly as a
    bit-flipping relay would).  Unarmed, the hook is one attribute check.
    """

    def __init__(self, injector=None):
        self.subscriptions: dict[str, list[GossipNode]] = defaultdict(list)
        self.injector = injector
        self.dropped = 0  # deliveries lost to injected wire faults

    def register(self, topic: str, node: GossipNode) -> None:
        if node not in self.subscriptions[topic]:
            self.subscriptions[topic].append(node)

    def route(self, topic: str, compressed: bytes, origin: str) -> None:
        for node in self.subscriptions[topic]:
            if node.node_id == origin:
                continue
            payload = compressed
            if self.injector is not None:
                try:
                    payload = self.injector.fire("gossip.route", compressed)
                except Exception:
                    self.dropped += 1
                    continue  # lost on the wire to this one peer
            node.deliver(topic, payload, origin)
