"""Req/resp RPC: protocol registry, SSZ-snappy chunk codec, rate limiting.

Twin of lighthouse_network/src/rpc (protocol registry protocol.rs:149-174:
Status, Goodbye, BlocksByRange, BlocksByRoot, Ping, MetaData, ...; SSZ-
snappy chunk codec rpc/codec/; token-bucket rate limiting
rpc/rate_limiter.rs both directions).  The transport underneath is
pluggable (in-process pipes for the simulator; TCP framing is the same
bytes).

Chunk wire form (the reference's ssz_snappy response chunk):
``<result u8> <uncompressed_len uvarint> <framed-snappy payload>``.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field

from ..consensus.containers import Checkpoint  # noqa: F401 (type anchors)
from ..consensus.ssz import Container, ByteVector, U64
from . import snappy
from .snappy import _read_uvarint

Root = ByteVector(32)
Bytes4 = ByteVector(4)


class StatusMessage(Container):
    """protocol.rs Status: fork digest + finalized/head pointers."""

    fields = {
        "fork_digest": Bytes4,
        "finalized_root": Root,
        "finalized_epoch": U64,
        "head_root": Root,
        "head_slot": U64,
    }


class GoodbyeReason(Container):
    fields = {"reason": U64}


class Ping(Container):
    fields = {"data": U64}


class MetaData(Container):
    fields = {
        "seq_number": U64,
        "attnets": U64,  # bitfield packed in a u64 for the 64 subnets
        "syncnets": U64,
    }


class BlocksByRangeRequest(Container):
    fields = {
        "start_slot": U64,
        "count": U64,
        "step": U64,  # deprecated = 1
    }


class BlobsByRangeRequest(Container):
    """protocol.rs:149-174 BlobsByRange (deneb)."""

    fields = {
        "start_slot": U64,
        "count": U64,
    }


class BlobIdentifier(Container):
    """types/src/blob_sidecar.rs BlobIdentifier — BlobsByRoot addresses a
    single (block, index) pair."""

    fields = {
        "block_root": Root,
        "index": U64,
    }


PROTOCOLS = {
    # name -> (version, request type or None, response type tag)
    "status": ("1", StatusMessage, StatusMessage),
    "goodbye": ("1", GoodbyeReason, None),
    "ping": ("1", Ping, Ping),
    "metadata": ("2", None, MetaData),
    "beacon_blocks_by_range": ("2", BlocksByRangeRequest, "signed_block"),
    "beacon_blocks_by_root": ("1", None, "signed_block"),
    "blob_sidecars_by_range": ("1", BlobsByRangeRequest, "blob_sidecar"),
    "blob_sidecars_by_root": ("1", None, "blob_sidecar"),
    # protocol.rs:149-174 light-client serving: request = block root
    "light_client_bootstrap": ("1", None, "light_client_bootstrap"),
    # request = (start_period u64, count u64); chunked best updates
    "light_client_updates_by_range": ("1", None, "light_client_update"),
}

PROTOCOL_PREFIX = "/eth2/beacon_chain/req"


def protocol_id(name: str) -> str:
    version = PROTOCOLS[name][0]
    return f"{PROTOCOL_PREFIX}/{name}/{version}/ssz_snappy"


# spec cap on BlocksByRange request size; a peer asking for more is
# misbehaving, not just ambitious (p2p-interface.md MAX_REQUEST_BLOCKS)
MAX_REQUEST_BLOCKS = 1024

# result codes (RPCCodedResponse)
SUCCESS = 0
# handler-side sentinel: response is already a stream of coded chunks
RAW_CHUNKS = -1
INVALID_REQUEST = 1
SERVER_ERROR = 2
RESOURCE_UNAVAILABLE = 3


def _uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_request(payload_ssz: bytes) -> bytes:
    """Requests: <len uvarint><framed snappy>."""
    return _uvarint(len(payload_ssz)) + snappy.compress_framed(payload_ssz)


def decode_request(data: bytes, max_len: int = 2**22) -> bytes:
    want, pos = _read_uvarint(data, 0)
    if want > max_len:
        raise ValueError(f"request over limit ({want} > {max_len})")
    out = snappy.decompress_framed(data[pos:])
    if len(out) != want:
        raise ValueError("request length mismatch")
    return out


def encode_response_chunk(result: int, payload_ssz: bytes = b"") -> bytes:
    return (
        bytes([result])
        + _uvarint(len(payload_ssz))
        + snappy.compress_framed(payload_ssz)
    )


def decode_response_chunk(data: bytes) -> tuple[int, bytes]:
    result = data[0]
    want, pos = _read_uvarint(data, 1)
    out = snappy.decompress_framed(data[pos:])
    if len(out) != want:
        raise ValueError("response length mismatch")
    return result, out


def decode_response_chunks(data: bytes) -> list[tuple[int, bytes]]:
    """Split a stream of back-to-back coded chunks (the multi-block
    BlocksByRange response shape: one <code><len><framed-snappy> per
    block on a single stream)."""
    out, pos = [], 0
    while pos < len(data):
        code = data[pos]
        want, p2 = _read_uvarint(data, pos + 1)
        payload, consumed = snappy.decompress_framed_prefix(data[p2:], want)
        out.append((code, payload))
        pos = p2 + consumed
    return out


# ---------------------------------------------------------------------------
# rate limiting (token bucket per protocol per peer, rate_limiter.rs)
# ---------------------------------------------------------------------------


@dataclass
class TokenBucket:
    capacity: float
    refill_per_sec: float
    tokens: float = field(default=-1.0)
    last: float = field(default=-1.0)

    def allow(self, cost: float = 1.0, now: float | None = None) -> bool:
        now = time.monotonic() if now is None else now
        if self.tokens < 0:  # lazy init pins `last` to the caller's clock
            self.tokens = self.capacity
            self.last = now
        self.tokens = min(
            self.capacity, self.tokens + (now - self.last) * self.refill_per_sec
        )
        self.last = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False


DEFAULT_LIMITS = {
    # protocol -> (capacity, refill/s); shaped after rate_limiter.rs defaults
    "status": (5, 1.0),
    "goodbye": (1, 0.1),
    "ping": (2, 0.5),
    "metadata": (2, 0.5),
    "beacon_blocks_by_range": (1024, 100.0),
    "beacon_blocks_by_root": (128, 20.0),
    "blob_sidecars_by_range": (768, 100.0),
    "blob_sidecars_by_root": (128, 20.0),
    # gossipsub IWANT retransmission budget (ids/sec, not requests)
    "gossip_iwant": (256, 32.0),
}


class RateLimiter:
    def __init__(self, limits: dict | None = None):
        self.limits = limits or DEFAULT_LIMITS
        self._buckets: dict[tuple[str, str], TokenBucket] = {}

    def allow(self, peer_id: str, protocol: str, cost: float = 1.0,
              now: float | None = None) -> bool:
        cap, refill = self.limits.get(protocol, (10, 1.0))
        key = (peer_id, protocol)
        if key not in self._buckets:
            self._buckets[key] = TokenBucket(cap, refill)
        return self._buckets[key].allow(cost, now)
