"""libp2p transport: TCP + noise + yamux + gossipsub/req-resp wire protocols.

The socket-level counterpart of the reference's lighthouse_network
service (`src/service/utils.rs:39-48` build_transport: TCP, noise
encryption, yamux muxing; behaviour composition `src/service/behaviour.rs`):

* multistream-select 1.0 protocol negotiation (uvarint-framed lines),
* Noise XX channel (noise.py) bound to the node's secp256k1 identity,
* yamux sessions (yamux.py), one per connection,
* gossipsub v1.1 wire RPCs (`/meshsub/1.1.0`, protobuf, StrictNoSign as
  eth2 requires) carrying snappy-compressed payloads with the spec
  message-id (gossip.py), flood-published to subscribed peers,
* req/resp: one stream per request negotiated to
  `/eth2/beacon_chain/req/<name>/<v>/ssz_snappy` (rpc.py chunk codec).

Synchronous, thread-per-connection — the IO layer of a node whose hot
path is device batches, not packet shuffling.
"""

from __future__ import annotations

from collections import OrderedDict
import socket
import threading
from typing import Callable

from ..utils.logging import get_logger
from ..utils.metrics import Counter, Gauge
from . import rpc as rpc_mod
from . import snappy
from .gossip import PeerManager, SeenCache, message_id
from .noise import (
    NoiseError,
    NoiseSession,
    _pb_field_bytes,
    _pb_parse,
    _pb_varint,
    initiator_handshake,
    peer_id_from_pubkey,
    responder_handshake,
)
from .quic import QuicEndpoint, QuicError
from .yamux import Session, Stream, YamuxError

log = get_logger("libp2p")

MULTISTREAM = "/multistream/1.0.0"
NOISE_PROTO = "/noise"
YAMUX_PROTO = "/yamux/1.0.0"

# transport observability (the reference's libp2p metrics: peers by
# transport, dial outcomes — lighthouse_network metrics.rs)
PEERS_GAUGE = Gauge("libp2p_peers_connected", "Connected peers",
                    ("transport",))
DIALS = Counter("libp2p_dials_total", "Outbound dial outcomes",
                ("transport", "outcome"))

# errors any transport's streams can surface (yamux-over-noise-over-TCP
# or native QUIC streams — the two stacks share the Stream contract)
TRANSPORT_ERRORS = (YamuxError, QuicError, OSError)
GOSSIP_PROTO = "/meshsub/1.1.0"
# eth2 GOSSIP_MAX_SIZE is 10 MiB; one RPC may carry a few messages
MAX_GOSSIP_RPC_SIZE = 11 * 1024 * 1024
# v1.2 IDONTWANT: only messages at least this large are worth the
# control-message round trip (blocks/blobs; never tiny attestations)
IDONTWANT_THRESHOLD = 16 * 1024


class Libp2pError(Exception):
    pass


# ---------------------------------------------------------------------------
# multistream-select over a byte-stream interface
# ---------------------------------------------------------------------------


def _ms_frame(line: str) -> bytes:
    raw = line.encode() + b"\n"
    return _pb_varint(len(raw)) + raw


MAX_MS_MESSAGE = 64 * 1024  # multistream-select message cap


class _MsgReader:
    """Adapts exact-read byte sources to uvarint-framed reads.  The ONE
    uvarint decoder for the wire layer — bounds enforced here apply to
    multistream lines and gossip RPC frames alike."""

    def __init__(self, read_exact: Callable[[int], bytes]):
        self.read_exact = read_exact

    def read_uvarint(self, max_value: int) -> int:
        n, shift = 0, 0
        while True:
            b = self.read_exact(1)[0]
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 63:
                raise Libp2pError("uvarint over 9 bytes")
        if n > max_value:
            raise Libp2pError(f"frame length {n} over cap {max_value}")
        return n

    def read_line(self) -> str:
        n = self.read_uvarint(MAX_MS_MESSAGE)
        raw = self.read_exact(n)
        return raw.rstrip(b"\n").decode()


def ms_negotiate_out(write, reader: _MsgReader, protocol: str) -> None:
    """Dialer side: propose ``protocol``; raise if the peer says na."""
    write(_ms_frame(MULTISTREAM) + _ms_frame(protocol))
    if reader.read_line() != MULTISTREAM:
        raise Libp2pError("peer is not multistream")
    got = reader.read_line()
    if got != protocol:
        raise Libp2pError(f"peer refused {protocol}: {got}")


def ms_negotiate_in(write, reader: _MsgReader, supported) -> str:
    """Listener side: accept the first supported proposal."""
    if reader.read_line() != MULTISTREAM:
        raise Libp2pError("peer is not multistream")
    write(_ms_frame(MULTISTREAM))
    while True:
        proposal = reader.read_line()
        if proposal in supported:
            write(_ms_frame(proposal))
            return proposal
        write(_ms_frame("na"))


# ---------------------------------------------------------------------------
# gossipsub wire RPCs (protobuf, StrictNoSign)
# ---------------------------------------------------------------------------


def encode_gossip_rpc(
    subscriptions: list[tuple[bool, str]] | None = None,
    publish: list[tuple[str, bytes]] | None = None,
    control: "GossipControl | None" = None,
) -> bytes:
    out = b""
    for sub, topic in subscriptions or []:
        opts = _pb_varint(1 << 3 | 0) + _pb_varint(1 if sub else 0)
        opts += _pb_field_bytes(2, topic.encode())
        out += _pb_field_bytes(1, opts)
    for topic, data in publish or []:
        msg = _pb_field_bytes(2, data) + _pb_field_bytes(4, topic.encode())
        out += _pb_field_bytes(2, msg)
    if control is not None and not control.empty():
        out += _pb_field_bytes(3, control.encode())
    return out


class GossipControl:
    """gossipsub ControlMessage: v1.1 ihave/iwant/graft/prune + the v1.2
    idontwant extension (field 5 — the episub/IDONTWANT work the
    reference vendors its gossipsub fork for)."""

    def __init__(self, ihave=None, iwant=None, graft=None, prune=None,
                 idontwant=None):
        self.ihave: list[tuple[str, list[bytes]]] = ihave or []
        self.iwant: list[bytes] = iwant or []
        self.graft: list[str] = graft or []
        self.prune: list[str] = prune or []
        self.idontwant: list[bytes] = idontwant or []

    def empty(self) -> bool:
        return not (self.ihave or self.iwant or self.graft or self.prune
                    or self.idontwant)

    def encode(self) -> bytes:
        out = b""
        for topic, mids in self.ihave:
            body = _pb_field_bytes(1, topic.encode())
            for mid in mids:
                body += _pb_field_bytes(2, mid)
            out += _pb_field_bytes(1, body)
        if self.iwant:
            body = b""
            for mid in self.iwant:
                body += _pb_field_bytes(1, mid)
            out += _pb_field_bytes(2, body)
        for topic in self.graft:
            out += _pb_field_bytes(3, _pb_field_bytes(1, topic.encode()))
        for topic in self.prune:
            out += _pb_field_bytes(4, _pb_field_bytes(1, topic.encode()))
        if self.idontwant:
            body = b""
            for mid in self.idontwant:
                body += _pb_field_bytes(1, mid)
            out += _pb_field_bytes(5, body)
        return out

    @classmethod
    def decode(cls, raw: bytes) -> "GossipControl":
        f = _pb_parse(raw)
        ctl = cls()
        for ih in f.get(1, []):
            g = _pb_parse(ih)
            ctl.ihave.append(
                (g.get(1, [b""])[0].decode(), list(g.get(2, [])))
            )
        for iw in f.get(2, []):
            g = _pb_parse(iw)
            ctl.iwant.extend(g.get(1, []))
        for gr in f.get(3, []):
            g = _pb_parse(gr)
            ctl.graft.append(g.get(1, [b""])[0].decode())
        for pr in f.get(4, []):
            g = _pb_parse(pr)
            ctl.prune.append(g.get(1, [b""])[0].decode())
        for dw in f.get(5, []):
            g = _pb_parse(dw)
            ctl.idontwant.extend(g.get(1, []))
        return ctl


def decode_gossip_rpc(raw: bytes):
    fields = _pb_parse(raw)
    subs: list[tuple[bool, str]] = []
    msgs: list[tuple[str, bytes]] = []
    for sub_raw in fields.get(1, []):
        f = _pb_parse(sub_raw)
        subs.append(
            (bool(f.get(1, [0])[0]), f.get(2, [b""])[0].decode())
        )
    for msg_raw in fields.get(2, []):
        f = _pb_parse(msg_raw)
        topic = f.get(4, [b""])[0].decode()
        data = f.get(2, [b""])[0]
        msgs.append((topic, data))
    control = None
    if fields.get(3):
        control = GossipControl.decode(fields[3][0])
    return subs, msgs, control


class MessageCache:
    """gossipsub mcache: full messages for IWANT service, sliding window
    of heartbeats for IHAVE advertisement."""

    def __init__(self, gossip_windows: int = 3, total_windows: int = 5):
        self.gossip_windows = gossip_windows
        self.windows: list[list[bytes]] = [[] for _ in range(total_windows)]
        self.msgs: dict[bytes, tuple[str, bytes]] = {}

    def put(self, mid: bytes, topic: str, data: bytes) -> None:
        if mid in self.msgs:
            return  # re-publish: the earlier window entry must stay unique
        self.windows[0].append(mid)
        self.msgs[mid] = (topic, data)

    def get(self, mid: bytes):
        return self.msgs.get(mid)

    def recent_ids(self, topic: str) -> list[bytes]:
        out = []
        for w in self.windows[: self.gossip_windows]:
            for mid in w:
                entry = self.msgs.get(mid)
                if entry is not None and entry[0] == topic:
                    out.append(mid)
        return out

    def shift(self) -> None:
        expired = self.windows.pop()
        for mid in expired:
            self.msgs.pop(mid, None)
        self.windows.insert(0, [])


# ---------------------------------------------------------------------------
# connections
# ---------------------------------------------------------------------------


class _QuicIdentity:
    """Stand-in for a NoiseSession on QUIC connections: the TLS
    handshake already authenticated the libp2p identity."""

    def __init__(self, remote_peer_id: bytes):
        self.remote_peer_id = remote_peer_id


class Connection:
    """One peer connection: secure channel + stream muxer + gossip state
    (noise+yamux over TCP, or a native QUIC connection)."""

    def __init__(self, host: "Libp2pHost", sock: socket.socket,
                 noise: NoiseSession, muxer: Session):
        self.host = host
        self.sock = sock
        self.noise = noise
        self.muxer = muxer
        self.peer_id = noise.remote_peer_id
        self.topics: set[str] = set()  # peer's subscriptions
        # mids this peer told us NOT to forward to it (v1.2 IDONTWANT);
        # bounded FIFO — stale entries age out with the seen-cache window
        self.dont_want: "OrderedDict[bytes, bool]" = OrderedDict()
        self._gossip_out: Stream | None = None
        self.transport = "tcp" if sock is not None else "quic"
        self._lock = threading.Lock()
        self._gossip_write_lock = threading.Lock()
        self.alive = True

    # -- gossip ------------------------------------------------------------

    def _ensure_gossip_stream(self) -> Stream:
        with self._lock:
            if self._gossip_out is None:
                st = self.muxer.open_stream()
                reader = _MsgReader(lambda n: st.read(n, timeout=5.0))
                ms_negotiate_out(st.write, reader, GOSSIP_PROTO)
                self._gossip_out = st
            return self._gossip_out

    def send_gossip_rpc(self, rpc: bytes) -> None:
        try:
            st = self._ensure_gossip_stream()
            # one writer at a time: a large RPC can split across yamux
            # frames while blocked on window credit, and interleaved
            # writers would corrupt the shared stream's varint framing
            with self._gossip_write_lock:
                st.write(_pb_varint(len(rpc)) + rpc)
        except (*TRANSPORT_ERRORS, Libp2pError) as exc:
            log.debug("gossip send to %s failed: %s", self.peer_id.hex()[:8], exc)
            self.alive = False

    # -- req/resp ----------------------------------------------------------

    def _request_raw(self, name: str, payload_ssz: bytes,
                     timeout: float) -> bytes:
        """Stream choreography shared by single- and multi-chunk requests:
        open, negotiate, write, FIN, read to EOF."""
        st = self.muxer.open_stream()
        reader = _MsgReader(lambda n: st.read(n, timeout=timeout))
        ms_negotiate_out(st.write, reader, rpc_mod.protocol_id(name))
        st.write(rpc_mod.encode_request(payload_ssz))
        st.close()  # FIN: request fully written
        return st.read_until_eof(timeout=timeout)

    def request(self, name: str, payload_ssz: bytes,
                timeout: float = 5.0) -> tuple[int, bytes]:
        """One shot request: returns (result_code, response_ssz)."""
        body = self._request_raw(name, payload_ssz, timeout)
        if not body:
            raise Libp2pError(f"empty response to {name}")
        return rpc_mod.decode_response_chunk(body)

    def request_multi(self, name: str, payload_ssz: bytes,
                      timeout: float = 10.0) -> list[tuple[int, bytes]]:
        """Streamed request (BlocksByRange shape): every coded chunk on
        the stream, in order.  An EMPTY stream is a valid response (all
        requested slots skipped / unknown) -> []."""
        return rpc_mod.decode_response_chunks(
            self._request_raw(name, payload_ssz, timeout)
        )

    def close(self) -> None:
        self.alive = False
        self.muxer.stop()
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass


class Libp2pHost:
    """A libp2p node: listener, dialer, gossip pub/sub, req/resp handlers.

    ``rpc_handlers[name] -> (request_ssz, peer_id) -> (code, response_ssz)``
    ``subscribe(topic, handler)`` with handler(payload, peer_id) -> accept/
    ignore/reject (MessageAcceptance semantics, gossip.py scoring).
    """

    # gossipsub v1.1 mesh parameters (the reference's defaults)
    D = 6
    D_LO = 4
    D_HI = 12
    D_LAZY = 6
    HEARTBEAT_SECS = 1.0

    def __init__(self, key=None, ip: str = "127.0.0.1", port: int = 0,
                 heartbeat: bool = True, quic_port: int | None = None):
        from cryptography.hazmat.primitives.asymmetric import ec

        self.key = key or ec.generate_private_key(ec.SECP256K1())
        from cryptography.hazmat.primitives import serialization

        pub = self.key.public_key().public_bytes(
            serialization.Encoding.X962, serialization.PublicFormat.CompressedPoint
        )
        self.peer_id = peer_id_from_pubkey(pub)
        self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.listener.bind((ip, port))
        self.listener.listen(16)
        self.ip, self.port = self.listener.getsockname()
        self.connections: dict[bytes, Connection] = {}
        self.subscriptions: dict[str, Callable] = {}
        self.rpc_handlers: dict[str, Callable] = {}
        self.seen = SeenCache()
        self.peer_manager = PeerManager()
        self.received: list[tuple[str, bytes]] = []
        self.rate_limiter = rpc_mod.RateLimiter()
        self.mesh: dict[str, set[bytes]] = {}  # topic -> mesh peer ids
        self._mesh_lock = threading.Lock()  # heartbeat/reader/publisher
        self.mcache = MessageCache()
        self._heartbeat_enabled = heartbeat
        self._running = False
        self._threads: list[threading.Thread] = []
        # optional QUIC listener (the reference runs TCP+QUIC side by
        # side, `service/utils.rs:39-48`); None disables it.  Bound here
        # (like the TCP listener) so the port is advertisable before
        # start() — the ENR is built between __init__ and start
        self.quic: QuicEndpoint | None = None
        self.quic_port: int | None = None
        if quic_port is not None:
            self.quic = QuicEndpoint(self.key, ip, quic_port)
            self.quic_port = self.quic.port

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._running = True
        t = threading.Thread(target=self._accept_loop,
                             name=f"libp2p-{self.port}", daemon=True)
        t.start()
        self._threads.append(t)
        if self.quic is not None:
            qt = threading.Thread(target=self._quic_accept_loop,
                                  name=f"libp2p-quic-{self.quic_port}",
                                  daemon=True)
            qt.start()
            self._threads.append(qt)
        if self._heartbeat_enabled:
            hb = threading.Thread(target=self._heartbeat_loop,
                                  name=f"gossip-hb-{self.port}", daemon=True)
            hb.start()
            self._threads.append(hb)

    def _heartbeat_loop(self) -> None:
        import time as _time

        while self._running:
            _time.sleep(self.HEARTBEAT_SECS)
            try:
                self.heartbeat()
            except Exception as exc:  # noqa: BLE001
                log.debug("heartbeat: %s", exc)

    def heartbeat(self) -> None:
        """gossipsub heartbeat: score decay + score-driven mesh maintenance
        + IHAVE gossip + mcache window shift (the vendored gossipsub's
        heartbeat(), with the v1.1 score gates)."""
        import random as _random

        self.peer_manager.maybe_decay()
        self._enforce_bans()
        for topic in list(self.subscriptions):
            grafts, prunes = [], []
            with self._mesh_lock:
                mesh = self.mesh.setdefault(topic, set())
                subscribed = [
                    pid for pid, c in self.connections.items()
                    if topic in c.topics and c.alive
                ]
                mesh.intersection_update(subscribed)
                # negative-score members are pruned FIRST (score gate)
                for pid_hex in self.peer_manager.mesh_prunable(
                    [p.hex() for p in mesh]
                ):
                    pid = bytes.fromhex(pid_hex)
                    mesh.discard(pid)
                    prunes.append(pid)
                # grow toward D when below D_LO — best score first, and
                # never below-zero peers (accept_graft gate)
                if len(mesh) < self.D_LO:
                    ranked = self.peer_manager.graft_candidates(
                        [p.hex() for p in subscribed if p not in mesh]
                    )
                    for pid_hex in ranked[: self.D - len(mesh)]:
                        pid = bytes.fromhex(pid_hex)
                        mesh.add(pid)
                        grafts.append(pid)
                # shrink toward D when above D_HI (drop worst scores)
                elif len(mesh) > self.D_HI:
                    worst = sorted(
                        mesh, key=lambda p: self.peer_manager.score(p.hex())
                    )
                    for pid in worst[: len(mesh) - self.D]:
                        mesh.discard(pid)
                        prunes.append(pid)
                lazy = [p for p in subscribed if p not in mesh]
            for pid in grafts:  # sends outside the lock
                self._send_control(pid, GossipControl(graft=[topic]))
            for pid in prunes:
                self._send_control(pid, GossipControl(prune=[topic]))
            # IHAVE gossip to a sample of non-mesh subscribers
            mids = self.mcache.recent_ids(topic)
            if mids:
                _random.shuffle(lazy)
                for pid in lazy[: self.D_LAZY]:
                    self._send_control(
                        pid, GossipControl(ihave=[(topic, mids[:64])])
                    )
        self.mcache.shift()

    def _send_control(self, peer_id: bytes, ctl: GossipControl) -> None:
        conn = self.connections.get(peer_id)
        if conn is not None:
            conn.send_gossip_rpc(encode_gossip_rpc(control=ctl))

    def _enforce_bans(self) -> None:
        """Disconnect any live connection whose peer crossed the ban
        threshold (peer_manager ban policy: ban implies disconnect)."""
        for pid, conn in list(self.connections.items()):
            if conn.alive and self.peer_manager.is_banned(pid.hex()):
                log.debug("disconnecting banned peer %s", pid.hex()[:8])
                self._drop_connection(conn)
                conn.close()

    def stop(self) -> None:
        self._running = False
        for conn in list(self.connections.values()):
            conn.close()
        try:
            self.listener.close()
        except OSError:
            pass
        if self.quic is not None:
            self.quic.stop()

    # -- socket plumbing ---------------------------------------------------

    @staticmethod
    def _sock_reader(sock: socket.socket):
        def read_exact(n: int) -> bytes:
            buf = b""
            while len(buf) < n:
                chunk = sock.recv(n - len(buf))
                if not chunk:
                    raise Libp2pError("connection closed")
                buf += chunk
            return buf

        return read_exact

    def _upgrade(self, sock: socket.socket, dialer: bool,
                 expected_peer_id: bytes | None = None) -> Connection:
        sock.settimeout(10.0)
        read_exact = self._sock_reader(sock)
        reader = _MsgReader(read_exact)
        if dialer:
            ms_negotiate_out(sock.sendall, reader, NOISE_PROTO)
            noise = initiator_handshake(self.key, sock.sendall, read_exact)
        else:
            got = ms_negotiate_in(sock.sendall, reader, {NOISE_PROTO})
            assert got == NOISE_PROTO
            noise = responder_handshake(self.key, sock.sendall, read_exact)

        # negotiate the muxer INSIDE the noise channel
        nbuf = [b""]

        def n_read_exact(n: int) -> bytes:
            while len(nbuf[0]) < n:
                nbuf[0] += noise.read(read_exact)
            out, nbuf[0] = nbuf[0][:n], nbuf[0][n:]
            return out

        def n_write(data: bytes) -> None:
            noise.write(sock.sendall, data)

        n_reader = _MsgReader(n_read_exact)
        if dialer:
            ms_negotiate_out(n_write, n_reader, YAMUX_PROTO)
        else:
            ms_negotiate_in(n_write, n_reader, {YAMUX_PROTO})

        def mux_recv() -> bytes:
            if nbuf[0]:
                out, nbuf[0] = nbuf[0], b""
                return out
            try:
                return noise.read(read_exact)
            except (Libp2pError, NoiseError, OSError):
                return b""

        muxer = Session(n_write, mux_recv, is_dialer=dialer,
                        on_stream=None)
        conn = Connection(self, sock, noise, muxer)
        conn = self._adopt_connection(conn, expected_peer_id)
        sock.settimeout(None)
        return conn

    def _adopt_connection(self, conn: Connection,
                          expected_peer_id: bytes | None) -> Connection:
        """Transport-agnostic admission: identity pinning, ban check,
        stream dispatch, duplicate replacement, subscription announce —
        shared by the TCP (noise+yamux) and QUIC upgrade paths."""
        # identity pinning (ADVICE r3): a dialer that knows who it meant to
        # reach (from the ENR) must reject an endpoint proving a different
        # identity — rust-libp2p rejects mismatched /p2p/<peer-id> the same
        # way.
        if expected_peer_id is not None and conn.peer_id != expected_peer_id:
            conn.close()
            raise Libp2pError(
                f"remote proved identity {conn.peer_id.hex()[:8]}, "
                f"expected {expected_peer_id.hex()[:8]}"
            )
        if self.peer_manager.is_banned(conn.peer_id.hex()):
            conn.close()
            raise Libp2pError(f"peer {conn.peer_id.hex()[:8]} is banned")
        muxer = conn.muxer
        muxer._on_stream = lambda st: self._spawn_stream_handler(conn, st)
        muxer._on_close = lambda: self._drop_connection(conn)
        muxer.start()
        old = self.connections.get(conn.peer_id)
        if old is not None and old is not conn:
            # replacing a live duplicate would leak its socket + pump
            # threads for the connection's remaining lifetime (ADVICE r3)
            self._drop_connection(old)
            old.close()
        self.connections[conn.peer_id] = conn
        PEERS_GAUGE.inc(labels=(conn.transport,))
        self.peer_manager.connect(conn.peer_id.hex())
        # announce our subscriptions
        if self.subscriptions:
            conn.send_gossip_rpc(encode_gossip_rpc(
                subscriptions=[(True, t) for t in self.subscriptions]
            ))
        return conn

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, _addr = self.listener.accept()
            except OSError:
                break
            threading.Thread(
                target=self._inbound, args=(sock,), daemon=True
            ).start()

    def _inbound(self, sock: socket.socket) -> None:
        try:
            self._upgrade(sock, dialer=False)
        except (Libp2pError, NoiseError, OSError, PermissionError) as exc:
            log.debug("inbound upgrade failed: %s", exc)
            try:
                sock.close()
            except OSError:
                pass

    def dial(self, ip: str, port: int,
             expected_peer_id: bytes | None = None) -> Connection:
        """``expected_peer_id``: pin the identity the noise handshake must
        prove (derived from the discovered ENR's secp256k1 key) — a
        hijacked endpoint cannot impersonate the discovered peer."""
        sock = None
        try:
            sock = socket.create_connection((ip, port), timeout=10.0)
            conn = self._upgrade(sock, dialer=True,
                                 expected_peer_id=expected_peer_id)
        except Exception:
            DIALS.inc(labels=("tcp", "failed"))
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            raise
        DIALS.inc(labels=("tcp", "ok"))
        return conn

    # -- QUIC transport ----------------------------------------------------

    def _quic_accept_loop(self) -> None:
        while self._running:
            try:
                qconn = self.quic.accept(timeout=1.0)
            except QuicError:
                continue
            try:
                self._adopt_quic(qconn, None)
            except Libp2pError as exc:
                log.debug("inbound QUIC rejected: %s", exc)

    def _adopt_quic(self, qconn, expected_peer_id) -> Connection:
        """A handshake-complete QUIC connection IS secure channel + muxer:
        TLS proved the libp2p identity, QUIC streams replace yamux."""
        conn = Connection(self, None, _QuicIdentity(qconn.remote_peer_id),
                          qconn)
        return self._adopt_connection(conn, expected_peer_id)

    def dial_quic(self, ip: str, port: int,
                  expected_peer_id: bytes | None = None) -> Connection:
        if self.quic is None:
            raise Libp2pError("QUIC transport not enabled on this host")
        try:
            qconn = self.quic.dial(ip, port,
                                   expected_peer_id=expected_peer_id)
            conn = self._adopt_quic(qconn, expected_peer_id)
        except Exception:
            DIALS.inc(labels=("quic", "failed"))
            raise
        DIALS.inc(labels=("quic", "ok"))
        return conn

    def _drop_connection(self, conn: Connection) -> None:
        """Muxer died (peer hung up or send failed): forget the connection
        and record the disconnect, keeping `connections` bounded."""
        conn.alive = False
        if self.connections.get(conn.peer_id) is conn:
            del self.connections[conn.peer_id]
            PEERS_GAUGE.dec(labels=(conn.transport,))
        with self._mesh_lock:
            for mesh in self.mesh.values():
                mesh.discard(conn.peer_id)  # stale entries eat publishes
        info = self.peer_manager.peers.get(conn.peer_id.hex())
        if info is not None:
            info.connected = False
        # stop the muxer itself, not just the raw socket: a QUIC
        # connection has no conn.sock and would otherwise live on as a
        # zombie (threads, endpoint registry, inbound stream dispatch)
        try:
            conn.muxer.stop()
        except Exception:  # noqa: BLE001 — teardown must not throw
            pass
        if conn.sock is not None:
            try:
                conn.sock.close()
            except OSError:
                pass

    # -- per-stream server side -------------------------------------------

    def _spawn_stream_handler(self, conn: Connection, st: Stream) -> None:
        threading.Thread(
            target=self._serve_stream, args=(conn, st), daemon=True
        ).start()

    def _serve_stream(self, conn: Connection, st: Stream) -> None:
        try:
            reader = _MsgReader(lambda n: st.read(n, timeout=10.0))
            supported = {GOSSIP_PROTO} | {
                rpc_mod.protocol_id(n) for n in self.rpc_handlers
            }
            proto = ms_negotiate_in(st.write, reader, supported)
            if proto == GOSSIP_PROTO:
                self._serve_gossip(conn, st, reader)
            else:
                name = proto.split("/")[-3]
                self._serve_rpc(conn, st, name)
        except (*TRANSPORT_ERRORS, Libp2pError, NoiseError, ValueError) as exc:
            log.debug("stream from %s: %s", conn.peer_id.hex()[:8], exc)

    def _serve_gossip(self, conn: Connection, st: Stream,
                      reader: _MsgReader) -> None:
        idle_reader = _MsgReader(lambda n: st.read(n, timeout=3600.0))
        while self._running and conn.alive:
            try:
                n = idle_reader.read_uvarint(MAX_GOSSIP_RPC_SIZE)
            except Libp2pError:
                # oversized/malformed: drop + penalize, never buffer
                self.peer_manager.on_behaviour_penalty(
                    conn.peer_id.hex(), 3.0, "oversized gossip rpc"
                )
                st.reset()
                return
            raw = st.read(n, timeout=10.0)
            subs, msgs, control = decode_gossip_rpc(raw)
            for subscribed, topic in subs:
                (conn.topics.add if subscribed else conn.topics.discard)(topic)
            for topic, data in msgs:
                self._on_gossip_message(conn, topic, data)
            if control is not None:
                self._on_gossip_control(conn, control)

    def _on_gossip_message(self, conn: Connection, topic: str,
                           data: bytes) -> None:
        mid = message_id(topic, data)
        if not self.seen.observe(mid):
            return
        handler = self.subscriptions.get(topic)
        if handler is None:
            return
        if len(data) >= IDONTWANT_THRESHOLD:
            # v1.2: tell mesh peers we have this LARGE message before we
            # even validate it — duplicates of blocks/blobs are the
            # bandwidth the extension exists to save.  Pre-mesh
            # (bootstrap flood mode) every subscriber is a forwarder, so
            # they are the audience.
            with self._mesh_lock:
                mesh = set(self.mesh.get(topic) or ())
            targets = mesh or {
                pid for pid, c in self.connections.items()
                if c.alive and topic in c.topics
            }
            for pid in targets:
                if pid == conn.peer_id:
                    continue
                self._send_control(pid, GossipControl(idontwant=[mid]))
        try:
            payload = snappy.decompress_block(data)
        except snappy.SnappyError:
            self.peer_manager.on_invalid_message(conn.peer_id.hex(), topic)
            return
        outcome = handler(payload, conn.peer_id)
        if outcome == "accept":
            self.received.append((topic, payload))
            self.mcache.put(mid, topic, data)
            self.peer_manager.on_first_delivery(conn.peer_id.hex(), topic)
            self._forward(topic, data, skip=conn.peer_id, mid=mid)
        elif outcome == "reject":
            # per-topic invalid delivery: the squared penalty is what makes
            # repeat offenders fall past the ban threshold
            self.peer_manager.on_invalid_message(conn.peer_id.hex(), topic)
            if self.peer_manager.is_banned(conn.peer_id.hex()):
                self._drop_connection(conn)
                conn.close()

    def _on_gossip_control(self, conn: Connection, ctl: GossipControl) -> None:
        """GRAFT/PRUNE mesh membership; IHAVE -> IWANT for unseen ids;
        IWANT served from the mcache."""
        for topic in ctl.graft:
            if topic in self.subscriptions and self.peer_manager.accept_graft(
                conn.peer_id.hex()
            ):
                with self._mesh_lock:
                    self.mesh.setdefault(topic, set()).add(conn.peer_id)
            else:
                # not subscribed, or the peer's score fails the graft
                # gate: refuse (spec: prune back)
                self._send_control(conn.peer_id, GossipControl(prune=[topic]))
        for topic in ctl.prune:
            with self._mesh_lock:
                self.mesh.get(topic, set()).discard(conn.peer_id)
        for mid in ctl.idontwant[:256]:
            conn.dont_want[mid] = True
            while len(conn.dont_want) > 1024:
                conn.dont_want.popitem(last=False)
        wanted = []
        for topic, mids in ctl.ihave:
            if topic not in self.subscriptions:
                continue
            wanted.extend(m for m in mids if not self.seen.contains(m))
        if wanted:
            self._send_control(conn.peer_id, GossipControl(iwant=wanted[:64]))
        if ctl.iwant:
            # retransmission bound (gossip_retransmission analog): IWANT
            # floods re-serve full messages — rate limit per peer
            if not self.rate_limiter.allow(
                conn.peer_id.hex(), "gossip_iwant",
                cost=float(min(len(ctl.iwant), 64)),  # the actual serve cost
            ):
                self.peer_manager.on_behaviour_penalty(
                    conn.peer_id.hex(), 1.0, "iwant flood"
                )
                return
            sends = []
            for mid in ctl.iwant[:64]:
                entry = self.mcache.get(mid)
                if entry is not None:
                    sends.append(entry)
            if sends:
                conn.send_gossip_rpc(encode_gossip_rpc(publish=sends))

    def _serve_rpc(self, conn: Connection, st: Stream, name: str) -> None:
        body = st.read_until_eof(timeout=10.0)
        if not self.rate_limiter.allow(conn.peer_id.hex(), name):
            st.write(rpc_mod.encode_response_chunk(
                rpc_mod.RESOURCE_UNAVAILABLE, b""))
            st.close()
            return
        request = rpc_mod.decode_request(body) if body else b""
        code, resp = self.rpc_handlers[name](request, conn.peer_id)
        if code == rpc_mod.RAW_CHUNKS:
            # handler returned pre-encoded coded chunks (multi-chunk
            # responses: one chunk per block on the same stream)
            st.write(resp)
        else:
            st.write(rpc_mod.encode_response_chunk(code, resp))
        st.close()

    # -- public API --------------------------------------------------------

    def subscribe(self, topic: str, handler: Callable) -> None:
        self.subscriptions[topic] = handler
        rpc = encode_gossip_rpc(subscriptions=[(True, topic)])
        for conn in list(self.connections.values()):
            conn.send_gossip_rpc(rpc)

    def publish(self, topic: str, payload: bytes) -> bytes:
        compressed = snappy.compress_block(payload)
        mid = message_id(topic, compressed)
        self.seen.observe(mid)
        self.mcache.put(mid, topic, compressed)
        self._forward(topic, compressed, skip=None, mid=mid)
        return mid

    def _forward(self, topic: str, compressed: bytes, skip: bytes | None,
                 mid: bytes) -> None:
        """Route to the topic mesh (gossipsub); peers outside the mesh
        learn of the message via heartbeat IHAVE + IWANT.  With no mesh
        formed yet (pre-heartbeat bootstrap), flood all subscribers.
        Peers that sent IDONTWANT for ``mid`` are skipped (v1.2; callers
        always hold the id — rehashing MBs here would double the relay
        path's hashing cost)."""
        rpc = encode_gossip_rpc(publish=[(topic, compressed)])
        live = {
            pid for pid, c in self.connections.items() if c.alive
        }
        with self._mesh_lock:
            mesh = set(self.mesh.get(topic) or ()) & live
        for conn in list(self.connections.values()):
            if not conn.alive:
                self._drop_connection(conn)
                continue
            if conn.peer_id == skip or topic not in conn.topics:
                continue
            if mesh and conn.peer_id not in mesh:
                continue
            if mid in conn.dont_want:
                continue  # the peer already has it: save the bandwidth
            conn.send_gossip_rpc(rpc)
