"""Peer manager: reputation with decay, per-topic gossip scores, ban expiry.

Twin of lighthouse_network/src/peer_manager/mod.rs (2,367 LoC) + peerdb.rs
(2,028) + service/gossipsub_scoring_parameters.rs, scaled to this stack's
needs but with the same load-bearing mechanics:

* **Score model** (gossipsub v1.1 shape): per-topic first-delivery reward
  (capped) and invalid-delivery penalty (squared — repeat offenders fall
  off a cliff), a global behaviour penalty (squared) for protocol abuse
  (oversized RPCs, IWANT floods), and a legacy manual delta channel.
* **Decay**: every component decays exponentially per decay tick, so
  reputation is earned and forgiven over time, not accumulated forever.
* **Ban policy with expiry**: crossing BAN_THRESHOLD bans for
  ``ban_duration`` seconds; the ban expires back to a greylist-level
  score rather than a clean slate.
* **PeerDB**: records persist across disconnects (bounded), so a
  reconnecting bad peer resumes its old reputation.

The mesh consumes scores through ``accept_graft`` / ``graft_candidates`` /
``mesh_prunable`` — scoring influences GRAFT/PRUNE, not just bans.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ..utils.metrics import PEER_BANS, PEER_PENALTIES

GREYLIST_THRESHOLD = -16.0
BAN_THRESHOLD = -40.0

FIRST_DELIVERY_WEIGHT = 0.5
FIRST_DELIVERY_CAP = 10.0
INVALID_DELIVERY_WEIGHT = 4.0  # applied negatively, × count²
BEHAVIOUR_WEIGHT = 1.0  # applied negatively, × penalty²
DECAY_FACTOR = 0.95  # per decay tick
DECAY_INTERVAL = 1.0  # seconds between ticks (heartbeat-driven)
MAX_DB_SIZE = 1024


@dataclass
class TopicScore:
    first_message_deliveries: float = 0.0
    invalid_message_deliveries: float = 0.0

    def value(self) -> float:
        reward = (
            min(self.first_message_deliveries, FIRST_DELIVERY_CAP)
            * FIRST_DELIVERY_WEIGHT
        )
        penalty = INVALID_DELIVERY_WEIGHT * self.invalid_message_deliveries**2
        return reward - penalty

    def decay(self) -> None:
        self.first_message_deliveries *= DECAY_FACTOR
        self.invalid_message_deliveries *= DECAY_FACTOR


@dataclass
class PeerRecord:
    """peerdb.rs PeerInfo: identity, liveness, reputation components."""

    connected: bool = True
    banned_until: float | None = None
    manual_score: float = 0.0
    behaviour_penalty: float = 0.0
    topics: dict[str, TopicScore] = field(default_factory=dict)
    subscriptions: set[str] = field(default_factory=set)
    last_seen: float = field(default_factory=time.monotonic)
    goodbyes: int = 0

    def score(self) -> float:
        s = self.manual_score - BEHAVIOUR_WEIGHT * self.behaviour_penalty**2
        for ts in self.topics.values():
            s += ts.value()
        return s

    def decay(self) -> None:
        self.manual_score *= DECAY_FACTOR
        self.behaviour_penalty *= DECAY_FACTOR
        for ts in self.topics.values():
            ts.decay()

    # legacy alias used by older call sites
    @property
    def banned(self) -> bool:
        return self.banned_until is not None and (
            time.monotonic() < self.banned_until
        )


class PeerManager:
    """Score-driven peer lifecycle.  Backwards compatible with the round-3
    interface (connect/report/is_banned/greylisted/connected_peers) and
    extended with the gossipsub scoring surface."""

    def __init__(self, ban_duration: float = 60.0):
        self.peers: dict[str, PeerRecord] = {}
        self.ban_duration = ban_duration
        # Reentrant: the SyncManager tick thread, connection handler
        # threads, and the heartbeat decay all mutate the same records,
        # and public methods compose (_rec → _prune_db, report →
        # _maybe_ban) while holding it.
        self._lock = threading.RLock()
        self._last_decay = time.monotonic()

    # -- db ----------------------------------------------------------------

    def _rec(self, peer_id: str) -> PeerRecord:
        with self._lock:
            rec = self.peers.get(peer_id)
            if rec is None:
                if len(self.peers) > MAX_DB_SIZE:
                    self._prune_db()
                rec = PeerRecord()
                self.peers[peer_id] = rec
            return rec

    def _prune_db(self) -> None:
        """Drop the oldest disconnected, non-banned records (peerdb.rs
        prune: banned peers are retained so bans stick)."""
        with self._lock:
            removable = sorted(
                (
                    (rec.last_seen, pid)
                    for pid, rec in self.peers.items()
                    if not rec.connected and not rec.banned
                ),
            )
            for _, pid in removable[: max(len(self.peers) - MAX_DB_SIZE, 16)]:
                del self.peers[pid]

    # -- lifecycle ---------------------------------------------------------

    def connect(self, peer_id: str) -> None:
        with self._lock:
            rec = self._rec(peer_id)
            if self.is_banned(peer_id):
                raise PermissionError(f"peer {peer_id} is banned")
            rec.connected = True
            rec.last_seen = time.monotonic()

    def disconnect(self, peer_id: str) -> None:
        with self._lock:
            rec = self.peers.get(peer_id)
            if rec is not None:
                rec.connected = False
                rec.last_seen = time.monotonic()

    # -- reputation events -------------------------------------------------

    def report(self, peer_id: str, delta: float, reason: str = "") -> None:
        """Legacy manual channel (protocol errors etc.); decays like the
        rest."""
        with self._lock:
            rec = self._rec(peer_id)
            rec.manual_score += delta
            self._maybe_ban(peer_id, rec)

    def on_first_delivery(self, peer_id: str, topic: str) -> None:
        with self._lock:
            rec = self._rec(peer_id)
            ts = rec.topics.setdefault(topic, TopicScore())
            ts.first_message_deliveries += 1.0
            rec.last_seen = time.monotonic()

    def on_invalid_message(self, peer_id: str, topic: str) -> None:
        with self._lock:
            rec = self._rec(peer_id)
            ts = rec.topics.setdefault(topic, TopicScore())
            ts.invalid_message_deliveries += 1.0
            self._maybe_ban(peer_id, rec)

    def on_behaviour_penalty(
        self, peer_id: str, amount: float = 1.0, reason: str = ""
    ) -> None:
        with self._lock:
            rec = self._rec(peer_id)
            rec.behaviour_penalty += amount
            PEER_PENALTIES.inc(labels=(reason or "unspecified",))
            self._maybe_ban(peer_id, rec)

    def on_goodbye(self, peer_id: str) -> None:
        """Peer said goodbye: count it and mark the record disconnected
        (reputation persists — a goodbye is not a reset)."""
        with self._lock:
            rec = self._rec(peer_id)
            rec.goodbyes += 1
            rec.connected = False
            rec.last_seen = time.monotonic()

    def _maybe_ban(self, peer_id: str, rec: PeerRecord) -> None:
        with self._lock:
            if rec.score() <= BAN_THRESHOLD and not rec.banned:
                rec.banned_until = time.monotonic() + self.ban_duration
                rec.connected = False
                PEER_BANS.inc()

    # -- decay -------------------------------------------------------------

    def decay(self) -> None:
        """One decay tick over every record; expired bans lift back to a
        greylist-level manual score (reputation is forgiven, slowly)."""
        with self._lock:
            now = time.monotonic()
            for rec in self.peers.values():
                rec.decay()
                if rec.banned_until is not None and now >= rec.banned_until:
                    rec.banned_until = None
                    # resume at greylist, not zero: recently-banned stays cold
                    rec.manual_score = min(rec.manual_score,
                                           GREYLIST_THRESHOLD)
                    rec.behaviour_penalty = 0.0
                    for ts in rec.topics.values():
                        ts.invalid_message_deliveries = 0.0

    def maybe_decay(self) -> None:
        """Rate-limited decay for heartbeat call sites."""
        with self._lock:
            now = time.monotonic()
            if now - self._last_decay >= DECAY_INTERVAL:
                self._last_decay = now
                self.decay()

    # -- queries -----------------------------------------------------------

    def score(self, peer_id: str) -> float:
        with self._lock:
            rec = self.peers.get(peer_id)
            return rec.score() if rec is not None else 0.0

    def is_banned(self, peer_id: str) -> bool:
        with self._lock:
            rec = self.peers.get(peer_id)
            return rec is not None and rec.banned

    def greylisted(self, peer_id: str) -> bool:
        return self.score(peer_id) <= GREYLIST_THRESHOLD

    def connected_peers(self) -> list[str]:
        with self._lock:
            return [p for p, r in self.peers.items() if r.connected]

    # -- mesh integration (scoring → GRAFT/PRUNE) --------------------------

    def accept_graft(self, peer_id: str) -> bool:
        """A peer below zero score does not get into our mesh
        (gossipsub v1.1 graft score gate)."""
        return not self.is_banned(peer_id) and self.score(peer_id) >= 0.0

    def graft_candidates(self, peer_ids: list[str]) -> list[str]:
        """Eligible peers, best score first (mesh growth ordering)."""
        ok = [p for p in peer_ids if self.accept_graft(p)]
        return sorted(ok, key=self.score, reverse=True)

    def mesh_prunable(self, peer_ids: list[str]) -> list[str]:
        """Mesh members whose score fell below zero — pruned before any
        random over-subscription trimming."""
        return [p for p in peer_ids if self.score(p) < 0.0]
