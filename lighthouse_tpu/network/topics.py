"""Gossip topics + subnet computation.

Twin of lighthouse_network/src/types/topics.rs (GossipKind :78,107) and the
subnet mapping of consensus/types/src/subnet_id.rs: topic strings are
`/eth2/<fork_digest_hex>/<kind>/ssz_snappy`, attestation load is sharded
over ATTESTATION_SUBNET_COUNT subnets (the protocol's own data-parallel
axis, SURVEY §2.8.4).
"""

from __future__ import annotations

from ..consensus.spec import ChainSpec, compute_fork_digest

ENCODING = "ssz_snappy"

CORE_KINDS = (
    "beacon_block",
    "beacon_aggregate_and_proof",
    "voluntary_exit",
    "proposer_slashing",
    "attester_slashing",
    "sync_committee_contribution_and_proof",
    "bls_to_execution_change",
    "light_client_finality_update",
    "light_client_optimistic_update",
)


def topic(kind: str, fork_digest: bytes) -> str:
    return f"/eth2/{fork_digest.hex()}/{kind}/{ENCODING}"


def attestation_subnet_topic(subnet_id: int, fork_digest: bytes) -> str:
    return topic(f"beacon_attestation_{subnet_id}", fork_digest)


def sync_subnet_topic(subnet_id: int, fork_digest: bytes) -> str:
    return topic(f"sync_committee_{subnet_id}", fork_digest)


def blob_sidecar_topic(index: int, fork_digest: bytes) -> str:
    return topic(f"blob_sidecar_{index}", fork_digest)


def core_topics(fork_digest: bytes) -> list[str]:
    return [topic(k, fork_digest) for k in CORE_KINDS]


def all_topics(spec: ChainSpec, fork_digest: bytes) -> list[str]:
    out = core_topics(fork_digest)
    out += [
        attestation_subnet_topic(i, fork_digest)
        for i in range(spec.attestation_subnet_count)
    ]
    out += [
        sync_subnet_topic(i, fork_digest)
        for i in range(spec.sync_committee_subnet_count)
    ]
    out += [
        blob_sidecar_topic(i, fork_digest)
        for i in range(spec.preset.max_blobs_per_block)
    ]
    return out


def parse_topic(t: str) -> tuple[bytes, str]:
    """-> (fork_digest, kind); raises ValueError on malformed topics."""
    parts = t.split("/")
    if len(parts) != 5 or parts[1] != "eth2" or parts[4] != ENCODING:
        raise ValueError(f"malformed gossip topic {t!r}")
    return bytes.fromhex(parts[2]), parts[3]


def fork_digest(spec: ChainSpec, epoch: int, genesis_validators_root: bytes) -> bytes:
    return compute_fork_digest(
        spec.fork_version_at_epoch(epoch), genesis_validators_root
    )


def compute_subnet_for_attestation(
    spec: ChainSpec, slot: int, committee_index: int, committees_per_slot: int
) -> int:
    """subnet_id.rs compute_subnet_for_attestation: position of the
    committee within the epoch, mod subnet count."""
    slots_since_epoch_start = slot % spec.preset.slots_per_epoch
    committees_since_epoch_start = committees_per_slot * slots_since_epoch_start
    return (
        committees_since_epoch_start + committee_index
    ) % spec.attestation_subnet_count
