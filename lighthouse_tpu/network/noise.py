"""libp2p-noise: the Noise XX secure channel (Noise_XX_25519_ChaChaPoly_SHA256).

The encryption layer of the reference's transport stack
(`lighthouse_network/src/service/utils.rs:39-48` — libp2p noise upgrade
over TCP).  Implements the Noise Protocol Framework primitives (HKDF
chaining key, mixHash/mixKey symmetric state, CipherState with the
96-bit little-endian counter nonce) for the XX pattern:

    -> e
    <- e, ee, s, es
    -> s, se

plus the libp2p payload: each party proves ownership of its libp2p
identity key by signing "noise-libp2p-static-key:" || static-noise-key
and shipping (identity pubkey protobuf, signature) inside the handshake
payload.  Wire framing: every handshake and transport message is
``uint16be length || data`` (noise spec §"message format" as used by
libp2p-noise).

Identity keys are secp256k1 (the same keys ENRs use), so one node key
drives both discovery and the libp2p transport — as in the reference
(`discovery/enr.rs` derives the libp2p keypair from the node's secp key).
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod

from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

from cryptography.hazmat.primitives import hashes

PROTOCOL_NAME = b"Noise_XX_25519_ChaChaPoly_SHA256"
STATIC_KEY_DOMAIN = b"noise-libp2p-static-key:"


class NoiseError(Exception):
    pass


# ---------------------------------------------------------------------------
# protobuf helpers (libp2p PublicKey + NoiseHandshakePayload are tiny
# protobufs; encode/decode by hand rather than depending on protoc output)
# ---------------------------------------------------------------------------


def _pb_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _pb_read_varint(data: bytes, pos: int) -> tuple[int, int]:
    shift = val = 0
    while True:
        if pos >= len(data):
            raise NoiseError("truncated varint")
        b = data[pos]
        val |= (b & 0x7F) << shift
        pos += 1
        if not b & 0x80:
            return val, pos
        shift += 7


def _pb_field_bytes(field_no: int, payload: bytes) -> bytes:
    return _pb_varint(field_no << 3 | 2) + _pb_varint(len(payload)) + payload


def _pb_parse(data: bytes) -> dict[int, list]:
    """Minimal parse: field_no -> list of values (bytes for len-delimited,
    int for varint)."""
    out: dict[int, list] = {}
    pos = 0
    while pos < len(data):
        tag, pos = _pb_read_varint(data, pos)
        field_no, wire = tag >> 3, tag & 7
        if wire == 2:
            ln, pos = _pb_read_varint(data, pos)
            out.setdefault(field_no, []).append(data[pos : pos + ln])
            pos += ln
        elif wire == 0:
            v, pos = _pb_read_varint(data, pos)
            out.setdefault(field_no, []).append(v)
        else:
            raise NoiseError(f"unsupported wire type {wire}")
    return out


KEYTYPE_SECP256K1 = 2


def marshal_identity_pubkey(pub_compressed: bytes) -> bytes:
    """libp2p PublicKey protobuf {key_type=1: enum, data=2: bytes}."""
    return _pb_varint(1 << 3 | 0) + _pb_varint(KEYTYPE_SECP256K1) + _pb_field_bytes(
        2, pub_compressed
    )


def unmarshal_identity_pubkey(data: bytes) -> bytes:
    fields = _pb_parse(data)
    if fields.get(1, [None])[0] != KEYTYPE_SECP256K1:
        raise NoiseError("unsupported identity key type")
    return fields[2][0]


def peer_id_from_pubkey(pub_compressed: bytes) -> bytes:
    """libp2p PeerId: multihash of the marshaled pubkey (identity hash —
    secp256k1 keys marshal to < 42 bytes)."""
    marshaled = marshal_identity_pubkey(pub_compressed)
    if len(marshaled) <= 42:
        return bytes([0x00, len(marshaled)]) + marshaled
    digest = hashlib.sha256(marshaled).digest()
    return bytes([0x12, 0x20]) + digest


# ---------------------------------------------------------------------------
# noise primitives
# ---------------------------------------------------------------------------


def _hkdf2(ck: bytes, ikm: bytes) -> tuple[bytes, bytes]:
    """Noise HKDF with 2 outputs (HMAC-SHA256 chain)."""
    prk = hmac_mod.new(ck, ikm, hashlib.sha256).digest()
    t1 = hmac_mod.new(prk, b"\x01", hashlib.sha256).digest()
    t2 = hmac_mod.new(prk, t1 + b"\x02", hashlib.sha256).digest()
    return t1, t2


class CipherState:
    def __init__(self, key: bytes | None = None):
        self.key = key
        self.n = 0

    def _nonce(self) -> bytes:
        return b"\x00" * 4 + self.n.to_bytes(8, "little")

    def encrypt(self, ad: bytes, plaintext: bytes) -> bytes:
        if self.key is None:
            return plaintext
        ct = ChaCha20Poly1305(self.key).encrypt(self._nonce(), plaintext, ad)
        self.n += 1
        return ct

    def decrypt(self, ad: bytes, ciphertext: bytes) -> bytes:
        if self.key is None:
            return ciphertext
        try:
            pt = ChaCha20Poly1305(self.key).decrypt(self._nonce(), ciphertext, ad)
        except Exception as exc:
            raise NoiseError(f"decrypt failed at n={self.n}") from exc
        self.n += 1
        return pt


class SymmetricState:
    def __init__(self):
        self.h = hashlib.sha256(PROTOCOL_NAME).digest() if len(
            PROTOCOL_NAME
        ) > 32 else PROTOCOL_NAME.ljust(32, b"\x00")
        self.ck = self.h
        self.cipher = CipherState()

    def mix_hash(self, data: bytes) -> None:
        self.h = hashlib.sha256(self.h + data).digest()

    def mix_key(self, ikm: bytes) -> None:
        self.ck, temp_k = _hkdf2(self.ck, ikm)
        self.cipher = CipherState(temp_k)

    def encrypt_and_hash(self, plaintext: bytes) -> bytes:
        ct = self.cipher.encrypt(self.h, plaintext)
        self.mix_hash(ct)
        return ct

    def decrypt_and_hash(self, ciphertext: bytes) -> bytes:
        pt = self.cipher.decrypt(self.h, ciphertext)
        self.mix_hash(ciphertext)
        return pt

    def split(self) -> tuple[CipherState, CipherState]:
        k1, k2 = _hkdf2(self.ck, b"")
        return CipherState(k1), CipherState(k2)


def _dh(priv: X25519PrivateKey, pub_raw: bytes) -> bytes:
    return priv.exchange(X25519PublicKey.from_public_bytes(pub_raw))


def _x25519_pub_raw(priv: X25519PrivateKey) -> bytes:
    return priv.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )


# ---------------------------------------------------------------------------
# identity signatures (secp256k1 over sha256, low-s DER -> raw64 via enr)
# ---------------------------------------------------------------------------


def _sign_identity(identity_key: ec.EllipticCurvePrivateKey, static_pub: bytes) -> bytes:
    # libp2p-noise ships the DER ECDSA signature (rust-libp2p encoding);
    # raw64 r||s stays confined to the ENR v4 identity scheme (ADVICE r3).
    return identity_key.sign(
        STATIC_KEY_DOMAIN + static_pub, ec.ECDSA(hashes.SHA256())
    )


def _verify_identity(pub_compressed: bytes, static_pub: bytes, sig: bytes) -> bool:
    try:
        pub = ec.EllipticCurvePublicKey.from_encoded_point(
            ec.SECP256K1(), pub_compressed
        )
        if len(sig) == 64:
            # tolerate the legacy raw64 encoding from older peers of this
            # stack; spec-conformant peers send DER (0x30-prefixed)
            from .enr import _raw64_to_der

            sig = _raw64_to_der(sig)
        pub.verify(
            sig,
            STATIC_KEY_DOMAIN + static_pub,
            ec.ECDSA(hashes.SHA256()),
        )
        return True
    except Exception:
        return False


def _handshake_payload(identity_key: ec.EllipticCurvePrivateKey,
                       static_pub: bytes) -> bytes:
    """NoiseHandshakePayload {identity_key=1, identity_sig=2}."""
    pub = identity_key.public_key().public_bytes(
        serialization.Encoding.X962, serialization.PublicFormat.CompressedPoint
    )
    return _pb_field_bytes(1, marshal_identity_pubkey(pub)) + _pb_field_bytes(
        2, _sign_identity(identity_key, static_pub)
    )


def _check_payload(payload: bytes, static_pub: bytes) -> bytes:
    """Verify the remote payload; returns the remote identity pubkey."""
    fields = _pb_parse(payload)
    try:
        identity = unmarshal_identity_pubkey(fields[1][0])
        sig = fields[2][0]
    except (KeyError, IndexError) as exc:
        raise NoiseError("handshake payload missing identity") from exc
    if not _verify_identity(identity, static_pub, sig):
        raise NoiseError("bad identity signature over static key")
    return identity


# ---------------------------------------------------------------------------
# the XX handshake over a stream
# ---------------------------------------------------------------------------


def _send(sock_send, data: bytes) -> None:
    if len(data) > 0xFFFF:
        raise NoiseError("noise message over 65535 bytes")
    sock_send(len(data).to_bytes(2, "big") + data)


def _recv(sock_recv) -> bytes:
    hdr = sock_recv(2)
    n = int.from_bytes(hdr, "big")
    return sock_recv(n) if n else b""


class NoiseSession:
    """An established channel: encrypt/decrypt transport frames."""

    def __init__(self, send_cs: CipherState, recv_cs: CipherState,
                 remote_identity: bytes):
        self.send_cs = send_cs
        self.recv_cs = recv_cs
        self.remote_identity = remote_identity  # compressed secp256k1
        self.remote_peer_id = peer_id_from_pubkey(remote_identity)

    def write(self, sock_send, plaintext: bytes) -> None:
        # transport frames: chunk to respect the uint16 length bound
        # (65535 incl. the 16-byte tag)
        for off in range(0, len(plaintext) or 1, 65519):
            chunk = plaintext[off : off + 65519]
            _send(sock_send, self.send_cs.encrypt(b"", chunk))

    def read(self, sock_recv) -> bytes:
        return self.recv_cs.decrypt(b"", _recv(sock_recv))


def initiator_handshake(
    identity_key: ec.EllipticCurvePrivateKey, sock_send, sock_recv
) -> NoiseSession:
    ss = SymmetricState()
    ss.mix_hash(b"")  # empty prologue
    s_priv = X25519PrivateKey.generate()
    s_pub = _x25519_pub_raw(s_priv)
    e_priv = X25519PrivateKey.generate()
    e_pub = _x25519_pub_raw(e_priv)

    # -> e
    ss.mix_hash(e_pub)
    _send(sock_send, e_pub)

    # <- e, ee, s, es  (+ responder payload)
    msg = _recv(sock_recv)
    if len(msg) < 32:
        raise NoiseError("short handshake message 2")
    re_pub = msg[:32]
    ss.mix_hash(re_pub)
    ss.mix_key(_dh(e_priv, re_pub))
    enc_rs = msg[32 : 32 + 32 + 16]
    rs_pub = ss.decrypt_and_hash(enc_rs)
    ss.mix_key(_dh(e_priv, rs_pub))
    remote_payload = ss.decrypt_and_hash(msg[32 + 48 :])
    remote_identity = _check_payload(remote_payload, rs_pub)

    # -> s, se  (+ our payload)
    enc_s = ss.encrypt_and_hash(s_pub)
    ss.mix_key(_dh(s_priv, re_pub))
    enc_payload = ss.encrypt_and_hash(_handshake_payload(identity_key, s_pub))
    _send(sock_send, enc_s + enc_payload)

    c1, c2 = ss.split()  # initiator sends with c1, receives with c2
    return NoiseSession(c1, c2, remote_identity)


def responder_handshake(
    identity_key: ec.EllipticCurvePrivateKey, sock_send, sock_recv
) -> NoiseSession:
    ss = SymmetricState()
    ss.mix_hash(b"")
    s_priv = X25519PrivateKey.generate()
    s_pub = _x25519_pub_raw(s_priv)
    e_priv = X25519PrivateKey.generate()
    e_pub = _x25519_pub_raw(e_priv)

    # -> e
    re_pub = _recv(sock_recv)
    if len(re_pub) != 32:
        raise NoiseError("message 1 must be a bare ephemeral key")
    ss.mix_hash(re_pub)

    # <- e, ee, s, es
    ss.mix_hash(e_pub)
    ss.mix_key(_dh(e_priv, re_pub))
    enc_s = ss.encrypt_and_hash(s_pub)
    ss.mix_key(_dh(s_priv, re_pub))
    enc_payload = ss.encrypt_and_hash(_handshake_payload(identity_key, s_pub))
    _send(sock_send, e_pub + enc_s + enc_payload)

    # -> s, se
    msg = _recv(sock_recv)
    rs_pub = ss.decrypt_and_hash(msg[: 32 + 16])
    ss.mix_key(_dh(e_priv, rs_pub))
    remote_payload = ss.decrypt_and_hash(msg[48:])
    remote_identity = _check_payload(remote_payload, rs_pub)

    c1, c2 = ss.split()  # responder receives with c1, sends with c2
    return NoiseSession(c2, c1, remote_identity)
