"""NAT traversal: UPnP IGD port mapping.

Twin of beacon_node/network/src/nat.rs (igd-based UPnP hole punching:
discover the gateway, read its external IP, install TCP+UDP mappings
with a renewal half-life).  Implemented from the wire up — SSDP
M-SEARCH over UDP multicast, device-description XML fetch, and the
WANIPConnection SOAP actions — so it runs against any spec IGD,
including the in-repo MockIgdGateway the tests use.
"""

from __future__ import annotations

import re
import socket
import threading
import time
from urllib import request as urlrequest

from ..utils.logging import get_logger

log = get_logger("nat")

SSDP_ADDR = ("239.255.255.250", 1900)
MAPPING_DURATION = 3600  # seconds a mapping lives on the gateway
MAPPING_TIMEOUT = MAPPING_DURATION // 2  # renewal half-life (nat.rs)

_ST_IGD = "urn:schemas-upnp-org:device:InternetGatewayDevice:1"
_WANIP = "urn:schemas-upnp-org:service:WANIPConnection:1"


class NatError(IOError):
    pass


def discover_gateway(timeout: float = 2.0, ssdp_addr=None) -> str:
    """SSDP M-SEARCH -> the gateway's device-description URL.

    ``ssdp_addr`` overrides the multicast destination (the mock gateway
    listens on a unicast loopback port; real IGDs on 239.255.255.250)."""
    dst = ssdp_addr or SSDP_ADDR
    msg = (
        "M-SEARCH * HTTP/1.1\r\n"
        f"HOST: {dst[0]}:{dst[1]}\r\n"
        'MAN: "ssdp:discover"\r\n'
        "MX: 2\r\n"
        f"ST: {_ST_IGD}\r\n\r\n"
    ).encode()
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.settimeout(timeout)
    try:
        sock.sendto(msg, dst)
        data, _ = sock.recvfrom(4096)
    except socket.timeout:
        raise NatError("no UPnP gateway answered the M-SEARCH") from None
    finally:
        sock.close()
    m = re.search(rb"(?im)^LOCATION:\s*(\S+)", data)
    if not m:
        raise NatError("SSDP response carried no LOCATION header")
    return m.group(1).decode()


def _soap(control_url: str, action: str, args: dict) -> str:
    body_args = "".join(f"<{k}>{v}</{k}>" for k, v in args.items())
    envelope = (
        '<?xml version="1.0"?>'
        '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/" '
        's:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
        f'<s:Body><u:{action} xmlns:u="{_WANIP}">{body_args}</u:{action}>'
        "</s:Body></s:Envelope>"
    ).encode()
    req = urlrequest.Request(
        control_url,
        data=envelope,
        headers={
            "Content-Type": 'text/xml; charset="utf-8"',
            "SOAPAction": f'"{_WANIP}#{action}"',
        },
        method="POST",
    )
    with urlrequest.urlopen(req, timeout=5) as resp:
        return resp.read().decode()


class Gateway:
    """A discovered IGD: external-IP query + port-mapping actions."""

    def __init__(self, description_url: str):
        self.description_url = description_url
        with urlrequest.urlopen(description_url, timeout=5) as resp:
            desc = resp.read().decode()
        m = re.search(
            rf"<serviceType>{re.escape(_WANIP)}</serviceType>.*?"
            r"<controlURL>([^<]+)</controlURL>",
            desc,
            re.S,
        )
        if not m:
            raise NatError("gateway exposes no WANIPConnection service")
        control = m.group(1)
        if control.startswith("/"):
            base = re.match(r"(https?://[^/]+)", description_url).group(1)
            control = base + control
        self.control_url = control

    def external_ip(self) -> str:
        out = _soap(self.control_url, "GetExternalIPAddress", {})
        m = re.search(r"<NewExternalIPAddress>([^<]+)<", out)
        if not m:
            raise NatError("gateway returned no external IP")
        return m.group(1)

    def add_port_mapping(
        self, protocol: str, external_port: int, internal_port: int,
        internal_client: str, description: str,
        duration: int = MAPPING_DURATION,
    ) -> None:
        _soap(
            self.control_url, "AddPortMapping",
            {
                "NewRemoteHost": "",
                "NewExternalPort": external_port,
                "NewProtocol": protocol,
                "NewInternalPort": internal_port,
                "NewInternalClient": internal_client,
                "NewEnabled": 1,
                "NewPortMappingDescription": description,
                "NewLeaseDuration": duration,
            },
        )

    def delete_port_mapping(self, protocol: str, external_port: int) -> None:
        _soap(
            self.control_url, "DeletePortMapping",
            {
                "NewRemoteHost": "",
                "NewExternalPort": external_port,
                "NewProtocol": protocol,
            },
        )


def lan_address() -> str:
    """The host's own LAN-facing address — what NewInternalClient must
    carry (a 0.0.0.0 placeholder maps to nowhere on a real IGD).  A UDP
    connect() selects the route's source address without sending a
    single packet."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.254.254.254", 1))  # unroutable is fine: no traffic
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def construct_upnp_mappings(
    addr: str, tcp_port: int | None = None, udp_port: int | None = None,
    ssdp_addr=None,
) -> Gateway:
    """nat.rs construct_upnp_mappings: discover, sanity-check the
    external address (a private one means a double NAT — mapping is
    useless), then install the requested TCP (libp2p) and/or UDP
    (discovery) mappings."""
    gw = Gateway(discover_gateway(ssdp_addr=ssdp_addr))
    external = gw.external_ip()
    first_octet = int(external.split(".")[0])
    second = int(external.split(".")[1])
    if (
        first_octet == 10
        or (first_octet == 172 and 16 <= second <= 31)
        or (first_octet == 192 and second == 168)
    ):
        raise NatError(
            f"gateway's external address {external} is itself private "
            "(double NAT): UPnP mapping would not make this node reachable"
        )
    if tcp_port is not None:
        gw.add_port_mapping(
            "TCP", tcp_port, tcp_port, addr, "lighthouse-tpu-p2p"
        )
        log.info("UPnP: mapped TCP %d via %s (external %s)",
                 tcp_port, gw.control_url, external)
    if udp_port is not None:
        gw.add_port_mapping(
            "UDP", udp_port, udp_port, addr, "lighthouse-tpu-discovery"
        )
        log.info("UPnP: mapped UDP %d", udp_port)
    return gw


class PortMappingService:
    """Keep mappings alive: renew every MAPPING_TIMEOUT (half the lease,
    the nat.rs cadence); drop them on stop."""

    def __init__(self, addr: str, tcp_port: int | None = None,
                 udp_port: int | None = None, ssdp_addr=None):
        self.addr = addr
        self.tcp_port = tcp_port
        self.udp_port = udp_port
        self.ssdp_addr = ssdp_addr
        self.gateway: Gateway | None = None
        self.renewals = 0
        self._stop = None
        self._thread = None

    def start(self, renew_interval: float | None = None) -> None:
        self.gateway = construct_upnp_mappings(
            self.addr, self.tcp_port, self.udp_port, ssdp_addr=self.ssdp_addr
        )
        self._stop = threading.Event()
        interval = renew_interval or MAPPING_TIMEOUT

        def loop():
            while not self._stop.wait(interval):
                try:
                    if self.tcp_port is not None:
                        self.gateway.add_port_mapping(
                            "TCP", self.tcp_port, self.tcp_port, self.addr,
                            "lighthouse-tpu-p2p",
                        )
                    if self.udp_port is not None:
                        self.gateway.add_port_mapping(
                            "UDP", self.udp_port, self.udp_port, self.addr,
                            "lighthouse-tpu-discovery",
                        )
                    self.renewals += 1
                except Exception as exc:  # noqa: BLE001 — gateway flap
                    log.warning("UPnP renewal failed: %s", exc)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="upnp-renew")
        self._thread.start()

    def stop(self) -> None:
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.gateway is not None:
            try:
                if self.tcp_port is not None:
                    self.gateway.delete_port_mapping("TCP", self.tcp_port)
                if self.udp_port is not None:
                    self.gateway.delete_port_mapping("UDP", self.udp_port)
            except Exception as exc:  # noqa: BLE001
                log.debug("UPnP unmap failed: %s", exc)
