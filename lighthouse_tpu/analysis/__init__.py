"""Repo-wide static invariant analyzer.

One entrypoint (``tools/pyrun tools/static_audit.py``) runs six lint
families over the package and emits a JSON report, failing on any
unwaivered violation:

* ``lock_lint``     — lock-discipline race detector + lock-order graph
* ``raise_lint``    — never-raise proofs + broad-except ban
* ``registry_lint`` — metrics / fault-site / chaos-spec / trace-span
  consistency
* ``jaxpr_lint``    — dispatch hot-path host-sync ban (the jaxpr walk
  and zero-dim guard live here too, but tracing is driven by
  ``tools/dispatch_audit.py`` and the test suite, not by the audit)
* ``range_lint``    — limb-range abstract interpreter: uint32
  overflow/carry proofs for every registered field kernel, LFp bound
  algebra soundness, and the MXU-readiness report
  (``RANGE_REPORT.json``)
* ``spmd_lint``     — SPMD soundness prover: re-stages the sharded
  programs over an abstract mesh and proves collective legality,
  verdict replication, pad absorption, registry-gather bounds, and
  donation discipline

The pure-AST families finish in seconds; ``range`` and ``spmd`` trace
programs through jax and dominate the wall time (both replay cached
verdicts from ``.range_proof_cache.json`` on an untouched tree) — use
``tools/static_audit.py --only lock,raise,registry,jaxpr`` (see
``AST_FAMILIES``) for the fast tier.

Justified exceptions go in ``analysis/waivers.toml`` (see ``waivers``).
Everything is configurable so the seeded-violation fixture corpus under
``tests/fixtures/lint/`` can run the identical pipeline against its own
tiny registries.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from . import (
    jaxpr_lint,
    lock_lint,
    raise_lint,
    range_lint,
    registry_lint,
    spmd_lint,
)
from .report import Violation
from .waivers import Waiver, apply_waivers, load_waivers, parse_toml_subset

__all__ = [
    "AuditConfig", "AuditResult", "Violation", "Waiver",
    "run_audit", "load_config", "discover_files", "load_waivers",
    "jaxpr_lint", "lock_lint", "raise_lint", "range_lint",
    "registry_lint", "spmd_lint", "ALL_FAMILIES", "AST_FAMILIES",
]

DEFAULT_NEVER_RAISE = (
    "lighthouse_tpu/beacon/processor.py::ResilientVerifier.verify_batch",
    "lighthouse_tpu/beacon/sync.py::SyncManager.tick",
    "lighthouse_tpu/utils/faults.py::FaultInjector.maybe_fire",
    "lighthouse_tpu/beacon/processor.py::BeaconProcessor.try_send",
    "lighthouse_tpu/ingest/engine.py::IngestEngine.marshal_sets",
    "lighthouse_tpu/parallel/pod.py::PodVerifier.verify_batch",
    "lighthouse_tpu/serve/service.py::VerifyService.tick",
    "lighthouse_tpu/integrity/guard.py::IntegrityGuard.verify_batch",
)

ALL_FAMILIES = ("lock", "raise", "registry", "jaxpr", "range", "spmd")
# the pure-AST tier: no jax import, finishes in seconds
AST_FAMILIES = ("lock", "raise", "registry", "jaxpr")


@dataclass
class AuditConfig:
    # roots (files or directories, relative to the audit root) that form
    # the python corpus
    scan_roots: tuple = ("lighthouse_tpu", "tools", "tests", "bench.py")
    # path prefixes eligible for the lock-discipline family (test classes
    # carry no threading discipline; scanning them is pure noise)
    lock_scan_include: tuple = ("lighthouse_tpu/",)
    # never-raise proofs also only bind inside the package
    never_raise: tuple = DEFAULT_NEVER_RAISE
    safe_calls: tuple = ("BatchOutcome", "MarshalledBatch")
    metrics_defs: str = "lighthouse_tpu/utils/metrics.py"
    faults_defs: str = "lighthouse_tpu/utils/faults.py"
    scenarios_defs: str = "lighthouse_tpu/scenario/spec.py"
    # committed regression corpus the continuous scenario search feeds:
    # every *.json under this directory must replay (scenario-fixture
    # family); "" disables the family
    scenario_fixture_dir: str = "tests/fixtures/scenarios"
    spans_defs: str = "lighthouse_tpu/obs/tracer.py"
    # scenario-search mutation surface: the literal constants in
    # search_defs must reference registered shapes/tracks/knobs
    search_defs: str = "lighthouse_tpu/scenario/search.py"
    traffic_defs: str = "lighthouse_tpu/scenario/traffic.py"
    adversity_defs: str = "lighthouse_tpu/scenario/adversity.py"
    # sharded-program rule table: PARTITION_RULES must stay total over
    # OPERAND_LEAVES and free of dead/shadowed rules
    partition_defs: str = "lighthouse_tpu/parallel/partition.py"
    # AOT executable store: AOT_KERNELS (the registered program set)
    # must name kernels defined in backend.py, and any audited store
    # manifests must cross-reference it (orphan/stale entries + broken
    # signatures are findings)
    aot_defs: str = "lighthouse_tpu/crypto/bls/jax_backend/aot.py"
    aot_backend_defs: str = "lighthouse_tpu/crypto/bls/jax_backend/backend.py"
    aot_manifests: tuple = ()
    # kernel autotuner: ARM_TABLE arms must route through toggles defined
    # in fp.py, and audited manifest plan tables must verify (signature,
    # known proven arms, power-of-2 shapes, registered kernels)
    tune_defs: str = "lighthouse_tpu/crypto/bls/jax_backend/autotune.py"
    fp_defs: str = "lighthouse_tpu/crypto/bls/jax_backend/fp.py"
    # verdict-integrity layer: CANARY_CORPUS rows must be well-formed
    # with a valid/invalid mix, and REQUIRED_CHAOS_KINDS must
    # cross-reference the chaos kind registry both directions
    integrity_defs: str = "lighthouse_tpu/integrity/corpus.py"
    docs: tuple = ("README.md", "STATUS.md")
    hot_path: dict = field(
        default_factory=lambda: dict(jaxpr_lint.DEFAULT_HOT_PATH)
    )
    site_scan_exclude: tuple = ("tests/",)
    # prefixes dropped from the corpus entirely — the seeded-violation
    # fixture corpus must not fail the live audit
    exclude: tuple = ("tests/fixtures/lint/",)
    families: tuple = ALL_FAMILIES
    # range family: fixture registry override (python file exposing
    # build_programs()/LFP_CLAIMS; empty = the live kernel registry) and
    # the checked-in report the audit verifies against ("" skips the
    # drift check)
    range_defs: str = ""
    range_report: str = "RANGE_REPORT.json"
    # program names to restrict the range family to (empty = all)
    range_only: tuple = ()
    # replay per-program range verdicts from .range_proof_cache.json
    # when the kernel sources are unchanged (False / CLI --no-cache
    # forces fresh interpret-mode traces); the spmd family shares the
    # flag and the cache file under its own fingerprint
    range_cache: bool = True
    # spmd family: fixture registry override (python file exposing
    # build_programs()/DECLARED_AXES; empty = the live staged-program
    # registry traced out of parallel/partition.py + mesh.py)
    spmd_defs: str = ""


@dataclass
class AuditResult:
    root: str
    files_scanned: int
    violations: list        # unwaivered [Violation]
    waived: list            # [(Violation, reason)]
    lock_edges: list        # [lock_lint.LockEdge]
    elapsed_s: float
    family_seconds: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> dict:
        counts: dict[str, int] = {}
        for v in self.violations:
            counts[v.rule] = counts.get(v.rule, 0) + 1
        return counts

    def to_dict(self) -> dict:
        return {
            "root": self.root,
            "pass": self.ok,
            "files_scanned": self.files_scanned,
            "elapsed_s": round(self.elapsed_s, 3),
            "family_seconds": {
                k: round(v, 3) for k, v in self.family_seconds.items()
            },
            "summary": self.summary(),
            "violations": [v.to_dict() for v in self.violations],
            "waived": [
                dict(v.to_dict(), reason=reason) for v, reason in self.waived
            ],
            "lock_order_edges": sorted(
                {(e.src, e.dst) for e in self.lock_edges}
            ),
        }


def discover_files(root: str, scan_roots) -> list[str]:
    """Repo-relative posix paths of every .py file under the roots."""
    out = []
    for entry in scan_roots:
        full = os.path.join(root, entry)
        if os.path.isfile(full) and entry.endswith(".py"):
            out.append(entry.replace(os.sep, "/"))
        elif os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git")
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, fn), root
                        )
                        out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


def _read_corpus(root, rel_paths):
    """[(rel_path, source)]; unreadable/unparsable files become
    parse-error violations rather than crashing the audit."""
    files, problems = [], []
    for rel in rel_paths:
        full = os.path.join(root, rel)
        try:
            with open(full, encoding="utf-8") as f:
                src = f.read()
            compile(src, rel, "exec", flags=0x400, dont_inherit=True)
        except SyntaxError as exc:
            problems.append(Violation(
                rule="parse-error", path=rel, line=exc.lineno or 0,
                symbol=rel, message=f"file does not parse: {exc.msg}",
            ))
            continue
        except OSError as exc:
            problems.append(Violation(
                rule="parse-error", path=rel, line=0,
                symbol=rel, message=f"unreadable: {exc}",
            ))
            continue
        files.append((rel, src))
    return files, problems


def load_config(path: str) -> AuditConfig:
    """Load an [audit] table (same TOML subset as waivers) into an
    AuditConfig — used by the fixture corpus to re-point the registries."""
    with open(path, encoding="utf-8") as f:
        doc = parse_toml_subset(f.read(), path)
    a = doc.get("audit", {})
    cfg = AuditConfig()
    if "scan_roots" in a:
        cfg.scan_roots = tuple(a["scan_roots"])
    if "lock_scan_include" in a:
        cfg.lock_scan_include = tuple(a["lock_scan_include"])
    if "never_raise" in a:
        cfg.never_raise = tuple(a["never_raise"])
    if "safe_calls" in a:
        cfg.safe_calls = tuple(a["safe_calls"])
    if "metrics_defs" in a:
        cfg.metrics_defs = a["metrics_defs"]
    if "faults_defs" in a:
        cfg.faults_defs = a["faults_defs"]
    if "scenarios_defs" in a:
        cfg.scenarios_defs = a["scenarios_defs"]
    if "scenario_fixture_dir" in a:
        cfg.scenario_fixture_dir = a["scenario_fixture_dir"]
    if "spans_defs" in a:
        cfg.spans_defs = a["spans_defs"]
    if "search_defs" in a:
        cfg.search_defs = a["search_defs"]
    if "traffic_defs" in a:
        cfg.traffic_defs = a["traffic_defs"]
    if "adversity_defs" in a:
        cfg.adversity_defs = a["adversity_defs"]
    if "partition_defs" in a:
        cfg.partition_defs = a["partition_defs"]
    if "aot_defs" in a:
        cfg.aot_defs = a["aot_defs"]
    if "aot_backend_defs" in a:
        cfg.aot_backend_defs = a["aot_backend_defs"]
    if "aot_manifests" in a:
        cfg.aot_manifests = tuple(a["aot_manifests"])
    if "tune_defs" in a:
        cfg.tune_defs = a["tune_defs"]
    if "fp_defs" in a:
        cfg.fp_defs = a["fp_defs"]
    if "integrity_defs" in a:
        cfg.integrity_defs = a["integrity_defs"]
    if "docs" in a:
        cfg.docs = tuple(a["docs"])
    if "site_scan_exclude" in a:
        cfg.site_scan_exclude = tuple(a["site_scan_exclude"])
    if "exclude" in a:
        cfg.exclude = tuple(a["exclude"])
    if "families" in a:
        cfg.families = tuple(a["families"])
    if "range_defs" in a:
        cfg.range_defs = a["range_defs"]
    if "range_report" in a:
        cfg.range_report = a["range_report"]
    if "range_only" in a:
        cfg.range_only = tuple(a["range_only"])
    if "range_cache" in a:
        cfg.range_cache = bool(a["range_cache"])
    if "spmd_defs" in a:
        cfg.spmd_defs = a["spmd_defs"]
    if "hot_path" in a:
        # entries are "relpath::fn" strings
        hp: dict[str, list] = {}
        for entry in a["hot_path"]:
            p, _, fn = entry.partition("::")
            hp.setdefault(p, []).append(fn)
        cfg.hot_path = {p: tuple(fns) for p, fns in hp.items()}
    return cfg


def run_audit(
    root: str,
    config: AuditConfig | None = None,
    waivers: list[Waiver] | str | None = None,
) -> AuditResult:
    t0 = time.perf_counter()
    cfg = config or AuditConfig()
    if isinstance(waivers, str):
        waivers = load_waivers(waivers)
    waivers = list(waivers or ())

    rel_paths = discover_files(root, cfg.scan_roots)
    if cfg.exclude:
        rel_paths = [
            p for p in rel_paths if not p.startswith(tuple(cfg.exclude))
        ]
    files, violations = _read_corpus(root, rel_paths)

    fam_t: dict[str, float] = {}

    lock_edges: list = []
    if "lock" in cfg.families:
        t = time.perf_counter()
        lock_files = [
            (p, s) for p, s in files
            if p.startswith(tuple(cfg.lock_scan_include))
        ]
        lock_violations, lock_edges = lock_lint.run(lock_files)
        violations.extend(lock_violations)
        fam_t["lock"] = time.perf_counter() - t

    if "raise" in cfg.families:
        t = time.perf_counter()
        for p, s in files:
            violations.extend(raise_lint.broad_except_violations(p, s))
        package_files = [
            (p, s) for p, s in files
            if p.startswith(tuple(cfg.lock_scan_include))
        ]
        violations.extend(raise_lint.never_raise_violations(
            package_files, cfg.never_raise, cfg.safe_calls
        ))
        fam_t["raise"] = time.perf_counter() - t

    if "registry" in cfg.families:
        t = time.perf_counter()
        docs = []
        for rel in cfg.docs:
            full = os.path.join(root, rel)
            try:
                with open(full, encoding="utf-8") as f:
                    docs.append((rel, f.read()))
            except OSError:
                violations.append(Violation(
                    rule="parse-error", path=rel, line=0, symbol=rel,
                    message="doc listed in audit config is unreadable",
                ))
        # the parse_scenario_arg round-trip only binds against the live
        # registry (fixture corpora re-point scenarios_defs at fakes)
        live_scenarios = (
            cfg.scenarios_defs == AuditConfig.scenarios_defs
        )
        # store manifests are JSON, outside the python corpus: read them
        # the way docs are read, unreadable ones become findings
        manifests = []
        for rel in cfg.aot_manifests:
            full = os.path.join(root, rel)
            try:
                with open(full, encoding="utf-8") as f:
                    manifests.append((rel, f.read()))
            except OSError:
                violations.append(Violation(
                    rule="parse-error", path=rel, line=0, symbol=rel,
                    message="AOT manifest listed in audit config is "
                            "unreadable",
                ))
        # committed scenario fixtures are JSON, outside the python
        # corpus: glob the corpus directory the way manifests are read
        scenario_fixtures = []
        if cfg.scenario_fixture_dir:
            fix_dir = os.path.join(root, cfg.scenario_fixture_dir)
            if os.path.isdir(fix_dir):
                for fn in sorted(os.listdir(fix_dir)):
                    if not fn.endswith(".json"):
                        continue
                    rel = f"{cfg.scenario_fixture_dir}/{fn}"
                    try:
                        with open(os.path.join(fix_dir, fn),
                                  encoding="utf-8") as f:
                            scenario_fixtures.append((rel, f.read()))
                    except OSError:
                        violations.append(Violation(
                            rule="parse-error", path=rel, line=0,
                            symbol=rel,
                            message="scenario fixture is unreadable",
                        ))
        violations.extend(registry_lint.run(
            files, docs, cfg.metrics_defs, cfg.faults_defs,
            cfg.site_scan_exclude,
            scenarios_defs_path=cfg.scenarios_defs,
            spans_defs_path=cfg.spans_defs,
            scenario_arg_validator=(
                registry_lint.default_scenario_arg_validator
                if live_scenarios else None
            ),
            search_defs_path=cfg.search_defs,
            traffic_defs_path=cfg.traffic_defs,
            adversity_defs_path=cfg.adversity_defs,
            partition_defs_path=cfg.partition_defs,
            aot_defs_path=cfg.aot_defs,
            aot_backend_defs_path=cfg.aot_backend_defs,
            aot_manifests=manifests,
            tune_defs_path=cfg.tune_defs,
            fp_defs_path=cfg.fp_defs,
            scenario_fixtures=scenario_fixtures,
            integrity_defs_path=cfg.integrity_defs,
        ))
        fam_t["registry"] = time.perf_counter() - t

    if "jaxpr" in cfg.families:
        t = time.perf_counter()
        violations.extend(jaxpr_lint.run(files, cfg.hot_path))
        fam_t["jaxpr"] = time.perf_counter() - t

    if "range" in cfg.families:
        t = time.perf_counter()
        violations.extend(range_lint.run(root, cfg, only=cfg.range_only))
        fam_t["range"] = time.perf_counter() - t

    if "spmd" in cfg.families:
        t = time.perf_counter()
        violations.extend(spmd_lint.run(root, cfg, files))
        fam_t["spmd"] = time.perf_counter() - t

    violations.sort(key=lambda v: (v.path, v.line, v.rule, v.symbol))
    failing, waived = apply_waivers(violations, waivers)
    for w in waivers:
        if w.used == 0:
            failing.append(Violation(
                rule="stale-waiver", path="analysis/waivers.toml", line=0,
                symbol=f"{w.rule}:{w.path}:{w.symbol}",
                message=(
                    "waiver matches nothing — the violation it excused is "
                    "gone; delete the waiver"
                ),
            ))
    return AuditResult(
        root=root,
        files_scanned=len(files),
        violations=failing,
        waived=waived,
        lock_edges=lock_edges,
        elapsed_s=time.perf_counter() - t0,
        family_seconds=fam_t,
    )
