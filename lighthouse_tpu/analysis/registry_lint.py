"""Registry-consistency checks: metrics, fault sites, chaos specs.

Three cross-reference families, all driven off the canonical registries:

* **metrics-registry** — every ``M.SOME_METRIC`` / ``from ..metrics
  import SOME_METRIC`` reference anywhere in the scanned corpus must
  resolve to a top-level definition in ``utils/metrics.py``; every
  Counter/Gauge/Histogram *defined* there must be referenced somewhere
  (no orphaned registrations); Prometheus names must be unique across
  the whole corpus; and every ``*_total`` metric name quoted in the docs
  must be a registered prom name.
* **fault-sites** — every literal site string passed to
  ``fire``/``check``/``maybe_fire`` must appear in the canonical
  ``SITES`` registry in ``utils/faults.py`` (f-string sites must start
  with a registered ``SITE_PREFIXES`` entry), and every registered site
  must actually be fired somewhere.
* **chaos-spec** — every ``--chaos <spec>`` example in README/STATUS
  must parse under the real ``FaultInjector.arm_from_spec`` grammar and
  name a registered site.
* **scenario-spec** — every ``--scenario <name>`` example in the docs
  must name a key of the ``SCENARIOS`` registry (scenario/spec.py),
  exactly the way chaos specs are validated; ``:key=val`` overrides are
  stripped first.  The registry is AST-parsed, never imported, so it
  must stay a literal dict.
* **span-registry** — every literal span name passed to
  ``.span("...")`` / ``.instant("...")`` must appear in the canonical
  ``SPANS`` registry (obs/tracer.py), and every registered span must
  actually be opened somewhere (no orphaned registrations) — the same
  both-direction cross-reference the fault-site family enforces.
* **serve-port** — every ``--serve-port <port>`` example in the docs
  must be a concrete valid TCP port (an integer in 0..65535; 0 is the
  ephemeral-port convention the serve tests use), the same
  doc-example validation ``--chaos`` and ``--scenario`` get.
* **partition-rules** — the rule table that drives the sharded verify
  program (``parallel/partition.py``) is proven total and live: every
  ``PARTITION_RULES`` regex must compile, name a registered
  ``SPEC_TOKENS`` spec, and match at least one ``OPERAND_LEAVES`` name
  not already claimed by an earlier rule (first match wins, so a
  fully-shadowed rule is dead code); every operand leaf must be
  matched by some rule (an orphan leaf would raise at program build).
  All three constants are AST-parsed, never imported, so they must
  stay literals.
* **aot-manifest** — the AOT executable store's registered program set
  (``AOT_KERNELS`` in ``jax_backend/aot.py``, AST-parsed literal) must
  bind both directions: every registered name must be a kernel actually
  defined in ``jax_backend/backend.py`` (a ghost entry could never be
  captured), and every entry of an audited store manifest must verify
  under the manifest signature, name a registered kernel (orphans are
  stale working sets the prewarm phase would waste boot time on), and
  carry the metadata fields ``prewarm`` keys on.
* **integrity-corpus** — the verdict-integrity canary registry
  (``CANARY_CORPUS`` in ``integrity/corpus.py``, AST-parsed literal)
  must hold well-formed ``(entry_id, kind, note)`` rows with unique
  ids and at least one valid AND one invalid canary, and
  ``REQUIRED_CHAOS_KINDS`` must cross-reference the chaos kind
  registry (``_KINDS`` in ``utils/faults.py``) both directions —
  every claimed silent-fault kind armable, every registered
  ``silent-*`` kind claimed.

The docs cross-check covers ``*_total``, ``*_seconds`` and ``*_percent``
metric tokens (counters, histograms and gauges).
"""

from __future__ import annotations

import ast
import os
import re

from .report import Violation

_METRIC_FACTORIES = {"Counter", "Gauge", "Histogram"}
_FIRE_METHODS = {"fire", "check", "maybe_fire"}
_SPAN_METHODS = {"span", "instant"}
_UPPER = re.compile(r"^[A-Z][A-Z0-9_]*$")
_DOC_METRIC = re.compile(r"\b([a-z][a-z0-9_]*_(?:total|seconds|percent))\b")
_DOC_SPEC = re.compile(r"--chaos[ =]+([^\s`'\")]+)")
_DOC_SCENARIO = re.compile(r"--scenario[ =]+([^\s`'\")]+)")
_DOC_SERVE_PORT = re.compile(r"--serve-port[ =]+([^\s`'\")]+)")


# -- metrics -------------------------------------------------------------


def metric_defs(src: str, path: str):
    """(metric name -> (prom_name, line), all top-level UPPER names)."""
    tree = ast.parse(src, filename=path)
    defs: dict[str, tuple[str, int]] = {}
    upper_names: set[str] = set()
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if not (isinstance(tgt, ast.Name) and _UPPER.match(tgt.id)):
                continue
            upper_names.add(tgt.id)
            v = node.value
            if (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Name)
                and v.func.id in _METRIC_FACTORIES
                and v.args
                and isinstance(v.args[0], ast.Constant)
                and isinstance(v.args[0].value, str)
            ):
                defs[tgt.id] = (v.args[0].value, node.lineno)
    return defs, upper_names


def _metric_refs(src: str, path: str, defs_basename: str = "metrics"):
    """References to registry members in one file:
    [(name, line)] for both ``M.NAME`` and directly-imported ``NAME``."""
    tree = ast.parse(src, filename=path)
    module_aliases: set[str] = set()
    direct: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = (node.module or "").rsplit(".", 1)[-1]
            if mod == defs_basename:
                for alias in node.names:
                    if _UPPER.match(alias.name):
                        direct.add(alias.asname or alias.name)
            else:
                for alias in node.names:
                    if alias.name == defs_basename:
                        module_aliases.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.rsplit(".", 1)[-1] == defs_basename:
                    module_aliases.add(
                        alias.asname or alias.name.split(".", 1)[0]
                    )
    refs = []
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in module_aliases
            and _UPPER.match(node.attr)
        ):
            refs.append((node.attr, node.lineno))
        elif isinstance(node, ast.Name) and node.id in direct and isinstance(
            node.ctx, ast.Load
        ):
            refs.append((node.id, node.lineno))
    return refs


def metrics_violations(files, metrics_defs_path, docs) -> list[Violation]:
    files = dict(files)
    out: list[Violation] = []
    defs_src = files.get(metrics_defs_path)
    if defs_src is None:
        return [Violation(
            rule="metrics-registry", path=metrics_defs_path, line=0,
            symbol="utils/metrics.py",
            message="metrics registry file not found in scan set",
        )]
    defs, known_names = metric_defs(defs_src, metrics_defs_path)
    used: set[str] = set()
    defs_basename = os.path.splitext(os.path.basename(metrics_defs_path))[0]

    for display, src in files.items():
        if display == metrics_defs_path:
            continue
        for name, line in _metric_refs(src, display, defs_basename):
            if name in defs:
                used.add(name)
            elif name not in known_names:
                out.append(Violation(
                    rule="metrics-registry", path=display, line=line,
                    symbol=name,
                    message=(
                        f"metric {name} referenced but not registered in "
                        f"{metrics_defs_path}"
                    ),
                ))
    for name, (prom, line) in sorted(defs.items()):
        if name not in used:
            out.append(Violation(
                rule="metrics-registry", path=metrics_defs_path, line=line,
                symbol=name,
                message=(
                    f"orphaned metric registration {name} ({prom!r}): "
                    f"defined but never referenced"
                ),
            ))

    # prom-name uniqueness across every factory call in the corpus
    prom_sites: dict[str, list[tuple[str, int]]] = {}
    for display, src in files.items():
        for node in ast.walk(ast.parse(src, filename=display)):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in _METRIC_FACTORIES
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                prom_sites.setdefault(node.args[0].value, []).append(
                    (display, node.lineno)
                )
    for prom, sites in sorted(prom_sites.items()):
        if len(sites) > 1:
            others = ", ".join(f"{p}:{ln}" for p, ln in sites[1:])
            out.append(Violation(
                rule="metrics-registry", path=sites[0][0],
                line=sites[0][1], symbol=prom,
                message=f"prometheus name {prom!r} registered twice "
                        f"(also at {others})",
            ))

    # docs: every *_total token must be a registered prom name
    registered_prom = {prom for prom, _ in defs.values()} | set(prom_sites)
    for display, text in docs:
        for lineno, line in enumerate(text.splitlines(), start=1):
            for token in _DOC_METRIC.findall(line):
                if token not in registered_prom:
                    out.append(Violation(
                        rule="metrics-registry", path=display, line=lineno,
                        symbol=token,
                        message=(
                            f"doc references metric {token!r} which is not "
                            f"a registered prometheus name"
                        ),
                    ))
    return out


# -- fault sites ---------------------------------------------------------


def fault_site_defs(src: str, path: str):
    """Parse SITES (dict/set/tuple of str) and SITE_PREFIXES from the
    canonical registry module."""
    tree = ast.parse(src, filename=path)
    sites: dict[str, int] = {}
    prefixes: list[str] = []
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        v = node.value
        if "SITES" in names:
            keys = []
            if isinstance(v, ast.Dict):
                keys = v.keys
            elif isinstance(v, (ast.Set, ast.Tuple, ast.List)):
                keys = v.elts
            for k in keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    sites[k.value] = k.lineno
        elif "SITE_PREFIXES" in names and isinstance(
            v, (ast.Tuple, ast.List, ast.Set)
        ):
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    prefixes.append(e.value)
    return sites, tuple(prefixes)


def _fire_call_sites(src: str, path: str):
    """[(site_literal | f-string-prefix+"*", line, exact: bool)] for every
    fire/check/maybe_fire call with a resolvable first argument."""
    out = []
    for node in ast.walk(ast.parse(src, filename=path)):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name not in _FIRE_METHODS:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, node.lineno, True))
        elif isinstance(arg, ast.JoinedStr) and arg.values and isinstance(
            arg.values[0], ast.Constant
        ):
            out.append((str(arg.values[0].value), node.lineno, False))
    return out


def fault_site_violations(
    files, faults_defs_path, exclude_prefixes=("tests/",)
) -> list[Violation]:
    files = dict(files)
    out: list[Violation] = []
    defs_src = files.get(faults_defs_path)
    if defs_src is None:
        return [Violation(
            rule="fault-sites", path=faults_defs_path, line=0,
            symbol="utils/faults.py",
            message="fault-site registry file not found in scan set",
        )]
    sites, prefixes = fault_site_defs(defs_src, faults_defs_path)
    if not sites:
        return [Violation(
            rule="fault-sites", path=faults_defs_path, line=0,
            symbol="SITES",
            message="canonical SITES registry missing or empty",
        )]
    used: set[str] = set()
    used_prefixes: set[str] = set()
    for display, src in files.items():
        if display == faults_defs_path or display.startswith(
            tuple(exclude_prefixes)
        ):
            continue
        for site, line, exact in _fire_call_sites(src, display):
            if exact:
                if site in sites:
                    used.add(site)
                elif any(site.startswith(p) for p in prefixes):
                    used_prefixes.update(
                        p for p in prefixes if site.startswith(p)
                    )
                else:
                    out.append(Violation(
                        rule="fault-sites", path=display, line=line,
                        symbol=site,
                        message=(
                            f"fault site {site!r} fired but not in the "
                            f"canonical SITES registry"
                        ),
                    ))
            else:
                if any(site.startswith(p) or p.startswith(site)
                       for p in prefixes):
                    used_prefixes.update(
                        p for p in prefixes
                        if site.startswith(p) or p.startswith(site)
                    )
                else:
                    out.append(Violation(
                        rule="fault-sites", path=display, line=line,
                        symbol=site + "*",
                        message=(
                            f"dynamic fault site prefix {site!r} does not "
                            f"match any registered SITE_PREFIXES entry"
                        ),
                    ))
    for site, line in sorted(sites.items()):
        if site not in used:
            out.append(Violation(
                rule="fault-sites", path=faults_defs_path, line=line,
                symbol=site,
                message=f"registered fault site {site!r} is never fired",
            ))
    for p in prefixes:
        if p not in used_prefixes:
            out.append(Violation(
                rule="fault-sites", path=faults_defs_path, line=0,
                symbol=p + "*",
                message=f"registered site prefix {p!r} is never fired",
            ))
    return out


# -- trace spans ---------------------------------------------------------


def span_defs(src: str, path: str) -> dict[str, int]:
    """AST-parse the literal ``SPANS`` dict's string keys from the
    canonical span registry module (never imported — it must stay a
    literal dict, same contract as SCENARIOS)."""
    tree = ast.parse(src, filename=path)
    names: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.AnnAssign):
            targets = (
                [node.target] if isinstance(node.target, ast.Name) else []
            )
            value = node.value
        elif isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        else:
            continue
        if not any(t.id == "SPANS" for t in targets):
            continue
        if isinstance(value, ast.Dict):
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    names[k.value] = k.lineno
    return names


def _span_call_sites(src: str, path: str):
    """[(name, line)] for every ``.span("...")``/``.instant("...")`` call
    whose first argument is a string literal.  Dynamic names are skipped
    (the tracer API takes literal names only; ``re.Match.span(int)``-style
    collisions carry non-str first args and never match)."""
    out = []
    for node in ast.walk(ast.parse(src, filename=path)):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None
        )
        if name not in _SPAN_METHODS:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, node.lineno))
    return out


def span_violations(
    files, spans_defs_path, exclude_prefixes=("tests/",)
) -> list[Violation]:
    """Both directions, exactly like fault sites: an instrumentation
    site naming an unregistered span, and a registered span that no
    instrumentation site ever opens."""
    files = dict(files)
    out: list[Violation] = []
    defs_src = files.get(spans_defs_path)
    if defs_src is None:
        return [Violation(
            rule="span-registry", path=spans_defs_path, line=0,
            symbol="obs/tracer.py",
            message="span registry file not found in scan set",
        )]
    spans = span_defs(defs_src, spans_defs_path)
    if not spans:
        return [Violation(
            rule="span-registry", path=spans_defs_path, line=0,
            symbol="SPANS",
            message="canonical SPANS registry missing or empty",
        )]
    used: set[str] = set()
    for display, src in files.items():
        if display == spans_defs_path or display.startswith(
            tuple(exclude_prefixes)
        ):
            continue
        for name, line in _span_call_sites(src, display):
            if name in spans:
                used.add(name)
            else:
                out.append(Violation(
                    rule="span-registry", path=display, line=line,
                    symbol=name,
                    message=(
                        f"span {name!r} opened but not in the canonical "
                        f"SPANS registry"
                    ),
                ))
    for name, line in sorted(spans.items()):
        if name not in used:
            out.append(Violation(
                rule="span-registry", path=spans_defs_path, line=line,
                symbol=name,
                message=f"registered span {name!r} is never opened",
            ))
    return out


# -- chaos specs ---------------------------------------------------------


def _default_spec_validator(spec: str):
    """Validate against the real arm_from_spec grammar on a scratch
    injector.  Returns an error string or None."""
    from lighthouse_tpu.utils.faults import FaultInjector

    try:
        FaultInjector().arm_from_spec(spec)
    except Exception as exc:
        return str(exc)
    return None


def chaos_spec_violations(
    docs, known_sites, site_prefixes=(), spec_validator=None
) -> list[Violation]:
    validator = spec_validator or _default_spec_validator
    out = []
    for display, text in docs:
        for lineno, line in enumerate(text.splitlines(), start=1):
            for raw in _DOC_SPEC.findall(line):
                if "<" in raw or "[" in raw:
                    continue  # usage template, not a concrete example
                err = validator(raw)
                if err is not None:
                    out.append(Violation(
                        rule="chaos-spec", path=display, line=lineno,
                        symbol=raw,
                        message=f"--chaos example does not parse under "
                                f"arm_from_spec: {err}",
                    ))
                    continue
                for part in raw.split(","):
                    site = part.split("=", 1)[0]
                    if site in known_sites or any(
                        site.startswith(p) for p in site_prefixes
                    ):
                        continue
                    out.append(Violation(
                        rule="chaos-spec", path=display, line=lineno,
                        symbol=site,
                        message=(
                            f"--chaos example targets unregistered "
                            f"site {site!r}"
                        ),
                    ))
    return out


# -- scenario specs ------------------------------------------------------


def scenario_defs(src: str, path: str) -> dict[str, int]:
    """AST-parse the literal ``SCENARIOS`` dict's string keys (the
    registry is never imported — it must stay a literal dict)."""
    tree = ast.parse(src, filename=path)
    names: dict[str, int] = {}
    for node in tree.body:
        if isinstance(node, ast.AnnAssign):
            targets = (
                [node.target] if isinstance(node.target, ast.Name) else []
            )
            value = node.value
        elif isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        else:
            continue
        if not any(t.id == "SCENARIOS" for t in targets):
            continue
        if isinstance(value, ast.Dict):
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    names[k.value] = k.lineno
    return names


def default_scenario_arg_validator(raw: str):
    """Validate a concrete doc example against the real
    ``parse_scenario_arg`` grammar (name lookup + ``:key=val`` override
    parsing).  Returns an error string or None."""
    from lighthouse_tpu.scenario.spec import parse_scenario_arg

    try:
        parse_scenario_arg(raw)
    except Exception as exc:
        return str(exc)
    return None


def scenario_spec_violations(docs, known_names,
                             arg_validator=None) -> list[Violation]:
    """Every concrete ``--scenario NAME[:key=val]`` doc example must name
    a registered scenario; with ``arg_validator`` (the live audit passes
    :func:`default_scenario_arg_validator`) the full example must also
    round-trip through the real ``parse_scenario_arg`` grammar."""
    out = []
    for display, text in docs:
        for lineno, line in enumerate(text.splitlines(), start=1):
            for raw in _DOC_SCENARIO.findall(line):
                if "<" in raw or "[" in raw:
                    continue  # usage template, not a concrete example
                name = raw.split(":", 1)[0]
                if name not in known_names:
                    out.append(Violation(
                        rule="scenario-spec", path=display, line=lineno,
                        symbol=name,
                        message=(
                            f"--scenario example names unregistered "
                            f"scenario {name!r}"
                        ),
                    ))
                    continue
                if arg_validator is not None:
                    err = arg_validator(raw)
                    if err is not None:
                        out.append(Violation(
                            rule="scenario-spec", path=display, line=lineno,
                            symbol=raw,
                            message=(
                                f"--scenario example does not parse under "
                                f"parse_scenario_arg: {err}"
                            ),
                        ))
    return out


# -- scenario fixture corpus ---------------------------------------------


def scenario_fixture_schema(src: str, path: str):
    """AST-parse the fixture schema from the scenario spec module: the
    ``_SPEC_JSON_FIELDS`` tuple (allowed fixture fields) and the
    ``DEFAULT_SLO`` dict's string keys (registerable SLO thresholds).
    Pure AST, never imported — both must stay literals."""
    tree = ast.parse(src, filename=path)
    json_fields: set[str] = set()
    slo_keys: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.AnnAssign):
            targets = (
                [node.target] if isinstance(node.target, ast.Name) else []
            )
            value = node.value
        elif isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        else:
            continue
        names = {t.id for t in targets}
        if "_SPEC_JSON_FIELDS" in names and isinstance(
            value, (ast.Tuple, ast.List)
        ):
            for e in value.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    json_fields.add(e.value)
        elif "DEFAULT_SLO" in names and isinstance(value, ast.Dict):
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    slo_keys.add(k.value)
    return json_fields, slo_keys


def scenario_fixture_violations(fixtures, scenarios_defs_src,
                                scenarios_defs_path,
                                arg_validator=None) -> list[Violation]:
    """The committed regression corpus (``tests/fixtures/scenarios/``)
    must stay replayable: every fixture parses as a JSON object, carries
    the required ``name``/``seed``, names only ``_SPEC_JSON_FIELDS``
    fields and registered ``DEFAULT_SLO`` keys, and its ``name`` matches
    the file stem ``--scenario`` resolves it by.  With ``arg_validator``
    (the live audit passes the real ``parse_scenario_arg``) the fixture
    must also rebuild a full ScenarioSpec end to end."""
    import json

    json_fields, slo_keys = (set(), set())
    if scenarios_defs_src is not None:
        json_fields, slo_keys = scenario_fixture_schema(
            scenarios_defs_src, scenarios_defs_path
        )
    out: list[Violation] = []
    for display, text in fixtures:
        stem = os.path.splitext(os.path.basename(display))[0]
        try:
            doc = json.loads(text)
            if not isinstance(doc, dict):
                raise ValueError("not a JSON object")
        except Exception:  # noqa: BLE001 — a broken fixture is a finding
            out.append(Violation(
                rule="scenario-fixture", path=display, line=0, symbol=stem,
                message="scenario fixture does not parse as a JSON object",
            ))
            continue
        for req in ("name", "seed"):
            if req not in doc:
                out.append(Violation(
                    rule="scenario-fixture", path=display, line=0,
                    symbol=req,
                    message=f"scenario fixture is missing required "
                            f"field {req!r}",
                ))
        name = doc.get("name")
        if isinstance(name, str) and name != stem:
            out.append(Violation(
                rule="scenario-fixture", path=display, line=0, symbol=name,
                message=(
                    f"fixture name {name!r} does not match file stem "
                    f"{stem!r} — parse_scenario_arg resolves by stem, so "
                    f"the finding cannot replay under its own name"
                ),
            ))
        if json_fields:
            for fld in sorted(set(doc) - json_fields):
                out.append(Violation(
                    rule="scenario-fixture", path=display, line=0,
                    symbol=fld,
                    message=(
                        f"fixture field {fld!r} is not in _SPEC_JSON_FIELDS "
                        f"— spec_from_json would reject it"
                    ),
                ))
        if slo_keys and isinstance(doc.get("slo"), dict):
            for key in sorted(set(doc["slo"]) - slo_keys):
                out.append(Violation(
                    rule="scenario-fixture", path=display, line=0,
                    symbol=key,
                    message=(
                        f"fixture names unregistered SLO key {key!r} "
                        f"(not in DEFAULT_SLO)"
                    ),
                ))
        if arg_validator is not None and isinstance(name, str) \
                and name == stem:
            err = arg_validator(name)
            if err is not None:
                out.append(Violation(
                    rule="scenario-fixture", path=display, line=0,
                    symbol=name,
                    message=(
                        f"fixture does not replay through "
                        f"parse_scenario_arg: {err}"
                    ),
                ))
    return out


# -- serve ports ---------------------------------------------------------


def serve_port_violations(docs) -> list[Violation]:
    """Every concrete ``--serve-port PORT`` doc example must be an
    integer in 0..65535 — a copy-pasteable example, exactly the way
    chaos and scenario examples are held to their real grammars."""
    out = []
    for display, text in docs:
        for lineno, line in enumerate(text.splitlines(), start=1):
            for raw in _DOC_SERVE_PORT.findall(line):
                if "<" in raw or "[" in raw:
                    continue  # usage template, not a concrete example
                try:
                    port = int(raw)
                except ValueError:
                    port = -1
                if not 0 <= port <= 65535:
                    out.append(Violation(
                        rule="serve-port", path=display, line=lineno,
                        symbol=raw,
                        message=(
                            f"--serve-port example {raw!r} is not a valid "
                            f"TCP port (integer in 0..65535)"
                        ),
                    ))
    return out


# -- scenario-search mutation surface ------------------------------------


def search_surface_defs(src: str, path: str):
    """AST-parse the literal mutation-surface constants from search.py:
    ``MUTATION_SHAPES``/``MUTATION_TRACKS`` (tuples of str, with lines)
    and ``KNOB_RANGES`` (track name -> [knob key, ...])."""
    tree = ast.parse(src, filename=path)
    shapes: dict[str, int] = {}
    tracks: dict[str, int] = {}
    knobs: dict[str, tuple[list[str], int]] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        v = node.value
        if ("MUTATION_SHAPES" in names or "MUTATION_TRACKS" in names) and \
                isinstance(v, (ast.Tuple, ast.List)):
            dst = shapes if "MUTATION_SHAPES" in names else tracks
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    dst[e.value] = e.lineno
        elif "KNOB_RANGES" in names and isinstance(v, ast.Dict):
            for k, val in zip(v.keys, v.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    continue
                keys = []
                if isinstance(val, ast.Dict):
                    keys = [kk.value for kk in val.keys
                            if isinstance(kk, ast.Constant)
                            and isinstance(kk.value, str)]
                knobs[k.value] = (keys, k.lineno)
    return shapes, tracks, knobs


def registry_class_names(src: str, path: str, registry_var: str):
    """Registered names from a ``REGISTRY = {cls.name: cls for cls in
    (A, B, ...)}`` module: name literal -> __init__ kwarg names.  Pure
    AST — maps the comprehension's class tuple through each class's
    literal ``name`` attribute and ``__init__`` signature."""
    tree = ast.parse(src, filename=path)
    cls_name_attr: dict[str, str] = {}
    cls_init_args: dict[str, list[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if (isinstance(stmt, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "name"
                            for t in stmt.targets)
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)):
                cls_name_attr[node.name] = stmt.value.value
            elif isinstance(stmt, ast.FunctionDef) and \
                    stmt.name == "__init__":
                cls_init_args[node.name] = [
                    a.arg for a in stmt.args.args[1:]
                ]
    members: dict[str, list[str]] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == registry_var
                   for t in node.targets):
            continue
        v = node.value
        if isinstance(v, ast.DictComp) and v.generators and isinstance(
            v.generators[0].iter, (ast.Tuple, ast.List)
        ):
            for e in v.generators[0].iter.elts:
                if isinstance(e, ast.Name) and e.id in cls_name_attr:
                    members[cls_name_attr[e.id]] = cls_init_args.get(
                        e.id, []
                    )
    return members


def search_surface_violations(
    files, search_defs_path, traffic_defs_path, adversity_defs_path
) -> list[Violation]:
    """Every mutation-surface name in search.py must reference a real
    registered shape/track, and every KNOB_RANGES knob must be a real
    ``__init__`` parameter of that track class — the same
    literal-vs-registry cross-reference the chaos/scenario families
    enforce, so search can never mutate toward a dimension the engine
    would reject."""
    files = dict(files)
    out: list[Violation] = []
    search_src = files.get(search_defs_path)
    if search_src is None:
        return out  # corpus without the search engine: skip the family
    shapes, tracks, knobs = search_surface_defs(search_src,
                                                search_defs_path)
    if not (shapes and tracks and knobs):
        return [Violation(
            rule="search-surface", path=search_defs_path, line=0,
            symbol="MUTATION_SHAPES",
            message="mutation-surface constants missing or non-literal "
                    "(MUTATION_SHAPES / MUTATION_TRACKS / KNOB_RANGES)",
        )]
    real_shapes = real_tracks = None
    traffic_src = files.get(traffic_defs_path)
    if traffic_src is not None:
        real_shapes = registry_class_names(
            traffic_src, traffic_defs_path, "SHAPES"
        )
    adversity_src = files.get(adversity_defs_path)
    if adversity_src is not None:
        real_tracks = registry_class_names(
            adversity_src, adversity_defs_path, "TRACKS"
        )
    if real_shapes:
        for name, line in sorted(shapes.items()):
            if name not in real_shapes:
                out.append(Violation(
                    rule="search-surface", path=search_defs_path,
                    line=line, symbol=name,
                    message=(
                        f"MUTATION_SHAPES entry {name!r} is not a "
                        f"registered traffic shape"
                    ),
                ))
    if real_tracks:
        for name, line in sorted(tracks.items()):
            if name not in real_tracks:
                out.append(Violation(
                    rule="search-surface", path=search_defs_path,
                    line=line, symbol=name,
                    message=(
                        f"MUTATION_TRACKS entry {name!r} is not a "
                        f"registered adversity track"
                    ),
                ))
        for track, (keys, line) in sorted(knobs.items()):
            if track not in tracks:
                out.append(Violation(
                    rule="search-surface", path=search_defs_path,
                    line=line, symbol=track,
                    message=(
                        f"KNOB_RANGES track {track!r} is not in "
                        f"MUTATION_TRACKS"
                    ),
                ))
            params = real_tracks.get(track)
            if params is None:
                continue
            for key in keys:
                if key not in params:
                    out.append(Violation(
                        rule="search-surface", path=search_defs_path,
                        line=line, symbol=f"{track}.{key}",
                        message=(
                            f"KNOB_RANGES knob {key!r} is not an "
                            f"__init__ parameter of the {track!r} track"
                        ),
                    ))
    return out


def partition_defs(src: str, path: str):
    """AST-parse the literal partition constants from
    ``parallel/partition.py``: ``PARTITION_RULES`` (tuple of
    ``(regex, token)`` pairs, with lines), ``OPERAND_LEAVES`` (tuple of
    leaf names, with lines) and the ``SPEC_TOKENS`` key set.  Pure AST
    — the rule table must stay a literal for the audit to bind."""
    tree = ast.parse(src, filename=path)
    rules: list[tuple[str, str, int]] = []
    leaves: dict[str, int] = {}
    tokens: dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        v = node.value
        if "PARTITION_RULES" in names and isinstance(v, (ast.Tuple, ast.List)):
            for e in v.elts:
                if (isinstance(e, (ast.Tuple, ast.List))
                        and len(e.elts) == 2
                        and all(isinstance(x, ast.Constant)
                                and isinstance(x.value, str)
                                for x in e.elts)):
                    rules.append(
                        (e.elts[0].value, e.elts[1].value, e.lineno)
                    )
        elif "OPERAND_LEAVES" in names and isinstance(v, (ast.Tuple, ast.List)):
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    leaves[e.value] = e.lineno
        elif "SPEC_TOKENS" in names and isinstance(v, ast.Dict):
            for k in v.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    tokens[k.value] = k.lineno
    return rules, leaves, tokens


def partition_rule_violations(files, partition_defs_path) -> list[Violation]:
    """The rule table must be total over the operand leaves (no orphan
    leaf — ``operand_partition_specs`` would raise at program build) and
    free of dead weight (every rule compiles, names a registered spec
    token, and is the FIRST match for at least one leaf — first match
    wins, so a fully-shadowed rule can never fire)."""
    files = dict(files)
    out: list[Violation] = []
    src = files.get(partition_defs_path)
    if src is None:
        return out  # corpus without the sharded program: skip the family
    rules, leaves, tokens = partition_defs(src, partition_defs_path)
    if not (rules and leaves and tokens):
        return [Violation(
            rule="partition-rules", path=partition_defs_path, line=0,
            symbol="PARTITION_RULES",
            message="partition constants missing or non-literal "
                    "(PARTITION_RULES / OPERAND_LEAVES / SPEC_TOKENS)",
        )]
    compiled: list = []
    for pattern, token, line in rules:
        try:
            rx = re.compile(pattern)
        except re.error as exc:
            out.append(Violation(
                rule="partition-rules", path=partition_defs_path,
                line=line, symbol=pattern,
                message=f"rule regex does not compile: {exc}",
            ))
            rx = None
        if token not in tokens:
            out.append(Violation(
                rule="partition-rules", path=partition_defs_path,
                line=line, symbol=pattern,
                message=(
                    f"rule names unregistered spec token {token!r} "
                    f"(SPEC_TOKENS: {', '.join(sorted(tokens))})"
                ),
            ))
        compiled.append((pattern, rx, line))
    claimed: dict[str, str] = {}   # leaf -> winning rule pattern
    first_hits: dict[str, int] = {p: 0 for p, _rx, _l in compiled}
    for leaf in leaves:
        for pattern, rx, _line in compiled:
            if rx is not None and rx.search(leaf):
                claimed[leaf] = pattern
                first_hits[pattern] += 1
                break
    for leaf, line in sorted(leaves.items()):
        if leaf not in claimed:
            out.append(Violation(
                rule="partition-rules", path=partition_defs_path,
                line=line, symbol=leaf,
                message=(
                    f"operand leaf {leaf!r} matches no partition rule "
                    f"(program build would raise)"
                ),
            ))
    for pattern, rx, line in compiled:
        if rx is None or first_hits.get(pattern):
            continue
        matches_any = any(rx.search(leaf) for leaf in leaves)
        shape = ("shadowed by an earlier rule for every leaf it matches"
                 if matches_any else "matches no operand leaf")
        out.append(Violation(
            rule="partition-rules", path=partition_defs_path,
            line=line, symbol=pattern,
            message=f"dead rule: {shape}",
        ))
    return out


def aot_manifest_defs(src: str, path: str) -> dict[str, int]:
    """AST-parse the literal ``AOT_KERNELS`` tuple from
    ``jax_backend/aot.py``: kernel name -> line.  Pure AST — the
    registered program set must stay a literal for the audit to bind."""
    tree = ast.parse(src, filename=path)
    out: dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "AOT_KERNELS" not in names:
            continue
        v = node.value
        if isinstance(v, (ast.Tuple, ast.List)):
            for e in v.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out[e.value] = e.lineno
    return out


# manifest entry fields prewarm/load key on; an entry missing one can
# never install (aot.py's _entry_current / AotStore.load contract)
_AOT_ENTRY_FIELDS = ("kernel", "cache_key", "jax", "backend", "blob",
                     "sha256")


def aot_manifest_violations(files, aot_defs_path, aot_backend_defs_path,
                            manifests=()) -> list[Violation]:
    """Both-direction cross-reference for the AOT executable store:
    ``AOT_KERNELS`` names must be kernels defined in backend.py, and
    audited manifests (``manifests`` = ``[(display, json_text)]``) must
    verify under the store's signature algorithm with every entry
    naming a registered kernel and carrying the prewarm metadata."""
    files = dict(files)
    out: list[Violation] = []
    src = files.get(aot_defs_path)
    if src is None:
        return out  # corpus without the AOT store: skip the family
    kernels = aot_manifest_defs(src, aot_defs_path)
    if not kernels:
        return [Violation(
            rule="aot-manifest", path=aot_defs_path, line=0,
            symbol="AOT_KERNELS",
            message="AOT_KERNELS missing or non-literal",
        )]
    backend_src = files.get(aot_backend_defs_path)
    if backend_src is not None:
        tree = ast.parse(backend_src, filename=aot_backend_defs_path)
        defined = {
            n.name for n in tree.body if isinstance(n, ast.FunctionDef)
        }
        for name, line in sorted(kernels.items()):
            if name not in defined:
                out.append(Violation(
                    rule="aot-manifest", path=aot_defs_path, line=line,
                    symbol=name,
                    message=(
                        f"AOT_KERNELS entry {name!r} is not a kernel "
                        f"defined in {aot_backend_defs_path} — a ghost "
                        f"registration can never be captured"
                    ),
                ))
    if not manifests:
        return out
    import json

    # the store's own signature algorithm — byte-identical, not a copy
    from ..crypto.bls.jax_backend.aot import manifest_signature

    for display, text in manifests:
        try:
            doc = json.loads(text)
            entries = doc.get("entries", {})
            if not isinstance(entries, dict):
                raise ValueError("entries is not a table")
        except Exception:  # noqa: BLE001 — a broken manifest is a finding
            out.append(Violation(
                rule="aot-manifest", path=display, line=0, symbol=display,
                message="store manifest does not parse as JSON",
            ))
            continue
        if doc.get("signature") != manifest_signature(entries):
            out.append(Violation(
                rule="aot-manifest", path=display, line=0,
                symbol="signature",
                message=(
                    "manifest signature does not verify — truncated, "
                    "tampered or hand-edited store index"
                ),
            ))
        for fp_hex, meta in sorted(entries.items()):
            if not isinstance(meta, dict):
                meta = {}
            kernel = meta.get("kernel")
            if kernel not in kernels:
                out.append(Violation(
                    rule="aot-manifest", path=display, line=0,
                    symbol=fp_hex,
                    message=(
                        f"manifest entry {fp_hex!r} names unregistered "
                        f"kernel {kernel!r} (AOT_KERNELS: "
                        f"{', '.join(sorted(kernels))}) — orphan/stale "
                        f"working set"
                    ),
                ))
            for fld in _AOT_ENTRY_FIELDS:
                if fld not in meta:
                    out.append(Violation(
                        rule="aot-manifest", path=display, line=0,
                        symbol=f"{fp_hex}.{fld}",
                        message=(
                            f"manifest entry {fp_hex!r} is missing the "
                            f"{fld!r} field prewarm keys on"
                        ),
                    ))
    return out


def tune_plan_defs(src: str, path: str) -> dict[str, tuple]:
    """AST-parse the literal ``ARM_TABLE`` from
    ``jax_backend/autotune.py``: arm id -> (spec, toggle, value, proof,
    line).  Pure AST — the kernel-arm registry must stay a literal for
    the audit to bind, exactly like AOT_KERNELS / SPANS."""
    tree = ast.parse(src, filename=path)
    arms: dict[str, tuple] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "ARM_TABLE" not in names:
            continue
        v = node.value
        if not isinstance(v, (ast.Tuple, ast.List)):
            continue
        for e in v.elts:
            if not isinstance(e, (ast.Tuple, ast.List)) or len(e.elts) != 5:
                continue
            if not all(isinstance(x, ast.Constant) for x in e.elts):
                continue
            vals = [x.value for x in e.elts]
            if isinstance(vals[0], str):
                arms[vals[0]] = (
                    vals[1], vals[2], vals[3], vals[4], e.lineno
                )
    return arms


def _power_of_two_shape(shape) -> bool:
    if not (isinstance(shape, str) and shape.isdigit()):
        return False
    n = int(shape)
    return n > 0 and (n & (n - 1)) == 0


def tune_plan_violations(files, tune_defs_path, fp_defs_path,
                         aot_defs_path=None,
                         manifests=()) -> list[Violation]:
    """Both-direction cross-reference for the kernel autotuner: every
    ``ARM_TABLE`` arm must route through a toggle actually defined in
    ``fp.py`` (a ghost toggle can never route), and audited manifest
    ``plan`` tables must verify under the store's signature algorithm
    with every tuned shape a power-of-2 batch selecting a known,
    range-proven arm and a registered AOT kernel."""
    files = dict(files)
    out: list[Violation] = []
    src = files.get(tune_defs_path)
    if src is None:
        return out  # corpus without the autotuner: skip the family
    arms = tune_plan_defs(src, tune_defs_path)
    if not arms:
        return [Violation(
            rule="tune-plan", path=tune_defs_path, line=0,
            symbol="ARM_TABLE",
            message="ARM_TABLE missing or non-literal",
        )]
    fp_src = files.get(fp_defs_path)
    if fp_src is not None:
        tree = ast.parse(fp_src, filename=fp_defs_path)
        toggles = {
            n.name for n in tree.body if isinstance(n, ast.FunctionDef)
        }
        for arm_id, (_spec, toggle, _value, _proof, line) in sorted(
                arms.items()):
            if toggle not in toggles:
                out.append(Violation(
                    rule="tune-plan", path=tune_defs_path, line=line,
                    symbol=arm_id,
                    message=(
                        f"arm {arm_id!r} routes through toggle {toggle!r} "
                        f"which is not defined in {fp_defs_path} — a "
                        f"ghost toggle can never route a plan"
                    ),
                ))
    kernels: dict[str, int] = {}
    aot_src = files.get(aot_defs_path) if aot_defs_path else None
    if aot_src is not None:
        kernels = aot_manifest_defs(aot_src, aot_defs_path)
    if not manifests:
        return out
    import json

    # the store's own signature algorithm — byte-identical, not a copy
    from ..crypto.bls.jax_backend.aot import manifest_signature

    for display, text in manifests:
        try:
            doc = json.loads(text)
        except Exception:  # noqa: BLE001 — aot-manifest flags parse errors
            continue
        plan = doc.get("plan")
        if plan is None:
            continue  # an untuned store is fine
        if not isinstance(plan, dict):
            out.append(Violation(
                rule="tune-plan", path=display, line=0, symbol="plan",
                message="manifest plan is not a table",
            ))
            continue
        if doc.get("plan_signature") != manifest_signature(plan):
            out.append(Violation(
                rule="tune-plan", path=display, line=0,
                symbol="plan_signature",
                message=(
                    "plan table signature does not verify — tampered or "
                    "hand-edited tuned plan (prewarm would boot cold)"
                ),
            ))
        for fld in ("schema", "jax", "device_kind"):
            if fld not in plan:
                out.append(Violation(
                    rule="tune-plan", path=display, line=0,
                    symbol=f"plan.{fld}",
                    message=(
                        f"plan is missing the {fld!r} field install "
                        f"currency keys on"
                    ),
                ))
        shapes = plan.get("shapes")
        if not isinstance(shapes, dict):
            out.append(Violation(
                rule="tune-plan", path=display, line=0,
                symbol="plan.shapes",
                message="plan has no shapes table",
            ))
            continue
        for shape, entry in sorted(shapes.items()):
            sym = f"plan.shapes[{shape}]"
            if not _power_of_two_shape(shape):
                out.append(Violation(
                    rule="tune-plan", path=display, line=0, symbol=sym,
                    message=(
                        f"tuned shape {shape!r} is not a positive "
                        f"power-of-2 batch (the dispatcher never pads "
                        f"to it; warm_compile would reject it)"
                    ),
                ))
            entry = entry if isinstance(entry, dict) else {}
            arm_id = entry.get("arm")
            if arm_id not in arms:
                out.append(Violation(
                    rule="tune-plan", path=display, line=0, symbol=sym,
                    message=(
                        f"plan selects unknown arm {arm_id!r} "
                        f"(ARM_TABLE: {', '.join(sorted(arms))})"
                    ),
                ))
            elif not arms[arm_id][3]:
                out.append(Violation(
                    rule="tune-plan", path=display, line=0, symbol=sym,
                    message=(
                        f"plan selects arm {arm_id!r} which names no "
                        f"range-proof program — an unproven arm may "
                        f"never serve"
                    ),
                ))
            kern = entry.get("kernel")
            if kernels and kern not in kernels:
                out.append(Violation(
                    rule="tune-plan", path=display, line=0, symbol=sym,
                    message=(
                        f"plan entry names unregistered kernel {kern!r} "
                        f"(AOT_KERNELS: {', '.join(sorted(kernels))})"
                    ),
                ))
    return out


def integrity_defs(src: str, path: str):
    """AST-parse the verdict-integrity registries from
    ``integrity/corpus.py``: the ``CANARY_CORPUS`` assign node (the
    known-answer rows) and the ``REQUIRED_CHAOS_KINDS`` assign node (the
    silent-fault kinds the canary layer claims to defend against).
    Either is None when missing.  Pure AST — both must stay literals for
    the audit to bind, exactly like ARM_TABLE / SPANS."""
    tree = ast.parse(src, filename=path)
    corpus = kinds = None
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "CANARY_CORPUS" in names:
            corpus = node
        if "REQUIRED_CHAOS_KINDS" in names:
            kinds = node
    return corpus, kinds


def _fault_kind_defs(src: str, path: str):
    """The literal ``_KINDS`` tuple from ``utils/faults.py`` (the chaos
    kind registry), or None when missing/non-literal."""
    tree = ast.parse(src, filename=path)
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "_KINDS" in names and isinstance(
                node.value, (ast.Tuple, ast.List)):
            return [
                x.value for x in node.value.elts
                if isinstance(x, ast.Constant) and isinstance(x.value, str)
            ]
    return None


def integrity_violations(files, integrity_defs_path,
                         faults_defs_path) -> list[Violation]:
    """Verdict-integrity registry consistency (integrity/corpus.py):

    * ``CANARY_CORPUS`` must be a literal tuple of 3-constant rows
      ``(entry_id, kind, note)`` with kind in {valid, invalid}, unique
      entry ids, and at least one row of EACH kind — a corpus without an
      invalid canary can never catch a stuck-True device, and one
      without a valid canary can never catch a stuck-False one.
    * ``REQUIRED_CHAOS_KINDS`` must cross-reference the chaos kind
      registry (``_KINDS`` in utils/faults.py) both directions: every
      claimed kind must be armable, and every registered ``silent-*``
      kind must be claimed — an unclaimed silent kind is corruption the
      coverage contract silently stopped defending against.
    """
    files = dict(files)
    out: list[Violation] = []
    src = files.get(integrity_defs_path)
    if src is None:
        return out  # corpus without the integrity layer: skip the family
    corpus, kinds = integrity_defs(src, integrity_defs_path)
    if corpus is None or not isinstance(
            corpus.value, (ast.Tuple, ast.List)):
        out.append(Violation(
            rule="integrity-corpus", path=integrity_defs_path,
            line=0 if corpus is None else corpus.lineno,
            symbol="CANARY_CORPUS",
            message="CANARY_CORPUS missing or non-literal",
        ))
    else:
        seen_ids: dict[str, int] = {}
        found_kinds: set[str] = set()
        for e in corpus.value.elts:
            sym = f"CANARY_CORPUS[{len(seen_ids)}]"
            if (
                not isinstance(e, (ast.Tuple, ast.List))
                or len(e.elts) != 3
                or not all(
                    isinstance(x, ast.Constant)
                    and isinstance(x.value, str) for x in e.elts
                )
            ):
                out.append(Violation(
                    rule="integrity-corpus", path=integrity_defs_path,
                    line=e.lineno, symbol=sym,
                    message=(
                        "canary row is not a literal (entry_id, kind, "
                        "note) string triple"
                    ),
                ))
                continue
            entry_id, kind, _note = (x.value for x in e.elts)
            if kind not in ("valid", "invalid"):
                out.append(Violation(
                    rule="integrity-corpus", path=integrity_defs_path,
                    line=e.lineno, symbol=entry_id,
                    message=(
                        f"canary row {entry_id!r} has unknown kind "
                        f"{kind!r} (want valid or invalid) — the "
                        f"generator cannot materialise it"
                    ),
                ))
                continue
            if entry_id in seen_ids:
                out.append(Violation(
                    rule="integrity-corpus", path=integrity_defs_path,
                    line=e.lineno, symbol=entry_id,
                    message=(
                        f"duplicate canary entry id {entry_id!r} (first "
                        f"at line {seen_ids[entry_id]}) — ids key the "
                        f"known-answer table"
                    ),
                ))
                continue
            seen_ids[entry_id] = e.lineno
            found_kinds.add(kind)
        for want in ("valid", "invalid"):
            if seen_ids and want not in found_kinds:
                out.append(Violation(
                    rule="integrity-corpus", path=integrity_defs_path,
                    line=corpus.lineno, symbol="CANARY_CORPUS",
                    message=(
                        f"corpus has no {want!r} canary — a one-sided "
                        f"corpus cannot catch a device stuck on the "
                        f"other verdict"
                    ),
                ))
    claimed: list[tuple[str, int]] = []
    if kinds is None or not isinstance(kinds.value, (ast.Tuple, ast.List)):
        out.append(Violation(
            rule="integrity-corpus", path=integrity_defs_path,
            line=0 if kinds is None else kinds.lineno,
            symbol="REQUIRED_CHAOS_KINDS",
            message="REQUIRED_CHAOS_KINDS missing or non-literal",
        ))
    else:
        for x in kinds.value.elts:
            if isinstance(x, ast.Constant) and isinstance(x.value, str):
                claimed.append((x.value, x.lineno))
    faults_src = files.get(faults_defs_path)
    if faults_src is None or not claimed:
        return out
    registered = _fault_kind_defs(faults_src, faults_defs_path)
    if registered is None:
        return out  # the fault-site family already covers a broken defs
    for kind, line in claimed:
        if kind not in registered:
            out.append(Violation(
                rule="integrity-corpus", path=integrity_defs_path,
                line=line, symbol=kind,
                message=(
                    f"REQUIRED_CHAOS_KINDS claims {kind!r} which is not "
                    f"a registered chaos kind in {faults_defs_path} — "
                    f"the sdc scenarios could never arm it"
                ),
            ))
    claimed_set = {k for k, _ in claimed}
    for kind in registered:
        if kind.startswith("silent-") and kind not in claimed_set:
            out.append(Violation(
                rule="integrity-corpus", path=integrity_defs_path,
                line=0 if kinds is None else kinds.lineno,
                symbol=kind,
                message=(
                    f"silent-corruption kind {kind!r} is registered in "
                    f"{faults_defs_path} but not claimed by "
                    f"REQUIRED_CHAOS_KINDS — the canary coverage "
                    f"contract went stale"
                ),
            ))
    return out


def run(
    files, docs, metrics_defs_path, faults_defs_path,
    site_scan_exclude=("tests/",), spec_validator=None,
    scenarios_defs_path=None, spans_defs_path=None,
    scenario_arg_validator=None,
    search_defs_path=None, traffic_defs_path=None,
    adversity_defs_path=None, partition_defs_path=None,
    aot_defs_path=None, aot_backend_defs_path=None, aot_manifests=(),
    tune_defs_path=None, fp_defs_path=None, scenario_fixtures=(),
    integrity_defs_path=None,
) -> list[Violation]:
    files = dict(files)
    out = metrics_violations(files, metrics_defs_path, docs)
    out.extend(
        fault_site_violations(files, faults_defs_path, site_scan_exclude)
    )
    if spans_defs_path is not None and files.get(spans_defs_path) is not None:
        # absent in older fixture corpora: skip the family, don't flag it
        out.extend(
            span_violations(files, spans_defs_path, site_scan_exclude)
        )
    defs_src = files.get(faults_defs_path)
    if defs_src is not None:
        sites, prefixes = fault_site_defs(defs_src, faults_defs_path)
        out.extend(chaos_spec_violations(
            docs, set(sites), prefixes, spec_validator
        ))
    if scenarios_defs_path is not None:
        scn_src = files.get(scenarios_defs_path)
        # absent in fixture corpora: skip the family rather than flag it
        if scn_src is not None:
            known = dict(scenario_defs(scn_src, scenarios_defs_path))
            for rel, _ in scenario_fixtures:
                # committed corpus fixtures are first-class --scenario
                # names (parse_scenario_arg falls back to the corpus)
                stem = os.path.splitext(os.path.basename(rel))[0]
                known.setdefault(stem, 0)
            out.extend(scenario_spec_violations(
                docs, known,
                arg_validator=scenario_arg_validator,
            ))
        if scenario_fixtures:
            out.extend(scenario_fixture_violations(
                scenario_fixtures, scn_src, scenarios_defs_path,
                arg_validator=scenario_arg_validator,
            ))
    if search_defs_path is not None:
        out.extend(search_surface_violations(
            files, search_defs_path,
            traffic_defs_path or "lighthouse_tpu/scenario/traffic.py",
            adversity_defs_path or "lighthouse_tpu/scenario/adversity.py",
        ))
    if partition_defs_path is not None:
        out.extend(partition_rule_violations(files, partition_defs_path))
    if aot_defs_path is not None:
        out.extend(aot_manifest_violations(
            files, aot_defs_path,
            aot_backend_defs_path
            or "lighthouse_tpu/crypto/bls/jax_backend/backend.py",
            aot_manifests,
        ))
    if tune_defs_path is not None:
        out.extend(tune_plan_violations(
            files, tune_defs_path,
            fp_defs_path
            or "lighthouse_tpu/crypto/bls/jax_backend/fp.py",
            aot_defs_path, aot_manifests,
        ))
    if integrity_defs_path is not None:
        out.extend(integrity_violations(
            files, integrity_defs_path, faults_defs_path,
        ))
    out.extend(serve_port_violations(docs))
    return out
