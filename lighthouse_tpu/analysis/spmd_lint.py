"""SPMD soundness prover — the ``spmd`` audit family.

The sharded verification program (partition.py) and the pod dispatch
layer await their hardware verdict with only dynamic multi-CPU tests
behind them.  This family is the static half of that contract: it
re-stages every sharded program over a device-less
``jax.sharding.AbstractMesh``, walks the staged jaxprs with an abstract
interpreter (built on ``range_lint``'s interval arrays plus a
per-device replication lattice), and proves four theorem classes:

* **collective legality** (``spmd-collective``) — every ``psum`` /
  ``all_gather`` / ``ppermute`` / ``all_to_all`` names a mesh axis in
  the declared registry (``mesh.BATCH_AXIS`` or the defs module's
  ``DECLARED_AXES``), and no collective executes under a shard-varying
  conditional, where the shards would disagree about whether to enter
  the rendezvous and deadlock or desync.
* **replication soundness** (``spmd-replication``) — a
  version-independent ``check_rep``: each value carries the set of
  device offsets it can depend on.  ``axis_index`` taints; ``psum`` /
  full-group ``all_gather`` restore invariance; a uniform-ring
  ``ppermute`` shifts the offset set, and a commutative combine whose
  offsets cover the whole axis promotes back to invariant — so the
  n-1-hop ``ring_reduce`` proves replicated even though jax's own
  ``check_vma`` cannot see it (the documented gap in multichip.py).
  An ``out_specs`` that claims replication for a value still inferred
  shard-varying is a finding: the pod's "first answer wins" read of
  the verdict vector would be unsound.
* **pad absorption / gather bounds** (``spmd-pad`` / ``spmd-bounds``)
  — pad lanes are proved to be *duplicates of a real column* by
  provenance: each real input column is seeded with a distinct marker
  interval and every pad column of the output must carry exactly some
  real column's marker (a zero- or mean-filled pad fails).  The
  verdict reduction's backward slice must be idempotent-combine only
  (AND/OR/min/max — a sum or product would double-count duplicated
  lanes).  Interval analysis with branch-constraint refinement proves
  masked ``take`` indices in the registry gather stay inside the local
  shard for every width x batch shape, including non-divisible
  remainders, and that ``dynamic_slice`` starts can never hit XLA's
  silent runtime clamp.
* **donation discipline** (``spmd-donate``) — an AST lint over the
  scanned corpus: ``donate_argnums`` must be an empty literal or
  assigned under a TPU-backend guard (the backend's dispatch contract
  — CPU/GPU test paths must never donate live buffers), and a buffer
  passed to a donating kernel must not be read again in the same
  function.

``spmd-interp`` reports analysis-infrastructure failures (a program
that fails to trace, an unreadable defs module) so they can never pass
silently.  Like the range family, per-program verdicts are cached in
``.range_proof_cache.json`` under the family's own ``spmd_fingerprint``
(the range fingerprint — which covers partition.py/mesh.py — extended
with this module), and fixture corpora are never cached.
"""

from __future__ import annotations

import ast
import importlib.util
import json
import os

import numpy as np

from .range_lint import (
    IV,
    _SAT,
    _aval_shape,
    _dtype_range,
    _eqn_src as _eqn_src_abs,
    _is_literal,
    iv_add,
    iv_mul,
    iv_sub,
)
from .report import Violation

RULE_COLLECTIVE = "spmd-collective"
RULE_REP = "spmd-replication"
RULE_PAD = "spmd-pad"
RULE_BOUNDS = "spmd-bounds"
RULE_DONATE = "spmd-donate"
RULE_INTERP = "spmd-interp"

MAX_FINDINGS_PER_PROGRAM = 16
_SCAN_ITERS = 16     # scan/while carry fixpoint cap before widening
_MARK_SHIFT = 8      # pad-provenance marker for column j is 1 << (j + 8)

_COLLECTIVES = {
    "psum", "pmax", "pmin", "all_gather", "ppermute", "pshuffle",
    "all_to_all", "reduce_scatter", "psum_invariant", "pbroadcast",
}
# verdict-path reductions that are NOT idempotent over duplicated pad
# lanes: a pad column contributing to one of these double-counts
_NON_IDEMPOTENT = {
    "reduce_sum", "reduce_prod", "cumsum", "cumprod", "dot_general",
    "cumlogsumexp",
}
# elementwise combines that commute, so "depends on every offset the
# same way" promotes a full-coverage offset set back to invariant
_COMMUTATIVE = {"add", "mul", "and", "or", "xor", "min", "max"}


def _eqn_src(eqn):
    """Basename (source hint, line) — keeps finding symbols stable
    across checkouts (the raw jax frame path is absolute)."""
    fname, line = _eqn_src_abs(eqn)
    return (os.path.basename(fname) if fname else fname), line


def _axis_names(params):
    """Flat tuple of axis names from a collective's params.

    psum-style primitives carry ``axes`` (already a flat tuple);
    all_gather/ppermute/axis_index carry ``axis_name``, which jax may
    store either as a bare string or as a one-tuple like ``('cols',)``.
    """
    axes = params.get("axes")
    if axes is None:
        axes = params.get("axis_name")
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    flat = []
    for ax in axes:
        if isinstance(ax, (tuple, list)):
            flat.extend(ax)
        else:
            flat.append(ax)
    return tuple(flat)


# ---------------------------------------------------------------------------
# Abstract values: interval x replication offsets x identity taint
# ---------------------------------------------------------------------------


class AV:
    """Abstract value for one jaxpr var.

    ``iv``     per-element integer interval (None: non-integer/unknown)
    ``off``    device offsets (relative shard indices, mod width) the
               value can depend on; ``None`` means axis-invariant —
               provably identical on every shard
    ``taint``  depends on device *identity* (``axis_index``), which no
               offset-coverage argument can wash out
    """

    __slots__ = ("iv", "off", "taint")

    def __init__(self, iv=None, off=None, taint=False):
        self.iv = iv
        self.off = off
        self.taint = bool(taint)

    @property
    def varying(self) -> bool:
        return self.taint or self.off is not None

    def same(self, other) -> bool:
        if (self.iv is None) != (other.iv is None):
            return False
        if self.iv is not None and not (
            np.array_equal(self.iv.lo, other.iv.lo)
            and np.array_equal(self.iv.hi, other.iv.hi)
        ):
            return False
        return self.off == other.off and self.taint == other.taint


def _aval_iv(aval):
    rng = _dtype_range(aval)
    if rng is None:
        return None
    return IV.full(_aval_shape(aval), rng[0], rng[1])


def _join_av(a: AV, b: AV) -> AV:
    if a.iv is None or b.iv is None:
        iv = None
    else:
        iv = a.iv.join(b.iv)
    if a.off is None and b.off is None:
        off = None
    else:
        off = frozenset(a.off or ()) | frozenset(b.off or ())
    return AV(iv, off, a.taint or b.taint)


def _mix_off(ins):
    """Offset-set/taint of an elementwise combination of ``ins``."""
    offs = [a.off for a in ins if a.off is not None]
    taint = any(a.taint for a in ins)
    if not offs:
        return None, taint
    u = frozenset()
    for o in offs:
        u = u | o
    return u, taint


# ---------------------------------------------------------------------------
# Program registry
# ---------------------------------------------------------------------------


class SpmdProgram:
    """One proof obligation over a staged sharded program.

    ``build()`` returns ``(fn, example_args)``; the program is traced
    with ``jax.make_jaxpr(fn)(*example_args)``.

    ``kind="mesh"`` programs must stage at least one ``shard_map``;
    every interior is walked for all four theorem classes.  ``domains``
    optionally maps shard_map operand position -> ``(lo, hi)`` input
    interval (e.g. the slot vector's validator-slot domain).

    ``kind="pad"`` programs take one integer array whose trailing axis
    is ``n_real`` real columns and produce an array with extra pad
    columns; provenance marking proves every pad column duplicates a
    real one.  ``combine`` names a reduction primitive expected on the
    verdict path (fixtures use it to seed non-idempotent shapes).
    """

    __slots__ = ("name", "path", "build", "kind", "domains", "n_real",
                 "axis", "note")

    def __init__(self, name, path, build, kind="mesh", domains=None,
                 n_real=0, axis="batch", note=""):
        self.name = name
        self.path = path
        self.build = build
        self.kind = kind
        self.domains = dict(domains or {})
        self.n_real = int(n_real)
        self.axis = axis
        self.note = note


def trace_mesh(axes):
    """A device-less mesh over ``axes`` (name -> size) that shard_map
    programs can be staged over with ``jax.make_jaxpr`` — no physical
    devices are touched, so any width is analyzable anywhere."""
    from jax.sharding import AbstractMesh

    return AbstractMesh(tuple((str(k), int(v)) for k, v in axes))


# ---------------------------------------------------------------------------
# Finding collection
# ---------------------------------------------------------------------------


class _Findings:
    def __init__(self, program: SpmdProgram):
        self.program = program
        self.seen: set = set()
        self.out: list = []

    def add(self, rule: str, symbol: str, message: str, line: int = 0):
        key = (rule, symbol, message)
        if key in self.seen or len(self.out) >= MAX_FINDINGS_PER_PROGRAM:
            return
        self.seen.add(key)
        self.out.append(Violation(
            rule=rule, path=self.program.path, line=line,
            symbol=f"{self.program.name}:{symbol}", message=message,
        ))


# ---------------------------------------------------------------------------
# The SPMD abstract interpreter
# ---------------------------------------------------------------------------


class _Interp:
    """One shard_map interior: interval + replication walk."""

    def __init__(self, program: SpmdProgram, findings: _Findings,
                 axis_sizes: dict, declared: set):
        self.program = program
        self.findings = findings
        self.axis_sizes = dict(axis_sizes)   # mesh axis -> size
        self.declared = set(declared)
        self.width = int(axis_sizes.get(program.axis, 1))
        self.diverging = 0   # >0: under a shard-varying conditional
        # bool var -> (true_map, false_map); each maps var -> (lo, hi)
        self.cons: dict = {}

    # -- eqn walk ------------------------------------------------------------

    def run_jaxpr(self, jaxpr, const_avs, in_avs):
        env: dict = {}

        def write(var, av):
            if type(var).__name__ == "DropVar":
                return
            env[var] = av

        def read(atom):
            if _is_literal(atom):
                return AV(IV.const(np.asarray(atom.val))
                          if np.issubdtype(np.asarray(atom.val).dtype,
                                           np.number)
                          or np.asarray(atom.val).dtype == np.bool_
                          else None)
            return env[atom]

        for var, av in zip(jaxpr.constvars, const_avs):
            write(var, av)
        for var, av in zip(jaxpr.invars, in_avs):
            write(var, av)
        for eqn in jaxpr.eqns:
            ins = [read(a) for a in eqn.invars]
            outs = self.eval_eqn(eqn, ins, env)
            for var, av in zip(eqn.outvars, outs):
                write(var, av)
        return [read(v) for v in jaxpr.outvars]

    def run_closed(self, closed, in_avs):
        consts = [AV(IV.const(np.asarray(c)))
                  if _np_intlike(c) else AV()
                  for c in closed.consts]
        return self.run_jaxpr(closed.jaxpr, consts, in_avs)

    def eval_eqn(self, eqn, ins, env):
        name = eqn.primitive.name
        if name in _COLLECTIVES:
            return self._collective(eqn, ins)
        handler = getattr(self, "_h_" + name, None)
        if handler is not None:
            return handler(eqn, ins, env)
        sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if sub is not None:   # pjit / closed_call / custom_* / remat
            self._import_cons(eqn, sub.jaxpr if hasattr(sub, "consts")
                              else sub)
            if hasattr(sub, "consts"):
                return self.run_closed(sub, ins)
            return self.run_jaxpr(sub, [], ins)
        return self.default(eqn, ins)

    def _import_cons(self, eqn, sub_jaxpr):
        """Carry var-vs-const bound maps across a call boundary: an
        outer operand's constraint entry is re-keyed onto the callee
        invars (``jnp.where`` lowers as a pjit, so the `hit` mask and
        the `rel` index it bounds both cross one)."""
        pos = {a: i for i, a in enumerate(eqn.invars)
               if not _is_literal(a)}
        inner = list(sub_jaxpr.invars)
        for atom, i in pos.items():
            maps = self.cons.get(atom)
            if maps is None or i >= len(inner):
                continue
            translated = []
            for m in maps:
                tm = {}
                for var, bound in m.items():
                    j = pos.get(var)
                    if j is not None and j < len(inner):
                        tm[inner[j]] = bound
                translated.append(tm)
            if any(translated):
                self.cons[inner[i]] = tuple(translated)

    def default(self, eqn, ins):
        off, taint = _mix_off(ins)
        outs = []
        for var in eqn.outvars:
            iv = _elementwise_iv(eqn.primitive.name, ins, var.aval)
            av = AV(iv, off, taint)
            self._promote(eqn.primitive.name, av)
            outs.append(av)
        return outs

    def _promote(self, prim: str, av: AV) -> None:
        # a commutative combine whose offset set covers the whole axis
        # depends on every shard symmetrically -> invariant again (the
        # ring_reduce theorem jax's check_vma cannot express)
        if (av.off is not None and not av.taint and self.width > 1
                and prim in _COMMUTATIVE
                and av.off >= frozenset(range(self.width))):
            av.off = None

    # -- collectives ---------------------------------------------------------

    def _collective(self, eqn, ins):
        name = eqn.primitive.name
        axes = _axis_names(eqn.params)
        fname, line = _eqn_src(eqn)
        for ax in axes:
            if isinstance(ax, str) and ax not in self.declared:
                self.findings.add(
                    RULE_COLLECTIVE, f"{name}@{ax}",
                    f"collective `{name}` names mesh axis {ax!r} which is"
                    f" not in the declared axis registry"
                    f" {sorted(self.declared)} ({fname}:{line})",
                    line,
                )
        if self.diverging:
            self.findings.add(
                RULE_COLLECTIVE, f"{name}:diverging",
                f"collective `{name}` executes under a shard-varying"
                f" conditional: shards can disagree about reaching this"
                f" rendezvous ({fname}:{line})",
                line,
            )
        groups = eqn.params.get("axis_index_groups")
        if name in ("psum", "pmax", "pmin", "psum_invariant"):
            n = 1
            for ax in axes:
                n *= int(self.axis_sizes.get(ax, 1))
            outs = []
            for var, a in zip(eqn.outvars, ins):
                if a.iv is not None and name in ("psum", "psum_invariant"):
                    iv = IV(np.clip(a.iv.lo * n, -_SAT, _SAT),
                            np.clip(a.iv.hi * n, -_SAT, _SAT))
                elif a.iv is not None:
                    iv = IV.full(_aval_shape(var.aval), a.iv.min_lo(),
                                 a.iv.max_hi())
                else:
                    iv = None
                # a full-group reduction is identical on every member
                outs.append(AV(iv, None if groups is None else
                              frozenset({0}),
                              a.taint and groups is not None))
            return outs
        if name == "all_gather":
            a = ins[0]
            var = eqn.outvars[0]
            iv = (IV.full(_aval_shape(var.aval), a.iv.min_lo(),
                          a.iv.max_hi()) if a.iv is not None else None)
            if groups is None:
                return [AV(iv, None, False)]
            return [AV(iv, frozenset({0}), a.taint)]
        if name == "ppermute":
            return [self._ppermute(eqn, a) for a in ins]
        # all_to_all / pshuffle / anything else: data crosses shards in
        # a layout we don't model — varying, identity-tainted
        return [AV(_aval_iv(v.aval), frozenset({0}), True)
                for v in eqn.outvars]

    def _ppermute(self, eqn, a: AV) -> AV:
        perm = eqn.params.get("perm") or ()
        axes = _axis_names(eqn.params)
        w = 1
        for ax in axes:
            w *= int(self.axis_sizes.get(ax, 1))
        shift = None
        if len(perm) == w and w > 0:
            shifts = {(dst - src) % w for src, dst in perm}
            if len(shifts) == 1:
                shift = next(iter(shifts))
        if shift is None or a.taint:
            # partial / non-uniform permutation: receiver-dependent data
            return AV(a.iv, frozenset({0}), True)
        off = a.off if a.off is not None else frozenset({0})
        return AV(a.iv, frozenset((o + shift) % w for o in off), False)

    # -- device identity ------------------------------------------------------

    def _h_axis_index(self, eqn, ins, env):
        names = _axis_names(eqn.params)
        ax = names[0] if names else None
        w = int(self.axis_sizes.get(ax, self.width))
        fname, line = _eqn_src(eqn)
        if isinstance(ax, str) and ax not in self.declared:
            self.findings.add(
                RULE_COLLECTIVE, f"axis_index@{ax}",
                f"`axis_index` names mesh axis {ax!r} outside the"
                f" declared registry {sorted(self.declared)}"
                f" ({fname}:{line})",
                line,
            )
        return [AV(IV.full((), 0, max(0, w - 1)), frozenset({0}), True)]

    # -- structured control flow ---------------------------------------------

    def _h_cond(self, eqn, ins, env):
        pred, ops = ins[0], ins[1:]
        branches = eqn.params["branches"]
        if pred.varying:
            # interior collectives fire spmd-collective via the
            # diverging counter as each branch is walked below
            self.diverging += 1
        branch_outs = []
        for br in branches:
            branch_outs.append(self.run_closed(br, list(ops)))
        if pred.varying:
            self.diverging -= 1
        outs = branch_outs[0]
        for bo in branch_outs[1:]:
            outs = [_join_av(a, b) for a, b in zip(outs, bo)]
        if pred.varying:
            outs = [AV(a.iv,
                       frozenset(a.off or ()) | frozenset(pred.off or ()),
                       a.taint or pred.taint) for a in outs]
        return outs

    def _h_while(self, eqn, ins, env):
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        cond_consts = ins[:cn]
        body_consts = ins[cn:cn + bn]
        carry = list(ins[cn + bn:])
        body = eqn.params["body_jaxpr"]
        cond = eqn.params["cond_jaxpr"]
        for it in range(_SCAN_ITERS):
            pred = self.run_closed(cond, cond_consts + carry)[0]
            if pred.varying:
                self.diverging += 1
            nxt = self.run_closed(body, body_consts + carry)
            if pred.varying:
                self.diverging -= 1
            joined = [_join_av(c, n) for c, n in zip(carry, nxt)]
            if all(a.same(b) for a, b in zip(joined, carry)):
                carry = joined
                break
            carry = joined
        else:
            carry = [AV(_aval_iv(v.aval), a.off, a.taint)
                     for v, a in zip(eqn.outvars, carry)]
        return carry

    def _h_scan(self, eqn, ins, env):
        nc = int(eqn.params.get("num_consts", 0))
        ncar = int(eqn.params.get("num_carry", 0))
        length = int(eqn.params.get("length", 0) or 0)
        body = eqn.params["jaxpr"]
        consts = ins[:nc]
        carry = list(ins[nc:nc + ncar])
        xs = ins[nc + ncar:]
        # per-iteration slice of xs: leading axis dropped, aggregate iv
        xslices = []
        for a, var in zip(xs, eqn.invars[nc + ncar:]):
            shape = _aval_shape(var.aval)[1:]
            iv = (IV.full(shape, a.iv.min_lo(), a.iv.max_hi())
                  if a.iv is not None else None)
            xslices.append(AV(iv, a.off, a.taint))
        ys_avs = None
        for it in range(_SCAN_ITERS):
            outs = self.run_closed(body, consts + carry + xslices)
            new_carry = [_join_av(c, n)
                         for c, n in zip(carry, outs[:ncar])]
            ys_avs = outs[ncar:]
            if all(a.same(b) for a, b in zip(new_carry, carry)):
                carry = new_carry
                break
            carry = new_carry
        else:
            carry = [AV(_aval_iv(v.aval), a.off, a.taint)
                     for v, a in zip(eqn.outvars[:ncar], carry)]
        ys = []
        for var, a in zip(eqn.outvars[ncar:], ys_avs or []):
            iv = (IV.full(_aval_shape(var.aval), a.iv.min_lo(),
                          a.iv.max_hi()) if a.iv is not None else None)
            ys.append(AV(iv, a.off, a.taint))
        return carry + ys

    # -- structural primitives (exact, needed by pad provenance) -------------

    def _h_reshape(self, eqn, ins, env):
        a = ins[0]
        shape = _aval_shape(eqn.outvars[0].aval)
        iv = (IV(a.iv.lo.reshape(shape), a.iv.hi.reshape(shape))
              if a.iv is not None else None)
        return [AV(iv, a.off, a.taint)]

    def _h_squeeze(self, eqn, ins, env):
        return self._h_reshape(eqn, ins, env)

    def _h_expand_dims(self, eqn, ins, env):
        return self._h_reshape(eqn, ins, env)

    def _h_broadcast_in_dim(self, eqn, ins, env):
        a = ins[0]
        shape = tuple(eqn.params["shape"])
        bdims = tuple(eqn.params["broadcast_dimensions"])
        if a.iv is None:
            return [AV(None, a.off, a.taint)]
        src = [1] * len(shape)
        for i, d in enumerate(bdims):
            src[d] = a.iv.lo.shape[i]
        lo = np.broadcast_to(a.iv.lo.reshape(src), shape).copy()
        hi = np.broadcast_to(a.iv.hi.reshape(src), shape).copy()
        return [AV(IV(lo, hi), a.off, a.taint)]

    def _h_transpose(self, eqn, ins, env):
        a = ins[0]
        perm = tuple(eqn.params["permutation"])
        iv = (IV(np.transpose(a.iv.lo, perm), np.transpose(a.iv.hi, perm))
              if a.iv is not None else None)
        return [AV(iv, a.off, a.taint)]

    def _h_slice(self, eqn, ins, env):
        a = ins[0]
        if a.iv is None:
            return [AV(None, a.off, a.taint)]
        idx = tuple(
            slice(s, l, st) for s, l, st in zip(
                eqn.params["start_indices"], eqn.params["limit_indices"],
                eqn.params.get("strides") or
                [1] * len(eqn.params["start_indices"]),
            )
        )
        return [AV(IV(a.iv.lo[idx].copy(), a.iv.hi[idx].copy()),
                   a.off, a.taint)]

    def _h_concatenate(self, eqn, ins, env):
        dim = int(eqn.params["dimension"])
        off, taint = _mix_off(ins)
        if any(a.iv is None for a in ins):
            return [AV(None, off, taint)]
        lo = np.concatenate([a.iv.lo for a in ins], axis=dim)
        hi = np.concatenate([a.iv.hi for a in ins], axis=dim)
        return [AV(IV(lo, hi), off, taint)]

    def _h_iota(self, eqn, ins, env):
        shape = _aval_shape(eqn.outvars[0].aval)
        dim = int(eqn.params["dimension"])
        vals = np.arange(shape[dim], dtype=np.int64)
        vals = vals.reshape([-1 if i == dim else 1
                             for i in range(len(shape))])
        vals = np.broadcast_to(vals, shape).copy()
        return [AV(IV(vals, vals.copy()))]

    def _h_convert_element_type(self, eqn, ins, env):
        a = ins[0]
        rng = _dtype_range(eqn.outvars[0].aval)
        if a.iv is None or rng is None:
            return [AV(_aval_iv(eqn.outvars[0].aval), a.off, a.taint)]
        return [AV(a.iv.clamp(rng[0], rng[1]), a.off, a.taint)]

    def _h_stop_gradient(self, eqn, ins, env):
        return [ins[0]]

    def _h_copy(self, eqn, ins, env):
        return [ins[0]]

    # -- arithmetic / comparisons with constraint recording ------------------

    def _binop(self, eqn, ins, fn):
        a, b = ins
        off, taint = _mix_off(ins)
        iv = fn(a.iv, b.iv) if (a.iv is not None and b.iv is not None) \
            else _aval_iv(eqn.outvars[0].aval)
        av = AV(iv, off, taint)
        self._promote(eqn.primitive.name, av)
        return [av]

    def _h_add(self, eqn, ins, env):
        return self._binop(eqn, ins, iv_add)

    def _h_sub(self, eqn, ins, env):
        return self._binop(eqn, ins, iv_sub)

    def _h_mul(self, eqn, ins, env):
        return self._binop(eqn, ins, iv_mul)

    def _h_max(self, eqn, ins, env):
        return self._binop(eqn, ins, lambda x, y: IV(
            np.maximum(x.lo, y.lo), np.maximum(x.hi, y.hi)))

    def _h_min(self, eqn, ins, env):
        return self._binop(eqn, ins, lambda x, y: IV(
            np.minimum(x.lo, y.lo), np.minimum(x.hi, y.hi)))

    def _cmp(self, eqn, ins, env, op):
        a, b = ins
        off, taint = _mix_off(ins)
        out = eqn.outvars[0]
        iv = IV.full(_aval_shape(out.aval), 0, 1)
        if a.iv is not None and b.iv is not None:
            always, never = _cmp_fold(op, a.iv, b.iv)
            if always:
                iv = IV.full(_aval_shape(out.aval), 1, 1)
            elif never:
                iv = IV.full(_aval_shape(out.aval), 0, 0)
        self._record_cmp(eqn, op, env)
        return [AV(iv, off, taint)]

    def _h_ge(self, eqn, ins, env):
        return self._cmp(eqn, ins, env, "ge")

    def _h_gt(self, eqn, ins, env):
        return self._cmp(eqn, ins, env, "gt")

    def _h_le(self, eqn, ins, env):
        return self._cmp(eqn, ins, env, "le")

    def _h_lt(self, eqn, ins, env):
        return self._cmp(eqn, ins, env, "lt")

    def _h_eq(self, eqn, ins, env):
        off, taint = _mix_off(ins)
        return [AV(IV.full(_aval_shape(eqn.outvars[0].aval), 0, 1),
                   off, taint)]

    def _h_ne(self, eqn, ins, env):
        return self._h_eq(eqn, ins, env)

    def _record_cmp(self, eqn, op, env):
        """var-vs-constant comparison -> (true, false) bound maps."""
        x, y = eqn.invars
        var, const, flipped = None, None, False
        if not _is_literal(x) and _const_scalar(y, env) is not None:
            var, const = x, _const_scalar(y, env)
        elif not _is_literal(y) and _const_scalar(x, env) is not None:
            var, const, flipped = y, _const_scalar(x, env), True
        if var is None:
            return
        if flipped:   # const OP var  ->  var FLIP(OP) const
            op = {"ge": "le", "gt": "lt", "le": "ge", "lt": "gt"}[op]
        c = int(const)
        bounds = {
            "ge": ((c, _SAT), (-_SAT, c - 1)),
            "gt": ((c + 1, _SAT), (-_SAT, c)),
            "le": ((-_SAT, c), (c + 1, _SAT)),
            "lt": ((-_SAT, c - 1), (c, _SAT)),
        }[op]
        self.cons[eqn.outvars[0]] = (
            {var: bounds[0]}, {var: bounds[1]}
        )

    def _h_and(self, eqn, ins, env):
        out = self._binop(eqn, ins, lambda x, y: IV(
            np.minimum(x.lo, y.lo) * 0,
            np.minimum(x.hi, y.hi),
        ) if (x.lo >= 0).all() and (y.lo >= 0).all()
            else _aval_iv(eqn.outvars[0].aval))
        # conjunction of constraints: both operands' true-maps hold
        tmap: dict = {}
        for a in eqn.invars:
            maps = self.cons.get(a)
            if maps:
                for v, (lo, hi) in maps[0].items():
                    plo, phi = tmap.get(v, (-_SAT, _SAT))
                    tmap[v] = (max(plo, lo), min(phi, hi))
        if tmap:
            self.cons[eqn.outvars[0]] = (tmap, {})
        return out

    def _h_or(self, eqn, ins, env):
        return self.default(eqn, ins)

    def _h_xor(self, eqn, ins, env):
        return self.default(eqn, ins)

    def _h_not(self, eqn, ins, env):
        a = ins[0]
        iv = (IV(1 - a.iv.hi, 1 - a.iv.lo)
              if a.iv is not None else
              IV.full(_aval_shape(eqn.outvars[0].aval), 0, 1))
        maps = self.cons.get(eqn.invars[0])
        if maps:
            self.cons[eqn.outvars[0]] = (maps[1], maps[0])
        return [AV(iv, a.off, a.taint)]

    def _h_select_n(self, eqn, ins, env):
        pred, cases = ins[0], ins[1:]
        out_shape = _aval_shape(eqn.outvars[0].aval)
        if pred.iv is not None and len(cases) == 2:
            if pred.iv.max_hi() == 0:
                chosen = [cases[0]]
            elif pred.iv.min_lo() == 1:
                chosen = [cases[1]]
            else:
                chosen = None
        else:
            chosen = None
        if chosen is None:
            maps = self.cons.get(eqn.invars[0]) if len(cases) == 2 \
                else None
            refined = []
            for i, c in enumerate(cases):
                av = c
                if maps is not None:
                    bound = (maps[1] if i == 0 else maps[0]).get(
                        eqn.invars[1 + i])
                    if bound is not None and av.iv is not None:
                        av = AV(av.iv.clamp(bound[0], bound[1]),
                                av.off, av.taint)
                refined.append(av)
            joined = refined[0]
            for av in refined[1:]:
                joined = _join_av(joined, av)
            off = frozenset(joined.off or ()) | frozenset(pred.off or ())
            chosen = [AV(joined.iv,
                         off if (joined.off is not None
                                 or pred.off is not None) else None,
                         joined.taint or pred.taint)]
        av = chosen[0]
        if av.iv is not None and av.iv.shape != out_shape:
            av = AV(av.iv.broadcast(out_shape), av.off, av.taint)
        return [av]

    # -- reductions -----------------------------------------------------------

    def _reduce(self, eqn, ins, np_fn, scale=False):
        a = ins[0]
        axes = tuple(eqn.params.get("axes", ()))
        out_shape = _aval_shape(eqn.outvars[0].aval)
        if a.iv is None:
            return [AV(_aval_iv(eqn.outvars[0].aval), a.off, a.taint)]
        lo = np_fn(a.iv.lo, axis=axes) if axes else np_fn(a.iv.lo)
        hi = np_fn(a.iv.hi, axis=axes) if axes else np_fn(a.iv.hi)
        lo = np.clip(np.asarray(lo, dtype=np.float64), -_SAT, _SAT)
        hi = np.clip(np.asarray(hi, dtype=np.float64), -_SAT, _SAT)
        iv = IV(lo.reshape(out_shape).astype(np.int64),
                hi.reshape(out_shape).astype(np.int64))
        return [AV(iv, a.off, a.taint)]

    def _h_reduce_and(self, eqn, ins, env):
        return self._reduce(eqn, ins, np.min)

    def _h_reduce_or(self, eqn, ins, env):
        return self._reduce(eqn, ins, np.max)

    def _h_reduce_min(self, eqn, ins, env):
        return self._reduce(eqn, ins, np.min)

    def _h_reduce_max(self, eqn, ins, env):
        return self._reduce(eqn, ins, np.max)

    def _h_reduce_sum(self, eqn, ins, env):
        return self._reduce(eqn, ins, np.sum)

    def _h_reduce_prod(self, eqn, ins, env):
        a = ins[0]
        return [AV(_aval_iv(eqn.outvars[0].aval), a.off, a.taint)]

    # -- indexing: the bounds theorems ----------------------------------------

    def _h_gather(self, eqn, ins, env):
        operand, indices = ins[0], ins[1]
        dnums = eqn.params["dimension_numbers"]
        slice_sizes = tuple(eqn.params["slice_sizes"])
        op_shape = _aval_shape(eqn.invars[0].aval)
        fname, line = _eqn_src(eqn)
        if indices.iv is not None:
            lo, hi = indices.iv.min_lo(), indices.iv.max_hi()
            for d in dnums.start_index_map:
                limit = op_shape[d] - slice_sizes[d]
                if lo < 0 or hi > limit:
                    self.findings.add(
                        RULE_BOUNDS, f"gather@{fname}:{line}",
                        f"gather index interval [{lo}, {hi}] escapes the"
                        f" local shard bound [0, {limit}] on operand dim"
                        f" {d} (shape {op_shape}, slice {slice_sizes})"
                        f" — out-of-shard slots must be masked before"
                        f" the take ({fname}:{line})",
                        line,
                    )
                    break
        else:
            self.findings.add(
                RULE_BOUNDS, f"gather@{fname}:{line}",
                f"gather indices carry no provable interval; shard-"
                f"bounds theorem fails open ({fname}:{line})",
                line,
            )
        off, taint = _mix_off(ins)
        out = eqn.outvars[0]
        iv = (IV.full(_aval_shape(out.aval), operand.iv.min_lo(),
                      operand.iv.max_hi())
              if operand.iv is not None else None)
        return [AV(iv, off, taint)]

    def _h_dynamic_slice(self, eqn, ins, env):
        operand, starts = ins[0], ins[1:]
        op_shape = _aval_shape(eqn.invars[0].aval)
        slice_sizes = tuple(eqn.params["slice_sizes"])
        fname, line = _eqn_src(eqn)
        for d, s in enumerate(starts):
            limit = op_shape[d] - slice_sizes[d]
            if s.iv is None:
                self.findings.add(
                    RULE_BOUNDS, f"dynamic_slice@{fname}:{line}",
                    f"dynamic_slice start on dim {d} carries no provable"
                    f" interval ({fname}:{line})",
                    line,
                )
                continue
            lo, hi = s.iv.min_lo(), s.iv.max_hi()
            if lo < 0 or hi > limit:
                self.findings.add(
                    RULE_BOUNDS, f"dynamic_slice@{fname}:{line}",
                    f"dynamic_slice start interval [{lo}, {hi}] on dim"
                    f" {d} escapes [0, {limit}] (shape {op_shape}, slice"
                    f" {slice_sizes}): XLA clamps silently, shifting the"
                    f" window to the wrong columns ({fname}:{line})",
                    line,
                )
        off, taint = _mix_off(ins)
        out = eqn.outvars[0]
        iv = (IV.full(_aval_shape(out.aval), operand.iv.min_lo(),
                      operand.iv.max_hi())
              if operand.iv is not None else None)
        return [AV(iv, off, taint)]


def _cmp_fold(op, a: IV, b: IV):
    """(always_true, always_false) for an aggregate comparison."""
    if op == "ge":
        return a.min_lo() >= b.max_hi(), a.max_hi() < b.min_lo()
    if op == "gt":
        return a.min_lo() > b.max_hi(), a.max_hi() <= b.min_lo()
    if op == "le":
        return a.max_hi() <= b.min_lo(), a.min_lo() > b.max_hi()
    return a.max_hi() < b.min_lo(), a.min_lo() >= b.max_hi()


def _const_scalar(atom, env):
    if _is_literal(atom):
        v = np.asarray(atom.val)
        if v.size == 1:
            return float(v.reshape(()))
        return None
    av = env.get(atom)
    if av is not None and av.iv is not None and av.iv.lo.size == 1 \
            and av.iv.lo.reshape(()) == av.iv.hi.reshape(()):
        return float(av.iv.lo.reshape(()))
    return None


def _elementwise_iv(prim, ins, out_aval):
    ivs = [a.iv for a in ins if a.iv is not None]
    rng = _dtype_range(out_aval)
    if rng is None:
        return None
    if prim in ("and", "or", "not", "xor") and \
            np.dtype(out_aval.dtype).name == "bool":
        return IV.full(_aval_shape(out_aval), 0, 1)
    if len(ivs) == len(ins) and ivs:
        lo = min(iv.min_lo() for iv in ivs)
        hi = max(iv.max_hi() for iv in ivs)
        if lo >= rng[0] and hi <= rng[1] and prim in (
                "neg", "abs", "rem", "clamp", "rev", "pad"):
            return IV.full(_aval_shape(out_aval), rng[0], rng[1])
    return IV.full(_aval_shape(out_aval), rng[0], rng[1])


def _np_intlike(c) -> bool:
    arr = np.asarray(c)
    return arr.dtype == np.bool_ or np.issubdtype(arr.dtype, np.integer)


def _sub_jaxprs(v):
    out = []
    stack = [v]
    while stack:
        x = stack.pop()
        if hasattr(x, "jaxpr") and hasattr(x, "consts"):
            out.append(x.jaxpr)
        elif hasattr(x, "eqns"):
            out.append(x)
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
    return out


# ---------------------------------------------------------------------------
# Program drivers
# ---------------------------------------------------------------------------


def _find_shard_maps(jaxpr, out=None):
    if out is None:
        out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "shard_map":
            out.append(eqn)
            continue
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                _find_shard_maps(sub, out)
    return out


def _names_dict(entry):
    """Normalize one shard_map in_names/out_names entry to a dict."""
    if isinstance(entry, dict):
        return entry
    return dict(getattr(entry, "items", lambda: {})()) or {}


def _check_mesh_program(prog: SpmdProgram, closed, declared,
                        findings: _Findings) -> None:
    smaps = _find_shard_maps(closed.jaxpr)
    if not smaps:
        findings.add(
            RULE_INTERP, "no-shard-map",
            "mesh program staged no shard_map eqn — nothing to prove",
        )
        return
    for eqn in smaps:
        mesh = eqn.params.get("mesh")
        axis_sizes = {str(k): int(v)
                      for k, v in dict(getattr(mesh, "shape", {})).items()}
        inner = eqn.params["jaxpr"]
        in_names = [_names_dict(e) for e in eqn.params.get("in_names", ())]
        out_names = [_names_dict(e)
                     for e in eqn.params.get("out_names", ())]
        interp = _Interp(prog, findings, axis_sizes, declared)
        in_avs = []
        for i, var in enumerate(inner.invars):
            names = in_names[i] if i < len(in_names) else {}
            dom = prog.domains.get(i)
            if dom is not None:
                iv = IV.full(_aval_shape(var.aval), int(dom[0]),
                             int(dom[1]))
            else:
                iv = _aval_iv(var.aval)
            off = frozenset({0}) if names else None
            in_avs.append(AV(iv, off, False))
        const_avs = [AV(_aval_iv(getattr(v, "aval", None)))
                     for v in inner.constvars]
        try:
            out_avs = interp.run_jaxpr(inner, const_avs, in_avs)
        except Exception as exc:
            findings.add(
                RULE_INTERP, "walk-failed",
                f"abstract interpretation of shard_map interior failed:"
                f" {exc!r}",
            )
            continue
        for j, av in enumerate(out_avs):
            names = out_names[j] if j < len(out_names) else {}
            if not names and av.varying:
                why = ("device-identity (axis_index) dependence"
                       if av.taint else
                       f"offset set {sorted(av.off or ())} does not"
                       f" prove shard-independence")
                findings.add(
                    RULE_REP, f"out{j}",
                    f"out_specs claims output {j} replicated but the"
                    f" inferred value is shard-varying ({why}); a"
                    f" first-answer-wins read of it is unsound",
                )
        _check_combine(inner, findings)


def _check_combine(jaxpr, findings: _Findings) -> None:
    """Backward slice from the interior outputs: duplicated pad lanes
    make sum/product-style reductions double-count, so the verdict path
    must be idempotent-combine only."""
    need = {v for v in jaxpr.outvars if not _is_literal(v)}
    for eqn in reversed(jaxpr.eqns):
        if not any(v in need for v in eqn.outvars):
            continue
        for a in eqn.invars:
            if not _is_literal(a):
                need.add(a)
        if eqn.primitive.name in _NON_IDEMPOTENT:
            fname, line = _eqn_src(eqn)
            findings.add(
                RULE_PAD, f"{eqn.primitive.name}@{fname}:{line}",
                f"verdict path reduces with non-idempotent"
                f" `{eqn.primitive.name}`: duplicated pad lanes"
                f" double-count under it ({fname}:{line})",
                line,
            )


def _check_pad_program(prog: SpmdProgram, closed,
                       findings: _Findings) -> None:
    jaxpr = closed.jaxpr
    if len(jaxpr.invars) != 1:
        findings.add(RULE_INTERP, "arity",
                     "pad program must take exactly one array")
        return
    var = jaxpr.invars[0]
    shape = _aval_shape(var.aval)
    n_real = prog.n_real
    if not shape or shape[-1] != n_real:
        findings.add(
            RULE_INTERP, "shape",
            f"pad program input trailing axis {shape} != n_real"
            f" {n_real}",
        )
        return
    # provenance seed: column j carries the singleton marker 1 << (j+8)
    marks = np.array([1 << (_MARK_SHIFT + j) for j in range(n_real)],
                     dtype=np.int64)
    lo = np.broadcast_to(marks, shape).copy()
    in_av = AV(IV(lo, lo.copy()))
    interp = _Interp(prog, findings, {}, set())
    const_avs = [AV(_aval_iv(getattr(v, "aval", None)))
                 for v in jaxpr.constvars]
    try:
        out_avs = interp.run_jaxpr(jaxpr, const_avs, [in_av])
    except Exception as exc:
        findings.add(RULE_INTERP, "walk-failed",
                     f"pad provenance walk failed: {exc!r}")
        return
    av = out_avs[0]
    if av.iv is None:
        findings.add(
            RULE_PAD, "unprovable",
            "pad output carries no integer interval (a float detour —"
            " e.g. a mean fill — destroys column provenance); pad"
            " lanes cannot be proved duplicates of a real column",
        )
        return
    out_shape = av.iv.shape
    if not out_shape or out_shape[-1] < n_real:
        findings.add(RULE_INTERP, "shape",
                     f"pad output shape {out_shape} narrower than"
                     f" n_real {n_real}")
        return
    markset = {int(m) for m in marks}
    flat_lo = av.iv.lo.reshape(-1, out_shape[-1])
    flat_hi = av.iv.hi.reshape(-1, out_shape[-1])
    for j in range(n_real, out_shape[-1]):
        col_lo, col_hi = flat_lo[:, j], flat_hi[:, j]
        exact = np.array_equal(col_lo, col_hi)
        vals = set(int(v) for v in col_lo) if exact else set()
        if not exact or len(vals) != 1 or next(iter(vals)) not in markset:
            got = (f"marker {sorted(vals)}" if exact
                   else f"interval [{int(col_lo.min())},"
                        f" {int(col_hi.max())}]")
            findings.add(
                RULE_PAD, f"col{j}",
                f"pad column {j} is not a duplicate of any real column"
                f" ({got} vs real markers"
                f" [{int(marks[0])}..{int(marks[-1])}]): a non-absorbing"
                f" pad lane can flip the AND-reduction verdict",
            )


def analyze_program(prog: SpmdProgram, declared) -> list:
    import jax

    findings = _Findings(prog)
    try:
        fn, args = prog.build()
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as exc:
        findings.add(RULE_INTERP, "trace-failed",
                     f"program failed to stage: {exc!r}")
        return findings.out
    if prog.kind == "pad":
        _check_pad_program(prog, closed, findings)
    else:
        _check_mesh_program(prog, closed, declared, findings)
    return findings.out


# ---------------------------------------------------------------------------
# Donation discipline (AST, over the scanned corpus)
# ---------------------------------------------------------------------------


def _tpu_gated(node, parents) -> bool:
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, ast.If):
            try:
                if "tpu" in ast.unparse(cur.test).lower():
                    return True
            except Exception:
                pass
        cur = parents.get(id(cur))
    return False


def _donate_literal(v):
    """True: provably non-empty literal; False: provably empty;
    None: not statically known here (a Name, a call, ...)."""
    if isinstance(v, (ast.Tuple, ast.List)):
        return bool(v.elts)
    if isinstance(v, ast.Constant):
        if v.value in ((), None):
            return False
        if isinstance(v.value, int) and not isinstance(v.value, bool):
            return True
    if isinstance(v, ast.Call):
        f = v.func
        name = getattr(f, "id", getattr(f, "attr", ""))
        if name in ("tuple", "range") and not v.args:
            return False
        return None
    return None


def _donate_positions(v):
    if isinstance(v, (ast.Tuple, ast.List)):
        pos = []
        for e in v.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                pos.append(int(e.value))
            else:
                return None
        return tuple(pos)
    if isinstance(v, ast.Constant) and isinstance(v.value, int) \
            and not isinstance(v.value, bool):
        return (int(v.value),)
    return None


def donation_violations(files) -> list:
    """The spmd-donate lint over a ``[(rel_path, src)]`` corpus."""
    out: list = []
    for path, src in files:
        if "donate_argnums" not in src:
            continue
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            continue
        parents: dict = {}
        for node in ast.walk(tree):
            for ch in ast.iter_child_nodes(node):
                parents[id(ch)] = node
        funcs = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for call in ast.walk(tree):
            if not isinstance(call, ast.Call):
                continue
            kw = next((k for k in call.keywords
                       if k.arg == "donate_argnums"), None)
            if kw is None:
                continue
            lit = _donate_literal(kw.value)
            if lit is True and not _tpu_gated(call, parents):
                out.append(Violation(
                    rule=RULE_DONATE, path=path, line=call.lineno,
                    symbol="ungated-donation",
                    message=(
                        "donate_argnums is non-empty outside a TPU-"
                        "backend guard: CPU/GPU paths would donate live"
                        " buffers (the dispatch contract gates donation"
                        " on jax.default_backend() == 'tpu')"
                    ),
                ))
            elif lit is None and isinstance(kw.value, ast.Name):
                fn = _enclosing_function(call, parents)
                body = fn if fn is not None else tree
                for sub in ast.walk(body):
                    if not isinstance(sub, ast.Assign):
                        continue
                    if not any(isinstance(t, ast.Name)
                               and t.id == kw.value.id
                               for t in sub.targets):
                        continue
                    if _donate_literal(sub.value) is False:
                        continue
                    if not _tpu_gated(sub, parents):
                        out.append(Violation(
                            rule=RULE_DONATE, path=path, line=sub.lineno,
                            symbol="ungated-donation",
                            message=(
                                f"donation flag {kw.value.id!r} is"
                                f" assigned a possibly non-empty value"
                                f" outside a TPU-backend guard"
                            ),
                        ))
        for fn in funcs:
            out.extend(_read_after_donate(fn, path))
    return out


def _enclosing_function(node, parents):
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parents.get(id(cur))
    return None


def _read_after_donate(fn, path: str) -> list:
    """Within one function: ``k = jit(f, donate_argnums=(i,))`` then
    ``k(a, b)`` donates the positional args at those indices — any
    later read of those names (before reassignment) is a finding."""
    out: list = []
    jitted: dict = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            kw = next((k for k in node.value.keywords
                       if k.arg == "donate_argnums"), None)
            if kw is None:
                continue
            pos = _donate_positions(kw.value)
            if not pos:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    jitted[t.id] = pos
    if not jitted:
        return out
    donated: list = []   # (argname, donate_lineno)
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in jitted):
            continue
        for p in jitted[node.func.id]:
            if p < len(node.args) and isinstance(node.args[p], ast.Name):
                donated.append((node.args[p].id, node.lineno))
    for name, line in donated:
        stores = sorted(
            n.lineno for n in ast.walk(fn)
            if isinstance(n, ast.Name) and n.id == name
            and isinstance(n.ctx, ast.Store) and n.lineno > line
        )
        horizon = stores[0] if stores else None
        for n in ast.walk(fn):
            if (isinstance(n, ast.Name) and n.id == name
                    and isinstance(n.ctx, ast.Load)
                    and n.lineno > line
                    and (horizon is None or n.lineno < horizon)):
                out.append(Violation(
                    rule=RULE_DONATE, path=path, line=n.lineno,
                    symbol="read-after-donate",
                    message=(
                        f"buffer {name!r} is read after being donated"
                        f" to a donate_argnums kernel at line {line}:"
                        f" the backing memory may already be aliased"
                        f" by the kernel's outputs"
                    ),
                ))
                break
    return out


# ---------------------------------------------------------------------------
# Live program registry
# ---------------------------------------------------------------------------

_LIVE_PATH = "lighthouse_tpu/parallel/partition.py"
_MESH_PATH = "lighthouse_tpu/parallel/mesh.py"
_POD_PATH = "lighthouse_tpu/parallel/pod.py"

# width x raw-batch shapes: every width a pod probe uses, every batch
# non-divisible so the dup-of-column-0 remainder path is always proved
_LIVE_SHAPES = ((2, 5, 8), (4, 10, 16), (8, 13, 40))
_LIMB_ROWS = 26
_WBIT_ROWS = 64


class _StubLFp:
    """Pytree-registered stand-in for the field stack's LFp: a limb
    plane plus a static bound, shaped exactly like the marshal output
    so ``named_operand_leaves``/``program_in_specs`` see the real
    operand structure without importing field code."""

    _registered = False

    def __init__(self, limbs, bound=1):
        self.limbs = limbs
        self.bound = bound

    @classmethod
    def register(cls):
        if cls._registered:
            return
        import jax

        jax.tree_util.register_pytree_node(
            cls,
            lambda x: ((x.limbs,), x.bound),
            lambda bound, ch: cls(ch[0], bound),
        )
        cls._registered = True


def _stub_verify(pk, sig, h, wbits):
    """Stub local kernel with the real kernel's SPMD-relevant shape: a
    scan with a replicated carry init (the exact pattern jax's
    check_vma rejects — see multichip.py) folding per-column bits into
    one scalar verdict via AND."""
    import jax
    import jax.numpy as jnp

    def body(c, w):
        return c & jnp.all(w > 0), None

    ok, _ = jax.lax.scan(body, jnp.asarray(True), wbits)
    ok = ok & jnp.all(pk[0].limbs < jnp.uint32(0xFFFFFFFF))
    ok = ok & jnp.all(sig[0][0].limbs < jnp.uint32(0xFFFFFFFF))
    ok = ok & jnp.all(h[0][0].limbs < jnp.uint32(0xFFFFFFFF))
    return ok


def _flat_stub_args(b_cols: int):
    import jax.numpy as jnp

    _StubLFp.register()

    def lfp():
        return _StubLFp(jnp.zeros((_LIMB_ROWS, b_cols), jnp.uint32))

    pk = (lfp(), lfp())
    sig = ((lfp(), lfp()), (lfp(), lfp()))
    h = ((lfp(), lfp()), (lfp(), lfp()))
    wbits = jnp.zeros((_WBIT_ROWS, b_cols), jnp.uint32)
    return pk, sig, h, wbits


def build_live_programs() -> list:
    """The live proof obligations: the flat and registry staged verify
    programs at every pod shape, ring_reduce replication at every
    width, and the operand/slot pad constructors."""
    from ..parallel import mesh as M
    from ..parallel import partition as P

    programs: list = []
    for width, b_raw, n_total in _LIVE_SHAPES:
        b_pad = b_raw + ((-b_raw) % width)
        n_local = n_total // width

        def mk_flat(width=width, b_pad=b_pad):
            def build():
                amesh = trace_mesh((("batch", width),))
                args = _flat_stub_args(b_pad)
                local = P.staged_local(_stub_verify, axis="batch")
                specs = P.program_in_specs(args, deferred_pk=False)
                fn = M.compat_shard_map(local, amesh, in_specs=specs,
                                        out_specs=P._ps())
                return fn, args
            return build

        programs.append(SpmdProgram(
            name=f"verify_flat_w{width}_b{b_raw}",
            path=_LIVE_PATH, build=mk_flat(), kind="mesh",
            note=f"flat staged verify, width {width}, padded batch"
                 f" {b_pad}",
        ))

        def mk_registry(width=width, b_pad=b_pad, n_total=n_total):
            def build():
                import jax.numpy as jnp

                amesh = trace_mesh((("batch", width),))
                _StubLFp.register()

                def kern(pk, sig, h, wbits):
                    return _stub_verify(
                        (_StubLFp(pk[0]), _StubLFp(pk[1])), sig, h,
                        wbits)

                _pk, sig, h, wbits = _flat_stub_args(b_pad)
                rest = (sig, h, wbits)
                reg_x = jnp.zeros((_LIMB_ROWS, n_total), jnp.uint32)
                reg_y = jnp.zeros((_LIMB_ROWS, n_total), jnp.uint32)
                slots = jnp.zeros((b_pad,), jnp.int32)
                args = (reg_x, reg_y, slots) + rest
                local = P.staged_local(
                    kern, axis="batch", deferred_pk=True,
                    pk_wrap=lambda x, y: (x, y),
                )
                specs = P.program_in_specs(rest, deferred_pk=True)
                fn = M.compat_shard_map(local, amesh, in_specs=specs,
                                        out_specs=P._ps())
                return fn, args
            return build

        programs.append(SpmdProgram(
            name=f"verify_registry_w{width}_b{b_raw}_n{n_total}",
            path=_LIVE_PATH, build=mk_registry(), kind="mesh",
            # slot vector (shard_map operand 2) holds validator slots:
            # registry_device_sharded zero-pads the validator axis, and
            # slots never reference pad columns -> [0, n_total - 1]
            domains={2: (0, n_total - 1)},
            note=f"registry staged verify, width {width}, registry"
                 f" {n_total} ({n_local}/shard)",
        ))

        def mk_pad(b_raw=b_raw, b_pad=b_pad):
            def build():
                import jax.numpy as jnp

                pad = b_pad - b_raw

                def f(a):
                    return P._pad_tail((a,), pad)[0] if pad else \
                        jnp.asarray(a)

                return f, (jnp.zeros((4, b_raw), jnp.int32),)
            return build

        programs.append(SpmdProgram(
            name=f"pad_operands_w{width}_b{b_raw}",
            path=_LIVE_PATH, build=mk_pad(), kind="pad", n_real=b_raw,
            note="operand dup-of-column-0 padding is absorbing",
        ))

        def mk_pad_slots(b_raw=b_raw, b_pad=b_pad):
            def build():
                import jax.numpy as jnp

                pad = b_pad - b_raw

                def f(s):
                    return P._pad_slots(s, pad)

                return f, (jnp.zeros((b_raw,), jnp.int32),)
            return build

        programs.append(SpmdProgram(
            name=f"pad_slots_w{width}_b{b_raw}",
            path=_LIVE_PATH, build=mk_pad_slots(), kind="pad",
            n_real=b_raw,
            note="slot dup-of-slot-0 padding matches operand padding",
        ))

    for width in sorted({w for w, _, _ in _LIVE_SHAPES}):
        def mk_ring(width=width):
            def build():
                import jax.numpy as jnp

                amesh = trace_mesh((("batch", width),))

                def local(x):
                    return M.ring_reduce(
                        jnp.reshape(x, ()), lambda a, b: a & b, "batch",
                    )

                fn = M.compat_shard_map(
                    local, amesh, in_specs=P._ps("batch"),
                    out_specs=P._ps(),
                )
                return fn, (jnp.ones((width,), jnp.uint32),)
            return build

        programs.append(SpmdProgram(
            name=f"ring_reduce_w{width}",
            path=_MESH_PATH, build=mk_ring(), kind="mesh",
            note="n-1-hop ring fold is replicated (check_vma's gap)",
        ))

    # the other two dispatch consumers stage through the same builders,
    # at their own characteristic shapes: stream_epoch pushes
    # committee-sized chunk batches and the pod's canary/probe path
    # dispatches tiny known-answer batches — prove both explicitly so
    # a shape-dependent regression (e.g. a pad rule keyed on batch
    # size) cannot hide behind the three pod shapes above
    def mk_shape(width, b_pad):
        def build():
            amesh = trace_mesh((("batch", width),))
            args = _flat_stub_args(b_pad)
            local = P.staged_local(_stub_verify, axis="batch")
            specs = P.program_in_specs(args, deferred_pk=False)
            fn = M.compat_shard_map(local, amesh, in_specs=specs,
                                    out_specs=P._ps())
            return fn, args
        return build

    programs.append(SpmdProgram(
        name="stream_chunk_w8_b64",
        path=_LIVE_PATH, build=mk_shape(8, 64), kind="mesh",
        note="stream_epoch committee-chunk shape through the flat"
             " program",
    ))
    programs.append(SpmdProgram(
        name="pod_canary_w4_b4",
        path=_POD_PATH, build=mk_shape(4, 4), kind="mesh",
        note="pod canary/probe dispatch shape (tiny known-answer"
             " batch, one column per shard)",
    ))
    return programs


def _declared_axes_live(root: str) -> tuple:
    """AST-parse the mesh module for the declared axis literals."""
    path = os.path.join(root, _MESH_PATH)
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return ("batch",)
    axes = []
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id.endswith("_AXIS") \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    axes.append(node.value.value)
    return tuple(axes) or ("batch",)


# ---------------------------------------------------------------------------
# Cache + audit entry
# ---------------------------------------------------------------------------

_CACHE_STATS = {"hits": 0, "misses": 0}


def _spmd_fingerprint(root: str) -> str:
    """The range-family fingerprint (which already covers partition.py
    and mesh.py) extended with this module: editing the prover
    invalidates spmd verdicts without discarding the minutes-scale
    range traces."""
    import hashlib

    from . import range_lint

    h = hashlib.sha256()
    h.update(range_lint._proof_fingerprint(root).encode())
    rel = "lighthouse_tpu/analysis/spmd_lint.py"
    h.update(rel.encode())
    try:
        with open(os.path.join(root, rel), "rb") as f:
            h.update(f.read())
    except OSError:
        h.update(b"?")
    return h.hexdigest()


def _load_defs(root: str, rel_path: str):
    full = os.path.join(root, rel_path)
    spec = importlib.util.spec_from_file_location("spmd_defs_corpus", full)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def generate(root: str, cfg) -> list:
    """Trace + prove the program registry (cached); no report dict —
    the theorems are pass/fail, there is no numeric envelope to pin."""
    from .range_lint import _CACHE_FILE

    _CACHE_STATS["hits"] = 0
    _CACHE_STATS["misses"] = 0
    try:
        import jax  # noqa: F401
    except Exception as exc:  # pragma: no cover - jax is baked in
        return [Violation(
            rule=RULE_INTERP, path="lighthouse_tpu/analysis/spmd_lint.py",
            line=0, symbol="import-jax",
            message=f"spmd family needs jax to stage programs: {exc}",
        )]
    defs_rel = getattr(cfg, "spmd_defs", None)
    if defs_rel:
        try:
            mod = _load_defs(root, defs_rel)
            programs = list(mod.build_programs())
            declared = set(getattr(mod, "DECLARED_AXES", ("batch",)))
        except Exception as exc:
            return [Violation(
                rule=RULE_INTERP, path=defs_rel, line=0, symbol="defs",
                message=f"spmd defs module failed to load: {exc!r}",
            )]
    else:
        programs = build_live_programs()
        declared = set(_declared_axes_live(root))
    use_cache = bool(getattr(cfg, "range_cache", True)) and not defs_rel
    cache_path = os.path.join(root, _CACHE_FILE)
    fingerprint = _spmd_fingerprint(root) if use_cache else ""
    cached: dict = {}
    disk: dict = {}
    if use_cache:
        try:
            with open(cache_path, encoding="utf-8") as f:
                disk = json.load(f)
            if disk.get("spmd_fingerprint") == fingerprint:
                cached = dict(disk.get("spmd_programs") or {})
        except (OSError, ValueError):
            disk, cached = {}, {}
    violations: list = []
    dirty = False
    for prog in programs:
        entry = cached.get(prog.name)
        if entry is not None:
            _CACHE_STATS["hits"] += 1
            vios = [Violation(**v) for v in entry["violations"]]
        else:
            _CACHE_STATS["misses"] += 1
            vios = analyze_program(prog, declared)
            if use_cache:
                cached[prog.name] = {
                    "violations": [v.to_dict() for v in vios],
                }
                dirty = True
        violations.extend(vios)
    if use_cache and dirty:
        # shared file: carry the range family's sections through
        doc = {k: v for k, v in disk.items()
               if not k.startswith("spmd_")}
        doc["spmd_fingerprint"] = fingerprint
        doc["spmd_programs"] = cached
        try:
            with open(cache_path, "w", encoding="utf-8") as f:
                json.dump(doc, f)
        except OSError:
            pass
    return violations


def run(root: str, cfg, files) -> list:
    """Audit-family entry: staged-program theorems + donation lint."""
    violations = generate(root, cfg)
    violations.extend(donation_violations(files))
    return violations
