"""Shared finding type for the static invariant analyzer."""

from __future__ import annotations

from dataclasses import dataclass, asdict


@dataclass
class Violation:
    rule: str      # lint family: lock-discipline | lock-order | never-raise
    #                | broad-except | metrics-registry | fault-sites
    #                | chaos-spec | jaxpr-hygiene
    path: str      # repo-relative posix path
    line: int
    symbol: str    # Class.attr, Class.method, metric/site name, …
    message: str

    def to_dict(self) -> dict:
        return asdict(self)

    def __str__(self) -> str:  # human-readable one-liner for CLI output
        return f"{self.path}:{self.line}: [{self.rule}] {self.symbol}: {self.message}"
