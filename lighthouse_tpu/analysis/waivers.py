"""Waiver file handling for the static invariant analyzer.

A waiver is a justified exception to a lint finding: the audit still
computes the violation, but a matching waiver moves it from the failing
``violations`` list to the reported-but-passing ``waived`` list.  Every
waiver MUST carry a one-line ``reason`` — an unexplained waiver is itself
a violation (the waiver file is part of the reviewed surface).

Format (``analysis/waivers.toml``)::

    [[waiver]]
    rule = "lock-discipline"
    path = "lighthouse_tpu/beacon/processor.py"
    symbol = "BeaconProcessor.*"
    reason = "single-threaded dispatch core by documented contract"

``rule``, ``path`` and ``symbol`` are fnmatch patterns; ``symbol`` may be
omitted (matches any).  The image's Python is 3.10 (no stdlib tomllib),
so this module carries a deliberately tiny TOML-subset parser: tables
(``[name]``), arrays of tables (``[[name]]``), and ``key = value`` where
value is a quoted string, an array of quoted strings, an integer, or a
bare boolean.  That subset is all the analyzer's config/waiver files use.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from fnmatch import fnmatchcase

_KEY_RE = re.compile(r'^\s*(?:"([^"]+)"|([A-Za-z0-9_.-]+))\s*=\s*(.+?)\s*$')
_STR_RE = re.compile(r'^"((?:[^"\\]|\\.)*)"$')


class WaiverFormatError(ValueError):
    """The waiver/config file does not parse under the supported subset."""


def _parse_value(raw: str, path: str, lineno: int):
    raw = raw.strip()
    m = _STR_RE.match(raw)
    if m:
        return m.group(1).replace('\\"', '"').replace("\\\\", "\\")
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip()
        if not inner:
            return []
        out = []
        # split on commas outside quotes
        for part in re.findall(r'"(?:[^"\\]|\\.)*"|[^,]+', inner):
            part = part.strip()
            if not part:
                continue
            out.append(_parse_value(part, path, lineno))
        return out
    if raw in ("true", "false"):
        return raw == "true"
    if re.fullmatch(r"-?\d+", raw):
        return int(raw)
    raise WaiverFormatError(
        f"{path}:{lineno}: unsupported TOML value {raw!r} "
        "(supported: quoted string, string array, integer, boolean)"
    )


def parse_toml_subset(text: str, path: str = "<toml>") -> dict:
    """Parse the supported TOML subset into nested dicts / lists-of-dicts."""
    root: dict = {}
    current: dict = root
    for lineno, line in enumerate(text.splitlines(), start=1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        if stripped.startswith("[["):
            if not stripped.endswith("]]"):
                raise WaiverFormatError(f"{path}:{lineno}: bad table array")
            name = stripped[2:-2].strip()
            current = {}
            root.setdefault(name, []).append(current)
            continue
        if stripped.startswith("["):
            if not stripped.endswith("]"):
                raise WaiverFormatError(f"{path}:{lineno}: bad table header")
            name = stripped[1:-1].strip()
            current = root.setdefault(name, {})
            if not isinstance(current, dict):
                raise WaiverFormatError(
                    f"{path}:{lineno}: table {name!r} conflicts with an array"
                )
            continue
        m = _KEY_RE.match(stripped)
        if m is None:
            raise WaiverFormatError(f"{path}:{lineno}: unparsable line {stripped!r}")
        key = m.group(1) or m.group(2)
        current[key] = _parse_value(m.group(3), path, lineno)
    return root


@dataclass
class Waiver:
    rule: str
    path: str
    reason: str
    symbol: str = "*"
    used: int = field(default=0, compare=False)

    def matches(self, rule: str, path: str, symbol: str) -> bool:
        return (
            fnmatchcase(rule, self.rule)
            and fnmatchcase(path, self.path)
            and fnmatchcase(symbol or "", self.symbol)
        )


def load_waivers(path: str) -> list[Waiver]:
    """Load ``waivers.toml``; a waiver missing rule/path/reason is rejected
    loudly (a silent bad waiver would silently un-waive on edit)."""
    with open(path, encoding="utf-8") as f:
        doc = parse_toml_subset(f.read(), path)
    out = []
    for i, entry in enumerate(doc.get("waiver", [])):
        missing = [k for k in ("rule", "path", "reason") if not entry.get(k)]
        if missing:
            raise WaiverFormatError(
                f"{path}: waiver #{i + 1} missing required key(s): {missing}"
            )
        out.append(
            Waiver(
                rule=entry["rule"],
                path=entry["path"],
                reason=entry["reason"],
                symbol=entry.get("symbol", "*"),
            )
        )
    return out


def apply_waivers(violations: list, waivers: list[Waiver]):
    """Split violations into (failing, waived-with-reason)."""
    failing, waived = [], []
    for v in violations:
        hit = None
        for w in waivers:
            if w.matches(v.rule, v.path, v.symbol):
                hit = w
                break
        if hit is None:
            failing.append(v)
        else:
            hit.used += 1
            waived.append((v, hit.reason))
    return failing, waived
