"""Jaxpr hygiene: program-budget walk, zero-dim guard, host-sync lint.

This module is the single home for the device-code health checks that
previously lived in two places:

* the ≤6-distinct-chain-program Mosaic compile budget walk from
  ``tools/dispatch_audit.py`` (that tool is now a thin wrapper);
* the zero-sized-vector abstract-eval guard from ``test_pallas_fp.py``
  (interpret mode tolerates zero-row intermediates; real Mosaic lowering
  rejects them — the i=25 ``_wide_square`` bug class).

Plus one new *AST-level* family that needs no tracing: **host-sync
lint** over the jax_backend dispatch hot path.  ``dispatch`` must stay
non-blocking (the PipelinedVerifier overlaps marshal workers with device
execution), so calls that force a device↔host round-trip —
``block_until_ready``, ``np.asarray`` on device values, ``.item()``,
``float()``/``int()`` on non-constant values — are banned inside the
registered hot-path functions.

The jaxpr helpers import jax lazily so the static audit itself never
pays (or requires) a jax import.
"""

from __future__ import annotations

import ast

from .report import Violation

DEFAULT_CHAIN_BUDGET = 6

# file -> functions whose bodies must not host-sync.  dispatch and every
# jitted kernel composition on the verify path.
DEFAULT_HOT_PATH = {
    "lighthouse_tpu/crypto/bls/jax_backend/backend.py": (
        "dispatch",
        "_verify_kernel",
        "_verify_kernel_h2c",
        "_aggregate_verify_kernel",
        "_epoch_verify_kernel",
        "_segment_aggregate_g1",
        "_tree_reduce_g2",
    ),
}

_HOST_SYNC_ATTRS = {"block_until_ready", "item"}
_NUMPY_ALIASES = {"np", "numpy", "onp"}
_SCALARIZERS = {"float", "int", "bool"}


# ---------------------------------------------------------------------------
# Jaxpr walk (compile-budget audit) — used by tools/dispatch_audit.py
# ---------------------------------------------------------------------------


def iter_jaxprs(obj):
    """Yield every Jaxpr reachable from a params value (ClosedJaxpr,
    Jaxpr, or containers thereof)."""
    import jax.core as jcore

    if isinstance(obj, jcore.ClosedJaxpr):
        yield obj.jaxpr
    elif isinstance(obj, jcore.Jaxpr):
        yield obj
    elif isinstance(obj, (list, tuple)):
        for item in obj:
            yield from iter_jaxprs(item)


def pallas_fingerprint(eqn):
    """Identity of one staged Pallas program: kernel name + source line
    (``name_and_src_info`` reprs as ``_mont_kernel at .../pallas_fp.py:135``),
    operand avals, grid.  Two eqns with equal fingerprints lower to one
    Mosaic program (the compile cache keys on the same data)."""
    params = eqn.params
    nsi = str(params.get("name_and_src_info", params.get("name", "?")))
    gm = params.get("grid_mapping")
    grid = tuple(getattr(gm, "grid", ()) or ())
    avals = tuple(str(v.aval) for v in eqn.invars)
    return (nsi, grid, avals)


def _walk(jaxpr, seen_jaxprs, programs, counts):
    if id(jaxpr) in seen_jaxprs:
        return
    seen_jaxprs.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            fp = pallas_fingerprint(eqn)
            programs.setdefault(fp, 0)
            programs[fp] += 1
            counts[0] += 1
        for val in eqn.params.values():
            for sub in iter_jaxprs(val):
                _walk(sub, seen_jaxprs, programs, counts)


def audit_jaxpr(closed):
    """(distinct pallas program fingerprints -> eqn count, total static
    pallas_call equation count) for a ClosedJaxpr."""
    programs: dict[tuple, int] = {}
    counts = [0]
    _walk(closed.jaxpr, set(), programs, counts)
    return programs, counts[0]


def is_chain_program(fp) -> bool:
    """Chain programs are the megachain kernels (pallas_fp.py); the
    budget bounds how many DISTINCT ones a composition stages."""
    return "megachain_kernel" in fp[0]


def chain_programs(programs) -> list:
    return [fp for fp in programs if is_chain_program(fp)]


# ---------------------------------------------------------------------------
# Zero-sized-vector abstract-eval guard (the i=25 _wide_square bug class)
# ---------------------------------------------------------------------------


def collect_zero_dim_avals(jaxpr, seen, bad):
    """Walk every equation of every staged sub-jaxpr, appending a
    description for each zero-sized operand/result aval."""
    if id(jaxpr) in seen:
        return
    seen.add(id(jaxpr))
    for eqn in jaxpr.eqns:
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            shape = getattr(aval, "shape", None)
            if shape and 0 in shape:
                bad.append(f"{eqn.primitive.name}: {aval}")
        for val in eqn.params.values():
            for sub in iter_jaxprs(val):
                collect_zero_dim_avals(sub, seen, bad)


def zero_dim_avals(fn, *args) -> list:
    """Trace `fn` (abstract eval only — nothing executes, nothing is
    Mosaic-compiled) and return descriptions of any zero-sized shapes."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    bad: list = []
    collect_zero_dim_avals(closed.jaxpr, set(), bad)
    return bad


def assert_no_zero_dims(fn, *args):
    bad = zero_dim_avals(fn, *args)
    assert not bad, (
        "zero-sized vector shapes staged (Mosaic rejects these even "
        "though interpret mode tolerates them): " + "; ".join(bad[:5])
    )


# ---------------------------------------------------------------------------
# Host-sync lint (AST-only, runs in the static audit)
# ---------------------------------------------------------------------------


def _host_sync_calls(fn_node):
    """(line, description) for every host-syncing call in a function."""
    out = []
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _HOST_SYNC_ATTRS:
                out.append((node.lineno, f".{f.attr}() forces a device sync"))
            elif (
                f.attr == "asarray"
                and isinstance(f.value, ast.Name)
                and f.value.id in _NUMPY_ALIASES
            ):
                out.append((
                    node.lineno,
                    f"{f.value.id}.asarray() copies device values to host",
                ))
        elif isinstance(f, ast.Name) and f.id in _SCALARIZERS:
            if node.args and not isinstance(node.args[0], ast.Constant):
                out.append((
                    node.lineno,
                    f"{f.id}() on a non-constant value scalarizes "
                    f"(host sync if the value is traced/on-device)",
                ))
    return out


def host_sync_violations(files, hot_path=None) -> list[Violation]:
    """files: iterable of (display_path, source).  hot_path: mapping of
    display path -> function names whose bodies must stay sync-free."""
    hot_path = dict(DEFAULT_HOT_PATH if hot_path is None else hot_path)
    files = dict(files)
    out = []
    for path, fn_names in sorted(hot_path.items()):
        src = files.get(path)
        if src is None:
            out.append(Violation(
                rule="jaxpr-hygiene", path=path, line=0, symbol=path,
                message="hot-path file not found in scan set "
                        "(hot-path registry drift)",
            ))
            continue
        tree = ast.parse(src, filename=path)
        found = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in fn_names:
                continue
            found.add(node.name)
            for line, why in _host_sync_calls(node):
                out.append(Violation(
                    rule="jaxpr-hygiene", path=path, line=line,
                    symbol=node.name,
                    message=f"host-sync call in dispatch hot path: {why}",
                ))
        for missing in sorted(set(fn_names) - found):
            out.append(Violation(
                rule="jaxpr-hygiene", path=path, line=0, symbol=missing,
                message=(
                    f"hot-path function {missing!r} not found "
                    f"(hot-path registry drift)"
                ),
            ))
    return out


def run(files, hot_path=None) -> list[Violation]:
    return host_sync_violations(files, hot_path)
