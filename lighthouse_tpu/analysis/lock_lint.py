"""Lock-discipline race detector and lock-order graph (pure AST).

Two checks, both lexical:

**Guarded-attribute discipline.**  For every class, each ``self.<attr>``
mutation site is classified as lock-held (lexically inside a
``with self._lock:``-style block, or inside a method that takes a lock
via ``self._lock.acquire()`` at its top) or bare.  An attribute whose
mutations are *majority* lock-held is considered guarded by convention,
and every bare mutation of it is a violation.  Bare *reads* are only
flagged for attributes that are mutated through container operations
(``d[k] = v``, ``.append``, ``.pop`` …) at ≥2 sites, all of them locked:
plain rebinding of an int/reference is atomic under the GIL and flagging
its reads would drown the signal, but iterating or len()-ing a dict that
another thread resizes under a lock is a real race.

**Lock-order graph.**  Acquiring ``self.B`` while lexically holding
``self.A`` adds the edge ``Class.A -> Class.B``.  One level of
intra-class calls is resolved: if a method calls ``self.m()`` while
holding ``A`` and ``m`` acquires ``B``, the same edge is added.  A cycle
in the union graph (including a self-edge on a non-reentrant lock) is a
potential deadlock and fails the audit.

``__init__`` bodies are skipped for discipline (construction happens
before the object escapes); nested ``def``/``lambda`` bodies reset the
held-lock context (they usually run on another thread later).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .report import Violation

_LOCKISH = re.compile(r"(lock|mutex|_cv$|^cv$|cond)", re.IGNORECASE)

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_REENTRANT_FACTORIES = {"RLock"}

_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "discard", "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse", "rotate",
}


def is_lockish(name: str) -> bool:
    return bool(_LOCKISH.search(name))


@dataclass
class _Site:
    attr: str
    method: str
    line: int
    locked: bool
    container: bool = False


@dataclass
class LockEdge:
    src: str          # "Class.attrA"
    dst: str          # "Class.attrB"
    path: str
    line: int
    via_call: str = ""


@dataclass
class _ClassInfo:
    name: str
    path: str
    lock_attrs: set = field(default_factory=set)
    reentrant: set = field(default_factory=set)
    mutations: list = field(default_factory=list)   # [_Site]
    reads: list = field(default_factory=list)       # [_Site]
    # method name -> set of self-lock attrs it acquires anywhere
    method_acquires: dict = field(default_factory=dict)
    # (held_attr, called_method, line) pending one-level resolution
    pending_calls: list = field(default_factory=list)
    edges: list = field(default_factory=list)       # [LockEdge]


def _self_attr(node) -> str | None:
    """Return A for an ``self.A`` expression, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _lock_factory_name(call: ast.expr) -> str | None:
    """Return the factory name if `call` is threading.Lock()/RLock()/…"""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_FACTORIES:
        return fn.attr
    if isinstance(fn, ast.Name) and fn.id in _LOCK_FACTORIES:
        return fn.id
    return None


class _ClassScanner:
    """Single-class analysis: discipline sites + lock-order edges."""

    def __init__(self, cls: ast.ClassDef, path: str):
        self.info = _ClassInfo(name=cls.name, path=path)
        self._cls = cls

    def scan(self) -> _ClassInfo:
        # pass 0: find self.<attr> = Lock()/RLock() assignments anywhere,
        # plus Condition(self._lock)-style aliases.
        for node in ast.walk(self._cls):
            if isinstance(node, ast.Assign):
                factory = _lock_factory_name(node.value)
                if factory is None:
                    continue
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    self.info.lock_attrs.add(attr)
                    if factory in _REENTRANT_FACTORIES:
                        self.info.reentrant.add(attr)

        # pass 1: per-method acquisition sets (for one-level call resolution)
        for stmt in self._cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.info.method_acquires[stmt.name] = self._acquired_in(stmt)

        # pass 2: walk each method tracking the lexically-held lock set
        for stmt in self._cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                held = tuple(sorted(self._base_locks(stmt)))
                self._walk_stmts(stmt.body, held, stmt.name,
                                 in_init=stmt.name == "__init__")

        # pass 3: resolve one level of intra-class calls made under a lock
        for held_attr, callee, line in self.info.pending_calls:
            for acquired in self.info.method_acquires.get(callee, ()):
                if acquired != held_attr:
                    self._add_edge(held_attr, acquired, line, via_call=callee)
        return self.info

    # -- helpers ---------------------------------------------------------

    def _with_lock_attr(self, item: ast.withitem) -> str | None:
        """Self lock attr acquired by a with-item, if any."""
        ce = item.context_expr
        attr = _self_attr(ce)
        if attr is not None and (attr in self.info.lock_attrs or is_lockish(attr)):
            self.info.lock_attrs.add(attr)
            return attr
        return None

    def _with_is_lockish(self, item: ast.withitem) -> bool:
        """Any lock-looking context manager (module lock, peer._lock, …)."""
        ce = item.context_expr
        name = None
        if isinstance(ce, ast.Attribute):
            name = ce.attr
        elif isinstance(ce, ast.Name):
            name = ce.id
        return name is not None and is_lockish(name)

    def _base_locks(self, fn) -> set:
        """Locks a method holds for its whole body via ``self._x.acquire()``
        as a top-level statement (the non-blocking-tick idiom)."""
        out = set()
        for stmt in fn.body:
            target = None
            if isinstance(stmt, ast.Expr):
                target = stmt.value
            elif isinstance(stmt, ast.Assign):
                target = stmt.value
            elif isinstance(stmt, ast.If):
                # `if not self._x.acquire(blocking=False): return`
                test = stmt.test
                if isinstance(test, ast.UnaryOp):
                    test = test.operand
                target = test
            if (
                isinstance(target, ast.Call)
                and isinstance(target.func, ast.Attribute)
                and target.func.attr == "acquire"
            ):
                attr = _self_attr(target.func.value)
                if attr is not None and is_lockish(attr):
                    self.info.lock_attrs.add(attr)
                    out.add(attr)
        return out

    def _acquired_in(self, fn) -> set:
        """All self-lock attrs a method acquires anywhere in its body."""
        out = set(self._base_locks(fn))
        for node in ast.walk(fn):
            if isinstance(node, ast.With):
                for item in node.items:
                    attr = self._with_lock_attr(item)
                    if attr is not None:
                        out.add(attr)
        return out

    def _add_edge(self, src_attr, dst_attr, line, via_call=""):
        self.info.edges.append(LockEdge(
            src=f"{self.info.name}.{src_attr}",
            dst=f"{self.info.name}.{dst_attr}",
            path=self.info.path, line=line, via_call=via_call,
        ))

    # -- the context-carrying walk ---------------------------------------

    def _walk_stmts(self, stmts, held, method, in_init):
        for stmt in stmts:
            self._walk_stmt(stmt, held, method, in_init)

    def _walk_stmt(self, stmt, held, method, in_init):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: runs later, possibly on another thread — the
            # enclosing lock is NOT held when it executes.
            self._walk_stmts(stmt.body, (), f"{method}.{stmt.name}", in_init)
            return
        if isinstance(stmt, ast.With):
            new_held = list(held)
            for item in stmt.items:
                attr = self._with_lock_attr(item)
                if attr is not None:
                    for h in held:
                        if h != attr:
                            self._add_edge(h, attr, stmt.lineno)
                        elif attr not in self.info.reentrant:
                            self._add_edge(h, attr, stmt.lineno)  # self-edge
                    new_held.append(attr)
                elif self._with_is_lockish(item):
                    new_held.append("")   # anonymous lock: guards, no node
                else:
                    self._walk_expr(item.context_expr, held, method, in_init)
                if item.optional_vars is not None:
                    self._walk_expr(item.optional_vars, held, method, in_init)
            self._walk_stmts(stmt.body, tuple(new_held), method, in_init)
            return
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                self._walk_target(tgt, held, method, in_init)
            self._walk_expr(stmt.value, held, method, in_init)
            return
        if isinstance(stmt, ast.AugAssign):
            self._walk_target(stmt.target, held, method, in_init,
                              aug=True)
            self._walk_expr(stmt.value, held, method, in_init)
            return
        if isinstance(stmt, ast.AnnAssign):
            self._walk_target(stmt.target, held, method, in_init)
            if stmt.value is not None:
                self._walk_expr(stmt.value, held, method, in_init)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                self._walk_target(tgt, held, method, in_init)
            return
        # generic: walk child expressions, recurse into child statements
        for fname, value in ast.iter_fields(stmt):
            if isinstance(value, list):
                if value and isinstance(value[0], ast.stmt):
                    self._walk_stmts(value, held, method, in_init)
                else:
                    for v in value:
                        if isinstance(v, ast.expr):
                            self._walk_expr(v, held, method, in_init)
                        elif isinstance(v, ast.excepthandler):
                            self._walk_stmts(v.body, held, method, in_init)
            elif isinstance(value, ast.expr):
                self._walk_expr(value, held, method, in_init)
            elif isinstance(value, ast.stmt):
                self._walk_stmt(value, held, method, in_init)

    def _walk_target(self, tgt, held, method, in_init, aug=False):
        """Assignment/Delete target: record self-attr mutations."""
        attr = _self_attr(tgt)
        if attr is not None:
            if attr not in self.info.lock_attrs and not in_init:
                self.info.mutations.append(_Site(
                    attr, method, tgt.lineno, locked=bool(held),
                    container=False,
                ))
            return
        if isinstance(tgt, ast.Subscript):
            base = _self_attr(tgt.value)
            if base is not None:
                if base not in self.info.lock_attrs and not in_init:
                    self.info.mutations.append(_Site(
                        base, method, tgt.lineno, locked=bool(held),
                        container=True,
                    ))
                self._walk_expr(tgt.slice, held, method, in_init)
                return
            self._walk_expr(tgt.value, held, method, in_init)
            self._walk_expr(tgt.slice, held, method, in_init)
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._walk_target(elt, held, method, in_init, aug=aug)
            return
        if isinstance(tgt, ast.Starred):
            self._walk_target(tgt.value, held, method, in_init, aug=aug)
            return
        self._walk_expr(tgt, held, method, in_init)

    def _walk_expr(self, expr, held, method, in_init):
        if expr is None:
            return
        if isinstance(expr, (ast.Lambda,)):
            self._walk_expr(expr.body, (), f"{method}.<lambda>", in_init)
            return
        if isinstance(expr, ast.Call):
            fn = expr.func
            if isinstance(fn, ast.Attribute):
                base_attr = _self_attr(fn.value)
                if base_attr is not None and fn.attr in _MUTATOR_METHODS:
                    # self.X.append(...) — container mutation of X
                    if base_attr not in self.info.lock_attrs and not in_init:
                        self.info.mutations.append(_Site(
                            base_attr, method, expr.lineno,
                            locked=bool(held), container=True,
                        ))
                    for a in expr.args:
                        self._walk_expr(a, held, method, in_init)
                    for kw in expr.keywords:
                        self._walk_expr(kw.value, held, method, in_init)
                    return
            callee = _self_attr(fn)
            if callee is not None and held:
                # self.m() while holding locks: queue for one-level
                # lock-order resolution.
                for h in held:
                    if h:
                        self.info.pending_calls.append(
                            (h, callee, expr.lineno)
                        )
        # generic expression walk
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                attr = _self_attr(child)
                if (
                    attr is not None
                    and isinstance(child.ctx, ast.Load)
                    and attr not in self.info.lock_attrs
                    and not in_init
                ):
                    self.info.reads.append(_Site(
                        attr, method, child.lineno, locked=bool(held),
                    ))
                    continue
                self._walk_expr(child, held, method, in_init)
            elif isinstance(child, (ast.comprehension,)):
                self._walk_expr(child.iter, held, method, in_init)
                for cond in child.ifs:
                    self._walk_expr(cond, held, method, in_init)
            elif isinstance(child, ast.keyword):
                self._walk_expr(child.value, held, method, in_init)
            elif isinstance(child, ast.FormattedValue):
                self._walk_expr(child.value, held, method, in_init)


# -- file / corpus level -------------------------------------------------


def scan_file(path: str, src: str, display_path: str | None = None):
    """Analyze one file; returns (list[_ClassInfo], list[LockEdge])."""
    tree = ast.parse(src, filename=path)
    display = display_path or path
    infos, edges = [], []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            info = _ClassScanner(node, display).scan()
            infos.append(info)
            edges.extend(info.edges)
    return infos, edges


def discipline_violations(info: _ClassInfo) -> list[Violation]:
    out = []
    by_attr: dict[str, list[_Site]] = {}
    for site in info.mutations:
        by_attr.setdefault(site.attr, []).append(site)
    for attr, sites in sorted(by_attr.items()):
        locked = [s for s in sites if s.locked]
        bare = [s for s in sites if not s.locked]
        if not locked or len(locked) <= len(bare):
            continue  # not guarded by convention
        for s in bare:
            out.append(Violation(
                rule="lock-discipline",
                path=info.path,
                line=s.line,
                symbol=f"{info.name}.{attr}",
                message=(
                    f"mutation of {info.name}.{attr} outside a lock "
                    f"({len(locked)}/{len(sites)} mutation sites are "
                    f"lock-held, so the attribute is guarded by convention)"
                ),
            ))
        # container attrs that are 100% lock-mutated at >=2 sites: bare
        # reads race with concurrent resizes.
        if (
            not bare
            and len(locked) >= 2
            and any(s.container for s in locked)
        ):
            for r in info.reads:
                if r.attr == attr and not r.locked:
                    out.append(Violation(
                        rule="lock-discipline",
                        path=info.path,
                        line=r.line,
                        symbol=f"{info.name}.{attr}",
                        message=(
                            f"read of lock-guarded container "
                            f"{info.name}.{attr} outside the lock (all "
                            f"{len(locked)} mutation sites are lock-held)"
                        ),
                    ))
    return out


def find_cycles(edges: list[LockEdge]):
    """Return a list of cycles; each cycle is a list of LockEdge forming
    the loop.  Simple iterative DFS over the edge multigraph."""
    graph: dict[str, list[LockEdge]] = {}
    for e in edges:
        graph.setdefault(e.src, []).append(e)
    cycles, seen_keys = [], set()

    def dfs(node, stack, stack_set, visited):
        visited.add(node)
        for e in graph.get(node, ()):
            if e.dst in stack_set:
                i = next(
                    idx for idx, se in enumerate(stack) if se.src == e.dst
                )
                cyc = stack[i:] + [e]
                key = tuple(sorted((c.src, c.dst) for c in cyc))
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(cyc)
                continue
            if e.dst not in visited:
                stack.append(e)
                stack_set.add(e.src)
                dfs(e.dst, stack, stack_set, visited)
                stack_set.discard(e.src)
                stack.pop()

    visited: set[str] = set()
    for node in sorted(graph):
        if node not in visited:
            dfs(node, [], set(), visited)
    # self-edges (with self._lock: ... with self._lock: on a plain Lock)
    for e in edges:
        if e.src == e.dst:
            key = ((e.src, e.dst),)
            if key not in seen_keys:
                seen_keys.add(key)
                cycles.append([e])
    return cycles


def cycle_violations(edges: list[LockEdge]) -> list[Violation]:
    out = []
    for cyc in find_cycles(edges):
        loop = " -> ".join([e.src for e in cyc] + [cyc[0].src])
        anchor = cyc[0]
        detail = "self-acquisition of a non-reentrant lock" \
            if len(cyc) == 1 and anchor.src == anchor.dst \
            else "lock-order cycle (potential deadlock)"
        out.append(Violation(
            rule="lock-order",
            path=anchor.path,
            line=anchor.line,
            symbol=anchor.src,
            message=f"{detail}: {loop}",
        ))
    return out


def run(files) -> tuple[list[Violation], list[LockEdge]]:
    """files: iterable of (display_path, source). Returns (violations,
    the full lock-order edge list for the runtime sanitizer to check
    against)."""
    violations, all_edges = [], []
    for display, src in files:
        infos, edges = scan_file(display, src, display)
        all_edges.extend(edges)
        for info in infos:
            violations.extend(discipline_violations(info))
    violations.extend(cycle_violations(all_edges))
    return violations, all_edges


def static_lock_order(files) -> set[tuple[str, str]]:
    """The static edge set as (src, dst) pairs — the runtime lockcheck
    sanitizer asserts its observed acquisition order is a subset."""
    _, edges = run(files)
    return {(e.src, e.dst) for e in edges}
