"""Limb-range abstract interpreter — the ``range`` audit family.

Every field kernel in the BLS stack (``crypto/bls/jax_backend``) computes
on 26x15-bit quasi-normalized uint32 limbs and justifies carry/overflow
safety by hand-reasoned bounds: ``fp.py`` carries trace-time ``LFp``
value bounds (units of P), the Pallas kernel interiors justify uint32
safety in comments.  This module machine-checks both layers.

**Interval layer** (``_Interp``): an abstract interpreter over jaxprs.
Every registered kernel program (``build_live_programs``) is traced with
``jax.make_jaxpr`` — Pallas kernels in interpret mode, so the kernel
body rides along as the ``pallas_call`` eqn's ``jaxpr`` param — and
per-element integer intervals are propagated through every primitive
(add/mul/shift/and/select/concat/pad/``scan`` fixpoint with widening/
...).  It proves, per program:

* theorem class 1 — **no uint32 overflow**: every integer intermediate
  stays inside its dtype; a violation names the eqn site and the
  computed interval;
* theorem class 2 — **representation contracts**: declared output
  contracts hold (STRICT limbs < 2^15 out of ``_mont_reduce``'s masked
  carry chain, quasi limbs <= QMAX after carry passes, and the
  ``fp_sub``/``ksub`` bias columns never underflow given the declared
  subtrahend bound — the per-k ``*_sub_k*``/``*_ksub_k*`` programs).

**Exact layer** (``lfp_check``): the hand-derived bound *algebra* in
``fp.py`` is re-derived in exact ``fractions.Fraction`` arithmetic —
``mont_mul``'s claimed ``prod/MONT_DIVISOR + MONT_EPS`` output bound
against the true ``prod*P/R + 1``, ``REDUCE_PIN``, the ``fp_pow``
fixpoint closure, ``MAX_BOUND`` top-column carry headroom, and the
per-k bias tables (value == k*P, low limbs >= QMAX, and the top-limb
domination rule enforced by ``fp._k_for``).  Theorem class 3: any
unsound constant is a ``range-lfp`` violation; a sound-but-loose one
(relative slack above ``SLACK_MAX``) is a ``range-slack`` violation.

**Why two layers.**  Top-limb facts like "a value < 2P has limb 25
<= floor(2P / 2^375) = 104" are *value*-bound consequences, not
derivable from limb intervals (a Montgomery output's limb interval is
[0, 2^15) — the interval layer cannot see that its *value* is < 2P).
The proof is therefore modular: the exact layer validates the bound
algebra, which justifies the per-limb input caps (``caps_iv``) fed to
the interval layer; the interval layer then closes the induction by
proving each op preserves the representation invariants for *all*
inputs satisfying those caps.  Whole-kernel composition runs (the
``heavy`` programs) set ``clamp_sub=True``: interior bias subtractions
are clamped non-negative without a finding because the per-k op
programs already discharge that obligation universally — the
composition run still proves accumulation/overflow safety and output
contracts.

**MXU report** (``mxu_report``): per-kernel max accumulation magnitude
from the interval run, the direct dot-product column magnitude of the
15-bit representation, the generic limb-split table (w <= 9 for
f32-mantissa MXU accumulation, w <= 13 for int32), and the
``selected_split`` block for the split ``pallas_mxu`` actually ships
(w=13, 31 limbs, int32 column budget 31 * QMAX13^2 < 2^31).  The MXU
kernels are registered programs like any other — their dot-product
column proof rides the precise non-negative ``dot_general`` transfer,
which in turn needs the iota/div/rem/eq handlers to constant-fold the
in-kernel band matrix to its exact 0/1 entries.  The full result is
serialized as ``RANGE_REPORT.json`` and checked in; the audit
regenerates it and fails with ``range-report`` on drift.

**Proof cache**: per-program verdicts are replayed from
``.range_proof_cache.json`` when a sha256 fingerprint over the kernel
sources (+ this module + jax/numpy versions) is unchanged — the
interpret-mode traces dominate audit wall time; the warm path skips
them all.  ``--no-cache`` (``cfg.range_cache = False``) forces fresh
traces; cached and fresh runs produce byte-identical verdicts.

Fixture corpora re-point the registry via the ``range_defs`` audit
config key (a python file exposing ``build_programs()`` /
``LFP_CLAIMS``); see ``tests/fixtures/lint/range_defs.py``.
"""

from __future__ import annotations

import importlib.util
import json
import math
import os
from dataclasses import dataclass
from fractions import Fraction

import numpy as np

from .report import Violation

RULE_OVERFLOW = "range-overflow"
RULE_CONTRACT = "range-contract"
RULE_LFP = "range-lfp"
RULE_SLACK = "range-slack"
RULE_INTERP = "range-interp"
RULE_REPORT = "range-report"

# sound-but-loose threshold: relative slack of a claimed bound over the
# exact one.  Live constants sit well under (max ~10.3% on REDUCE_PIN).
SLACK_MAX = 0.5

# saturation ceiling for interval endpoints; interval arithmetic runs in
# float64 (exact below 2^53 — far above any sound kernel's 2^36) and
# clips here, so int64 endpoint math can never itself wrap
_SAT = 1 << 62

_DTYPE_RANGE = {
    "uint8": (0, (1 << 8) - 1),
    "uint16": (0, (1 << 16) - 1),
    "uint32": (0, (1 << 32) - 1),
    "uint64": (0, _SAT),
    "int8": (-(1 << 7), (1 << 7) - 1),
    "int16": (-(1 << 15), (1 << 15) - 1),
    "int32": (-(1 << 31), (1 << 31) - 1),
    "int64": (-_SAT, _SAT),
}

MAX_FINDINGS_PER_PROGRAM = 8
_FIX_ITERS = 64     # scan/while fixpoint iteration cap; must exceed 2N+2:
                    #  the shift-register scans in fp._mul_cols_wide/_low
                    #  stabilise one accumulator slot per round (52 slots)
_WIDEN_AFTER = 56   # rounds before power-of-two widening kicks in; widening
                    #  an additive chain early cascades one bit per round,
                    #  so it must start only after natural convergence fails

DEFAULT_REPORT = "RANGE_REPORT.json"


# ---------------------------------------------------------------------------
# Interval arrays
# ---------------------------------------------------------------------------


def _i64(arr):
    """Clip a float64 array into the saturation range and cast int64."""
    return np.clip(np.asarray(arr, dtype=np.float64),
                   -float(_SAT), float(_SAT)).astype(np.int64)


class IV:
    """Per-element integer interval: two int64 arrays of the aval shape."""

    __slots__ = ("lo", "hi")

    def __init__(self, lo, hi):
        self.lo = np.asarray(lo, dtype=np.int64)
        self.hi = np.asarray(hi, dtype=np.int64)

    @property
    def shape(self):
        return self.lo.shape

    @classmethod
    def const(cls, arr):
        a = _i64(np.asarray(arr, dtype=np.float64))
        return cls(a, a.copy())

    @classmethod
    def full(cls, shape, lo, hi):
        return cls(np.full(shape, lo, dtype=np.int64),
                   np.full(shape, hi, dtype=np.int64))

    def broadcast(self, shape):
        return IV(np.broadcast_to(self.lo, shape).copy(),
                  np.broadcast_to(self.hi, shape).copy())

    def join(self, other):
        return IV(np.minimum(self.lo, other.lo), np.maximum(self.hi, other.hi))

    def contains(self, other) -> bool:
        return bool(np.all(self.lo <= other.lo) and np.all(self.hi >= other.hi))

    def clamp(self, lo, hi):
        return IV(np.clip(self.lo, lo, hi), np.clip(self.hi, lo, hi))

    def min_lo(self) -> int:
        return int(self.lo.min()) if self.lo.size else 0

    def max_hi(self) -> int:
        return int(self.hi.max()) if self.hi.size else 0


def iv_add(a: IV, b: IV) -> IV:
    return IV(_i64(a.lo.astype(np.float64) + b.lo.astype(np.float64)),
              _i64(a.hi.astype(np.float64) + b.hi.astype(np.float64)))


def iv_sub(a: IV, b: IV) -> IV:
    return IV(_i64(a.lo.astype(np.float64) - b.hi.astype(np.float64)),
              _i64(a.hi.astype(np.float64) - b.lo.astype(np.float64)))


def iv_mul(a: IV, b: IV) -> IV:
    al, ah = a.lo.astype(np.float64), a.hi.astype(np.float64)
    bl, bh = b.lo.astype(np.float64), b.hi.astype(np.float64)
    cands = np.stack(np.broadcast_arrays(al * bl, al * bh, ah * bl, ah * bh))
    return IV(_i64(cands.min(axis=0)), _i64(cands.max(axis=0)))


def log2_or_zero(v) -> float:
    v = float(v)
    return round(math.log2(v), 2) if v > 0 else 0.0


# ---------------------------------------------------------------------------
# Program registry
# ---------------------------------------------------------------------------


@dataclass
class RangeProgram:
    """One proof obligation: a traceable callable plus input intervals.

    ``build()`` returns ``(fn, example_args, in_ivs)`` — ``fn`` is traced
    with ``jax.make_jaxpr(fn)(*example_args)`` and ``in_ivs`` (aligned
    with the jaxpr invars; ``None`` or a short list is completed by
    ``_default_ivs``) define the universally-quantified input set.
    ``contracts`` is a tuple of ``(out_index, kind)`` with kind one of
    ``"strict"`` (< 2^15), ``"quasi"`` (<= QMAX) or ``("max", cap)``.
    ``clamp_sub=True`` marks a whole-kernel composition run whose bias
    subtractions are discharged by the per-k op programs (see module
    docstring); ``heavy`` marks minutes-scale traces the fast test tier
    skips.
    """

    name: str
    path: str
    build: object
    contracts: tuple = ()
    clamp_sub: bool = False
    heavy: bool = False
    note: str = ""


# ---------------------------------------------------------------------------
# The abstract interpreter
# ---------------------------------------------------------------------------


def _eqn_src(eqn) -> tuple:
    """(source file hint, line) for an eqn, best effort."""
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return frame.file_name, int(frame.start_line)
    except Exception:
        pass
    return "", 0


class _Findings:
    """Deduplicated finding collector for one program."""

    def __init__(self, program: RangeProgram):
        self.program = program
        self.by_key: dict = {}
        self.order: list = []

    def add(self, rule: str, symbol: str, message: str, line: int = 0):
        key = (rule, symbol, line)
        if key in self.by_key:
            self.by_key[key] += 1
            return
        self.by_key[key] = 1
        self.order.append((rule, symbol, message, line))

    def violations(self) -> list:
        out = []
        for rule, symbol, message, line in self.order[:MAX_FINDINGS_PER_PROGRAM]:
            n = self.by_key[(rule, symbol, line)]
            if n > 1:
                message += f" [x{n} eqns at this site]"
            out.append(Violation(
                rule=rule, path=self.program.path, line=line,
                symbol=f"{self.program.name}:{symbol}", message=message,
            ))
        dropped = len(self.order) - MAX_FINDINGS_PER_PROGRAM
        if dropped > 0:
            out.append(Violation(
                rule=self.order[MAX_FINDINGS_PER_PROGRAM][0],
                path=self.program.path, line=0,
                symbol=f"{self.program.name}:more",
                message=f"{dropped} further distinct finding sites suppressed",
            ))
        return out


def _dtype_range(aval):
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return None
    name = np.dtype(dt).name
    if name == "bool":
        return (0, 1)
    return _DTYPE_RANGE.get(name)


def _aval_shape(aval):
    return tuple(getattr(aval, "shape", ()))


def _is_literal(atom) -> bool:
    return hasattr(atom, "val")


class _Interp:
    """Interval evaluation of one (possibly nested) jaxpr."""

    def __init__(self, program: RangeProgram, findings: _Findings):
        self.program = program
        self.findings = findings
        self.eqn_count = 0
        self.max_any = 0   # max |endpoint| over every integer intermediate
        self.max_acc = 0   # max over `add` outputs — accumulation magnitude
        self.max_dot = 0   # max over `dot_general` outputs — MXU column sums
        self.unknown_prims: set = set()
        self._swap_target = None
        self._ref_state: dict = {}

    # -- jaxpr evaluation --------------------------------------------------

    def run_closed(self, closed, in_ivs):
        consts = [IV.const(np.asarray(c)) for c in closed.consts]
        return self.run_jaxpr(closed.jaxpr, consts, in_ivs)

    def run_jaxpr(self, jaxpr, const_ivs, in_ivs):
        env: dict = {}

        def write(var, iv):
            if type(var).__name__ == "DropVar":
                return
            env[var] = iv

        def read(atom):
            if _is_literal(atom):
                return IV.const(np.asarray(atom.val))
            return env[atom]

        for var, iv in zip(jaxpr.constvars, const_ivs):
            write(var, iv)
        for var, iv in zip(jaxpr.invars, in_ivs):
            write(var, iv)

        # liveness: drop intermediates after their last use so deep
        # kernels (a fused Miller step is ~180k eqns) hold a bounded
        # working set instead of every interval ever computed
        last_use: dict = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for a in eqn.invars:
                if not _is_literal(a):
                    last_use[a] = i
        keep = set(jaxpr.invars) | set(jaxpr.constvars)
        for v in jaxpr.outvars:
            if not _is_literal(v):
                keep.add(v)

        for i, eqn in enumerate(jaxpr.eqns):
            ins = [read(a) for a in eqn.invars]
            self._swap_target = None
            outs = self.eval_eqn(eqn, ins)
            if self._swap_target is not None:
                # a `swap` stored into a ref: rebind the ref var (and the
                # kernel-body ref state, for pallas output refs)
                tvar, tiv = self._swap_target
                env[tvar] = tiv
                if tvar in self._ref_state:
                    self._ref_state[tvar] = tiv
                self._swap_target = None
            for var, iv in zip(eqn.outvars, outs):
                iv = self._post(eqn, var, iv)
                write(var, iv)
            for a in eqn.invars:
                if not _is_literal(a) and last_use.get(a) == i \
                        and a not in keep and a in env:
                    del env[a]
        return [read(v) for v in jaxpr.outvars]

    def run_ref_body(self, body, ref_ivs):
        """Evaluate a pallas kernel body whose invars are refs."""
        self._ref_state = dict(zip(body.invars, ref_ivs))
        self.run_jaxpr(body, [], ref_ivs)

    # -- per-eqn postprocessing: overflow theorem + stats -----------------

    def _post(self, eqn, var, iv: IV) -> IV:
        self.eqn_count += 1
        rng = _dtype_range(getattr(var, "aval", None))
        if rng is None or not iv.lo.size:
            return iv
        if eqn.primitive.name == "swap":
            # the returned pre-write buffer contents (kernels discard them)
            # carry the out-ref's initial full-range state, not a computed
            # value; counting them would pin max_any at the dtype ceiling
            return iv
        mag = max(abs(iv.min_lo()), abs(iv.max_hi()))
        if mag > self.max_any:
            self.max_any = mag
        name = eqn.primitive.name
        if name == "add" and iv.max_hi() > self.max_acc:
            self.max_acc = iv.max_hi()
        if name == "dot_general" and iv.max_hi() > self.max_dot:
            self.max_dot = iv.max_hi()
        lo_ok, hi_ok = iv.min_lo() >= rng[0], iv.max_hi() <= rng[1]
        if lo_ok and hi_ok:
            return iv
        if name == "sub" and self.program.clamp_sub and hi_ok:
            # composition run: interior bias-subtraction non-negativity
            # is discharged universally by the per-k op programs
            return iv.clamp(rng[0], rng[1])
        fname, line = _eqn_src(eqn)
        dt = np.dtype(var.aval.dtype).name
        self.findings.add(
            RULE_OVERFLOW, f"{name}@{os.path.basename(fname) or '?'}:{line}",
            f"`{name}` interval [{iv.min_lo()}, {iv.max_hi()}] escapes "
            f"{dt} (2^{log2_or_zero(mag)}) at {fname}:{line}",
            line,
        )
        return iv.clamp(rng[0], rng[1])

    # -- eqn dispatch ------------------------------------------------------

    def eval_eqn(self, eqn, ins):
        handler = _HANDLERS.get(eqn.primitive.name)
        if handler is not None:
            return handler(self, eqn, ins)
        sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if sub is not None:   # pjit / closed_call / custom_* wrappers
            if hasattr(sub, "consts"):
                return self.run_closed(sub, ins)
            return self.run_jaxpr(sub, [], ins)
        return self.unknown(eqn, ins)

    def unknown(self, eqn, ins):
        name = eqn.primitive.name
        if name not in self.unknown_prims:
            self.unknown_prims.add(name)
            self.findings.add(
                RULE_INTERP, name,
                f"no interval transfer for primitive `{name}`; result "
                f"assumed full dtype range (analysis precision loss)",
            )
        outs = []
        for var in eqn.outvars:
            rng = _dtype_range(var.aval) or (-_SAT, _SAT)
            outs.append(IV.full(_aval_shape(var.aval), rng[0], rng[1]))
        return outs


# -- primitive handlers ------------------------------------------------------


def _h_add(it, eqn, ins):
    return [iv_add(ins[0], ins[1])]


def _h_sub(it, eqn, ins):
    return [iv_sub(ins[0], ins[1])]


def _h_mul(it, eqn, ins):
    return [iv_mul(ins[0], ins[1])]


def _h_and(it, eqn, ins):
    a, b = ins
    if a.min_lo() >= 0 and b.min_lo() >= 0:
        if _is_exact(a) and _is_exact(b):   # e.g. floor-correction preds
            v = np.broadcast_arrays(a.lo, b.lo)
            v = (v[0] & v[1]).copy()
            return [IV(v, v.copy())]
        hi = np.minimum(*np.broadcast_arrays(a.hi, b.hi)).copy()
        return [IV(np.zeros_like(hi), hi)]
    return it.unknown(eqn, ins)


def _h_or_xor(it, eqn, ins):
    a, b = ins
    if a.min_lo() >= 0 and b.min_lo() >= 0:
        cap = (1 << max(a.max_hi(), b.max_hi(), 1).bit_length()) - 1
        shape = np.broadcast_shapes(a.shape, b.shape)
        return [IV.full(shape, 0, cap)]
    return it.unknown(eqn, ins)


def _h_shr(it, eqn, ins):
    a, s = ins
    if a.min_lo() >= 0 and s.min_lo() >= 0:
        s_lo, s_hi = s.min_lo(), min(s.max_hi(), 63)
        shape = np.broadcast_shapes(a.shape, s.shape)
        return [IV(np.broadcast_to(a.lo >> s_hi, shape).copy(),
                   np.broadcast_to(a.hi >> s_lo, shape).copy())]
    return it.unknown(eqn, ins)


def _h_shl(it, eqn, ins):
    a, s = ins
    if a.min_lo() >= 0 and s.min_lo() >= 0:
        s_lo, s_hi = s.min_lo(), min(s.max_hi(), 62)
        shape = np.broadcast_shapes(a.shape, s.shape)
        lo = _i64(np.broadcast_to(a.lo, shape).astype(np.float64)
                  * float(1 << s_lo))
        hi = _i64(np.broadcast_to(a.hi, shape).astype(np.float64)
                  * float(1 << s_hi))
        return [IV(lo, hi)]
    return it.unknown(eqn, ins)


def _is_exact(iv: IV) -> bool:
    return bool(np.array_equal(iv.lo, iv.hi))


_CMP_NP = {
    "eq": np.equal, "ne": np.not_equal, "lt": np.less, "le": np.less_equal,
    "gt": np.greater, "ge": np.greater_equal,
}


def _h_cmp(it, eqn, ins):
    # exact on degenerate intervals — load-bearing for the MXU path: the
    # band matrix is built in-kernel as `(iota // n + iota % n) == k`
    # (pallas_call forbids captured constants), and the dot-product
    # column proof needs the exact 0/1 band, not the [0, 1] envelope
    # ([0, 1] weights would put every outer-product element in every
    # column: ~2^37 >> 2^31).  jnp's integer `//`/`%` also lower their
    # floor corrections through lt/ne over exact values.
    a, b = ins
    shape = np.broadcast_shapes(a.shape, b.shape)
    op = _CMP_NP.get(eqn.primitive.name)
    if op is not None and _is_exact(a) and _is_exact(b):
        v = op(np.broadcast_to(a.lo, shape),
               np.broadcast_to(b.lo, shape)).astype(np.int64)
        return [IV(v.copy(), v.copy())]
    return [IV.full(shape, 0, 1)]


def _h_sign(it, eqn, ins):
    # sign is monotone, so endpoint evaluation is sound and exact on
    # degenerate intervals (jnp floor_div/floor_mod corrections use it)
    a = ins[0]
    return [IV(np.sign(a.lo).copy(), np.sign(a.hi).copy())]


def _h_div(it, eqn, ins):
    # jax integer `div` rounds toward zero == floor for non-negative
    # operands, so monotone endpoint division is exact on degenerate
    # intervals and sound everywhere non-negative
    a, b = ins
    if a.min_lo() >= 0 and b.min_lo() >= 1:
        shape = np.broadcast_shapes(a.shape, b.shape)
        return [IV(np.broadcast_to(a.lo, shape) // np.broadcast_to(b.hi, shape),
                   np.broadcast_to(a.hi, shape) // np.broadcast_to(b.lo, shape))]
    return it.unknown(eqn, ins)


def _h_rem(it, eqn, ins):
    a, b = ins
    if a.min_lo() >= 0 and b.min_lo() >= 1:
        shape = np.broadcast_shapes(a.shape, b.shape)
        if _is_exact(a) and _is_exact(b):
            v = np.broadcast_to(a.lo, shape) % np.broadcast_to(b.lo, shape)
            return [IV(v.copy(), v.copy())]
        hi = np.minimum(np.broadcast_to(a.hi, shape),
                        np.broadcast_to(b.hi, shape) - 1).copy()
        return [IV(np.zeros_like(hi), hi)]
    return it.unknown(eqn, ins)


def _h_select_n(it, eqn, ins):
    pred, cases = ins[0], ins[1:]
    shape = _aval_shape(eqn.outvars[0].aval)
    if pred.min_lo() == pred.max_hi():   # statically-known selector
        idx = int(pred.min_lo())
        if 0 <= idx < len(cases):
            return [cases[idx].broadcast(shape)]
    out = cases[0]
    for c in cases[1:]:
        out = out.join(c)
    return [out.broadcast(shape)]


def _h_broadcast_in_dim(it, eqn, ins):
    shape = tuple(eqn.params["shape"])
    bdims = tuple(eqn.params["broadcast_dimensions"])
    src = ins[0]
    reshape = [1] * len(shape)
    for i, d in enumerate(bdims):
        reshape[d] = src.shape[i] if i < len(src.shape) else 1
    return [IV(np.broadcast_to(src.lo.reshape(reshape), shape).copy(),
               np.broadcast_to(src.hi.reshape(reshape), shape).copy())]


def _h_reshape(it, eqn, ins):
    shape = _aval_shape(eqn.outvars[0].aval)
    return [IV(ins[0].lo.reshape(shape), ins[0].hi.reshape(shape))]


def _h_slice(it, eqn, ins):
    p = eqn.params
    strides = p.get("strides") or (1,) * len(p["start_indices"])
    idx = tuple(slice(s, l, st) for s, l, st in
                zip(p["start_indices"], p["limit_indices"], strides))
    return [IV(ins[0].lo[idx].copy(), ins[0].hi[idx].copy())]


def _h_concatenate(it, eqn, ins):
    d = eqn.params["dimension"]
    return [IV(np.concatenate([iv.lo for iv in ins], axis=d),
               np.concatenate([iv.hi for iv in ins], axis=d))]


def _h_pad(it, eqn, ins):
    operand, padval = ins
    cfg = eqn.params["padding_config"]
    shape = _aval_shape(eqn.outvars[0].aval)
    lo = np.full(shape, padval.min_lo(), dtype=np.int64)
    hi = np.full(shape, padval.max_hi(), dtype=np.int64)
    idx = tuple(slice(max(l, 0), max(l, 0) + (d - 1) * (i + 1) + 1, i + 1)
                for (l, _h, i), d in zip(cfg, operand.shape))
    try:
        lo[idx] = operand.lo
        hi[idx] = operand.hi
    except ValueError:
        return it.unknown(eqn, ins)   # negative (clipping) pads: unused here
    return [IV(lo, hi)]


def _h_transpose(it, eqn, ins):
    perm = eqn.params["permutation"]
    return [IV(np.transpose(ins[0].lo, perm).copy(),
               np.transpose(ins[0].hi, perm).copy())]


def _h_rev(it, eqn, ins):
    dims = tuple(eqn.params["dimensions"])
    return [IV(np.flip(ins[0].lo, dims).copy(),
               np.flip(ins[0].hi, dims).copy())]


def _h_iota(it, eqn, ins):
    shape = _aval_shape(eqn.outvars[0].aval)
    d = eqn.params["dimension"]
    vals = np.arange(shape[d], dtype=np.int64)
    vals = np.broadcast_to(
        vals.reshape([-1 if i == d else 1 for i in range(len(shape))]), shape)
    return [IV(vals.copy(), vals.copy())]


def _h_identity(it, eqn, ins):
    return [IV(ins[0].lo.copy(), ins[0].hi.copy())]


def _h_scatter_add(it, eqn, ins):
    # blunt but sound: every output element may absorb any update sum;
    # the XLA mont path's `.at[].add` touches each slot once, so the
    # global update min/max is the exact increment envelope
    operand, _indices, updates = ins
    return [IV(_i64(operand.lo.astype(np.float64) + min(0, updates.min_lo())),
               _i64(operand.hi.astype(np.float64) + max(0, updates.max_hi())))]


def _h_reduce_sum(it, eqn, ins):
    axes = tuple(eqn.params["axes"])
    return [IV(_i64(ins[0].lo.astype(np.float64).sum(axis=axes)),
               _i64(ins[0].hi.astype(np.float64).sum(axis=axes)))]


def _h_reduce_minmax(it, eqn, ins):
    axes = tuple(eqn.params["axes"])
    return [IV(ins[0].lo.min(axis=axes), ins[0].hi.max(axis=axes))]


def _h_min(it, eqn, ins):
    a, b = ins
    return [IV(np.minimum(*np.broadcast_arrays(a.lo, b.lo)).copy(),
               np.minimum(*np.broadcast_arrays(a.hi, b.hi)).copy())]


def _h_max(it, eqn, ins):
    a, b = ins
    return [IV(np.maximum(*np.broadcast_arrays(a.lo, b.lo)).copy(),
               np.maximum(*np.broadcast_arrays(a.hi, b.hi)).copy())]


def _h_dot_general(it, eqn, ins):
    a, b = ins
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    shape = _aval_shape(eqn.outvars[0].aval)
    if not lb and not rb and a.min_lo() >= 0 and b.min_lo() >= 0:
        # precise non-negative interval matmul: every product term is
        # monotone in both endpoints, so lo = lo.lo and hi = hi.hi,
        # contracted per element.  This is what proves the MXU column
        # budget: an exact 0/1 band row sums only its own diagonal's
        # outer products (31 * 8194^2 < 2^31), where the k*max*max
        # envelope below would claim 961 * 8194^2.  float64 is exact
        # here (sums stay far below 2^53) and _i64 saturates the cast.
        lo = np.tensordot(a.lo.astype(np.float64),
                          b.lo.astype(np.float64), axes=(lc, rc))
        hi = np.tensordot(a.hi.astype(np.float64),
                          b.hi.astype(np.float64), axes=(lc, rc))
        if np.shape(lo) == shape:
            return [IV(_i64(lo), _i64(hi))]
    # coarse envelope (mixed signs / batch dims): k * max|a| * max|b|
    k = 1
    for d in lc:
        k *= a.shape[d]
    mag = float(k) * max(abs(a.min_lo()), abs(a.max_hi())) \
        * max(abs(b.min_lo()), abs(b.max_hi()))
    lo = 0.0 if (a.min_lo() >= 0 and b.min_lo() >= 0) else -mag
    return [IV.full(shape, int(_i64(np.float64(lo))),
                    int(_i64(np.float64(mag))))]


def _h_get(it, eqn, ins):
    ref = ins[0]
    out_shape = _aval_shape(eqn.outvars[0].aval)
    if ref.shape == out_shape:
        return [IV(ref.lo.copy(), ref.hi.copy())]
    # indexed read (e.g. the SMEM digit tape): envelope of the ref
    return [IV.full(out_shape, ref.min_lo(), ref.max_hi())]


def _h_swap(it, eqn, ins):
    ref_var = eqn.invars[0]
    old, val = ins[0], ins[1]
    out_shape = _aval_shape(eqn.outvars[0].aval)
    if val.shape == old.shape:
        new = IV(val.lo.copy(), val.hi.copy())
    else:   # partial store: conservative join over the whole ref
        new = old.join(IV.full(old.shape, val.min_lo(), val.max_hi()))
    it._swap_target = (ref_var, new)
    if old.shape == out_shape:
        return [IV(old.lo.copy(), old.hi.copy())]
    return [IV.full(out_shape, old.min_lo(), old.max_hi())]


def _widen(iv: IV) -> IV:
    hi = (1 << min(62, max(1, iv.max_hi()).bit_length() + 1)) - 1
    lo_m = iv.min_lo()
    lo = 0 if lo_m >= 0 else -(1 << min(62, int(-lo_m).bit_length() + 1))
    return IV.full(iv.shape, lo, hi)


def _fixpoint(it, run_body, carry, what, pinned=()):
    state = list(carry)
    for rounds in range(_FIX_ITERS):
        outs = run_body(state)
        stable, nxt = True, []
        for i, (old, new) in enumerate(zip(state, outs[:len(state)])):
            if i in pinned or old.contains(new):
                nxt.append(old)
                continue
            stable = False
            j = old.join(new)
            if rounds >= _WIDEN_AFTER:
                j = _widen(j)
            nxt.append(j)
        state = nxt
        if stable:
            return state, outs
    it.findings.add(
        RULE_INTERP, f"{what}-fixpoint",
        f"{what} carry did not converge within {_FIX_ITERS} iterations; "
        f"intervals widened to saturation",
    )
    state = [IV.full(s.shape, -_SAT, _SAT) for s in state]
    return state, run_body(state)


def _scan_counter_pins(body, nc, ncarry, carry, length):
    """Exact ranges for arithmetic-progression carry slots.

    ``fori_loop`` lowers to ``scan`` with its counter in the carry; a
    counter has no fixpoint (it strictly increments), but the scan's
    static trip count bounds it exactly: a slot whose body output is
    ``add(slot_invar, literal c)`` holds ``init + c*t`` for
    ``t in [0, length-1]``."""
    pins = {}
    if not length:
        return pins
    try:
        jaxpr = body.jaxpr
        for i in range(ncarry):
            ov, in_v = jaxpr.outvars[i], jaxpr.invars[nc + i]
            for eq in jaxpr.eqns:
                if (len(eq.outvars) != 1 or eq.outvars[0] is not ov
                        or eq.primitive.name != "add"):
                    continue
                a, b = eq.invars
                c = None
                if a is in_v and _is_literal(b):
                    c = int(b.val)
                elif b is in_v and _is_literal(a):
                    c = int(a.val)
                if c is None:
                    continue
                lo0, hi0 = carry[i].min_lo(), carry[i].max_hi()
                last = c * (int(length) - 1)
                pins[i] = IV.full(carry[i].shape,
                                  min(lo0, lo0 + last), max(hi0, hi0 + last))
    except Exception:
        return {}
    return pins


def _h_scan(it, eqn, ins):
    p = eqn.params
    nc, ncarry = p["num_consts"], p["num_carry"]
    body = p["jaxpr"]
    consts, carry, xs = ins[:nc], ins[nc:nc + ncarry], ins[nc + ncarry:]
    x_elems = []
    for iv in xs:
        if iv.lo.ndim >= 1 and iv.lo.shape[0] > 0:
            x_elems.append(IV(iv.lo.min(axis=0), iv.hi.max(axis=0)))
        else:
            x_elems.append(IV.full(iv.shape[1:], 0, 0))
    pins = _scan_counter_pins(body, nc, ncarry, carry, p.get("length"))
    carry = [pins.get(i, c) for i, c in enumerate(carry)]

    def run_body(state):
        return it.run_closed(body, consts + state + x_elems)

    state, outs = _fixpoint(it, run_body, carry, "scan",
                            pinned=frozenset(pins))
    stacked = []
    for y, var in zip(outs[ncarry:], eqn.outvars[ncarry:]):
        shape = _aval_shape(var.aval)
        stacked.append(IV(np.broadcast_to(y.lo, shape).copy(),
                          np.broadcast_to(y.hi, shape).copy()))
    return list(state) + stacked


def _while_counter_caps(p, cond_consts):
    """Strict upper bounds the loop condition imposes on carry slots.

    ``fori_loop`` lowers to ``while`` with an ``i < n`` condition; without
    this refinement the counter has no fixpoint and widens to saturation.
    Sound for any ``lt(carry_i, B)``: while the body runs the condition
    held, so carry_i <= hi(B) - 1 inside the body (the loop *output* may
    still equal hi(B) and is not clamped)."""
    caps = {}
    try:
        jaxpr = p["cond_jaxpr"].jaxpr
        cn = len(cond_consts)
        slot = {v: i - cn for i, v in enumerate(jaxpr.invars)}
        out = jaxpr.outvars[0]
        for eq in jaxpr.eqns:
            if eq.primitive.name != "lt" or eq.outvars[0] is not out:
                continue
            a, b = eq.invars
            if _is_literal(a) or slot.get(a, -1) < 0:
                continue
            if _is_literal(b):
                caps[slot[a]] = int(b.val) - 1
            elif b in slot:
                bound = cond_consts[slot[b]] if slot[b] < 0 else None
                if bound is not None:
                    caps[slot[a]] = bound.max_hi() - 1
    except Exception:
        return {}
    return caps


def _h_while(it, eqn, ins):
    p = eqn.params
    cn, bn = p["cond_nconsts"], p["body_nconsts"]
    body = p["body_jaxpr"]
    b_consts = ins[cn:cn + bn]
    carry = ins[cn + bn:]
    caps = _while_counter_caps(p, ins[:cn])

    def run_body(state):
        fed = list(state)
        for i, cap in caps.items():
            s = fed[i]
            if s.max_hi() > cap:
                fed[i] = IV(np.minimum(s.lo, cap), np.minimum(s.hi, cap))
        return it.run_closed(body, b_consts + fed)

    state, _outs = _fixpoint(it, run_body, carry, "while")
    return state


def _h_cond(it, eqn, ins):
    branches = eqn.params["branches"]
    ops = list(ins[1:])
    outs = None
    for br in branches:
        b_outs = it.run_closed(br, ops)
        outs = b_outs if outs is None else [
            a.join(b) for a, b in zip(outs, b_outs)
        ]
    return outs


def _h_pallas_call(it, eqn, ins):
    body = eqn.params["jaxpr"]   # kernel body; invars are refs
    n_in, n_out = len(eqn.invars), len(eqn.outvars)
    if len(body.invars) != n_in + n_out:
        it.findings.add(
            RULE_INTERP, "pallas-refs",
            f"kernel body has {len(body.invars)} refs for {n_in} inputs + "
            f"{n_out} outputs (scratch refs unsupported); outputs assumed "
            f"full-range",
        )
        return [IV.full(_aval_shape(v.aval), *(
            _dtype_range(v.aval) or (-_SAT, _SAT))) for v in eqn.outvars]
    out_states = []
    for v in eqn.outvars:
        rng = _dtype_range(v.aval) or (-_SAT, _SAT)
        out_states.append(IV.full(_aval_shape(v.aval), rng[0], rng[1]))
    it.run_ref_body(body, list(ins) + out_states)
    return [it._ref_state[body.invars[n_in + i]] for i in range(n_out)]


_HANDLERS = {
    "add": _h_add, "sub": _h_sub, "mul": _h_mul,
    "and": _h_and, "or": _h_or_xor, "xor": _h_or_xor,
    "shift_right_logical": _h_shr, "shift_right_arithmetic": _h_shr,
    "shift_left": _h_shl,
    "eq": _h_cmp, "ne": _h_cmp, "lt": _h_cmp, "le": _h_cmp,
    "gt": _h_cmp, "ge": _h_cmp,
    "div": _h_div, "rem": _h_rem, "sign": _h_sign,
    "select_n": _h_select_n,
    "broadcast_in_dim": _h_broadcast_in_dim,
    "reshape": _h_reshape, "squeeze": _h_reshape,
    "slice": _h_slice, "concatenate": _h_concatenate, "pad": _h_pad,
    "transpose": _h_transpose, "rev": _h_rev, "iota": _h_iota,
    "convert_element_type": _h_identity,
    "device_put": _h_identity, "copy": _h_identity,
    "stop_gradient": _h_identity,
    "scatter-add": _h_scatter_add,
    "reduce_sum": _h_reduce_sum,
    "reduce_max": _h_reduce_minmax, "reduce_min": _h_reduce_minmax,
    "min": _h_min, "max": _h_max,
    "dot_general": _h_dot_general,
    "get": _h_get, "swap": _h_swap,
    "scan": _h_scan, "while": _h_while, "cond": _h_cond,
    "pallas_call": _h_pallas_call,
}


# ---------------------------------------------------------------------------
# Input-interval builders (exported for registries and fixtures)
# ---------------------------------------------------------------------------


def _fp_mod():
    from lighthouse_tpu.crypto.bls.jax_backend import fp as F
    return F


def cap_for_bound(bound) -> int:
    """Max top limb of a 26x15 representation of a value < bound*P."""
    F = _fp_mod()
    return int((Fraction(bound) * F.P_INT) // (1 << (F.BITS * (F.N - 1))))


def caps_iv(shape, kind="quasi", bound=None) -> IV:
    """Per-limb input interval for a (26, T) limb plane.

    kind "strict" caps rows at 2^15 - 1, "quasi" at QMAX; a value bound
    (units of P) additionally caps the top row at cap_for_bound(bound) —
    justified by the exact-layer bound algebra (see module docstring).
    """
    F = _fp_mod()
    base = F.MASK if kind == "strict" else F.QMAX
    hi = np.full(shape, int(base), dtype=np.int64)
    if bound is not None:
        hi[F.N - 1] = min(int(base), cap_for_bound(bound))
    return IV(np.zeros(shape, dtype=np.int64), hi)


def _limbs_mod():
    from lighthouse_tpu.crypto.bls.jax_backend import limbs as L
    return L


def caps13_iv(shape, kind="quasi13") -> IV:
    """Per-limb input interval for a (31, T) 13-bit limb plane.

    kind "strict13" caps rows at 2^13 - 1, "quasi13" at limbs.SPEC13's
    QMAX13 = 2^13 + 2 — the declared representation contract of the MXU
    re-limb (``_to13`` actually proves <= 8193; the extra headroom keeps
    the contract independent of the conversion's incidental tightness).
    """
    L = _limbs_mod()
    base = (1 << 13) - 1 if kind == "strict13" else int(L.SPEC13.qmax)
    return IV(np.zeros(shape, dtype=np.int64),
              np.full(shape, base, dtype=np.int64))


def bits_iv(shape) -> IV:
    return IV.full(shape, 0, 1)


def range_iv(shape, lo, hi) -> IV:
    return IV.full(shape, lo, hi)


# ---------------------------------------------------------------------------
# Live program registry
# ---------------------------------------------------------------------------

_TILE = 128
_FP_PATH = "lighthouse_tpu/crypto/bls/jax_backend/fp.py"
_PF_PATH = "lighthouse_tpu/crypto/bls/jax_backend/pallas_fp.py"
_PM_PATH = "lighthouse_tpu/crypto/bls/jax_backend/pallas_miller.py"
_PW_PATH = "lighthouse_tpu/crypto/bls/jax_backend/pallas_wsm.py"
_PMX_PATH = "lighthouse_tpu/crypto/bls/jax_backend/pallas_mxu.py"

STRICT_CONTRACT = "strict"
QUASI_CONTRACT = "quasi"
STRICT13_CONTRACT = "strict13"   # < 2^13 (MXU plane, post carry chain)
QUASI13_CONTRACT = "quasi13"     # <= QMAX13 = 2^13 + 2 (MXU plane)


def _u32(shape):
    import jax.numpy as jnp
    return jnp.ones(shape, dtype=jnp.uint32)


def _build_pallas_mont():
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_fp as PF

    def fn(x, y):
        return PF.mont_mul_limbs(x, y, interpret=True)

    a = _u32((26, _TILE))
    return fn, (a, a), [caps_iv((26, _TILE)), caps_iv((26, _TILE))]


def _build_pallas_mont_sqr():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_fp as PF

    def kernel(a_ref, p_ref, pp_ref, o_ref):
        o_ref[:] = PF._mont_sqr_core(a_ref[:], p_ref[:], pp_ref[:])

    p = jnp.broadcast_to(jnp.asarray(PF._P_COLS, dtype=jnp.uint32),
                         (26, _TILE))
    pp = jnp.broadcast_to(jnp.asarray(PF._PP_COLS, dtype=jnp.uint32),
                          (26, _TILE))

    def fn(a, pc, ppc):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((26, _TILE), jnp.uint32),
            interpret=True,
        )(a, pc, ppc)

    return fn, (_u32((26, _TILE)), p, pp), [
        caps_iv((26, _TILE)), IV.const(np.asarray(p)), IV.const(np.asarray(pp)),
    ]


def _build_megachain():
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_fp as PF

    # 4 base-16 digits -> in-kernel power table + 3 window iterations
    def fn(x):
        return PF.pow_chain_limbs(x, 0x1234, interpret=True)

    a = _u32((26, _TILE))
    return fn, (a,), [caps_iv((26, _TILE))]


def _build_fp2_megachain():
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_fp as PF
    bits = (1, 0, 1, 1, 0, 1, 0, 1)

    def fn(x, y):
        return PF.fp2_pow_chain(x, y, bits, interpret=True)

    a = _u32((26, _TILE))
    return fn, (a, a), [caps_iv((26, _TILE)), caps_iv((26, _TILE))]


def _strict2():
    return caps_iv((26, _TILE), "strict", 2.0)


def _build_miller(which):
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_miller as PM
    consts = PM._const_arrays(_TILE)
    plane = _u32((26, _TILE))
    bitp = _u32((1, _TILE))
    if which == "dbl":
        call = PM._dbl_call(_TILE, _TILE, True)
        n_planes = PM._F12 + PM._TPT + 2
        args = [plane] * n_planes + list(consts)
        ivs = [_strict2() for _ in range(n_planes)] \
            + [IV.const(np.asarray(c)) for c in consts]
    else:
        call = PM._add_call(_TILE, _TILE, True)
        n_planes = PM._F12 + PM._TPT + 4 + 2
        args = [plane] * n_planes + [bitp] + list(consts)
        ivs = [_strict2() for _ in range(n_planes)] + [bits_iv((1, _TILE))] \
            + [IV.const(np.asarray(c)) for c in consts]

    def fn(*xs):
        return call(*xs)

    return fn, tuple(args), ivs


def _build_wsm(ncoords):
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_miller as PM
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_wsm as PW
    consts = PM._const_arrays(_TILE)
    plane = _u32((26, _TILE))
    flag = _u32((1, _TILE))
    call = PW._step_call(ncoords, _TILE, _TILE, True)
    n_acc = 3 * ncoords    # jacobian accumulator
    n_base = 2 * ncoords   # affine base point
    args = [plane] * n_acc + [flag] + [plane] * n_base + [flag, flag] \
        + list(consts)
    ivs = [_strict2() for _ in range(n_acc)] + [bits_iv((1, _TILE))] \
        + [_strict2() for _ in range(n_base)] \
        + [bits_iv((1, _TILE)), bits_iv((1, _TILE))] \
        + [IV.const(np.asarray(c)) for c in consts]

    def fn(*xs):
        return call(*xs)

    return fn, tuple(args), ivs


def _build_mxu_mont():
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_fp as PF

    def fn(x, y):
        return PF.mont_mul_limbs(x, y, interpret=True, mxu=True)

    a = _u32((26, _TILE))
    return fn, (a, a), [caps_iv((26, _TILE)), caps_iv((26, _TILE))]


def _build_mxu_mont_sqr():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_fp as PF
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_mxu as PMX

    def kernel(a_ref, p_ref, pp_ref, o_ref):
        a = a_ref[:]
        o_ref[:] = PMX.mont_core_mxu(a, a, p_ref[:], pp_ref[:])

    p = jnp.broadcast_to(jnp.asarray(PF._P_COLS, dtype=jnp.uint32),
                         (26, _TILE))
    pp = jnp.broadcast_to(jnp.asarray(PF._PP_COLS, dtype=jnp.uint32),
                          (26, _TILE))

    def fn(a, pc, ppc):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((26, _TILE), jnp.uint32),
            interpret=True,
        )(a, pc, ppc)

    return fn, (_u32((26, _TILE)), p, pp), [
        caps_iv((26, _TILE)), IV.const(np.asarray(p)), IV.const(np.asarray(pp)),
    ]


def _build_mxu_megachain():
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_fp as PF

    def fn(x):
        return PF.pow_chain_limbs(x, 0x1234, interpret=True, mxu=True)

    a = _u32((26, _TILE))
    return fn, (a,), [caps_iv((26, _TILE))]


def _build_mxu_component(which):
    """Standalone traces of the MXU re-limb/dot building blocks at their
    *declared* representation caps — stronger than the derived bounds the
    whole-kernel runs propagate, so the contracts stay meaningful if the
    conversions ever get looser."""
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_mxu as PMX
    if which == "to13":
        a = _u32((26, _TILE))
        return (lambda x: PMX._to13(x)), (a,), [caps_iv((26, _TILE))]
    if which == "to15":
        a = _u32((31, _TILE))
        return (lambda x: PMX._to15(x)), (a,), \
            [caps13_iv((31, _TILE), "strict13")]
    a = _u32((31, _TILE))
    return (lambda x, y: PMX._dot_cols(x, y)), (a, a), \
        [caps13_iv((31, _TILE)), caps13_iv((31, _TILE))]


def _build_xla_mont():
    from lighthouse_tpu.crypto.bls.jax_backend import fp as F

    def fn(a, b):
        was = F.pallas_enabled()
        F.set_pallas(False)
        try:
            # the bound labels only steer trace-time bookkeeping; the
            # intervals below quantify over ALL quasi limb planes
            return F.mont_mul(F.LFp(a, 40.0), F.LFp(b, 40.0)).limbs
        finally:
            F.set_pallas(was)

    a = _u32((26, 8))
    return fn, (a, a), [caps_iv((26, 8)), caps_iv((26, 8))]


def _build_xla_fp_add():
    from lighthouse_tpu.crypto.bls.jax_backend import fp as F
    half = F.MAX_BOUND / 2

    def fn(a, b):
        return F.fp_add(F.LFp(a, half), F.LFp(b, half)).limbs

    a = _u32((26, 8))
    return fn, (a, a), [caps_iv((26, 8), "quasi", half),
                        caps_iv((26, 8), "quasi", half)]


def _build_xla_fp_sub(k):
    from lighthouse_tpu.crypto.bls.jax_backend import fp as F
    b_bound = F.sub_bias_max_bound(k)
    a_bound = F.MAX_BOUND - k

    def fn(a, b):
        return F.fp_sub(F.LFp(a, a_bound), F.LFp(b, b_bound)).limbs

    a = _u32((26, 8))
    return fn, (a, a), [caps_iv((26, 8), "quasi", a_bound),
                        caps_iv((26, 8), "quasi", b_bound)]


def _build_ksub(k):
    """Pallas-side bias subtraction columns (pad-based _compress1)."""
    import jax.numpy as jnp
    from lighthouse_tpu.crypto.bls.jax_backend import fp as F
    from lighthouse_tpu.crypto.bls.jax_backend import pallas_fp as PF
    b_bound = F.sub_bias_max_bound(k)
    a_bound = F.MAX_BOUND - k
    bias = jnp.asarray(F._BIAS_NP[k].reshape(26, 1))

    def fn(a, b):
        return PF._compress1((a + jnp.broadcast_to(bias, a.shape)) - b)

    a = _u32((26, _TILE))
    return fn, (a, a), [caps_iv((26, _TILE), "quasi", a_bound),
                        caps_iv((26, _TILE), "quasi", b_bound)]


def build_live_programs() -> list:
    from lighthouse_tpu.crypto.bls.jax_backend import fp as F
    progs = [
        RangeProgram(
            "pallas_mont_mul", _PF_PATH, _build_pallas_mont,
            contracts=((0, STRICT_CONTRACT),),
            note="Montgomery product kernel, ALL quasi inputs",
        ),
        RangeProgram(
            "pallas_mont_sqr", _PF_PATH, _build_pallas_mont_sqr,
            contracts=((0, STRICT_CONTRACT),),
            note="_mont_sqr_core triangle square, ALL quasi inputs",
        ),
        RangeProgram(
            "pallas_megachain_w4", _PF_PATH, _build_megachain,
            contracts=((0, QUASI_CONTRACT),), clamp_sub=True,
            note="fused pow chain: SMEM digit tape + in-kernel table "
                 "(loop output joins the quasi power-table init, so the "
                 "provable exit contract is quasi, not strict)",
        ),
        RangeProgram(
            "pallas_fp2_megachain_w4", _PF_PATH, _build_fp2_megachain,
            contracts=((0, QUASI_CONTRACT), (1, QUASI_CONTRACT)),
            clamp_sub=True,
            note="fp2 Karatsuba pow chain; exit bounds <= (3.2P, 5.2P)",
        ),
        RangeProgram(
            "mxu_mont_mul", _PMX_PATH, _build_mxu_mont,
            contracts=((0, STRICT_CONTRACT),),
            note="13-bit dot-product Montgomery kernel, ALL quasi inputs; "
                 "the int32 MXU column budget rides the precise "
                 "dot_general transfer (exact iota-built 0/1 band)",
        ),
        RangeProgram(
            "mxu_mont_sqr", _PMX_PATH, _build_mxu_mont_sqr,
            contracts=((0, STRICT_CONTRACT),),
            note="MXU square (mont_core_mxu(a, a)), ALL quasi inputs",
        ),
        RangeProgram(
            "mxu_megachain_w4", _PMX_PATH, _build_mxu_megachain,
            contracts=((0, QUASI_CONTRACT),), clamp_sub=True,
            note="fused pow chain on the MXU cores (mxu=True route); "
                 "same exit contract as the VPU megachain",
        ),
        RangeProgram(
            "mxu_to13", _PMX_PATH, lambda: _build_mxu_component("to13"),
            contracts=((0, QUASI13_CONTRACT),),
            note="15->13 re-limb: quasi-15 in, quasi-13 (<= QMAX13) out",
        ),
        RangeProgram(
            "mxu_to15", _PMX_PATH, lambda: _build_mxu_component("to15"),
            contracts=((0, STRICT_CONTRACT),),
            note="13->15 bit regroup: strict-13 in, strict-15 out",
        ),
        RangeProgram(
            "mxu_dot_cols", _PMX_PATH, lambda: _build_mxu_component("dot"),
            contracts=((0, QUASI13_CONTRACT),),
            note="61-column banded matmul at the declared quasi-13 cap: "
                 "31 * QMAX13^2 < 2^31 int32 budget",
        ),
        RangeProgram(
            "pallas_miller_dbl", _PM_PATH, lambda: _build_miller("dbl"),
            contracts=tuple((i, QUASI_CONTRACT) for i in range(18)),
            clamp_sub=True, heavy=True,
            note="fused Miller double step (f12 sqr + line + mul_by_023)",
        ),
        RangeProgram(
            "pallas_miller_add", _PM_PATH, lambda: _build_miller("add"),
            contracts=tuple((i, QUASI_CONTRACT) for i in range(18)),
            clamp_sub=True, heavy=True,
            note="fused Miller add step (line add + select by bit)",
        ),
        RangeProgram(
            "pallas_wsm_g1", _PW_PATH, lambda: _build_wsm(1),
            contracts=tuple((i, QUASI_CONTRACT) for i in range(3)),
            clamp_sub=True, heavy=True,
            note="fused WSM double+add step, G1 (Fp coords)",
        ),
        RangeProgram(
            "pallas_wsm_g2", _PW_PATH, lambda: _build_wsm(2),
            contracts=tuple((i, QUASI_CONTRACT) for i in range(6)),
            clamp_sub=True, heavy=True,
            note="fused WSM double+add step, G2 (Fp2 coords)",
        ),
        RangeProgram(
            "xla_mont_mul", _FP_PATH, _build_xla_mont,
            contracts=((0, STRICT_CONTRACT),),
            note="XLA Horner-scan Montgomery path, ALL quasi inputs",
        ),
        RangeProgram(
            "xla_fp_add", _FP_PATH, _build_xla_fp_add,
            contracts=((0, QUASI_CONTRACT),),
            note="fp_add at the MAX_BOUND admissibility edge",
        ),
    ]
    for k in F._BIAS_KS:
        progs.append(RangeProgram(
            f"xla_fp_sub_k{k}", _FP_PATH,
            (lambda kk: lambda: _build_xla_fp_sub(kk))(k),
            contracts=((0, QUASI_CONTRACT),),
            note=f"fp_sub bias domination, k={k}, subtrahend at the "
                 f"_k_for threshold bound",
        ))
        progs.append(RangeProgram(
            f"pallas_ksub_k{k}", _PF_PATH,
            (lambda kk: lambda: _build_ksub(kk))(k),
            contracts=((0, QUASI_CONTRACT),),
            note=f"in-kernel ksub columns, k={k}",
        ))
    return progs


# ---------------------------------------------------------------------------
# Program analysis
# ---------------------------------------------------------------------------


def _default_ivs(closed, provided):
    """Align provided IVs with the jaxpr invars; fill gaps generically.

    Registries may leave trailing invars unspecified when they are
    wrapper-materialized operands (digit tapes, broadcast constant
    planes): int32 vectors are treated as window-digit tapes, 26-row
    uint32 planes as quasi limb planes, anything else full dtype range.
    """
    invars = closed.jaxpr.invars
    out = list(provided or ())
    for var in invars[len(out):]:
        rng = _dtype_range(var.aval) or (-_SAT, _SAT)
        shape = _aval_shape(var.aval)
        dt = np.dtype(getattr(var.aval, "dtype", np.int64)).name
        if dt == "int32" and len(shape) == 1:
            out.append(IV.full(shape, 0, 15))
        elif dt == "uint32" and len(shape) == 2 and shape[0] == 26:
            out.append(caps_iv(shape))
        elif dt == "uint32" and len(shape) == 2 and shape[0] == 31:
            out.append(caps13_iv(shape))
        else:
            out.append(IV.full(shape, rng[0], rng[1]))
    return out


def analyze_program(prog: RangeProgram) -> tuple:
    """(violations, per-program report entry)."""
    import jax
    fn, args, ivs = prog.build()
    closed = jax.make_jaxpr(fn)(*args)
    findings = _Findings(prog)
    interp = _Interp(prog, findings)
    outs = interp.run_jaxpr(
        closed.jaxpr,
        [IV.const(np.asarray(c)) for c in closed.consts],
        _default_ivs(closed, ivs),
    )
    F = _fp_mod()
    contracts_ok = True
    for idx, kind in prog.contracts:
        if idx >= len(outs):
            continue
        iv = outs[idx]
        if isinstance(kind, (tuple, list)):
            label, cap = kind
        elif kind == STRICT_CONTRACT:
            label, cap = "strict", F.MASK
        elif kind == STRICT13_CONTRACT:
            label, cap = "strict13", (1 << 13) - 1
        elif kind == QUASI13_CONTRACT:
            label, cap = "quasi13", int(_limbs_mod().SPEC13.qmax)
        else:
            label, cap = "quasi", F.QMAX
        if iv.max_hi() > cap or iv.min_lo() < 0:
            contracts_ok = False
            findings.add(
                RULE_CONTRACT, f"out{idx}",
                f"output {idx} violates `{label}` contract: interval "
                f"[{iv.min_lo()}, {iv.max_hi()}] vs cap {cap}",
            )
    report = {
        "eqns": interp.eqn_count,
        "max_any_log2": log2_or_zero(interp.max_any),
        "max_acc_log2": log2_or_zero(interp.max_acc),
        "max_dot_log2": log2_or_zero(interp.max_dot),
        "out_caps": [iv.max_hi() for iv in outs],
        "contracts_ok": contracts_ok,
        "note": prog.note,
    }
    return findings.violations(), report


# ---------------------------------------------------------------------------
# Exact LFp bound-algebra checks
# ---------------------------------------------------------------------------


def live_claims() -> dict:
    F = _fp_mod()
    return {
        "name": "live",
        "path": _FP_PATH,
        "mont_divisor": F.MONT_DIVISOR,
        "mont_eps": F.MONT_EPS,
        "reduce_pin": F.REDUCE_PIN,
        "max_mul_product": F.MAX_MUL_PRODUCT,
        "max_bound": F.MAX_BOUND,
    }


def lfp_check(claims: dict) -> tuple:
    """Exact-arithmetic soundness/slack audit of one claims set."""
    F = _fp_mod()
    P = F.P_INT
    R = 1 << (F.BITS * F.N)
    shift = F.BITS * (F.N - 1)
    name = claims.get("name", "live")
    path = claims.get("path", _FP_PATH)
    div = Fraction(claims["mont_divisor"])
    eps = Fraction(claims["mont_eps"])
    pin = Fraction(claims["reduce_pin"])
    prod_max = Fraction(claims["max_mul_product"])
    bound_max = Fraction(claims["max_bound"])
    pr = Fraction(P, R)   # exact P/R
    checks: list = []

    def rec(check, sound, claimed, true, slack=None, detail=""):
        checks.append({
            "check": check, "sound": bool(sound),
            "claimed": float(claimed) if claimed is not None else None,
            "true": float(true) if true is not None else None,
            "slack": round(float(slack), 4) if slack is not None else None,
            "detail": detail,
        })

    # 1. mont output bound: claimed prod/div + eps vs exact prod*P/R + 1;
    #    both sides are affine in prod, so endpoint checks suffice
    for prod in (Fraction(0), prod_max):
        claimed = prod / div + eps
        true = prod * pr + 1
        rec(f"mont-output-bound@prod={float(prod):g}", claimed >= true,
            claimed, true,
            float((claimed - true) / claimed) if claimed else None,
            f"exact R/P = {float(Fraction(R, P)):.4f} vs divisor "
            f"{float(div):g}")
    # 2. reduce pin: must cover both the exact bound of a MAX_BOUND input
    #    through one mont-by-one and the trace-time formula label
    true_reduce = bound_max * pr + 1
    formula_reduce = bound_max / div + eps
    rec("reduce-pin", pin >= true_reduce and pin >= formula_reduce,
        pin, true_reduce, float((pin - true_reduce) / pin),
        "fp_reduce pins the scan-stable label; exact worst case "
        "MAX_BOUND*P/R + 1")
    # 3. fp_pow fixpoint closure: fix = claimed(prod_max); requires
    #    fix^2 admissible and claimed(fix^2) <= fix (no slack metric —
    #    this is a closure property, not a tightness one)
    fix = prod_max / div + eps
    closure = (fix * fix) / div + eps
    rec("pow-fix-closure", fix * fix <= prod_max and closure <= fix,
        fix, closure, None,
        "fix must absorb one squaring step (fix^2 admissible, output "
        "re-enters the class)")
    # 4. top-column carry headroom: compress1 silently drops the top
    #    limb's carry; the worst top column of any admissible value is
    #    cap(MAX_BOUND) and must stay below 2^15
    cap_max = int((bound_max * P) // (1 << shift))
    rec("compress1-top-carry", cap_max <= F.MASK,
        Fraction(cap_max), Fraction(F.MASK), None,
        f"cap(MAX_BOUND) = {cap_max} must stay below 2^15 so the "
        f"dropped top carry is identically zero")
    # 5. per-k bias tables: exact value, low-limb quasi domination,
    #    top-limb domination at the _k_for threshold, and top-column
    #    headroom of the fp_sub result at the MAX_BOUND edge
    for k in F._BIAS_KS:
        limbs = [int(v) for v in F._biased_kp(k)]
        value_ok = sum(v << (F.BITS * i)
                       for i, v in enumerate(limbs)) == k * P
        low_ok = all(v >= F.QMAX for v in limbs[:-1])
        top = limbs[-1]
        thr = F.sub_bias_max_bound(k)
        cap_thr = int((Fraction(thr) * P) // (1 << shift))
        dom_ok = cap_thr <= top
        a_cap = int(((bound_max - k) * P) // (1 << shift))
        col_ok = (a_cap + top) <= F.MASK
        rec(f"bias-k{k}", value_ok and low_ok and dom_ok and col_ok,
            Fraction(top), Fraction(cap_thr), None,
            f"value==k*P:{value_ok} low>=QMAX:{low_ok} "
            f"top {top} >= cap(thr {thr:.6g}) = {cap_thr}:{dom_ok} "
            f"top-col {a_cap}+{top} < 2^15:{col_ok}")
    # 6. wide-product admissibility: prod_max * P^2 must fit the 52-limb
    #    double-width accumulator
    rec("mont-prod-admissible", prod_max * P * P < Fraction(R) * R,
        prod_max, Fraction(R) * R / (P * P), None,
        "a*b < prod_max*P^2 must fit the 52-limb wide accumulator")

    violations = []
    for c in checks:
        if not c["sound"]:
            violations.append(Violation(
                rule=RULE_LFP, path=path, line=0,
                symbol=f"{name}:{c['check']}",
                message=(
                    f"unsound bound constant: claimed {c['claimed']} vs "
                    f"exact {c['true']} — {c['detail']}"
                ),
            ))
        elif c["slack"] is not None and c["slack"] > SLACK_MAX:
            violations.append(Violation(
                rule=RULE_SLACK, path=path, line=0,
                symbol=f"{name}:{c['check']}",
                message=(
                    f"needlessly loose bound constant: claimed "
                    f"{c['claimed']} vs exact {c['true']} "
                    f"(slack {c['slack']:.0%} > {SLACK_MAX:.0%})"
                ),
            ))
    return violations, checks


# ---------------------------------------------------------------------------
# MXU-readiness report
# ---------------------------------------------------------------------------

F32_MANTISSA_BUDGET = 1 << 24
I32_BUDGET = 1 << 31
FIELD_BITS = 381


def mxu_limb_split_table() -> list:
    rows = []
    for w in range(6, 16):
        n = -(-FIELD_BITS // w)
        col = n * ((1 << w) - 1) ** 2
        rows.append({
            "w": w, "limbs": n, "col_log2": log2_or_zero(col),
            "f32_ok": col < F32_MANTISSA_BUDGET,
            "i32_ok": col < I32_BUDGET,
        })
    return rows


def mxu_report(program_reports: dict) -> dict:
    F = _fp_mod()
    table = mxu_limb_split_table()
    w_f32 = max(r["w"] for r in table if r["f32_ok"])
    w_i32 = max(r["w"] for r in table if r["i32_ok"])
    direct_col = F.N * F.QMAX ** 2   # un-split dot column, current limbs
    per_kernel = {}
    for name in sorted(program_reports):
        rep = program_reports[name]
        acc = rep["max_acc_log2"]
        per_kernel[name] = {
            "max_acc_log2": acc,
            "max_any_log2": rep["max_any_log2"],
            "max_dot_log2": rep.get("max_dot_log2", 0.0),
            "f32_ok": acc < 24,
            "i32_ok": acc < 31,
        }
    L = _limbs_mod()
    q13, nl13 = int(L.SPEC13.qmax), int(L.SPEC13.n)
    col13 = nl13 * q13 * q13
    return {
        "budgets": {"f32_mantissa_log2": 24, "i32_log2": 31},
        "current_rep": {
            "w": F.BITS, "limbs": F.N,
            "direct_dot_col_log2": log2_or_zero(direct_col),
            "f32_ok": direct_col < F32_MANTISSA_BUDGET,
            "i32_ok": direct_col < I32_BUDGET,
        },
        "limb_split_table": table,
        "max_w_f32": w_f32,
        "max_w_i32": w_i32,
        # the split pallas_mxu ships: w=13 with one spill row (quasi-15
        # inputs overhang 2^390 by up to 2^-15), proved by the mxu_*
        # programs above rather than read off the generic table
        "selected_split": {
            "w": 13, "limbs": nl13, "qmax": q13,
            "col_log2": log2_or_zero(col13),
            "i32_ok": col13 < I32_BUDGET,
            "kernels": ["mxu_mont_mul", "mxu_mont_sqr",
                        "mxu_megachain_w4"],
        },
        "per_kernel": per_kernel,
        "conclusion": (
            f"direct {F.BITS}-bit columns cannot MXU-accumulate "
            f"(2^{log2_or_zero(direct_col)} > 2^31); the shipped MXU "
            f"path (pallas_mxu, LIGHTHOUSE_TPU_MXU=1) re-limbs to w=13 "
            f"({nl13} limbs incl. the spill row, column ceiling "
            f"2^{log2_or_zero(col13)} < 2^31, int32-proved by "
            f"mxu_mont_mul/mxu_dot_cols); f32 dot-products would need "
            f"w<={w_f32} ({-(-FIELD_BITS // w_f32)} limbs)"
        ),
    }


# ---------------------------------------------------------------------------
# Audit-family entry points
# ---------------------------------------------------------------------------


_CACHE_FILE = ".range_proof_cache.json"
_CACHE_VERSION = 1
# per-generate() hit/miss side channel (tests and tooling read it) —
# kept OUT of the report dict so cold and warm reports stay
# byte-identical and the drift check cannot tell them apart
_CACHE_STATS = {"hits": 0, "misses": 0}


def _fingerprint_deps(root: str) -> list:
    """Repo-relative source files whose bytes feed ``_proof_fingerprint``.

    Covers the kernel package AND the sharded-program sources
    (``parallel/partition.py``/``mesh.py``): the spmd family keys its
    cached theorem verdicts off the same fingerprint, so an edit to the
    staged SPMD programs must invalidate them."""
    deps = [
        "lighthouse_tpu/analysis/range_lint.py",
        "lighthouse_tpu/analysis/report.py",
        "lighthouse_tpu/crypto/bls/params.py",
        "lighthouse_tpu/parallel/partition.py",
        "lighthouse_tpu/parallel/mesh.py",
    ]
    kdir = "lighthouse_tpu/crypto/bls/jax_backend"
    full_kdir = os.path.join(root, kdir)
    if os.path.isdir(full_kdir):
        deps.extend(
            f"{kdir}/{fn}" for fn in sorted(os.listdir(full_kdir))
            if fn.endswith(".py")
        )
    return deps


def _proof_fingerprint(root: str) -> str:
    """Content hash of everything a live program verdict depends on.

    Coarse by design: one hash over the whole kernel package, this
    module, and the jax/numpy versions.  Any kernel edit invalidates
    every cached verdict (sound, and the cold run is the status quo);
    an untouched tree replays all of them (the >=5x warm win the audit
    wall-time needs — the traces are minutes, the hash is milliseconds).
    """
    import hashlib

    import jax
    h = hashlib.sha256()
    h.update(
        f"v{_CACHE_VERSION}|jax {jax.__version__}|np {np.__version__}"
        .encode()
    )
    for rel in _fingerprint_deps(root):
        h.update(rel.encode())
        try:
            with open(os.path.join(root, rel), "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"?")
    return h.hexdigest()


def _load_defs(root: str, rel_path: str):
    full = os.path.join(root, rel_path)
    spec = importlib.util.spec_from_file_location("range_defs_corpus", full)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _resolve_registry(root: str, cfg):
    """(programs, claim_sets) for the live tree or a fixture corpus."""
    defs = getattr(cfg, "range_defs", None)
    if defs:
        mod = _load_defs(root, defs)
        programs = list(mod.build_programs())
        claim_sets = list(getattr(mod, "LFP_CLAIMS", ()))
        return programs, claim_sets
    return build_live_programs(), [live_claims()]


def generate(root: str, cfg, only: tuple = ()) -> tuple:
    """Run the range family; returns (violations, report dict).

    ``only`` restricts to named programs (test tiers use it to skip the
    minutes-scale Miller traces).

    Per-program verdicts (violations + report entry) are cached in
    ``.range_proof_cache.json`` keyed by ``_proof_fingerprint``: warm
    re-audits of an untouched tree replay them without re-tracing, and
    a replayed report is byte-identical to a fresh one (entries are
    json-round-tripped before first use).  ``cfg.range_cache = False``
    (CLI ``--no-cache``) bypasses read AND write; fixture corpora
    (``range_defs``) are never cached — their programs are trivial and
    their verdicts must not share a file with the live tree's.
    """
    _CACHE_STATS["hits"] = 0
    _CACHE_STATS["misses"] = 0
    try:
        import jax  # noqa: F401
    except Exception as exc:  # pragma: no cover - jax is baked in
        return [Violation(
            rule=RULE_INTERP, path="lighthouse_tpu/analysis/range_lint.py",
            line=0, symbol="import-jax",
            message=f"range family needs jax to trace kernels: {exc}",
        )], {}
    violations: list = []
    programs, claim_sets = _resolve_registry(root, cfg)
    if only:
        programs = [p for p in programs if p.name in only]
    use_cache = bool(getattr(cfg, "range_cache", True)) \
        and not getattr(cfg, "range_defs", None)
    cache_path = os.path.join(root, _CACHE_FILE)
    fingerprint = _proof_fingerprint(root) if use_cache else ""
    cached: dict = {}
    disk: dict = {}
    if use_cache:
        try:
            with open(cache_path, encoding="utf-8") as f:
                disk = json.load(f)
            if disk.get("fingerprint") == fingerprint:
                cached = dict(disk.get("programs") or {})
        except (OSError, ValueError):
            disk, cached = {}, {}
    dirty = False
    prog_reports: dict = {}
    for prog in programs:
        entry = cached.get(prog.name)
        if entry is not None:
            _CACHE_STATS["hits"] += 1
            vios = [Violation(**v) for v in entry["violations"]]
            rep = entry["report"]
        else:
            _CACHE_STATS["misses"] += 1
            try:
                vios, rep = analyze_program(prog)
            except Exception as exc:
                violations.append(Violation(
                    rule=RULE_INTERP, path=prog.path, line=0,
                    symbol=prog.name,
                    message=f"program failed to trace/analyze: {exc!r}",
                ))
                continue
            rep = json.loads(json.dumps(rep))
            if use_cache:
                cached[prog.name] = {
                    "violations": [v.to_dict() for v in vios],
                    "report": rep,
                }
                dirty = True
        violations.extend(vios)
        prog_reports[prog.name] = rep
    if use_cache and dirty:
        # the cache file is shared with the spmd family: carry its
        # sections (spmd_fingerprint / spmd_programs) through unchanged
        # — each family validates only its own fingerprint on read
        doc = {k: v for k, v in disk.items() if k.startswith("spmd_")}
        doc["fingerprint"] = fingerprint
        doc["programs"] = cached
        try:
            with open(cache_path, "w", encoding="utf-8") as f:
                json.dump(doc, f)
        except OSError:
            pass   # unwritable cache just means the next run is cold too
    checks_out: list = []
    for claims in claim_sets:
        vios, checks = lfp_check(claims)
        violations.extend(vios)
        checks_out.extend(checks)
    report = {
        "version": 1,
        "programs": {k: prog_reports[k] for k in sorted(prog_reports)},
        "lfp_checks": checks_out,
        "mxu": mxu_report(prog_reports),
    }
    return violations, report


def run(root: str, cfg, only: tuple = ()) -> list:
    """Audit entry: full registry + checked-in report drift check.

    A restricted run (``only`` non-empty) cannot validate the full
    checked-in report, so the drift check is skipped for it."""
    violations, report = generate(root, cfg, only=only)
    report_rel = None if only else getattr(cfg, "range_report", None)
    if report_rel:
        report_path = os.path.join(root, report_rel)
        try:
            with open(report_path, encoding="utf-8") as f:
                want = json.load(f)
        except (OSError, ValueError) as exc:
            violations.append(Violation(
                rule=RULE_REPORT, path=report_rel, line=0,
                symbol="missing",
                message=(
                    f"checked-in range report unreadable ({exc}); "
                    f"regenerate with tools/pyrun tools/static_audit.py "
                    f"--write-range-report"
                ),
            ))
            return violations
        got = json.loads(json.dumps(report))
        if got != want:
            diffs = _report_diff(want, got)
            violations.append(Violation(
                rule=RULE_REPORT, path=report_rel, line=0,
                symbol="drift",
                message=(
                    "checked-in range report drifted from the kernels: "
                    + "; ".join(diffs[:6])
                    + " — regenerate with tools/pyrun "
                      "tools/static_audit.py --write-range-report"
                ),
            ))
    return violations


def _report_diff(want, got, prefix="") -> list:
    out = []
    if isinstance(want, dict) and isinstance(got, dict):
        for k in sorted(set(want) | set(got)):
            if k not in want:
                out.append(f"+{prefix}{k}")
            elif k not in got:
                out.append(f"-{prefix}{k}")
            elif want[k] != got[k]:
                out.extend(_report_diff(want[k], got[k], f"{prefix}{k}."))
        return out
    if isinstance(want, list) and isinstance(got, list):
        if len(want) != len(got):
            return [f"{prefix}len {len(want)}->{len(got)}"]
        for i, (w, g) in enumerate(zip(want, got)):
            if w != g:
                out.extend(_report_diff(w, g, f"{prefix}{i}."))
        return out
    return [f"{prefix}: {want!r} -> {got!r}"]


def write_report(root: str, cfg, path: str | None = None) -> str:
    """Regenerate and write the range report; returns the path."""
    _violations, report = generate(root, cfg)
    rel = path or getattr(cfg, "range_report", None) or DEFAULT_REPORT
    full = os.path.join(root, rel)
    with open(full, "w", encoding="utf-8") as f:
        json.dump(json.loads(json.dumps(report)), f, indent=1, sort_keys=True)
        f.write("\n")
    return full
