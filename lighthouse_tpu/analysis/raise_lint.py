"""Never-raise proof + repo-wide broad-except ban.

**Broad-except ban** (whole repo): a bare ``except:`` or
``except BaseException`` handler swallows ``SystemExit`` and
``KeyboardInterrupt``; it is only legal when the handler body re-raises
(cleanup-then-propagate, e.g. the slashing-protection ROLLBACK path).
Everything else must narrow to ``except Exception``.

**Never-raise proof** (registry-driven): functions documented as never
raising (``ResilientVerifier.verify_batch``, ``SyncManager.tick``,
``FaultInjector.maybe_fire``, ``BeaconProcessor.try_send``) are proven
so lexically: every statement in the body must be *dominated by* a
``try`` whose handlers cannot re-raise, or be in the small whitelist of
statements that cannot raise (``return None``, assignments of safe
expressions, calls to known-total functions like ``len``/``log.debug``/
``lock.release``).  A covering ``try`` must have at least one broad
handler (``Exception`` or wider), no handler may contain ``raise``, and
every handler body must itself consist only of safe statements — an
exception raised *inside* a handler escapes the ladder.

The proof is conservative: it can reject raise-free code (then you
restructure or waive), it cannot accept raising code within the modeled
semantics.
"""

from __future__ import annotations

import ast

from .report import Violation

BROAD_TYPES = {"Exception", "BaseException"}

DEFAULT_SAFE_NAME_CALLS = {
    "len", "list", "tuple", "dict", "set", "frozenset", "bool", "str",
    "repr", "isinstance", "min", "max", "abs", "sorted", "getattr",
    "id", "type", "range", "enumerate", "print",
}

DEFAULT_SAFE_ATTR_CALLS = {
    # locks / events
    "release", "acquire", "locked", "is_set", "clear",
    # containers (total ops only — no popleft/pop, those raise on empty)
    "append", "appendleft", "add", "discard", "get", "items", "values",
    "keys", "copy", "setdefault",
    # metrics
    "inc", "dec", "set", "observe",
    # time
    "monotonic", "perf_counter", "time", "sleep",
    # logging (logging.Handler.handleError swallows formatting errors)
    "debug", "info", "warning", "error", "exception", "log",
    # the never-raise injector entrypoint itself
    "maybe_fire",
}

_UNSAFE_BINOPS = (ast.Div, ast.FloorDiv, ast.Mod, ast.Pow, ast.MatMult)


def _handler_names(handler: ast.ExceptHandler):
    """Exception type names a handler catches ([] for bare except)."""
    t = handler.type
    if t is None:
        return []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for e in elts:
        if isinstance(e, ast.Name):
            out.append(e.id)
        elif isinstance(e, ast.Attribute):
            out.append(e.attr)
        else:
            out.append("<expr>")
    return out


def _contains_raise(node) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(node))


# -- broad-except ban ----------------------------------------------------


def broad_except_violations(path, src) -> list[Violation]:
    tree = ast.parse(src, filename=path)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        names = _handler_names(node)
        bare = node.type is None
        if not bare and "BaseException" not in names:
            continue
        if any(_contains_raise(s) for s in node.body):
            continue  # cleanup-then-propagate is legitimate
        what = "bare `except:`" if bare else "`except BaseException`"
        out.append(Violation(
            rule="broad-except",
            path=path,
            line=node.lineno,
            symbol=",".join(names) or "except:",
            message=(
                f"{what} without re-raise swallows SystemExit/"
                f"KeyboardInterrupt; narrow to `except Exception`"
            ),
        ))
    return out


# -- never-raise proof ---------------------------------------------------


class _Prover:
    def __init__(self, safe_name_calls, safe_attr_calls):
        self.safe_name_calls = safe_name_calls
        self.safe_attr_calls = safe_attr_calls
        self.problems: list[tuple[int, str]] = []

    # expressions ---------------------------------------------------------

    def safe_expr(self, e) -> bool:
        if e is None or isinstance(e, ast.Constant):
            return True
        if isinstance(e, ast.Name):
            return True
        if isinstance(e, ast.Attribute):
            return self.safe_expr(e.value)
        if isinstance(e, ast.JoinedStr):
            return all(self.safe_expr(v) for v in e.values)
        if isinstance(e, ast.FormattedValue):
            return self.safe_expr(e.value)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return all(self.safe_expr(v) for v in e.elts)
        if isinstance(e, ast.Dict):
            return all(self.safe_expr(k) for k in e.keys if k is not None) \
                and all(self.safe_expr(v) for v in e.values)
        if isinstance(e, ast.BoolOp):
            return all(self.safe_expr(v) for v in e.values)
        if isinstance(e, ast.UnaryOp):
            return self.safe_expr(e.operand)
        if isinstance(e, ast.Compare):
            return self.safe_expr(e.left) and all(
                self.safe_expr(c) for c in e.comparators
            )
        if isinstance(e, ast.IfExp):
            return (
                self.safe_expr(e.test)
                and self.safe_expr(e.body)
                and self.safe_expr(e.orelse)
            )
        if isinstance(e, ast.BinOp):
            if isinstance(e.op, _UNSAFE_BINOPS):
                return False  # ZeroDivisionError etc.
            return self.safe_expr(e.left) and self.safe_expr(e.right)
        if isinstance(e, ast.Call):
            return self.safe_call(e)
        return False  # Subscript (KeyError), Await, Yield, comprehensions…

    def safe_call(self, call: ast.Call) -> bool:
        args_ok = all(self.safe_expr(a) for a in call.args) and all(
            kw.value is not None and self.safe_expr(kw.value)
            for kw in call.keywords
        )
        if not args_ok:
            return False
        fn = call.func
        if isinstance(fn, ast.Name):
            return fn.id in self.safe_name_calls
        if isinstance(fn, ast.Attribute):
            return fn.attr in self.safe_attr_calls and self.safe_expr(fn.value)
        return False

    # statements ----------------------------------------------------------

    def safe_or_covered(self, stmt) -> bool:
        """True iff `stmt` cannot let an exception escape."""
        if isinstance(stmt, ast.Try):
            return self.covering_try(stmt)
        if isinstance(stmt, (ast.Pass, ast.Break, ast.Continue,
                             ast.Global, ast.Nonlocal)):
            return True
        if isinstance(stmt, ast.Return):
            return self.safe_expr(stmt.value)
        if isinstance(stmt, ast.Expr):
            return self.safe_expr(stmt.value)
        if isinstance(stmt, ast.Assign):
            return all(self.safe_target(t) for t in stmt.targets) \
                and self.safe_expr(stmt.value)
        if isinstance(stmt, ast.AnnAssign):
            return self.safe_target(stmt.target) and self.safe_expr(stmt.value)
        if isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.op, _UNSAFE_BINOPS):
                return False
            return self.safe_target(stmt.target) and self.safe_expr(stmt.value)
        if isinstance(stmt, ast.If):
            return (
                self.safe_expr(stmt.test)
                and all(self.safe_or_covered(s) for s in stmt.body)
                and all(self.safe_or_covered(s) for s in stmt.orelse)
            )
        if isinstance(stmt, ast.While):
            return (
                self.safe_expr(stmt.test)
                and all(self.safe_or_covered(s) for s in stmt.body)
                and all(self.safe_or_covered(s) for s in stmt.orelse)
            )
        if isinstance(stmt, ast.With):
            return all(
                self.safe_expr(i.context_expr) for i in stmt.items
            ) and all(self.safe_or_covered(s) for s in stmt.body)
        return False  # For (iterator may raise), Raise, Import, Assert, …

    def safe_target(self, t) -> bool:
        if isinstance(t, ast.Name):
            return True
        if isinstance(t, ast.Attribute):
            return self.safe_expr(t.value)
        return False  # Subscript / unpacking can raise

    def covering_try(self, node: ast.Try) -> bool:
        """A try covers its body iff its ladder cannot re-raise: one
        broad handler, no `raise` in any handler, all handler bodies
        built from safe statements, and orelse/finally themselves safe
        (they run outside the handlers' protection)."""
        has_broad = False
        for h in node.handlers:
            names = _handler_names(h)
            if h.type is None or any(n in BROAD_TYPES for n in names):
                has_broad = True
            if any(_contains_raise(s) for s in h.body):
                return False
            if not all(self.safe_or_covered(s) for s in h.body):
                return False
        if not has_broad:
            return False
        return all(self.safe_or_covered(s) for s in node.orelse) and all(
            self.safe_or_covered(s) for s in node.finalbody
        )

    def prove(self, fn) -> list[tuple[int, str]]:
        problems = []
        for stmt in fn.body:
            if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Constant
            ):
                continue  # docstring
            if not self.safe_or_covered(stmt):
                kind = type(stmt).__name__
                if isinstance(stmt, ast.Try):
                    problems.append((
                        stmt.lineno,
                        "try block whose handler ladder can re-raise or "
                        "whose handlers/finally contain unsafe statements",
                    ))
                else:
                    problems.append((
                        stmt.lineno,
                        f"{kind} statement not dominated by a non-re-raising "
                        f"try and not provably exception-free",
                    ))
        return problems


def never_raise_violations(
    files, registry, extra_safe_calls=(), extra_safe_attr_calls=()
) -> list[Violation]:
    """files: iterable of (display_path, source).  registry: iterable of
    "relpath::Qual.name" strings.  Returns violations, including one per
    registry entry whose function no longer exists (registry drift)."""
    wanted: dict[tuple[str, str], bool] = {}
    for entry in registry:
        path, _, qual = entry.partition("::")
        wanted[(path, qual)] = False

    prover = _Prover(
        DEFAULT_SAFE_NAME_CALLS | set(extra_safe_calls),
        DEFAULT_SAFE_ATTR_CALLS | set(extra_safe_attr_calls),
    )
    out = []
    for display, src in files:
        quals = {
            q for (p, q), _ in wanted.items() if p == display or p == "*"
        }
        if not quals:
            continue
        tree = ast.parse(src, filename=display)
        for cls_or_fn, qual in _iter_functions(tree):
            if qual not in quals:
                continue
            for p, q in list(wanted):
                if q == qual and (p == display or p == "*"):
                    wanted[(p, q)] = True
            for line, why in prover.prove(cls_or_fn):
                out.append(Violation(
                    rule="never-raise",
                    path=display,
                    line=line,
                    symbol=qual,
                    message=f"never-raise contract not proven: {why}",
                ))
    for (path, qual), found in sorted(wanted.items()):
        if not found:
            out.append(Violation(
                rule="never-raise",
                path=path,
                line=0,
                symbol=qual,
                message=(
                    "registered never-raise function not found "
                    "(registry drift — update the registry)"
                ),
            ))
    return out


def _iter_functions(tree):
    """Yield (FunctionDef, qualname) for module- and class-level defs."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.name
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield sub, f"{node.name}.{sub.name}"


def run(files, registry, extra_safe_calls=()) -> list[Violation]:
    files = list(files)
    out = []
    for display, src in files:
        out.extend(broad_except_violations(display, src))
    out.extend(never_raise_violations(files, registry, extra_safe_calls))
    return out
