"""Stage-attribution math over trace events.

Shared by ``tools/trace_report.py`` (offline reports over dump files),
``bench.py`` (the ``BENCH_PIPELINE=1`` per-stage block), and the
scenario engine (the overlap-efficiency SLO gate).  All functions work
on *normalized events*: dicts with ``name`` (str), ``ts`` and ``dur``
(microseconds, Chrome trace-event convention) — exactly the shape
``Tracer.chrome_trace()["traceEvents"]`` emits, so a live tracer
snapshot and a dump file on disk feed the same code path.

Definitions
-----------

* **stage stats** — per-span-name count / total / p50 / p99 (seconds).
* **host vs device share** — host stages are the Python-side work
  (marshal, CPU fallback); device stages block on or run on the
  accelerator (resolve, device rung, compiles).
* **overlap efficiency** — ``wall / max(marshal_busy, device_busy)``
  over the pipelined window: 1.0 means the slower stage fully hides the
  other (perfect overlap); ~2.0 means the stages ran serially.  When no
  pipeline spans exist (the serial ladder path) the degenerate form is
  ``ladder_wall / engine_busy`` — how much verify wall time was actual
  engine work — which is the same "1.0 is perfect" scale.
"""

from __future__ import annotations

# Span names considered host-side vs device-side work for the share
# split.  Names absent from both sets (breaker events, scenario slots,
# block/sync lifecycle wrappers) are structural and attributed to
# neither side.
HOST_STAGES = frozenset({"pipeline.marshal", "verify.cpu"})
DEVICE_STAGES = frozenset({
    "pipeline.dispatch", "pipeline.resolve", "verify.device", "jit.compile",
})

# The stages the pipelined overlap window is computed over.
_PIPELINE_STAGES = frozenset({
    "pipeline.marshal", "pipeline.dispatch", "pipeline.resolve",
})


def _quantile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def stage_stats(events: list) -> dict:
    """Per-name stats: ``{name: {count, total_s, p50_s, p99_s}}``."""
    by_name: dict = {}
    for ev in events:
        by_name.setdefault(ev["name"], []).append(ev.get("dur", 0.0) / 1e6)
    out = {}
    for name, durs in sorted(by_name.items()):
        durs.sort()
        out[name] = {
            "count": len(durs),
            "total_s": sum(durs),
            "p50_s": _quantile(durs, 0.50),
            "p99_s": _quantile(durs, 0.99),
        }
    return out


def host_device_share(events: list) -> dict:
    """Busy-seconds split into host / device / other buckets."""
    host = device = other = 0.0
    for ev in events:
        dur = ev.get("dur", 0.0) / 1e6
        if ev["name"] in HOST_STAGES:
            host += dur
        elif ev["name"] in DEVICE_STAGES:
            device += dur
        else:
            other += dur
    busy = host + device
    return {
        "host_s": host,
        "device_s": device,
        "other_s": other,
        "host_share": (host / busy) if busy > 0 else 0.0,
        "device_share": (device / busy) if busy > 0 else 0.0,
    }


def overlap_efficiency(events: list) -> dict:
    """Overlap ratio ``wall / max(stage busy)`` (1.0 = perfect overlap).

    Returns ``{"ratio": float|None, "mode": "pipeline"|"serial"|"empty",
    "wall_s": float, "marshal_s": float, "device_s": float}``.  ``ratio``
    is None when there is nothing to attribute.
    """
    pipe = [ev for ev in events if ev["name"] in _PIPELINE_STAGES]
    if pipe:
        t0 = min(ev["ts"] for ev in pipe)
        t1 = max(ev["ts"] + ev.get("dur", 0.0) for ev in pipe)
        wall = (t1 - t0) / 1e6
        marshal = sum(
            ev["dur"] for ev in pipe if ev["name"] == "pipeline.marshal"
        ) / 1e6
        device = sum(
            ev["dur"] for ev in pipe
            if ev["name"] in ("pipeline.dispatch", "pipeline.resolve")
        ) / 1e6
        busiest = max(marshal, device)
        return {
            "ratio": (wall / busiest) if busiest > 0 else None,
            "mode": "pipeline",
            "wall_s": wall,
            "marshal_s": marshal,
            "device_s": device,
        }
    # Serial ladder path: engine-busy share of the ladder wall.
    ladder = [ev for ev in events if ev["name"] == "verify.batch"]
    engine = [
        ev for ev in events if ev["name"] in ("verify.device", "verify.cpu")
    ]
    wall = sum(ev.get("dur", 0.0) for ev in ladder) / 1e6
    busy = sum(ev.get("dur", 0.0) for ev in engine) / 1e6
    if wall <= 0 or busy <= 0:
        return {
            "ratio": None, "mode": "empty",
            "wall_s": wall, "marshal_s": 0.0, "device_s": busy,
        }
    return {
        "ratio": wall / busy,
        "mode": "serial",
        "wall_s": wall,
        "marshal_s": 0.0,
        "device_s": busy,
    }


def compile_events(events: list) -> list:
    """``jit.compile`` events as ``[{fingerprint, seconds, ...fields}]``."""
    out = []
    for ev in events:
        if ev["name"] != "jit.compile":
            continue
        args = dict(ev.get("args") or {})
        args.pop("sid", None)
        args.pop("parent", None)
        row = {"seconds": ev.get("dur", 0.0) / 1e6}
        row.update(args)
        out.append(row)
    return out


def attribution(events: list) -> dict:
    """The full report: stages + share + overlap + compiles."""
    return {
        "stages": stage_stats(events),
        "share": host_device_share(events),
        "overlap": overlap_efficiency(events),
        "compiles": compile_events(events),
        "events": len(events),
    }


def unknown_names(events: list, registry) -> list:
    """Event names not present in the span registry (sorted, unique)."""
    return sorted({ev["name"] for ev in events} - set(registry))
