"""Standalone metrics scrape endpoint — twin of ``beacon_node/http_metrics``.

A stdlib ``ThreadingHTTPServer`` on its own port (``bn --metrics-port``),
separate from the beacon API server, serving:

* ``/metrics`` — the process-global registry via ``metrics.render()``
  (Prometheus text exposition format 0.0.4);
* ``/health``  — ``utils/monitoring.SystemHealth`` plus process info,
  as JSON;
* ``/trace``   — the flight recorder as Chrome trace-event JSON
  (loadable in Perfetto / ``chrome://tracing``).

Port 0 binds an ephemeral port (the bound port is logged and exposed as
``MetricsServer.port``); the server thread is a daemon and never blocks
node shutdown.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.logging import get_logger
from ..utils.metrics import render as render_metrics
from ..utils.monitoring import SystemHealth
from .tracer import TRACER

log = get_logger("obs.http")

# The most recently started server, for tests that boot `bn
# --metrics-port 0` and need to learn the ephemeral port.
_LAST: "MetricsServer | None" = None


def last_server() -> "MetricsServer | None":
    return _LAST


class MetricsServer:
    """Serve ``/metrics``, ``/health`` and ``/trace`` on a daemon thread."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1", tracer=None):
        self._host = host
        self._want_port = port
        self._tracer = tracer if tracer is not None else TRACER
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.port: int = 0

    def start(self) -> "MetricsServer":
        global _LAST
        tracer = self._tracer

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet access log
                pass

            def _send(self, code: int, body: bytes, content_type: str):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200, render_metrics().encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/health":
                        health = dataclasses.asdict(SystemHealth.observe())
                        health.update(status="ok", pid=os.getpid())
                        self._send(
                            200, json.dumps(health).encode(),
                            "application/json",
                        )
                    elif path == "/trace":
                        doc = tracer.chrome_trace()
                        self._send(
                            200, json.dumps(doc).encode(),
                            "application/json",
                        )
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception as exc:  # scrape must not kill the server thread
                    log.warning("metrics request %s failed: %s", path, exc)

        self._httpd = ThreadingHTTPServer((self._host, self._want_port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="obs-metrics-http",
            daemon=True,
        )
        self._thread.start()
        _LAST = self
        log.info(
            "metrics endpoint on http://%s:%d/metrics (/health, /trace)",
            self._host, self.port,
        )
        return self

    def stop(self) -> None:
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)
