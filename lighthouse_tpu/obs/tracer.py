"""Span tracer + ring-buffer flight recorder.

The tracer is the repo's low-overhead timing substrate: every hot-path
stage (pipeline marshal/dispatch/resolve, the resilience ladder rungs,
breaker transitions, block import, sync batches, JIT compiles) wraps
itself in a named span, and the most recent ``capacity`` spans live in a
process-global ring buffer — always on, cheap enough to leave enabled,
and dumpable as Chrome trace-event JSON (loadable in Perfetto /
``chrome://tracing``) the moment something goes wrong.  Dumps fire
automatically on breaker-open and scenario SLO failure via
:meth:`Tracer.maybe_dump`, so a failed run always leaves an artifact.

Span names are a closed registry (``SPANS`` below): the static audit
cross-references every literal ``.span("...")`` / ``.instant("...")``
call site against it, both directions, exactly the way fault sites and
metric names are checked — keep the keys literal (AST-parsed, never
imported, by ``analysis/registry_lint.py``).

Clocks are ``time.perf_counter()`` (monotonic): span timestamps are
relative to an arbitrary process epoch and only deltas are meaningful.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import NamedTuple

from ..utils.logging import get_logger
from ..utils.metrics import TRACE_DUMPS, TRACE_SPANS_DROPPED

log = get_logger("obs.tracer")


# ---------------------------------------------------------------------------
# The canonical span-name registry.  Keys are the only names
# instrumentation sites may pass to span()/instant(); the registry lint
# AST-parses this dict and flags unknown names and orphaned entries.
# ---------------------------------------------------------------------------

SPANS: dict[str, str] = {
    # PipelinedVerifier stages (beacon/processor.py)
    "pipeline.marshal": "host marshal of one batch (pool worker wall)",
    "pipeline.dispatch": "non-blocking device enqueue of a marshalled batch",
    "pipeline.resolve": "verdict resolution (blocks on the device)",
    # ResilientVerifier ladder (beacon/processor.py)
    "verify.batch": "resilience ladder around one signature batch",
    "verify.device": "device-engine attempt inside the ladder",
    "verify.cpu": "pure-Python CPU fallback rung",
    "breaker.transition": "circuit-breaker state change (instant event)",
    # chain / sync lifecycle (beacon/chain.py, beacon/sync.py)
    "block.import": "BeaconChain.process_block end-to-end",
    "sync.batch": "sync batch lifecycle: request through import",
    # JIT compiles (crypto/bls/jax_backend/backend.py)
    "jit.compile": "XLA/Mosaic program compile, per-program fingerprint",
    # AOT executable store (crypto/bls/jax_backend/aot.py)
    "aot.capture": "export+serialize of a just-compiled staged program",
    "prewarm.load": "AOT store load+install of one program at warm boot",
    # kernel autotuner (crypto/bls/jax_backend/autotune.py)
    "autotune.trial": "timed arm x batch-shape microbench (best-of-iters)",
    # scenario engine virtual slots (scenario/engine.py)
    "scenario.slot": "one virtual slot of a scenario run",
    # vectorized ingest engine (ingest/engine.py)
    "ingest.marshal": "IngestEngine vectorized marshal of one batch",
    "ingest.expand": "batched SHA-256 hash-to-field draws for the batch",
    "ingest.encode": "pubkey cache resolve + operand limb assembly",
    # pod-scale verification service (parallel/pod.py)
    "pod.dispatch": "one pod round: per-shard device dispatch + gather",
    "pod.reshard": "mesh shrink onto surviving devices (instant event)",
    # multi-tenant verification front door (serve/service.py)
    "serve.submit": "one tenant submission: admission through enqueue",
    "serve.dispatch": "one coalesced device batch: flush through verdicts",
    # verdict-integrity layer (integrity/guard.py, integrity/selfcheck.py)
    "integrity.canary": "canary known-answer sweep around one dispatch",
    "integrity.audit": "cross-arm audit re-verify of a sampled batch",
    "integrity.quarantine": "device trust quarantine (instant event)",
    "integrity.selfcheck": "boot-time known-answer sweep over installed kernels",
}


class SpanRecord(NamedTuple):
    """One committed span: ``(name, start, duration, parent, fields)``."""

    sid: int          # unique, monotonically increasing span id
    parent: int       # sid of the enclosing span on this thread, or 0
    name: str         # key into SPANS
    t0: float         # perf_counter() at entry
    dur: float        # seconds
    tid: int          # OS thread id
    fields: tuple     # sorted (key, value) pairs, JSON-safe values


class _NopSpan:
    """Singleton no-op context manager returned by a disabled tracer."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add(self, **fields):
        return self


_NOP = _NopSpan()


class _LiveSpan:
    """An open span; commits itself to the tracer ring on ``__exit__``."""

    __slots__ = ("_tracer", "name", "fields", "sid", "parent", "t0")

    def __init__(self, tracer, name, fields):
        self._tracer = tracer
        self.name = name
        self.fields = fields
        self.sid = 0
        self.parent = 0
        self.t0 = 0.0

    def add(self, **fields):
        """Attach extra fields to the span before it closes."""
        self.fields.update(fields)
        return self

    def __enter__(self):
        tracer = self._tracer
        self.sid = next(tracer._ids)
        stack = tracer._stack()
        if stack:
            self.parent = stack[-1]
        stack.append(self.sid)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self.t0
        stack = self._tracer._stack()
        if stack and stack[-1] == self.sid:
            stack.pop()
        if exc_type is not None:
            self.fields["error"] = exc_type.__name__
        self._tracer._commit(self, dur)
        return False


class Tracer:
    """Thread-safe ring-buffer flight recorder of timing spans.

    ``capacity`` bounds memory: beyond it the oldest spans are dropped
    (and counted in ``trace_spans_dropped_total``).  A disabled tracer's
    ``span()`` call is a single attribute test returning a shared no-op
    context manager — cheap enough to leave instrumentation in place
    unconditionally.
    """

    def __init__(self, capacity: int = 8192, enabled: bool = True):
        if capacity < 1:
            raise ValueError("tracer capacity must be >= 1")
        self.capacity = capacity
        self.enabled = enabled
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=capacity)
        self._dropped = 0
        self._dump_dir: str | None = None
        self._dump_seq: dict = {}
        self._dump_limit = 8

    # -- emission ---------------------------------------------------------

    def span(self, name: str, **fields):
        """Open a span; use as ``with TRACER.span("pipeline.marshal"):``."""
        if not self.enabled:
            return _NOP
        return _LiveSpan(self, name, fields)

    def instant(self, name: str, **fields) -> None:
        """Record a zero-duration point event (e.g. a state transition)."""
        if not self.enabled:
            return
        sp = _LiveSpan(self, name, fields)
        sp.sid = next(self._ids)
        stack = self._stack()
        if stack:
            sp.parent = stack[-1]
        sp.t0 = time.perf_counter()
        self._commit(sp, 0.0)

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def _commit(self, sp: _LiveSpan, dur: float) -> None:
        rec = SpanRecord(
            sid=sp.sid,
            parent=sp.parent,
            name=sp.name,
            t0=sp.t0,
            dur=dur,
            tid=threading.get_ident(),
            fields=tuple(sorted(sp.fields.items())),
        )
        with self._lock:
            if len(self._buf) == self.capacity:
                self._dropped += 1
                TRACE_SPANS_DROPPED.inc()
            self._buf.append(rec)

    # -- inspection -------------------------------------------------------

    def snapshot(self, since_sid: int = 0) -> list:
        """Spans currently in the ring with ``sid > since_sid``, oldest first."""
        with self._lock:
            recs = list(self._buf)
        if since_sid:
            recs = [r for r in recs if r.sid > since_sid]
        return recs

    def mark(self) -> int:
        """Current high-water span id; pass to snapshot()/dump() as ``since``."""
        with self._lock:
            return self._buf[-1].sid if self._buf else 0

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._dropped = 0

    # -- export -----------------------------------------------------------

    def chrome_trace(self, since_sid: int = 0) -> dict:
        """The ring as a Chrome trace-event JSON object (Perfetto-loadable)."""
        events = []
        for r in self.snapshot(since_sid):
            args = dict(r.fields)
            args["sid"] = r.sid
            if r.parent:
                args["parent"] = r.parent
            events.append({
                "name": r.name,
                "cat": "lighthouse_tpu",
                "ph": "X",
                "ts": round(r.t0 * 1e6, 3),
                "dur": round(r.dur * 1e6, 3),
                "pid": os.getpid(),
                "tid": r.tid,
                "args": args,
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump(self, path: str, since_sid: int = 0) -> str:
        """Write the ring as Chrome trace JSON to ``path``; returns ``path``."""
        doc = self.chrome_trace(since_sid)
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=None, separators=(",", ":"))
        os.replace(tmp, path)
        TRACE_DUMPS.inc()
        return path

    def configure_dump_dir(self, path: str | None) -> None:
        """Directory for automatic ``maybe_dump`` artifacts (None disables)."""
        with self._lock:
            self._dump_dir = path
            self._dump_seq = {}

    def maybe_dump(self, reason: str, since_sid: int = 0) -> str | None:
        """Best-effort automatic dump (breaker-open, SLO failure, ...).

        Writes ``trace-<reason>-<NNN>.json`` into the configured dump dir
        (or ``$LIGHTHOUSE_TPU_TRACE_DIR``), at most ``_dump_limit`` files
        per reason per process.  Never raises — this is called from
        never-raise paths like the breaker transition.
        """
        try:
            with self._lock:
                dump_dir = self._dump_dir
            dump_dir = dump_dir or os.environ.get("LIGHTHOUSE_TPU_TRACE_DIR")
            if not dump_dir or not self.enabled:
                return None
            with self._lock:
                seq = self._dump_seq.get(reason, 0) + 1
                if seq > self._dump_limit:
                    return None
                self._dump_seq[reason] = seq
            os.makedirs(dump_dir, exist_ok=True)
            path = os.path.join(dump_dir, f"trace-{reason}-{seq:03d}.json")
            self.dump(path, since_sid)
            log.info("flight-recorder dump (%s) -> %s", reason, path)
            return path
        except Exception as exc:  # never-raise: diagnostics must not kill the node
            log.warning("flight-recorder dump failed (%s): %s", reason, exc)
            return None


def _env_capacity() -> int:
    try:
        return max(1, int(os.environ.get("LIGHTHOUSE_TPU_TRACE_RING", "8192")))
    except ValueError:
        return 8192


#: The process-global flight recorder every instrumentation site uses.
#: ``LIGHTHOUSE_TPU_TRACE=0`` disables it; ``LIGHTHOUSE_TPU_TRACE_RING``
#: resizes the ring.
TRACER = Tracer(
    capacity=_env_capacity(),
    enabled=os.environ.get("LIGHTHOUSE_TPU_TRACE", "1") != "0",
)
