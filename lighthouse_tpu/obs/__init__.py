"""Observability layer — twin of ``beacon_node/http_metrics`` plus a
flight recorder the reference client does not have.

* :mod:`tracer` — the process-global span tracer / ring-buffer flight
  recorder (``TRACER``), the canonical ``SPANS`` registry, and Chrome
  trace-event export with automatic dumps on breaker-open and scenario
  SLO failure.
* :mod:`http` — the ``bn --metrics-port`` scrape endpoint serving
  ``/metrics`` (Prometheus text), ``/health`` and ``/trace``.
* :mod:`report` — stage-attribution math (per-stage p50/p99,
  host-vs-device share, pipeline overlap efficiency) shared by
  ``tools/trace_report.py``, ``bench.py`` and the scenario SLO gate.
"""

from .http import MetricsServer, last_server  # noqa: F401
from .report import attribution, overlap_efficiency  # noqa: F401
from .tracer import SPANS, TRACER, SpanRecord, Tracer  # noqa: F401
