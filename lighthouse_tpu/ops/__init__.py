"""Shared numeric/hashing ops used across the framework (host + device)."""

from .sha256 import sha256, sha256_many, sha256_many_vec  # noqa: F401
