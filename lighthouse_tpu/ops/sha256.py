"""Batch SHA-256 — the hashing workhorse under Merkleization and shuffling.

The reference leans on `ethereum_hashing` (SHA-256 with CPU intrinsics,
Cargo.toml:66) for tree-hash and swap-or-not shuffling. Here the equivalent
is a *lane-parallel* SHA-256: k independent 64-byte messages are compressed
simultaneously with numpy uint32 vector ops (one message per lane), which is
exactly the layout a TPU tree-hash kernel wants (the compression function is
64 rounds of elementwise uint32 arithmetic — VPU-shaped, no MXU needed).

Two paths:
* default: loop over hashlib (OpenSSL with SHA-NI — measured ~700k
  hashes/s/core, ~10x faster than the numpy compressor, which pays heavy
  memory traffic for its 64 rounds of temporaries).
* `sha256_many_vec`: the lane-parallel compressor — kept as the correctness
  reference and the blueprint for the jax/Pallas device tree-hash kernel
  (identical dataflow, jnp.uint32 for np.uint32).
"""

from __future__ import annotations

import hashlib

import numpy as np

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
        0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
        0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
        0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
        0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
        0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
        0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
        0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
        0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
        0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
        0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

_H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)

# The second (final) block of a 64-byte message: 0x80 delimiter, zero pad,
# 512-bit length — constant across all lanes.
_PAD_BLOCK_WORDS = np.zeros(16, dtype=np.uint32)
_PAD_BLOCK_WORDS[0] = 0x80000000
_PAD_BLOCK_WORDS[15] = 512


def _rotr(x: np.ndarray, n: int) -> np.ndarray:
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def _compress(state: np.ndarray, words: np.ndarray) -> np.ndarray:
    """One compression round batch: state (k, 8), words (k, 16) -> (k, 8)."""
    w = [words[:, i].copy() for i in range(16)]
    a, b, c, d, e, f, g, h = (state[:, i].copy() for i in range(8))
    for t in range(64):
        if t < 16:
            wt = w[t]
        else:
            w15, w2 = w[(t - 15) % 16], w[(t - 2) % 16]
            s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> np.uint32(3))
            s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> np.uint32(10))
            wt = w[t % 16] + s0 + w[(t - 7) % 16] + s1
            w[t % 16] = wt
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + _K[t] + wt
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = S0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
    out = np.empty_like(state)
    for i, v in enumerate((a, b, c, d, e, f, g, h)):
        out[:, i] = state[:, i] + v
    return out


def sha256_many(data: np.ndarray) -> np.ndarray:
    """SHA-256 of k 64-byte messages: (k, 64) uint8 -> (k, 32) uint8."""
    data = np.ascontiguousarray(data, dtype=np.uint8)
    k = data.shape[0]
    assert data.shape == (k, 64), data.shape
    out = np.empty((k, 32), dtype=np.uint8)
    for i in range(k):
        out[i] = np.frombuffer(
            hashlib.sha256(data[i].tobytes()).digest(), dtype=np.uint8
        )
    return out


def sha256_many_vec(data: np.ndarray) -> np.ndarray:
    """Lane-parallel SHA-256 (numpy compressor): (k, 64) -> (k, 32)."""
    data = np.ascontiguousarray(data, dtype=np.uint8)
    k = data.shape[0]
    assert data.shape == (k, 64), data.shape
    if k == 0:
        return np.empty((0, 32), dtype=np.uint8)
    # big-endian word view of the message block
    words = data.reshape(k, 16, 4).astype(np.uint32)
    words = (
        (words[:, :, 0] << np.uint32(24))
        | (words[:, :, 1] << np.uint32(16))
        | (words[:, :, 2] << np.uint32(8))
        | words[:, :, 3]
    )
    with np.errstate(over="ignore"):
        state = np.broadcast_to(_H0, (k, 8)).copy()
        state = _compress(state, words)
        pad = np.broadcast_to(_PAD_BLOCK_WORDS, (k, 16))
        state = _compress(state, pad)
    # back to big-endian bytes
    out = np.empty((k, 32), dtype=np.uint8)
    for i in range(8):
        out[:, 4 * i] = (state[:, i] >> np.uint32(24)).astype(np.uint8)
        out[:, 4 * i + 1] = (state[:, i] >> np.uint32(16)).astype(np.uint8)
        out[:, 4 * i + 2] = (state[:, i] >> np.uint32(8)).astype(np.uint8)
        out[:, 4 * i + 3] = state[:, i].astype(np.uint8)
    return out


def sha256(data: bytes) -> bytes:
    """Plain single-message SHA-256 (hashlib passthrough)."""
    return hashlib.sha256(data).digest()
