"""SLO evaluation against the live metrics registry.

The engine snapshots the robustness counters/histograms before and after
the run; every assertion here is over the *delta*, so scenarios compose
with whatever else the process has already recorded (pytest runs many
scenarios against one process-global registry).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils import metrics as M


@dataclass
class SLOResult:
    name: str
    ok: bool
    observed: object
    threshold: object
    detail: str = ""
    # "fail" gates decide the run verdict; "warn" gates are advisory —
    # reported (and logged) but never flip a passing run to failed.
    level: str = "fail"

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "ok": self.ok,
            "observed": self.observed,
            "threshold": self.threshold,
            "detail": self.detail,
            "level": self.level,
        }


def _counter_total(counter) -> float:
    """Sum over every label combination."""
    return sum(v for _, v in counter.samples())


class MetricsSnapshot:
    """Point-in-time capture of every metric the SLO gates read."""

    def __init__(self):
        self.counters = {
            "processor_shed_total": _counter_total(M.PROCESSOR_SHED),
            "sync_stalls_total": _counter_total(M.SYNC_STALLS),
            "breaker_transitions_total": _counter_total(
                M.BREAKER_TRANSITIONS
            ),
            "verify_device_retries_total": _counter_total(
                M.VERIFY_DEVICE_RETRIES
            ),
            "faults_injected_total": _counter_total(M.FAULTS_INJECTED),
        }
        self.import_buckets = M.BLOCK_IMPORT_LATENCY.bucket_counts()
        self.verify_buckets = M.VERIFY_BATCH_LATENCY.bucket_counts()

    def delta(self, earlier: "MetricsSnapshot") -> dict:
        out = {
            k: self.counters[k] - earlier.counters[k] for k in self.counters
        }
        out["import_p99_s"] = M.BLOCK_IMPORT_LATENCY.quantile(
            0.99,
            counts=[a - b for a, b in
                    zip(self.import_buckets, earlier.import_buckets)],
        )
        out["verify_p99_s"] = M.VERIFY_BATCH_LATENCY.quantile(
            0.99,
            counts=[a - b for a, b in
                    zip(self.verify_buckets, earlier.verify_buckets)],
        )
        return out


def evaluate(thresholds: dict, deltas: dict, run: dict) -> list[SLOResult]:
    """Gate a finished run.

    ``deltas``: MetricsSnapshot.delta output.  ``run``: engine-collected
    facts — heads, finalized epochs, enqueue count, never-raise
    violations, breaker end state, crash-recovery reports, slashings.
    Every gate with a non-None threshold produces one SLOResult.
    """
    out: list[SLOResult] = []

    def gate(name, ok, observed, threshold, detail="", level="fail"):
        out.append(
            SLOResult(name, bool(ok), observed, threshold, detail, level)
        )

    t = thresholds

    if t.get("max_shed_rate") is not None:
        enq = max(1, run.get("processor_enqueues", 0))
        rate = deltas["processor_shed_total"] / enq
        gate("shed_rate", rate <= t["max_shed_rate"], round(rate, 4),
             t["max_shed_rate"],
             f"{int(deltas['processor_shed_total'])} shed / {enq} enqueued")

    if t.get("max_sync_stalls") is not None:
        v = deltas["sync_stalls_total"]
        gate("sync_stalls", v <= t["max_sync_stalls"], int(v),
             t["max_sync_stalls"])

    if t.get("max_breaker_transitions") is not None:
        v = deltas["breaker_transitions_total"]
        gate("breaker_transitions", v <= t["max_breaker_transitions"],
             int(v), t["max_breaker_transitions"])

    if t.get("min_breaker_transitions") is not None:
        v = deltas["breaker_transitions_total"]
        gate("breaker_engaged", v >= t["min_breaker_transitions"], int(v),
             t["min_breaker_transitions"],
             "the device-fault track must actually trip the breaker")

    if t.get("max_device_retries") is not None:
        v = deltas["verify_device_retries_total"]
        gate("device_retries", v <= t["max_device_retries"], int(v),
             t["max_device_retries"],
             "unbounded retry amplification = the breaker is not doing "
             "its job")

    if t.get("max_import_p99_s") is not None:
        v = deltas["import_p99_s"]
        gate("import_p99", v <= t["max_import_p99_s"], round(v, 4),
             t["max_import_p99_s"])

    if t.get("max_verify_p99_s") is not None:
        v = deltas["verify_p99_s"]
        gate("verify_p99", v <= t["max_verify_p99_s"], round(v, 4),
             t["max_verify_p99_s"])

    if t.get("require_head_convergence"):
        heads = run.get("heads", [])
        converged = len(set(heads)) == 1 and bool(heads)
        gate("head_convergence", converged, len(set(heads)), 1,
             "distinct heads across nodes at run end")

    if t.get("min_finalized_advance") is not None:
        fins = run.get("finalized_epochs", [0])
        worst = min(fins) if fins else 0
        gate("finalization", worst >= t["min_finalized_advance"], worst,
             t["min_finalized_advance"],
             f"per-node finalized epochs {fins}")

    if t.get("max_never_raise_violations") is not None:
        v = run.get("never_raise_violations", 0)
        gate("never_raise", v <= t["max_never_raise_violations"], v,
             t["max_never_raise_violations"],
             "exceptions escaping contracts that promise not to raise")

    if t.get("require_breaker_recovered"):
        closed = run.get("breaker_closed", True)
        gate("breaker_recovered", closed, closed, True,
             "breaker must re-close once faults stop")

    if t.get("require_crash_recovery") and run.get("crash_reports"):
        oks = [r.get("ok", False) for r in run["crash_reports"]]
        gate("crash_recovery", all(oks), oks, True,
             "every kill -9 iteration must recover committed records")

    if t.get("max_overlap_wall_ratio") is not None:
        # Trace-derived overlap efficiency (obs/report.py): wall over the
        # busiest stage's busy time — 1.0 is perfect overlap.  Warn-level:
        # pipeline efficiency regressions should be loud, not flaky run
        # failures (the ratio depends on host load).
        ov = run.get("overlap_efficiency") or {}
        ratio = ov.get("ratio")
        gate("overlap_efficiency",
             ratio is None or ratio <= t["max_overlap_wall_ratio"],
             None if ratio is None else round(ratio, 3),
             t["max_overlap_wall_ratio"],
             f"trace wall / max(stage busy), mode={ov.get('mode', 'empty')}",
             level="warn")

    if t.get("min_slashings_detected") is not None:
        v = run.get("slashings_detected", 0)
        gate("slashings_detected", v >= t["min_slashings_detected"], v,
             t["min_slashings_detected"],
             "the equivocation shape must be caught by the slashers")

    # ---- hostile-regime gates ------------------------------------------

    if t.get("max_op_pool_attestations") is not None:
        v = run.get("op_pool_attestations", 0)
        gate("op_pool_growth", v <= t["max_op_pool_attestations"], int(v),
             t["max_op_pool_attestations"],
             "largest per-node op-pool attestation count at run end — "
             "pruning must bound growth under non-finality")

    if t.get("max_naive_pool_groups") is not None:
        v = run.get("naive_pool_groups", 0)
        gate("naive_pool_growth", v <= t["max_naive_pool_groups"], int(v),
             t["max_naive_pool_groups"],
             "largest per-node naive-aggregation group count at run end")

    if t.get("max_committee_caches") is not None:
        v = run.get("committee_cache_entries", 0)
        gate("shuffling_cache_pressure", v <= t["max_committee_caches"],
             int(v), t["max_committee_caches"],
             "shared shuffling-cache entries — the bounded cache must "
             "hold its budget across epochs of non-finality")

    if t.get("max_finalized_advance") is not None:
        fins = run.get("finalized_epochs", [0])
        best = max(fins) if fins else 0
        gate("finality_stalled", best <= t["max_finalized_advance"], best,
             t["max_finalized_advance"],
             "the stall track must actually prevent finality "
             f"(per-node finalized epochs {fins})")

    if t.get("min_exits_processed") is not None:
        v = run.get("exits_processed", 0)
        gate("exits_processed", v >= t["min_exits_processed"], int(v),
             t["min_exits_processed"],
             "the exit-flood must drain through op-pool packing and the "
             "voluntary-exit transition")

    if t.get("require_checkpoint_convergence"):
        converged = run.get("checkpoint_converged", False)
        gate("checkpoint_convergence", converged, converged, True,
             "the checkpoint-synced node must reach the honest head "
             "despite a hostile peer majority")

    if t.get("min_hostile_peers_banned") is not None:
        v = run.get("hostile_peers_banned", 0)
        gate("hostile_peers_banned", v >= t["min_hostile_peers_banned"],
             int(v), t["min_hostile_peers_banned"],
             "peer scoring must ban byzantine checkpoint servers")

    # ---- saturation-soak gates (deposit saturation / storms / soak) ----

    if t.get("max_deposit_queue_depth") is not None:
        v = run.get("deposit_queue_depth_max", 0)
        gate("deposit_queue_depth", v <= t["max_deposit_queue_depth"],
             int(v), t["max_deposit_queue_depth"],
             "worst per-epoch deposit backlog (voted deposit_count - "
             "drained index) — the drain must keep pace with the "
             "over-rate inflow")

    if t.get("min_deposits_applied") is not None:
        v = run.get("deposits_applied", 0)
        gate("deposit_drain", v >= t["min_deposits_applied"], int(v),
             t["min_deposits_applied"],
             "the eth1 voting + block-packing drain must stay live "
             "under saturation")

    if t.get("max_ssz_cache_bytes") is not None:
        v = run.get("ssz_cache_bytes_max", 0)
        gate("ssz_cache_bytes", v <= t["max_ssz_cache_bytes"], int(v),
             t["max_ssz_cache_bytes"],
             "worst per-epoch growth of the SSZ/state cache byte "
             "footprint since run start — the eviction budget must "
             "bound it across epochs")

    if t.get("max_pool_estimated_verify_cost") is not None:
        v = run.get("pool_estimated_verify_cost_max", 0)
        gate("pool_verify_cost", v <= t["max_pool_estimated_verify_cost"],
             int(v), t["max_pool_estimated_verify_cost"],
             "worst per-epoch estimated marginal verify cost of the "
             "naive pool — near-duplicate aggregation storms inflate "
             "this superlinearly unless admission sheds them")

    if t.get("min_storm_shed_rate") is not None:
        v = run.get("storm_shed_rate", 0.0)
        gate("storm_shed", v >= t["min_storm_shed_rate"], round(v, 4),
             t["min_storm_shed_rate"],
             "cost-based admission must shed the aggregation storm's "
             "overage before it reaches the pools")

    # ---- verification-front-door tenancy gates (tenant-overload) -------

    if t.get("max_honest_deadline_miss_rate") is not None:
        v = run.get("serve_honest_deadline_miss_rate", 0.0)
        gate("honest_deadline_misses",
             v <= t["max_honest_deadline_miss_rate"], round(v, 4),
             t["max_honest_deadline_miss_rate"],
             "the deadline-sensitive tenant must keep its deadlines while "
             f"a greedy tenant floods ({run.get('serve_honest_completed', 0)}"
             " honest requests completed)")

    if t.get("max_honest_shed") is not None:
        v = run.get("serve_honest_shed", 0)
        gate("honest_shed", v <= t["max_honest_shed"], int(v),
             t["max_honest_shed"],
             "admission must shed only the offender, never the honest "
             "tenant's in-rate ingress")

    if t.get("min_greedy_shed_rate") is not None:
        v = run.get("serve_greedy_shed_rate", 0.0)
        gate("greedy_shed", v >= t["min_greedy_shed_rate"], round(v, 4),
             t["min_greedy_shed_rate"],
             "the greedy tenant's overage must actually be shed — its "
             "token bucket is the isolation boundary")

    # ---- warm-standby handoff gates (warm-standby-handoff track) -------

    if t.get("max_handoff_shed") is not None:
        v = run.get("handoff_shed", 0)
        gate("handoff_shed", v <= t["max_handoff_shed"], int(v),
             t["max_handoff_shed"],
             "zero-downtime means zero: no request may be shed while "
             "the standby prewarms and the device rung cuts over "
             f"({run.get('handoff_completed', 0)} requests completed)")

    if t.get("require_handoff_cutover"):
        done = run.get("handoff_cutover_done", False)
        gate("handoff_cutover", done, done, True,
             "the standby must actually take over serving after its "
             "prewarm verified against the old node's outputs")

    if t.get("max_standby_compiles") is not None:
        v = run.get("handoff_standby_compiles", 0)
        gate("standby_compiles", v <= t["max_standby_compiles"], int(v),
             t["max_standby_compiles"],
             "the standby must boot from the AOT store, not the "
             "tracer — a compile here is the minutes-long stall the "
             "store exists to delete")

    if t.get("min_prewarm_loaded") is not None:
        v = run.get("handoff_prewarm_loaded", 0)
        gate("prewarm_loaded", v >= t["min_prewarm_loaded"], int(v),
             t["min_prewarm_loaded"],
             "every program the old node captured must deserialize and "
             "install on the standby")

    # ---- verdict-integrity gates (sdc-storm track) ---------------------

    if t.get("max_sdc_wrong_accepts") is not None:
        v = run.get("sdc_wrong_accepts", 0)
        gate("sdc_wrong_accepts", v <= t["max_sdc_wrong_accepts"], int(v),
             t["max_sdc_wrong_accepts"],
             "flipped verdicts released to a consumer, counted against "
             "the scalar-oracle truth — a wrong-accept here is a "
             "consensus-safety escape, not a liveness blip")

    if t.get("min_sdc_detected") is not None:
        v = run.get("sdc_detected", 0)
        gate("sdc_detected", v >= t["min_sdc_detected"], int(v),
             t["min_sdc_detected"],
             "canary mismatches + audit disagreements — every injected "
             "silent flip must be caught before verdict release "
             f"({run.get('sdc_injected', 0)} silent faults injected)")

    if t.get("min_sdc_quarantined") is not None:
        v = run.get("sdc_quarantined", 0)
        gate("sdc_quarantined", v >= t["min_sdc_quarantined"], int(v),
             t["min_sdc_quarantined"],
             "devices the trust score pulled from the mesh — a lying "
             "device must not keep serving shards")

    return out


#: the threshold keys evaluate_epoch localizes — per-epoch facts the
#: engine snapshots at every epoch boundary, so a slow leak or a
#: mid-run saturation blows the gate AT THE EPOCH IT STARTS
#: (``first_violation_epoch`` in the report) instead of only at run end
EPOCH_GATED_KEYS = (
    "max_deposit_queue_depth",
    "max_ssz_cache_bytes",
    "max_pool_estimated_verify_cost",
    "max_sdc_wrong_accepts",
)


def evaluate_epoch(thresholds: dict, facts: dict) -> list[SLOResult]:
    """Gate one epoch's snapshot facts (a subset of the run-level gates
    — see :data:`EPOCH_GATED_KEYS`).  The run-level ``evaluate`` gates
    the worst epoch's value, so the verdict has one source of truth;
    this localizes the violation to the epoch it first appears in."""
    out: list[SLOResult] = []
    t = thresholds

    if t.get("max_deposit_queue_depth") is not None:
        v = facts.get("deposit_queue_depth", 0)
        out.append(SLOResult(
            "deposit_queue_depth", v <= t["max_deposit_queue_depth"],
            int(v), t["max_deposit_queue_depth"],
            "deposit backlog at this epoch's boundary",
        ))

    if t.get("max_ssz_cache_bytes") is not None:
        v = facts.get("ssz_cache_bytes", 0)
        out.append(SLOResult(
            "ssz_cache_bytes", v <= t["max_ssz_cache_bytes"], int(v),
            t["max_ssz_cache_bytes"],
            "SSZ/state cache byte growth since run start",
        ))

    if t.get("max_pool_estimated_verify_cost") is not None:
        v = facts.get("pool_estimated_verify_cost", 0)
        out.append(SLOResult(
            "pool_verify_cost",
            v <= t["max_pool_estimated_verify_cost"], int(v),
            t["max_pool_estimated_verify_cost"],
            "naive-pool estimated verify cost at this epoch's boundary",
        ))

    if t.get("max_sdc_wrong_accepts") is not None:
        v = facts.get("sdc_wrong_accepts", 0)
        out.append(SLOResult(
            "sdc_wrong_accepts", v <= t["max_sdc_wrong_accepts"], int(v),
            t["max_sdc_wrong_accepts"],
            "flipped verdicts released to a consumer during this epoch "
            "(scalar-oracle truth check)",
        ))

    return out
