"""Coverage-guided hostile-regime scenario search.

A small mutation-based fuzzer over :class:`ScenarioSpec` space: start
from registered corpus scenarios, mutate one dimension at a time (seed,
traffic shapes, adversity tracks and their ``k=v`` knobs, node/validator
counts, epochs, the breaker toggle), run each candidate through the
deterministic :class:`ScenarioEngine`, and use the run's sha256
fingerprint as the novelty/coverage signal — a candidate whose
fingerprint was never seen exercised a genuinely new fault interleaving
and earns a corpus slot.  SLO *proximity* (worst observed/threshold
ratio across the numeric fail-level gates) is the fitness that biases
parent selection toward near-violating regions.

Any candidate that violates a fail-level SLO is handed to
:mod:`minimize`, which delta-debugs it to a minimal reproducing spec and
renders a ready-to-register ``SCENARIOS`` entry — the search output IS a
regression scenario, not just a crash log.

Everything is deterministic under ``SearchConfig.seed``: one
``random.Random`` drives every mutation choice, candidate seeds are
drawn from it, and each engine run is deterministic by the scenario
contract — so a search that found a violation replays bit-identically.

The ``MUTATION_SHAPES`` / ``MUTATION_TRACKS`` / ``KNOB_RANGES``
constants below are the search's mutation surface; the registry lint
cross-checks every name against the real ``SHAPES``/``TRACKS``
registries (keep them literal — AST-parsed, never imported).
``hostile-checkpoint`` is deliberately NOT in the mutation surface: its
finalize builds a full byzantine fork chain, too heavy for budgeted
search (run it via its registered scenario instead).
"""

from __future__ import annotations

import json
import os
import random
import time
from dataclasses import dataclass, field, replace

from .minimize import MinimizeResult, minimize, render_spec
from .spec import (
    SCENARIOS,
    ScenarioSpec,
    fixture_scenario_dir,
    spec_from_json,
    spec_to_json,
)

# ---------------------------------------------------------------------------
# The mutation surface.  Keep these literal: analysis/registry_lint.py
# AST-parses them and cross-checks every name/knob against the live
# traffic.SHAPES / adversity.TRACKS registries.
# ---------------------------------------------------------------------------

MUTATION_SHAPES = (
    "attestation-flood",
    "deposit-queue",
    "proposer-reorg",
    "equivocation",
    "equivocation-storm",
    "exit-flood",
)

MUTATION_TRACKS = (
    "gossip-faults",
    "device-faults",
    "byzantine-sync",
    "kill-recovery",
    "pod-device-drop",
    "finality-stall",
    "tenant-overload",
)

# knob -> (lo, hi) ranges drawn uniformly (ints when both ends are ints)
KNOB_RANGES = {
    "gossip-faults": {"p": (0.05, 0.45), "start": (2, 10), "end": (8, 28)},
    "device-faults": {"delay": (0.0, 0.03), "start": (4, 14), "end": (8, 22)},
    "kill-recovery": {"at": (8, 28)},
    "pod-device-drop": {"p": (0.3, 0.9), "shards": (2, 6),
                        "start": (4, 12), "end": (8, 18)},
    "finality-stall": {"p": (0.35, 0.8), "start": (2, 8), "end": (16, 64)},
    "tenant-overload": {"greedy_mult": (2, 20), "slow_p": (0.0, 0.9),
                        "deadline": (0.2, 2.0), "steps": (4, 16)},
}

# hard caps so mutation can't wander into hour-long candidates
MAX_NODES = 5
MAX_VALIDATORS = 48
MAX_EPOCHS = 4


@dataclass
class SearchConfig:
    seed: int = 0
    budget: int = 32                 # candidate engine runs
    corpus: tuple = ("smoke",)       # starting scenario names (SCENARIOS)
    minimize_steps: int = 24         # oracle budget per violation (0 = off)
    corpus_cap: int = 12             # live corpus bound
    # mutation-surface narrowing (None = the full module constants);
    # lets a budgeted CI search focus on one fault family
    shapes: tuple | None = None
    tracks: tuple | None = None


@dataclass
class Violation:
    spec: ScenarioSpec
    failed: tuple                    # failing fail-level gate names
    fingerprint: str
    minimized: MinimizeResult | None = None
    rendered: str = ""               # ready-to-register registry entry
    registered: str = ""             # fixture path the finding landed in


@dataclass
class SearchResult:
    candidates_run: int = 0
    violations: list = field(default_factory=list)
    novel_fingerprints: int = 0
    minimization_steps: int = 0
    corpus_names: list = field(default_factory=list)
    sweeps: int = 1                  # >1 only in continuous mode

    def to_dict(self) -> dict:
        return {
            "candidates_run": self.candidates_run,
            "violations_found": len(self.violations),
            "novel_fingerprints": self.novel_fingerprints,
            "minimization_steps": self.minimization_steps,
            "sweeps": self.sweeps,
            "violations": [
                {
                    "name": v.spec.name,
                    "failed": list(v.failed),
                    "fingerprint": v.fingerprint,
                    "minimized_steps": (
                        v.minimized.steps if v.minimized else 0
                    ),
                    "removed": (
                        v.minimized.removed if v.minimized else []
                    ),
                    "rendered": v.rendered,
                    "registered": v.registered,
                }
                for v in self.violations
            ],
        }


def failing_gates(report: dict) -> tuple:
    """Names of the fail-level gates a report violates (warns excluded)."""
    return tuple(
        s["name"] for s in report.get("slo", ())
        if not s["ok"] and s.get("level") != "warn"
    )


def slo_proximity(report: dict) -> float:
    """Worst observed/threshold pressure across numeric fail-level gates
    (1.0 = at the limit).  Drives parent selection toward near-violating
    corpus entries."""
    worst = 0.0
    for s in report.get("slo", ()):
        if s.get("level") == "warn":
            continue
        obs, thr = s.get("observed"), s.get("threshold")
        if isinstance(obs, (int, float)) and isinstance(thr, (int, float)) \
                and thr > 0:
            worst = max(worst, float(obs) / float(thr))
    return worst


def default_runner(spec: ScenarioSpec) -> dict:
    """Run one candidate through the real engine (no report/history I/O)."""
    from .engine import ScenarioEngine

    return ScenarioEngine(spec).run()


def violation_oracle(runner, gates: tuple):
    """The reproduces-callback minimize() consumes: a candidate
    reproduces iff its run still fails at least one of the ORIGINAL
    violation's gates (a different failure is a different bug — don't
    let the minimizer drift onto it)."""
    gate_set = set(gates)

    def reproduces(spec: ScenarioSpec) -> bool:
        report = runner(spec)
        return bool(gate_set & set(failing_gates(report)))

    return reproduces


class ScenarioSearch:
    """One budgeted search session.  ``runner`` is injectable for tests
    (spec -> report dict); everything else is pure spec surgery."""

    def __init__(self, config: SearchConfig, runner=None,
                 scenarios: dict | None = None, log=None):
        self.config = config
        self.rng = random.Random(config.seed)
        self.runner = runner or default_runner
        self.log = log or (lambda msg: None)
        self._shapes = (config.shapes if config.shapes is not None
                        else MUTATION_SHAPES)
        self._tracks = (config.tracks if config.tracks is not None
                        else MUTATION_TRACKS)
        registry = scenarios if scenarios is not None else SCENARIOS
        self.corpus: list[ScenarioSpec] = []
        for name in config.corpus:
            if name not in registry:
                raise ValueError(
                    f"unknown corpus scenario {name!r}; "
                    f"have {sorted(registry)}"
                )
            self.corpus.append(registry[name])
        self._fitness: dict[str, float] = {}   # spec.name -> proximity
        self.seen: set[str] = set()            # fingerprints covered
        self.result = SearchResult()

    # ------------------------------------------------------------ mutation

    def _mutate_knob(self, track_spec: str) -> str:
        name, _, rest = track_spec.partition(":")
        ranges = KNOB_RANGES.get(name)
        if not ranges:
            return track_spec
        kwargs = {}
        if rest:
            for kv in rest.split(","):
                k, _, v = kv.partition("=")
                kwargs[k.strip()] = v.strip()
        key = self.rng.choice(sorted(ranges))
        lo, hi = ranges[key]
        if isinstance(lo, int) and isinstance(hi, int):
            kwargs[key] = str(self.rng.randint(lo, hi))
        else:
            kwargs[key] = f"{self.rng.uniform(lo, hi):.3f}"
        rendered = ",".join(f"{k}={v}" for k, v in kwargs.items())
        return f"{name}:{rendered}"

    def mutate(self, parent: ScenarioSpec, index: int) -> ScenarioSpec:
        """One mutated child: a fresh seed plus ONE structural mutation
        (single-dimension steps keep the minimizer's job small)."""
        spec = replace(parent, seed=self.rng.randrange(1, 2 ** 20),
                       name=f"{parent.name.partition('~')[0]}~m{index}")
        # adversity exploration is double-weighted: the hostile regimes
        # we hunt live in track space far more often than in scale space
        op = self.rng.choice((
            "reseed", "add_shape", "drop_shape",
            "add_track", "add_track", "drop_track",
            "mutate_knob", "mutate_knob",
            "scale_nodes", "scale_validators",
            "scale_epochs", "toggle_breaker",
        ))
        if op == "add_shape":
            missing = [s for s in self._shapes if s not in spec.traffic]
            if missing:
                shape = self.rng.choice(missing)
                spec = replace(spec, traffic=spec.traffic + (shape,))
                if shape == "exit-flood":
                    # exits need exit-eligible validators inside the run
                    spec = replace(spec, spec_overrides=(
                        ("shard_committee_period", 0),
                    ))
        elif op == "drop_shape" and spec.traffic:
            victim = self.rng.choice(sorted(spec.traffic))
            spec = replace(spec, traffic=tuple(
                s for s in spec.traffic if s != victim
            ))
        elif op == "add_track":
            have = {t.partition(":")[0] for t in spec.adversity}
            missing = [t for t in self._tracks if t not in have]
            if missing:
                track = self.rng.choice(missing)
                spec = replace(spec, adversity=spec.adversity + (
                    self._mutate_knob(track),
                ))
        elif op == "drop_track" and spec.adversity:
            victim = self.rng.choice(sorted(spec.adversity))
            spec = replace(spec, adversity=tuple(
                t for t in spec.adversity if t != victim
            ))
        elif op == "mutate_knob" and spec.adversity:
            victim = self.rng.choice(sorted(spec.adversity))
            spec = replace(spec, adversity=tuple(
                self._mutate_knob(t) if t == victim else t
                for t in spec.adversity
            ))
        elif op == "scale_nodes":
            spec = replace(spec, n_nodes=min(
                MAX_NODES, max(2, spec.n_nodes + self.rng.choice((-1, 1)))
            ))
        elif op == "scale_validators":
            spec = replace(spec, n_validators=min(
                MAX_VALIDATORS,
                max(8, spec.n_validators + self.rng.choice((-8, 8))),
            ))
        elif op == "scale_epochs":
            spec = replace(spec, epochs=min(
                MAX_EPOCHS, max(1, spec.epochs + self.rng.choice((-1, 1)))
            ))
        elif op == "toggle_breaker":
            spec = replace(spec, breaker_enabled=not spec.breaker_enabled)
        return spec

    # ---------------------------------------------------------- the loop

    def _pick_parent(self) -> ScenarioSpec:
        """Fitness-weighted pick: corpus entries closer to an SLO limit
        breed more often (weight 1 + proximity)."""
        weights = [1.0 + self._fitness.get(s.name, 0.0) for s in self.corpus]
        return self.rng.choices(self.corpus, weights=weights, k=1)[0]

    def run(self, deadline: float | None = None,
            clock=time.monotonic) -> SearchResult:
        res = self.result
        while res.candidates_run < self.config.budget:
            if deadline is not None and clock() >= deadline:
                break
            parent = self._pick_parent()
            cand = self.mutate(parent, res.candidates_run)
            report = self.runner(cand)
            res.candidates_run += 1
            fp = report.get("fingerprint", "")
            novel = fp not in self.seen
            if novel:
                self.seen.add(fp)
                res.novel_fingerprints += 1
            failed = failing_gates(report)
            if failed:
                self.log(f"violation after {res.candidates_run} candidates:"
                         f" {cand.name} fails {list(failed)}")
                self._handle_violation(cand, failed, fp)
                continue  # violating specs don't join the corpus
            if novel and len(self.corpus) < self.config.corpus_cap:
                self.corpus.append(cand)
            self._fitness[cand.name] = slo_proximity(report)
        res.corpus_names = [s.name for s in self.corpus]
        return res

    def _handle_violation(self, spec: ScenarioSpec, failed: tuple,
                          fp: str) -> None:
        known = {v.failed for v in self.result.violations}
        violation = Violation(spec=spec, failed=failed, fingerprint=fp)
        if failed not in known and self.config.minimize_steps > 0:
            oracle = violation_oracle(self.runner, failed)
            violation.minimized = minimize(
                spec, oracle, max_steps=self.config.minimize_steps
            )
            self.result.minimization_steps += violation.minimized.steps
            minimal = violation.minimized.spec
            reg_name = f"regress-{'-'.join(failed)}-{minimal.seed}"
            violation.rendered = render_spec(minimal, name=reg_name)
        self.result.violations.append(violation)


def run_search(config: SearchConfig, runner=None, log=None) -> SearchResult:
    """One budgeted search session (the tools/scenario_search.py core)."""
    return ScenarioSearch(config, runner=runner, log=log).run()


# ---------------------------------------------------------------------------
# Continuous mode: wall-clock-budgeted sweeps feeding the committed
# regression corpus (tests/fixtures/scenarios/).
# ---------------------------------------------------------------------------


def register_violation(violation: Violation,
                       register_dir: str | None = None) -> str | None:
    """Land one ddmin-minimized finding in the regression corpus.

    The minimal spec is renamed to its registry name
    (``regress-<gates>-<seed>``), round-tripped through
    ``spec_to_json``/``spec_from_json`` (a fixture that can't rebuild
    its spec must never be committed), and written as
    ``<register_dir>/<name>.json`` — the exact file
    ``parse_scenario_arg`` resolves, so the finding replays standalone
    via ``--scenario <name>``.  Dedup is by name: an already-registered
    finding (same gates, same minimal seed) is left untouched and
    returns None.
    """
    if violation.minimized is None:
        return None
    minimal = violation.minimized.spec
    reg_name = f"regress-{'-'.join(violation.failed)}-{minimal.seed}"
    doc = spec_to_json(replace(minimal, name=reg_name))
    spec_from_json(doc)  # validate the round-trip BEFORE touching disk
    out_dir = register_dir or fixture_scenario_dir()
    path = os.path.join(out_dir, f"{reg_name}.json")
    if os.path.exists(path):
        return None
    os.makedirs(out_dir, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    violation.registered = path
    return path


def run_continuous(config: SearchConfig, budget_seconds: float,
                   runner=None, log=None, register_dir: str | None = None,
                   clock=time.monotonic) -> SearchResult:
    """Wall-clock-budgeted search: repeated sweeps until the budget is
    spent, each under a seed derived from ``config.seed`` (so a given
    ``(seed, sweep)`` pair replays deterministically even though the
    sweep COUNT depends on wall time), with every newly-minimized
    violation registered into the regression corpus via
    :func:`register_violation`.

    Distinct-by-gates dedup carries across sweeps: a gate combination
    already minimized in an earlier sweep is recorded but not
    re-minimized (and by construction not re-registered — the fixture
    name is keyed on the failing gates).
    """
    emit = log or (lambda msg: None)
    deadline = clock() + max(0.0, budget_seconds)
    combined = SearchResult(sweeps=0)
    seen_gates: set[tuple] = set()
    while True:
        sweep = combined.sweeps
        cfg = replace(config, seed=config.seed + sweep * 1_000_003)
        search = ScenarioSearch(cfg, runner=runner, log=log)
        # skip re-minimizing gate combinations earlier sweeps landed
        for gates in seen_gates:
            search.result.violations.append(
                Violation(spec=search.corpus[0], failed=gates,
                          fingerprint="")
            )
        placeholders = len(search.result.violations)
        res = search.run(deadline=deadline, clock=clock)
        combined.sweeps += 1
        combined.candidates_run += res.candidates_run
        combined.novel_fingerprints += res.novel_fingerprints
        combined.minimization_steps += res.minimization_steps
        combined.corpus_names = res.corpus_names
        for v in res.violations[placeholders:]:
            combined.violations.append(v)
            seen_gates.add(v.failed)
            if v.minimized is not None:
                path = register_violation(v, register_dir)
                if path:
                    emit(f"registered regression fixture: {path}")
        if clock() >= deadline:
            break
    return combined
