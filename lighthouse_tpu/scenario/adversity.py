"""Adversity tracks: the failure modes a scenario runs *through*.

Tracks are parsed from ``"name[:key=val,...]"`` specs (mirroring the
``--chaos`` arming-spec style) and get the same per-slot hooks as
traffic shapes.  Each reuses machinery built by earlier robustness PRs:
the FaultInjector's ``gossip.route``/``processor.verify`` sites, the
byzantine peer servers from the chaos-sync soak, and the ``kill -9``
crash harness (run in-process here, subprocess child and all).
"""

from __future__ import annotations

import importlib.util
import os
import sys
import tempfile
import time

from ..utils.faults import DeviceFault


def _flip_mid_byte(b: bytes) -> bytes:
    if not b:
        return b
    mid = len(b) // 2
    return b[:mid] + bytes([b[mid] ^ 0xFF]) + b[mid + 1:]


class Track:
    name = ""

    def install(self, engine) -> None:
        """One-time setup before slot 0."""

    def on_slot(self, engine, slot: int) -> None:
        """Called at the start of every slot (before the proposal)."""

    def on_attestations(self, engine, slot: int, atts: list) -> None:
        """Called after the honest committees attested at ``slot`` —
        tracks that piggyback on the honest stream (e.g. crafting
        near-duplicate aggregates from a real template) hook here."""

    def on_epoch(self, engine, epoch: int, facts: dict) -> None:
        """Contribute to the engine's per-epoch snapshot ``facts``."""

    def finalize(self, engine) -> None:
        """End-of-run bookkeeping into the engine report."""


class GossipFaultTrack(Track):
    """Arm the router's per-delivery ``gossip.route`` site over a slot
    window: ``drop`` is a lossy wire (per-peer delivery loss the
    epoch-boundary heal must repair), ``corrupt`` a bit-flipping relay
    (corrupted payloads fail snappy and penalize the path instead)."""

    name = "gossip-faults"

    def __init__(self, kind="drop", p="0.15", start="4", end="10"):
        self.kind = kind
        self.p = float(p)
        self.start = int(start)
        self.end = int(end)

    def on_slot(self, engine, slot: int) -> None:
        if slot == self.start:
            mutate = _flip_mid_byte if self.kind == "corrupt" else None
            engine.injector.arm("gossip.route", self.kind,
                                probability=self.p, mutate=mutate)
            engine.note("gossip-faults", slot=slot, armed=self.kind,
                        p=self.p)
        elif slot == self.end + 1:
            engine.injector.disarm("gossip.route")
            engine.note("gossip-faults", slot=slot, disarmed=self.kind)

    def finalize(self, engine) -> None:
        engine.injector.disarm("gossip.route")
        engine.run_facts["gossip_deliveries_dropped"] = (
            engine.sim.router.dropped
        )


class DeviceFaultTrack(Track):
    """A device-outage window: every ``processor.verify`` call sleeps
    ``delay`` then raises :class:`DeviceFault` (a slow-then-dead
    accelerator).  With the breaker enabled this trips it OPEN within
    ``failure_threshold`` batches and the run recovers through probes;
    with the breaker disabled every batch pays the full retry budget and
    the ``max_device_retries`` SLO blows — the degraded-run proof."""

    name = "device-faults"

    def __init__(self, delay="0.02", start="10", end="14"):
        self.delay = float(delay)
        self.start = int(start)
        self.end = int(end)

    def _exc(self):
        time.sleep(self.delay)
        return DeviceFault("injected scenario device-fault window")

    def on_slot(self, engine, slot: int) -> None:
        if slot == self.start:
            engine.injector.arm("processor.verify", "error", exc=self._exc)
            engine.note("device-faults", slot=slot, armed="error",
                        delay=self.delay)
        elif slot == self.end + 1:
            engine.injector.disarm("processor.verify")
            engine.note("device-faults", slot=slot, disarmed="error")

    def finalize(self, engine) -> None:
        engine.injector.disarm("processor.verify")


class ByzantineSyncTrack(Track):
    """Every epoch-boundary heal gains byzantine company: alongside the
    honest serving peer, a block-reordering peer and a crashing peer join
    the SyncManager's peer set (the chaos-sync soak's adversaries), so
    lagging nodes must score out liars while catching up."""

    name = "byzantine-sync"

    def install(self, engine) -> None:
        engine.byzantine_sync = True

    def finalize(self, engine) -> None:
        engine.run_facts["byzantine_heals"] = engine.run_facts.get(
            "byzantine_heals", 0
        )


class KillRecoveryTrack(Track):
    """Mid-run ``kill -9`` + recovery: at slot ``at`` the crash harness
    runs one full iteration in-process (subprocess child, SIGKILL landing
    inside a record's write window, WAL recovery + verification against
    the committed prefix).  A failed recovery is recorded and fails the
    ``crash_recovery`` SLO."""

    name = "kill-recovery"

    def __init__(self, at="24", kill_after="3", blocks="16"):
        self.at = int(at)
        self.kill_after = int(kill_after)
        self.blocks = int(blocks)

    @staticmethod
    def _load_harness():
        path = os.path.join(
            os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
            "tools", "crash_harness.py",
        )
        if "crash_harness" in sys.modules:
            return sys.modules["crash_harness"]
        spec = importlib.util.spec_from_file_location("crash_harness", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["crash_harness"] = mod
        spec.loader.exec_module(mod)
        return mod

    def on_slot(self, engine, slot: int) -> None:
        if slot != self.at:
            return
        harness = self._load_harness()
        datadir = tempfile.mkdtemp(prefix="scenario-crash-")
        report = {"slot": slot, "kill_after": self.kill_after, "ok": False}
        try:
            result = harness.run_iteration(
                engine.spec.seed, datadir, self.kill_after,
                blocks=self.blocks,
            )
            report.update(result)
            report["ok"] = True
        except Exception as exc:  # noqa: BLE001 — a failed recovery is an
            # SLO verdict, not a harness crash
            report["error"] = f"{type(exc).__name__}: {exc}"
        engine.run_facts.setdefault("crash_reports", []).append(report)
        engine.note("kill-recovery", slot=slot, ok=report["ok"])


class PodDeviceDropTrack(Track):
    """Pod-serving device loss: at install the engine's verify path is
    lifted onto a list-mode :class:`~...parallel.pod.PodVerifier` —
    ``shards`` fault domains over the ResilientVerifier's own
    ``device_verify``, sharing its breaker/journal — and over the slot
    window the ``pod.dispatch`` site drops shards with probability ``p``.
    Repeat offenders are excluded, the batch re-shards onto the
    surviving mesh (never dropping a batch), and after the window probe
    shards re-arm the excluded devices."""

    name = "pod-device-drop"

    def __init__(self, shards="4", p="0.7", start="8", end="12",
                 timeout="30.0"):
        self.shards = int(shards)
        self.p = float(p)
        self.start = int(start)
        self.end = int(end)
        self.timeout = float(timeout)
        self.pod = None

    def install(self, engine) -> None:
        from ..parallel.pod import PodVerifier

        inner = engine.verifier
        self.pod = PodVerifier(
            inner,
            shard_verify=lambda sub: bool(inner.device_verify(sub)),
            devices=list(range(self.shards)),
            injector=engine.injector,
            shard_timeout=self.timeout,
            max_shard_retries=1,
            backoff_base=0.0,
            exclusion_threshold=2,
            probe_after=1,
        )
        engine.verifier = self.pod

    def on_slot(self, engine, slot: int) -> None:
        if slot == self.start:
            engine.injector.arm("pod.dispatch", "shard-drop",
                                probability=self.p)
            engine.note("pod-device-drop", slot=slot, armed="shard-drop",
                        p=self.p, shards=self.shards)
        elif slot == self.end + 1:
            engine.injector.disarm("pod.dispatch")
            engine.note("pod-device-drop", slot=slot,
                        disarmed="shard-drop")

    def finalize(self, engine) -> None:
        engine.injector.disarm("pod.dispatch")
        if self.pod is None:
            return
        health = self.pod.health
        engine.run_facts["pod_batches"] = sum(
            1 for kind, _n in self.pod.journal if kind == "pod"
        )
        engine.run_facts["pod_excluded_at_end"] = (
            health.excluded() if health is not None else []
        )


class FinalityStallTrack(Track):
    """A multi-epoch finality stall: over the slot window each committee
    aggregate is suppressed before publication with probability ``p``
    (drawn from the engine's seeded rng, so the stall is deterministic).
    With p above ~1/3 the surviving participation can't justify, so
    finality pins at its pre-window value — the regime the pool-growth
    and shuffling-cache SLOs are judged under."""

    name = "finality-stall"

    def __init__(self, p="0.6", start="2", end="999"):
        self.p = float(p)
        self.start = int(start)
        self.end = int(end)
        self.suppressed = 0

    def on_slot(self, engine, slot: int) -> None:
        if slot == self.start:
            rng, p = engine.rng, self.p

            def keep(att) -> bool:
                if rng.random() < p:
                    self.suppressed += 1
                    return False
                return True

            engine.att_filter = keep
            engine.note("finality-stall", slot=slot, armed=True, p=p)
        elif slot == self.end + 1:
            engine.att_filter = None
            engine.note("finality-stall", slot=slot, disarmed=True)

    def finalize(self, engine) -> None:
        engine.att_filter = None
        engine.run_facts["attestations_suppressed"] = self.suppressed


class HostileCheckpointTrack(Track):
    """Checkpoint sync through a byzantine peer majority.

    At slot ``at`` the best node's head (block + post-state) is captured
    as a checkpoint anchor.  At run end a fresh node is built from that
    anchor (``chain_from_anchor``) and forward-syncs over the real
    SyncManager with an initial peer set that is ENTIRELY hostile:
    ``hostile`` peers serving a structurally-valid byzantine fork (same
    genesis, different graffiti ancestry — batches fail import with
    unknown parents).  Scoring must grind them down (strike 1 greylists,
    the last-resort re-pick bans) until the sync stalls; then discovery
    lands ONE honest peer, the sync re-arms, and the node must reach the
    honest head — the ``checkpoint_convergence`` /
    ``hostile_peers_banned`` SLOs."""

    name = "hostile-checkpoint"

    def __init__(self, at="12", hostile="3"):
        self.at = int(at)
        self.hostile = int(hostile)
        self._anchor = None

    def on_slot(self, engine, slot: int) -> None:
        if slot != self.at:
            return
        sim = engine.sim
        for n in sim.nodes:
            n.chain.recompute_head()
        best = max(
            sim.nodes,
            key=lambda n: (int(n.chain.head_state().slot), n.chain.head_root),
        )
        cls = best.chain.types.SignedBeaconBlock_BY_FORK[engine.spec.fork]
        blk = best.chain.store.get_block(best.chain.head_root, cls)
        self._anchor = (best, best.chain.head_state().copy(), blk)
        engine.note("hostile-checkpoint", slot=slot,
                    anchor_slot=int(blk.message.slot))

    def _build_fork(self, engine, head_slot: int):
        """A full byzantine fork off the shared genesis: every block
        carries fork graffiti, so roots diverge from slot 1 while every
        block remains structurally valid."""
        from ..beacon.chain import BeaconChain
        from ..consensus.testing import interop_state
        from ..utils import ManualSlotClock

        spec = engine.sim.spec
        genesis, keypairs = interop_state(
            engine.spec.n_validators, spec, fork=engine.spec.fork,
            registry_padding=engine.spec.registry_padding,
        )
        clock = ManualSlotClock(
            genesis_time=float(genesis.genesis_time),
            seconds_per_slot=spec.seconds_per_slot,
        )
        chain = BeaconChain(spec, genesis, store=None, slot_clock=clock,
                            fork=engine.spec.fork)
        for slot in range(1, head_slot + 1):
            clock.set_slot(slot)
            signed = chain.produce_block(slot, keypairs,
                                         graffiti=b"byzantine-fork")
            chain.process_block(signed, verify_signatures=False)
        return chain

    def finalize(self, engine) -> None:
        if self._anchor is None:
            return  # run shorter than `at`: nothing to sync
        from ..beacon.checkpoint_sync import chain_from_anchor
        from ..beacon.sync import (
            SyncManager,
            SyncPeer,
            SyncState,
            serve_blocks_by_range,
        )
        from ..network import rpc
        from ..network.peer_manager import PeerManager

        best, anchor_state, anchor_block = self._anchor
        best.chain.recompute_head()
        head_slot = int(best.chain.head_state().slot)
        fork_chain = self._build_fork(engine, head_slot)
        chain, _backfill = chain_from_anchor(
            engine.sim.spec, anchor_state, anchor_block,
            fork=engine.spec.fork,
        )
        honest_serve = serve_blocks_by_range(best.chain, engine.spec.fork)
        byz_serve = serve_blocks_by_range(fork_chain, engine.spec.fork)

        def honest(start_slot, count):
            return [rpc.decode_response_chunk(c)
                    for c in honest_serve(start_slot, count)]

        def hostile(start_slot, count):
            return [rpc.decode_response_chunk(c)
                    for c in byz_serve(start_slot, count)]

        pm = PeerManager()
        mgr = SyncManager(chain, fork=engine.spec.fork, peer_manager=pm,
                          batch_slots=engine.slots_per_epoch,
                          request_timeout=0.5)
        hostile_ids = [f"byz-fork-{i}" for i in range(self.hostile)]
        for pid in hostile_ids:
            mgr.add_peer(SyncPeer(peer_id=pid, head_slot=head_slot,
                                  request_blocks=hostile))

        def ticks(bound: int) -> None:
            for _ in range(bound):
                try:
                    state = mgr.tick()
                except Exception as exc:  # noqa: BLE001 — promises not to
                    engine.run_facts["never_raise_violations"] += 1
                    engine.note("never-raise-violation",
                                where="hostile-checkpoint.tick",
                                error=f"{type(exc).__name__}: {exc}")
                    return
                if state in (SyncState.SYNCED, SyncState.STALLED,
                             SyncState.IDLE):
                    return

        # phase 1: only liars to sync from — scoring must stall this out
        ticks(16)
        # phase 2: discovery finds one honest peer; sync re-arms off it
        mgr.add_peer(SyncPeer(peer_id="honest", head_slot=head_slot,
                              request_blocks=honest))
        ticks(16)
        chain.recompute_head()
        converged = chain.head_root == best.chain.head_root
        banned = sum(1 for pid in hostile_ids if pm.is_banned(pid))
        engine.run_facts["checkpoint_converged"] = converged
        engine.run_facts["hostile_peers_banned"] = banned
        engine.note("hostile-checkpoint-result", converged=converged,
                    banned=banned, head_slot=head_slot)


class TenantOverloadTrack(Track):
    """Multi-tenant front-door overload: a standalone
    :class:`~...serve.service.VerifyService` (a stub device rung under a
    real ``ResilientVerifier``, sharing the engine's injector) serves two
    tenants over the slot window — a greedy tenant submitting at
    ``greedy_mult`` times its admitted rate and a deadline-sensitive
    honest tenant inside its own — while a ``slow_p`` fraction of honest
    submissions arrive from slow clients (the ``serve.submit``
    slow-client arm fires for the fault fingerprint; the burned deadline
    headroom is modeled by halving those submissions' budgets, since
    scenario time is virtual).  Each slot is split into ``steps``
    sub-slot micro-steps on a fractional-offset clock over the engine's
    virtual clock, so the batcher's fill-or-flush policy runs at its
    natural sub-second scale.  The isolation SLOs judge the finalize
    facts: the honest tenant's deadline-miss rate stays bounded and none
    of its ingress is shed while the greedy tenant's overage is."""

    name = "tenant-overload"

    def __init__(self, greedy_rate="64", greedy_mult="10",
                 honest_rate="16", deadline="0.5", slow_p="0.2",
                 steps="10", start="1", end="999"):
        self.greedy_rate = float(greedy_rate)
        self.greedy_mult = float(greedy_mult)
        self.honest_rate = float(honest_rate)
        self.deadline = float(deadline)
        self.slow_p = float(slow_p)
        self.steps = max(1, int(steps))
        self.start = int(start)
        self.end = int(end)
        self.service = None
        self.slow_submissions = 0
        self._frac = 0.0

    def _now_factory(self, engine):
        def now() -> float:
            return engine.clock.now() + self._frac
        return now

    def install(self, engine) -> None:
        from ..beacon.processor import CircuitBreaker, ResilientVerifier
        from ..serve.admission import TenantPolicy
        from ..serve.service import VerifyService

        now = self._now_factory(engine)
        # A stub device rung: verdicts are not under test here (the serve
        # tests pin those); admission/batching under overload is.  The
        # real ladder would repay its crypto cost with nothing.
        resilient = ResilientVerifier(
            device_verify=lambda sets: True,
            cpu_verify=lambda sets: True,
            breaker=CircuitBreaker(now=now),
            now=now,
            injector=engine.injector,
        )
        self.service = VerifyService(
            resilient,
            policies={
                "greedy": TenantPolicy(
                    rate=self.greedy_rate, burst=self.greedy_rate,
                    max_queue=4096, priority="p1",
                ),
                "honest": TenantPolicy(
                    rate=self.honest_rate * 4.0,
                    burst=self.honest_rate * 4.0, priority="p0",
                ),
            },
            compiled_sizes=(8, 32),
            # the flush margin must cover the pump period or deadline
            # flushes land one tick late — here the pump is the sub-slot
            # micro-step, so the margin is one step plus headroom
            flush_margin=1.0 / self.steps + 0.02,
            default_deadline_s=self.deadline,
            injector=engine.injector,
            now=now,
        )
        if self.slow_p > 0.0:
            engine.injector.arm("serve.submit", "slow-client",
                                probability=self.slow_p, delay=0.0)

    def on_slot(self, engine, slot: int) -> None:
        if self.service is None or not (self.start <= slot <= self.end):
            return
        svc = self.service
        greedy_per = int(round(
            self.greedy_rate * self.greedy_mult / self.steps
        ))
        honest_per = max(1, int(round(self.honest_rate / self.steps)))
        for i in range(self.steps):
            # never rewound: the engine advances its clock a full virtual
            # second per slot, strictly more than the largest fraction
            self._frac = i / self.steps
            for j in range(greedy_per):
                svc.submit("greedy", [("greedy", slot, i, j)],
                           deadline_s=self.deadline)
            for j in range(honest_per):
                dl = self.deadline
                if engine.rng.random() < self.slow_p:
                    # the slow client burned half its deadline budget
                    # dribbling the request in
                    self.slow_submissions += 1
                    dl *= 0.5
                svc.submit("honest", [("honest", slot, i, j)],
                           deadline_s=dl)
            svc.tick()

    def finalize(self, engine) -> None:
        engine.injector.disarm("serve.submit")
        if self.service is None:
            return
        svc = self.service
        svc.flush()
        adm = svc.admission
        completed = svc.completed.get("honest", 0)
        misses = svc.deadline_misses.get("honest", 0)
        honest_shed = sum(adm.shed.get("honest", {}).values())
        greedy_shed = sum(adm.shed.get("greedy", {}).values())
        greedy_total = adm.accepted.get("greedy", 0) + greedy_shed
        miss_rate = (misses / completed) if completed else 0.0
        shed_rate = (greedy_shed / greedy_total) if greedy_total else 0.0
        engine.run_facts["serve_honest_completed"] = completed
        engine.run_facts["serve_honest_deadline_miss_rate"] = round(
            miss_rate, 6
        )
        engine.run_facts["serve_honest_shed"] = honest_shed
        engine.run_facts["serve_greedy_shed_rate"] = round(shed_rate, 6)
        engine.run_facts["serve_slow_submissions"] = self.slow_submissions
        engine.note("tenant-overload-result", honest_completed=completed,
                    honest_miss_rate=round(miss_rate, 6),
                    honest_shed=honest_shed, greedy_shed=greedy_shed,
                    slow=self.slow_submissions)


class AggregationStormTrack(Track):
    """Committee-overlap aggregation storm through the serve front door.

    Each slot in the window the storm tenant submits ``payloads``
    near-duplicate aggregation payloads: every payload is ``dup``
    signature sets sharing ONE message (bit-twiddled participation sets
    over the same attestation data), the shape that defeats both dedup
    and batch amortization — set-count admission prices it at ``dup``
    while its true marginal verify cost is superlinear (1+2+...+dup).
    With ``cost=1`` the service's admission charges the token bucket
    via :func:`~...serve.admission.estimated_verify_cost`; with
    ``cost=0`` it charges raw set counts (the degraded twin).

    Only ADMITTED storm payloads reach the node naive pools: each one
    becomes ``dup`` disjoint-bit attestation variants over a crafted
    far-future data root (real signature bytes cloned from the honest
    template), so every insert appends a fresh resident signature —
    the pool's estimated-verify-cost gauge — while staying packing-
    ineligible (produced blocks stay valid).  A deadline-sensitive
    honest tenant runs alongside; the SLOs judge whether cost-based
    admission keeps the pools and the honest tenant inside budget.
    """

    name = "aggregation-storm"

    def __init__(self, payloads="12", dup="6", cost="1", rate="96",
                 honest_rate="16", deadline="0.5", unit="0",
                 steps="4", start="2", end="999"):
        self.payloads = int(payloads)
        self.dup = max(1, int(dup))
        self.cost = cost not in ("0", "false", "off")
        self.rate = float(rate)
        self.honest_rate = float(honest_rate)
        self.deadline = float(deadline)
        self.unit = float(unit)
        self.steps = max(1, int(steps))
        self.start = int(start)
        self.end = int(end)
        self.service = None
        self.template = None
        self.admitted = 0
        self.submitted = 0
        self._frac = 0.0
        self._virt = 0.0

    def _now_factory(self, engine):
        def now() -> float:
            return engine.clock.now() + self._frac + self._virt
        return now

    def install(self, engine) -> None:
        from ..beacon.processor import CircuitBreaker, ResilientVerifier
        from ..serve.admission import (
            TenantPolicy,
            estimated_verify_cost,
        )
        from ..serve.service import VerifyService

        now = self._now_factory(engine)
        track = self

        def device_verify(sets) -> bool:
            # verdicts are not under test (stub rung, tenant-overload
            # posture).  With a non-zero ``unit`` knob the rung burns
            # virtual time proportional to the batch's estimated
            # marginal cost so the latency histogram sees the
            # superlinear price of admitted near-duplicates — but the
            # burned time also ages deadlines and refills buckets, so
            # the default keeps it off and the pool gauges carry the
            # cost story.
            if track.unit > 0.0:
                track._virt += track.unit * estimated_verify_cost(sets)
            return True

        resilient = ResilientVerifier(
            device_verify=device_verify,
            cpu_verify=lambda sets: True,
            breaker=CircuitBreaker(now=now),
            now=now,
            injector=engine.injector,
        )
        self.service = VerifyService(
            resilient,
            policies={
                "storm": TenantPolicy(
                    rate=self.rate, burst=self.rate,
                    max_queue=4096, priority="p1",
                ),
                "honest": TenantPolicy(
                    rate=self.honest_rate * 4.0,
                    burst=self.honest_rate * 4.0, priority="p0",
                ),
            },
            compiled_sizes=(8, 32),
            flush_margin=1.0 / self.steps + 0.02,
            default_deadline_s=self.deadline,
            injector=engine.injector,
            now=now,
            cost_model=estimated_verify_cost if self.cost else None,
        )

    def _storm_data(self, slot: int, p: int):
        """One crafted AttestationData per (slot, payload): a unique
        far-future slot + fake root, so pool groups are distinct, the
        packing window never selects them (blocks stay valid), and the
        one-epoch prune retention never fires."""
        from ..consensus.containers import AttestationData

        t = self.template.data
        return AttestationData(
            slot=100_000 + slot,
            index=int(t.index),
            beacon_block_root=(
                b"\xab" + slot.to_bytes(8, "little")
                + p.to_bytes(8, "little") + bytes(15)
            ),
            source=t.source,
            target=t.target,
        )

    def on_attestations(self, engine, slot: int, atts: list) -> None:
        if self.template is None and atts:
            self.template = atts[0]
        if (self.service is None or self.template is None
                or not (self.start <= slot <= self.end)):
            return
        from ..consensus.containers import Attestation

        svc = self.service
        sig = bytes(self.template.signature)
        per_step = max(1, self.payloads // self.steps)
        honest_per = max(1, int(round(self.honest_rate / self.steps)))
        p = 0
        for i in range(self.steps):
            self._frac = i / self.steps
            for _ in range(per_step):
                if p >= self.payloads:
                    break
                data = self._storm_data(slot, p)
                msg = bytes(data.beacon_block_root)
                sets = [(msg, k) for k in range(self.dup)]
                self.submitted += 1
                res = svc.submit("storm", sets,
                                 deadline_s=self.deadline)
                if res.accepted:
                    self.admitted += 1
                    for k in range(self.dup):
                        bits = [j == k for j in range(self.dup)]
                        att = Attestation(
                            aggregation_bits=bits, data=data,
                            signature=sig,
                        )
                        for node in engine.sim.nodes:
                            node.chain.naive_pool.insert(att)
                p += 1
            for j in range(honest_per):
                svc.submit("honest", [((b"honest", slot, i, j),)],
                           deadline_s=self.deadline)
            svc.tick()

    def on_epoch(self, engine, epoch: int, facts: dict) -> None:
        facts["storm_admitted"] = self.admitted
        facts["storm_submitted"] = self.submitted

    def finalize(self, engine) -> None:
        if self.service is None:
            return
        svc = self.service
        svc.flush()
        adm = svc.admission
        storm_shed = sum(adm.shed.get("storm", {}).values())
        storm_total = self.submitted
        shed_rate = (storm_shed / storm_total) if storm_total else 0.0
        completed = svc.completed.get("honest", 0)
        misses = svc.deadline_misses.get("honest", 0)
        miss_rate = (misses / completed) if completed else 0.0
        engine.run_facts["storm_submitted"] = storm_total
        engine.run_facts["storm_admitted"] = self.admitted
        engine.run_facts["storm_shed_rate"] = round(shed_rate, 6)
        engine.run_facts["serve_honest_completed"] = completed
        engine.run_facts["serve_honest_deadline_miss_rate"] = round(
            miss_rate, 6
        )
        engine.note("aggregation-storm-result",
                    submitted=storm_total, admitted=self.admitted,
                    shed_rate=round(shed_rate, 4),
                    honest_completed=completed,
                    honest_miss_rate=round(miss_rate, 6),
                    cost_model=self.cost)


class WarmStandbyHandoffTrack(Track):
    """Zero-downtime upgrade drill over the REAL AOT machinery: an "old
    node" :class:`~...serve.service.VerifyService` (stub verdict rung,
    TenantOverloadTrack posture) serves a steady tenant while it stages
    ``programs`` synthetic jitted programs through ``traced_jit``'s
    capture hook into a shared :class:`~...crypto.bls.jax_backend.aot.
    AotStore`; at ``prewarm_at`` a standby backend prewarms from that
    store (real ``prewarm()``, ``prewarm.load`` spans, zero
    tracing-compiles expected) and its installed executables are
    checked byte-for-byte against the old node's outputs; at
    ``cutover`` the service's device rung atomically flips to the
    standby — the front door never closes, so the SLO contract is zero
    shed requests across the whole window, an actually-completed
    cutover, and a standby that compiled nothing."""

    name = "warm-standby-handoff"

    def __init__(self, programs="4", rate="16", deadline="0.5",
                 prewarm_at="4", cutover="6", steps="4", start="1",
                 end="999"):
        self.programs = max(1, int(programs))
        self.rate = float(rate)
        self.deadline = float(deadline)
        self.prewarm_at = int(prewarm_at)
        self.cutover = int(cutover)
        self.steps = max(1, int(steps))
        self.start = int(start)
        self.end = int(end)
        self.service = None
        self.store = None
        self.store_dir = None
        self.standby = None
        self.prewarm_report = None
        self.serving = "old"
        self.served = {"old": 0, "standby": 0}
        self.expected = {}   # program index -> old node's output
        self.standby_ok = False
        self._frac = 0.0

    def _now_factory(self, engine):
        def now() -> float:
            return engine.clock.now() + self._frac
        return now

    @staticmethod
    def _program(i: int):
        """One synthetic staged program per index — cheap to compile,
        distinct fingerprint, deterministic output."""
        import jax.numpy as jnp

        def handoff_prog(x):
            return ((x + jnp.float32(i)) * 2.0).sum()

        return handoff_prog

    def install(self, engine) -> None:
        import tempfile

        import jax.numpy as jnp

        from ..beacon.processor import CircuitBreaker, ResilientVerifier
        from ..crypto.bls.jax_backend import aot
        from ..crypto.bls.jax_backend.backend import (
            program_fingerprint, traced_jit,
        )
        from ..serve.admission import TenantPolicy
        from ..serve.service import VerifyService

        self.store_dir = tempfile.mkdtemp(prefix="aot-handoff-")
        self.store = aot.AotStore(self.store_dir)
        # The old node's organic working set: compile each program
        # through the instrumented path; the capture hook populates the
        # shared store exactly as a serving node would.
        x = jnp.arange(8, dtype=jnp.float32)
        for i in range(self.programs):
            key = ("handoff", i)
            st = self.store

            def hook(call, args, _key=key):
                st.capture(call, _key, args, kernel="handoff_prog")

            call = traced_jit(
                self._program(i),
                program_fingerprint("handoff_prog", i=i),
                capture=hook,
            )
            self.expected[i] = float(call(x))
        now = self._now_factory(engine)
        # Stub verdict rung (TenantOverloadTrack posture): continuity of
        # service across the cutover is under test, not crypto verdicts
        # — but WHICH node served each batch is recorded, so the
        # cutover fact is observed, not assumed.
        track = self

        def device_verify(sets) -> bool:
            track.served[track.serving] += 1
            return True

        resilient = ResilientVerifier(
            device_verify=device_verify,
            cpu_verify=lambda sets: True,
            breaker=CircuitBreaker(now=now),
            now=now,
            injector=engine.injector,
        )
        self.service = VerifyService(
            resilient,
            policies={
                "client": TenantPolicy(
                    rate=self.rate * 4.0, burst=self.rate * 4.0,
                    priority="p0",
                ),
            },
            compiled_sizes=(8, 32),
            flush_margin=1.0 / self.steps + 0.02,
            default_deadline_s=self.deadline,
            injector=engine.injector,
            now=now,
        )

    def _prewarm_standby(self) -> None:
        """The new process boots: a fresh backend prewarms from the
        shared store.  Its installed executables must reproduce the old
        node's outputs before it is eligible to take over."""
        import jax.numpy as jnp

        from ..crypto.bls.jax_backend import aot
        from ..crypto.bls.jax_backend.backend import JaxBackend

        self.standby = JaxBackend(min_batch=8, device_h2c=False)
        self.prewarm_report = aot.prewarm(self.standby, self.store)
        x = jnp.arange(8, dtype=jnp.float32)
        ok = len(self.prewarm_report.loaded) == self.programs
        for i in range(self.programs):
            call = self.standby._kernels.get(("handoff", i))
            if call is None or float(call(x)) != self.expected[i]:
                ok = False
                break
        self.standby_ok = ok

    def on_slot(self, engine, slot: int) -> None:
        if self.service is None or not (self.start <= slot <= self.end):
            return
        if slot == self.prewarm_at and self.standby is None:
            self._prewarm_standby()
        if slot == self.cutover and self.standby_ok:
            self.serving = "standby"
        svc = self.service
        per_step = max(1, int(round(self.rate / self.steps)))
        for i in range(self.steps):
            self._frac = i / self.steps
            for j in range(per_step):
                svc.submit("client", [("client", slot, i, j)],
                           deadline_s=self.deadline)
            svc.tick()

    def finalize(self, engine) -> None:
        import shutil

        if self.service is None:
            return
        svc = self.service
        svc.flush()
        shed = sum(svc.admission.shed.get("client", {}).values())
        rep = self.prewarm_report
        compiled = len(rep.compiled) if rep else 0
        loaded = len(rep.loaded) if rep else 0
        cutover_done = (
            self.serving == "standby" and self.served["standby"] > 0
        )
        engine.run_facts["handoff_shed"] = shed
        engine.run_facts["handoff_cutover_done"] = cutover_done
        engine.run_facts["handoff_standby_compiles"] = compiled
        engine.run_facts["handoff_prewarm_loaded"] = loaded
        engine.run_facts["handoff_completed"] = svc.completed.get(
            "client", 0
        )
        engine.note("warm-standby-handoff-result", shed=shed,
                    cutover=cutover_done, loaded=loaded,
                    compiled=compiled,
                    served_old=self.served["old"],
                    served_standby=self.served["standby"])
        if self.store_dir:
            shutil.rmtree(self.store_dir, ignore_errors=True)


class _TruthCheckedVerifier:
    """Scenario-only measurement shim OUTSIDE the integrity guard: while
    the silent-fault window is active it byte-compares every released
    verdict against the scalar-oracle truth and counts wrong-accepts
    (verdict True, truth False) — the ground truth behind the
    ``max_sdc_wrong_accepts`` gate.  Wrong-rejects are fail-closed by
    design and not counted.  Not a defense: it exists so the scenario
    can *prove* what escaped, defended or not."""

    def __init__(self, inner, track):
        self.inner = inner
        self.track = track

    def verify_batch(self, sets):
        sets = list(sets)
        out = self.inner.verify_batch(sets)
        if self.track.truth_active:
            for v, s in zip(out.verdicts, sets):
                if v and not bool(s.verify()):
                    self.track.wrong_accepts += 1
                    self.track.wrong_accepts_epoch += 1
        return out

    def __getattr__(self, name):
        return getattr(self.inner, name)


class SdcStormTrack(Track):
    """Silent-data-corruption storm over a pod mesh.

    At install the engine's verify path is lifted onto a list-mode
    ``PodVerifier`` (``shards`` fault domains over the ladder's own
    ``device_verify``) wrapped by an :class:`~...integrity.IntegrityGuard`
    with ``k`` canary batches per dispatch, and finally by a
    truth-checking shim that counts wrong-accepts against the scalar
    oracle.  Over the slot window the ``pod.gather`` site is armed with
    ``kind`` (default ``silent-stuck-true``): every shard verdict lies
    True with probability ``p`` and *nothing raises* — the regime where
    only the canary layer stands between a flipped conjunction and block
    import.  ``canaries=0`` is the undefended twin: the guard passes the
    pod's verdicts straight through and the truth check records what
    escapes.

    Every windowed slot also dispatches one *hostile traffic* batch — a
    known valid/invalid mix drawn from a second canary corpus (distinct
    seed, so the guard's own canaries share no bytes with it) — through
    the full verify path.  That is what makes the twin falsifiable: the
    engine's organic traffic is honest, so a stuck-True gather merely
    re-confirms verdicts that were already True; the hostile batch is
    the invalid signature a lying device would wave through."""

    name = "sdc-storm"

    def __init__(self, canaries="1", shards="4", k="2",
                 kind="silent-stuck-true", p="1.0", start="9", end="17",
                 threshold="2", audit="0.0", timeout="30.0"):
        self.canaries = bool(int(canaries))
        self.shards = int(shards)
        self.k = int(k)
        self.kind = kind
        self.p = float(p)
        self.start = int(start)
        self.end = int(end)
        self.threshold = int(threshold)
        self.audit = float(audit)
        self.timeout = float(timeout)
        self.pod = None
        self.guard = None
        self.truth_active = False
        self.wrong_accepts = 0
        self.wrong_accepts_epoch = 0
        self._traffic = ()

    def install(self, engine) -> None:
        import random as _random

        from ..integrity.corpus import CanaryCorpus
        from ..integrity.guard import IntegrityGuard
        from ..parallel.pod import PodVerifier

        inner = engine.verifier
        self.pod = PodVerifier(
            inner,
            shard_verify=lambda sub: bool(inner.device_verify(sub)),
            devices=list(range(self.shards)),
            injector=engine.injector,
            shard_timeout=self.timeout,
            max_shard_retries=1,
            backoff_base=0.0,
            exclusion_threshold=2,
            probe_after=1,
        )
        self.guard = IntegrityGuard(
            self.pod, inner,
            corpus=CanaryCorpus(seed=engine.spec.seed),
            k=self.k,
            enabled=self.canaries,
            audit_fraction=self.audit,
            rng=_random.Random(engine.spec.seed ^ 0x5DC),
            strike_threshold=self.threshold,
        )
        self.guard.attach_pod(self.pod)
        # hostile traffic: a known valid/invalid mix from a second corpus
        # seed, dispatched each windowed slot (see class docstring)
        self._traffic = tuple(
            s for e in CanaryCorpus(seed=engine.spec.seed ^ 0x7AFF1C)
            .entries(0) for s in e.sets
        )
        engine.verifier = _TruthCheckedVerifier(self.guard, self)

    def on_slot(self, engine, slot: int) -> None:
        if slot == self.start:
            self.truth_active = True
            engine.injector.arm("pod.gather", self.kind,
                                probability=self.p)
            engine.note("sdc-storm", slot=slot, armed=self.kind,
                        p=self.p, shards=self.shards,
                        canaries=self.canaries, k=self.k)
        elif slot == self.end + 1:
            engine.injector.disarm("pod.gather")
            engine.note("sdc-storm", slot=slot, disarmed=self.kind)
        if self.start <= slot <= self.end:
            engine.verifier.verify_batch(list(self._traffic))

    def on_epoch(self, engine, epoch: int, facts: dict) -> None:
        facts["sdc_wrong_accepts"] = self.wrong_accepts_epoch
        self.wrong_accepts_epoch = 0
        # rotate the canary corpus at every epoch boundary, the same
        # cadence the serve front end's rotate_epoch hook uses
        self.guard.rotate(epoch + 1)

    def finalize(self, engine) -> None:
        engine.injector.disarm("pod.gather")
        # the truth window stays open from the first armed slot to run
        # end: a flipped verdict released after the disarm point still
        # counts as an escape
        self.truth_active = False
        g = self.guard
        injected = sum(
            1 for _site, kind in engine.injector.fired_sequence()
            if kind.startswith("silent") or kind == "corrupt-shard-result"
        )
        engine.run_facts["sdc_wrong_accepts"] = self.wrong_accepts
        engine.run_facts["sdc_detected"] = g.sdc_events
        engine.run_facts["sdc_quarantined"] = len(g.quarantined)
        engine.run_facts["sdc_injected"] = injected
        engine.run_facts["sdc_canary_checks"] = g.canary_checks
        engine.run_facts["sdc_reladdered_sets"] = g.reladdered_sets
        engine.note("sdc-storm-result", wrong_accepts=self.wrong_accepts,
                    detected=g.sdc_events, quarantined=len(g.quarantined),
                    injected=injected, reladdered=g.reladdered_sets)


TRACKS = {
    cls.name: cls
    for cls in (GossipFaultTrack, DeviceFaultTrack, ByzantineSyncTrack,
                KillRecoveryTrack, PodDeviceDropTrack, FinalityStallTrack,
                HostileCheckpointTrack, TenantOverloadTrack,
                AggregationStormTrack, WarmStandbyHandoffTrack,
                SdcStormTrack)
}


def build_tracks(specs) -> list[Track]:
    out = []
    for spec_str in specs:
        name, _, rest = spec_str.partition(":")
        name = name.strip()
        cls = TRACKS.get(name)
        if cls is None:
            raise ValueError(
                f"unknown adversity track {name!r}; have {sorted(TRACKS)}"
            )
        kwargs = {}
        if rest:
            for kv in rest.split(","):
                k, _, v = kv.partition("=")
                kwargs[k.strip()] = v.strip()
        out.append(cls(**kwargs))
    return out
