"""Declarative scenario specs + the named registry.

A :class:`ScenarioSpec` is pure data: how many nodes/validators/epochs,
which traffic shapes run (by name, see :mod:`traffic`), which adversity
tracks fire (``"name:key=val,..."`` specs, see :mod:`adversity`), and the
SLO thresholds the run is gated on (see :mod:`slo`).  The ``SCENARIOS``
dict below is the canonical registry — the static audit cross-checks
every ``--scenario`` example in the docs against its keys, exactly the
way ``--chaos`` specs are validated against the fault-site registry, so
keep the keys literal (AST-parsed, never imported, by
``analysis/registry_lint.py``).

Reproduction workflow: every run's JSON report records ``spec.seed`` and
the injector's fired-fault sequence; re-running the same scenario name
with the same seed replays the identical run (virtual breaker clock, one
shared ``random.Random(seed)``, probability gates drawn from a private
seeded stream).
"""

from __future__ import annotations

from dataclasses import dataclass, field


# Default SLO thresholds; per-scenario overrides merge over these.  A
# ``None`` threshold disables that gate.  See slo.evaluate for semantics.
DEFAULT_SLO: dict = {
    # shed / stall / breaker budgets (counter deltas over the run)
    "max_shed_rate": 0.5,          # shed events / processor enqueues
    "max_sync_stalls": 0,          # sync_stalls_total delta
    "max_breaker_transitions": 12,  # breaker_transitions_total delta
    "max_device_retries": 16,      # verify_device_retries_total delta
    # latency tails (histogram quantiles over the run's delta counts).
    # Gross-regression tripwires, not tight latency targets: the pure-
    # Python pairing fallback costs ~0.5 s/set and CI hosts run loaded,
    # so the budget carries headroom over the ~1 s quiet-host p99.
    "max_import_p99_s": 6.0,       # block_import_latency_seconds
    "max_verify_p99_s": 6.0,       # verify_batch_latency_seconds
    # liveness
    "require_head_convergence": True,
    "min_finalized_advance": 0,    # epochs every node must finalize
    # harness invariants
    "max_never_raise_violations": 0,
    "require_breaker_recovered": True,   # breaker CLOSED at run end
    "require_crash_recovery": True,      # kill -9 iterations all verified
    # "did the adversity actually bite" gates (None = not asserted)
    "min_breaker_transitions": None,     # breaker must have engaged
    "min_slashings_detected": None,      # slashers must have caught it
    # trace-derived overlap efficiency (warn-level; see slo.evaluate and
    # obs/report.py — wall / max(stage busy), 1.0 = perfect overlap)
    "max_overlap_wall_ratio": None,
    # hostile-regime gates (None = not asserted) — pool growth and
    # shuffling-cache pressure under non-finality, exit-flood drainage,
    # and checkpoint-sync convergence through byzantine serving peers
    "max_op_pool_attestations": None,   # largest per-node op-pool att count
    "max_naive_pool_groups": None,      # largest per-node naive-pool groups
    "max_committee_caches": None,       # shared shuffling-cache entries
    "max_finalized_advance": None,      # finality must NOT advance past this
    "min_exits_processed": None,        # exit-flood must drain on-chain
    "require_checkpoint_convergence": False,  # ckpt-synced node reaches head
    "min_hostile_peers_banned": None,   # scoring must ban byzantine servers
    # verification-front-door tenancy gates (None = not asserted): honest
    # tenants keep their deadlines and none of their ingress is shed while
    # admission sheds the greedy tenant's overage (tenant-overload track)
    "max_honest_deadline_miss_rate": None,  # honest deadline misses / done
    "max_honest_shed": None,            # honest submissions shed (any reason)
    "min_greedy_shed_rate": None,       # greedy submissions shed / submitted
    # warm-standby handoff gates (None = not asserted): the upgrade
    # contract — no request shed across the cutover window, the standby
    # actually takes over, and it boots from the AOT store (zero
    # tracing-compiles) with every captured program installed
    "max_handoff_shed": None,           # requests shed over the whole run
    "require_handoff_cutover": False,   # standby must end up serving
    "max_standby_compiles": None,       # standby tracing-compiles
    "min_prewarm_loaded": None,         # store entries installed on standby
    # saturation-soak gates (None = not asserted): deposit backlog under
    # over-rate inflow, the drain staying live, byte-bounded SSZ/state
    # caches across epochs, and the naive pool's estimated marginal
    # verify cost under committee-overlap aggregation storms.  The max_*
    # keys here are also gated PER EPOCH (slo.EPOCH_GATED_KEYS) so the
    # report names the first violating epoch.
    "max_deposit_queue_depth": None,    # worst per-epoch deposit backlog
    "min_deposits_applied": None,       # deposits drained on-chain
    "max_ssz_cache_bytes": None,        # worst per-epoch cache growth
    "max_pool_estimated_verify_cost": None,  # worst per-epoch pool cost
    "min_storm_shed_rate": None,        # storm submissions shed / submitted
    # verdict-integrity gates (None = not asserted): silent-data-
    # corruption detection under the sdc-storm regime.  Wrong-accepts
    # (a flipped verdict released to a consumer) are also gated PER
    # EPOCH (slo.EPOCH_GATED_KEYS) so the undefended twin's report
    # names the first epoch a silent flip escaped.
    "max_sdc_wrong_accepts": None,      # flipped verdicts released (truth)
    "min_sdc_detected": None,           # canary/audit SDC detections
    "min_sdc_quarantined": None,        # devices quarantined by trust strikes
}


@dataclass(frozen=True)
class ScenarioSpec:
    name: str
    seed: int
    n_nodes: int = 3
    n_validators: int = 32
    epochs: int = 2
    fork: str = "altair"
    breaker_enabled: bool = True
    slasher: bool = True
    traffic: tuple = ()    # shape names from traffic.SHAPES
    adversity: tuple = ()  # track specs "name[:k=v,...]" (adversity.TRACKS)
    slo: dict = field(default_factory=dict)  # overrides over DEFAULT_SLO
    # cheap-node knobs: pad the registry with inactive synthetic validators
    # (copy-on-write shared across nodes) and override ChainSpec/Preset
    # fields (dataclasses.replace pairs, e.g. (("max_deposits", 4),) —
    # Preset-level keys are routed into the nested preset)
    registry_padding: int = 0
    spec_overrides: tuple = ()
    # soak mode: per-epoch SLO snapshots become the primary artifact and
    # the history row is kind="soak" (epochs survived, peak RSS, worst
    # per-epoch verify p99) instead of kind="scenario"
    soak: bool = False

    def slo_thresholds(self) -> dict:
        merged = dict(DEFAULT_SLO)
        merged.update(self.slo)
        return merged

    def with_seed(self, seed: int) -> "ScenarioSpec":
        from dataclasses import replace

        return replace(self, seed=seed)


# ---------------------------------------------------------------------------
# The registry.  Keys are the names `--scenario` accepts; keep them
# literal string constants (the registry lint AST-parses this dict).
# ---------------------------------------------------------------------------

SCENARIOS: dict[str, ScenarioSpec] = {
    # Fast tier-1 smoke: 3 nodes, 2 epochs, one fault track.  Gossip
    # drops force the epoch-boundary heal path; no finalization is
    # expected inside 2 minimal-preset epochs.
    "smoke": ScenarioSpec(
        name="smoke",
        seed=1234,
        n_nodes=3,
        n_validators=16,
        epochs=2,
        traffic=("attestation-flood",),
        adversity=("gossip-faults:kind=drop,p=0.15,start=4,end=10",),
        slo={
            "min_finalized_advance": 0,
            "require_crash_recovery": False,
        },
    ),
    # The flagship mainnet-shape run: every traffic shape and every
    # adversity track at once — epoch-boundary attestation floods at
    # committee fan-out, a deposit queue draining through eth1 voting, a
    # proposer reorg, a slashable equivocation, lossy gossip, a
    # breaker-tripping device-fault window, byzantine sync peers on the
    # heal path, and a mid-run kill -9 + recovery — with a fault
    # cool-down tail so convergence + finalization SLOs are honest.
    "mainnet-shape": ScenarioSpec(
        name="mainnet-shape",
        seed=7,
        n_nodes=4,
        n_validators=32,
        epochs=6,
        traffic=(
            "attestation-flood",
            "deposit-queue",
            "proposer-reorg",
            "equivocation",
        ),
        adversity=(
            "gossip-faults:kind=drop,p=0.12,start=6,end=28",
            "device-faults:delay=0.02,start=10,end=14",
            "byzantine-sync",
            "kill-recovery:at=24",
        ),
        slo={
            "min_finalized_advance": 1,
            "min_breaker_transitions": 1,
            "min_slashings_detected": 1,
            # warn-level pipeline-health gate: generous so a loaded CI
            # host never flips it, loud when overlap truly collapses
            "max_overlap_wall_ratio": 8.0,
        },
    ),
    # Pod-serving device loss under mainnet-shape SLOs: the verify path
    # rides a list-mode PodVerifier (4 per-shard fault domains over the
    # resilience ladder) and a mid-epoch window drops shards at the
    # pod.dispatch site until repeat offenders are excluded and batches
    # re-shard onto the surviving mesh — no batch is ever dropped, the
    # breaker must be CLOSED again by run end, and the excluded devices
    # must be probed back in after the window.
    "pod-degrade": ScenarioSpec(
        name="pod-degrade",
        seed=11,
        n_nodes=3,
        n_validators=32,
        epochs=4,
        traffic=("attestation-flood",),
        adversity=(
            "gossip-faults:kind=drop,p=0.10,start=6,end=18",
            "pod-device-drop:shards=4,p=0.7,start=8,end=14",
        ),
        slo={
            "min_finalized_advance": 0,
            "require_crash_recovery": False,
        },
    ),
    # The same run with the circuit breaker disabled (failure threshold
    # parked at infinity): the device-fault window must now blow the
    # device-retry budget — proof the SLO gates catch regressions.
    "mainnet-shape-degraded": ScenarioSpec(
        name="mainnet-shape-degraded",
        seed=7,
        n_nodes=4,
        n_validators=32,
        epochs=6,
        breaker_enabled=False,
        traffic=(
            "attestation-flood",
            "deposit-queue",
            "proposer-reorg",
            "equivocation",
        ),
        adversity=(
            "gossip-faults:kind=drop,p=0.12,start=6,end=28",
            "device-faults:delay=0.02,start=10,end=14",
            "byzantine-sync",
            "kill-recovery:at=24",
        ),
        slo={
            "min_finalized_advance": 1,
            "require_breaker_recovered": False,
        },
    ),
    # Multi-epoch finality stall: the finality-stall track suppresses
    # ~60% of committee aggregates (deterministically, off the engine
    # rng) so justification never reaches 2/3, while the attestation
    # flood keeps pool pressure on.  The SLOs assert the stall is REAL
    # (finality pinned at genesis) and that pool pruning + the bounded
    # shuffling cache hold their budgets across epochs of non-finality.
    "long-non-finality": ScenarioSpec(
        name="long-non-finality",
        seed=29,
        n_nodes=3,
        n_validators=16,
        epochs=4,
        traffic=("attestation-flood",),
        adversity=("finality-stall:p=0.6,start=2,end=999",),
        slo={
            "max_finalized_advance": 0,
            "max_op_pool_attestations": 96,
            "max_naive_pool_groups": 96,
            "max_committee_caches": 16,
            "require_crash_recovery": False,
        },
    ),
    # Mass slashable misbehaviour + exit traffic through the real
    # machinery: four proposers double-propose (equivocation storm) and a
    # quarter of the registry floods voluntary exits into every op pool.
    # shard_committee_period is overridden to 0 (a spec_overrides pair)
    # so genesis-epoch validators are exit-eligible inside the run.  The
    # slashers must catch the equivocations and the exits must drain
    # through packing + the transition without stalling convergence.
    "slashing-flood": ScenarioSpec(
        name="slashing-flood",
        seed=31,
        n_nodes=3,
        n_validators=32,
        epochs=3,
        traffic=("equivocation-storm", "exit-flood"),
        spec_overrides=(("shard_committee_period", 0),),
        slo={
            "min_slashings_detected": 2,
            "min_exits_processed": 6,
            "require_crash_recovery": False,
        },
    ),
    # Checkpoint sync where a majority of the SyncManager's peers serve a
    # structurally-valid byzantine fork (same genesis, different
    # ancestry): a node anchored mid-run at the honest head must score
    # out and ban the hostile servers, forward-sync off the lone honest
    # peer, and land on the honest head.
    "hostile-checkpoint-sync": ScenarioSpec(
        name="hostile-checkpoint-sync",
        seed=37,
        n_nodes=3,
        n_validators=16,
        epochs=3,
        adversity=("hostile-checkpoint:at=12,hostile=3",),
        slo={
            "require_checkpoint_convergence": True,
            "min_hostile_peers_banned": 2,
            # the all-hostile phase MUST stall exactly once (that stall is
            # the regime); a second one means the honest re-arm failed
            "max_sync_stalls": 1,
            "require_crash_recovery": False,
        },
    ),
    # The verification front door under tenant overload: a standalone
    # VerifyService serves a greedy tenant submitting at 10x its admitted
    # rate next to a deadline-sensitive honest tenant, with a fifth of
    # honest submissions arriving through slow clients.  The isolation
    # SLOs are the point: the honest tenant misses (almost) no deadlines
    # and none of its ingress is shed, while admission sheds the bulk of
    # the greedy tenant's overage — one tenant's flood must never become
    # everyone's outage.
    "tenant-overload": ScenarioSpec(
        name="tenant-overload",
        seed=43,
        n_nodes=3,
        n_validators=16,
        epochs=2,
        adversity=(
            "tenant-overload:greedy_mult=10,slow_p=0.2,deadline=0.5",
        ),
        slo={
            "max_honest_deadline_miss_rate": 0.02,
            "max_honest_shed": 0,
            "min_greedy_shed_rate": 0.5,
            "require_crash_recovery": False,
        },
    ),
    # The zero-downtime upgrade drill: an "old node" VerifyService keeps
    # serving a steady tenant while it stages four programs through the
    # real AOT executable store; a standby backend prewarms from the
    # shared store mid-run and takes over the device rung at the cutover
    # slot.  The SLOs are the upgrade contract (ROADMAP item 4): zero
    # requests shed across the window, a completed cutover, a standby
    # that loaded everything and compiled nothing.
    "warm-handoff": ScenarioSpec(
        name="warm-handoff",
        seed=53,
        n_nodes=3,
        n_validators=16,
        epochs=2,
        adversity=(
            "warm-standby-handoff:programs=4,prewarm_at=4,cutover=6",
        ),
        slo={
            "max_handoff_shed": 0,
            "require_handoff_cutover": True,
            "max_standby_compiles": 0,
            "min_prewarm_loaded": 4,
            "require_crash_recovery": False,
        },
    ),
    # The cheap-node acceptance run: 12 in-process nodes over a 100k-entry
    # validator registry (16 interop + 99,984 inactive padding, frozen and
    # copy-on-write shared).  No adversity — this scenario exists to pin
    # that registry-scale state stays inside the fast-tier budget.
    "registry-pressure": ScenarioSpec(
        name="registry-pressure",
        seed=41,
        n_nodes=12,
        n_validators=16,
        epochs=1,
        registry_padding=99_984,
        slo={
            "require_crash_recovery": False,
        },
    ),
    # Deposit-queue saturation: eth1 inflow pinned ABOVE the per-block
    # drain rate for the whole run (6 logs/slot against max_deposits=4
    # draining only after each one-epoch voting period's majority), so
    # the backlog grows by design — the gates assert the drain stays
    # live (deposits actually land on-chain), the backlog stays inside
    # its budget, and finality survives the sustained pressure.
    # Historical DepositTree proofs (merkle.proof(index, count)) are the
    # load-bearing machinery: blocks drain against the *voted* snapshot
    # while the contract tree keeps growing past it.
    "deposit-saturation": ScenarioSpec(
        name="deposit-saturation",
        seed=61,
        n_nodes=3,
        n_validators=16,
        epochs=4,
        traffic=("deposit-saturation",),
        spec_overrides=(
            ("epochs_per_eth1_voting_period", 1),
            ("eth1_follow_distance", 2),
            ("max_deposits", 4),
        ),
        slo={
            # healthy run peaks at 44 queued / 88 drained; the lagging
            # twin crosses 64 at epoch 3 and drains only 27
            "max_deposit_queue_depth": 64,
            "min_deposits_applied": 48,
            "min_finalized_advance": 1,
            "require_crash_recovery": False,
        },
    ),
    # The weakened-drain twin: identical inflow, max_deposits=1 — the
    # drain cannot keep pace, the backlog blows the queue-depth budget
    # mid-run, and the per-epoch snapshots name the first violating
    # epoch.  This scenario is EXPECTED to fail; it proves the gate.
    "deposit-saturation-lagging": ScenarioSpec(
        name="deposit-saturation-lagging",
        seed=61,
        n_nodes=3,
        n_validators=16,
        epochs=4,
        traffic=("deposit-saturation",),
        spec_overrides=(
            ("epochs_per_eth1_voting_period", 1),
            ("eth1_follow_distance", 2),
            ("max_deposits", 1),
        ),
        slo={
            "max_deposit_queue_depth": 64,
            "min_deposits_applied": 48,
            "min_finalized_advance": 1,
            "require_crash_recovery": False,
        },
    ),
    # Committee-overlap aggregation storm through the serve front door:
    # near-duplicate aggregates (bit-twiddled participation sets over a
    # shared message) defeat dedup and price superlinearly in both pool
    # growth and batch-verify cost.  Cost-based admission (the
    # estimated_verify_cost model on the storm service's token bucket)
    # sheds the storm's overage while the honest tenant keeps its
    # deadlines and the naive pools stay inside their budgets.
    "aggregation-storm": ScenarioSpec(
        name="aggregation-storm",
        seed=67,
        n_nodes=3,
        n_validators=16,
        epochs=3,
        adversity=("aggregation-storm:cost=1",),
        slo={
            # costed run: 108 groups / 648 pool cost, 61% storm shed;
            # the uncosted twin hits 276 / 1656 (crossing 1024 at
            # epoch 2) with nothing shed
            "max_naive_pool_groups": 160,
            "max_pool_estimated_verify_cost": 1024,
            "min_storm_shed_rate": 0.5,
            "max_honest_deadline_miss_rate": 0.02,
            "require_crash_recovery": False,
        },
    ),
    # The same storm with the cost model OFF: admission prices payloads
    # by raw set count, the storm is admitted wholesale, and the pool
    # budgets blow — the degraded-twin proof that the cost knob (not
    # luck) is what holds the line.  EXPECTED to fail.
    "aggregation-storm-uncosted": ScenarioSpec(
        name="aggregation-storm-uncosted",
        seed=67,
        n_nodes=3,
        n_validators=16,
        epochs=3,
        adversity=("aggregation-storm:cost=0",),
        slo={
            "max_naive_pool_groups": 160,
            "max_pool_estimated_verify_cost": 1024,
            "max_honest_deadline_miss_rate": 0.02,
            "require_crash_recovery": False,
        },
    ),
    # Silent-data-corruption storm: mid-run, every pod-shard verdict
    # gather starts lying True (the wrong-accept direction nothing below
    # the integrity tier can see).  The canary layer must mark every
    # corrupted dispatch distrusted before a verdict is released, the
    # real sets re-ladder through the CPU-oracle rung, trust strikes
    # quarantine the lying devices, and the truth-checked wrong-accept
    # count stays zero.
    "sdc-storm": ScenarioSpec(
        name="sdc-storm",
        seed=73,
        n_nodes=3,
        n_validators=16,
        epochs=3,
        adversity=("sdc-storm:canaries=1,shards=4,start=9,end=17",),
        slo={
            "max_sdc_wrong_accepts": 0,
            "min_sdc_detected": 1,
            "min_sdc_quarantined": 1,
            "require_crash_recovery": False,
        },
    ),
    # The same storm with the canary layer OFF: the pod's all-True
    # short-circuit accepts the lying gathers wholesale and flipped
    # verdicts reach the consumer — the per-epoch wrong-accept gate
    # names the first epoch a silent flip escaped.  EXPECTED to fail;
    # it proves the canaries (not luck) are what hold the line.
    "sdc-storm-undefended": ScenarioSpec(
        name="sdc-storm-undefended",
        seed=73,
        n_nodes=3,
        n_validators=16,
        epochs=3,
        adversity=("sdc-storm:canaries=0,shards=4,start=9,end=17",),
        slo={
            "max_sdc_wrong_accepts": 0,
            "min_sdc_detected": 1,
            "min_sdc_quarantined": 1,
            "require_crash_recovery": False,
        },
    ),
    # The 1M-validator multi-epoch soak: registry-pressure's frozen
    # copy-on-write registry trick stretched 10x (16 interop + 999,984
    # inactive padding shared across 2 nodes), run for 3 epochs with
    # per-epoch SSZ-cache byte snapshots.  The eviction budget must
    # bound cache growth at every epoch — a slow leak fails at the
    # epoch it starts, not at run end.  Slow tier only (pytest -m soak).
    "soak-1m": ScenarioSpec(
        name="soak-1m",
        seed=71,
        n_nodes=2,
        n_validators=16,
        epochs=3,
        registry_padding=999_984,
        soak=True,
        slo={
            # measured ~94.4 MiB steady per epoch on this image; 256 MiB
            # budget leaves ~2.7x headroom while still catching a leak
            "max_ssz_cache_bytes": 268_435_456,
            # wall-clock latency gates track host speed, not correctness —
            # a 1M-registry import on CPU legitimately exceeds the 6s
            # default; the soak's verdict must be deterministic
            "max_import_p99_s": None,
            "max_verify_p99_s": None,
            "require_crash_recovery": False,
        },
    ),
}


# Integer spec fields a CLI arg (and the scenario-search mutator) may
# override; everything richer stays declarative in the registry.
OVERRIDABLE_INT_FIELDS = ("seed", "n_nodes", "n_validators", "epochs")


# ---------------------------------------------------------------------------
# The committed regression corpus: ddmin-minimized SLO violations the
# continuous scenario search registered as JSON fixtures.  ``--scenario``
# falls back to this directory for names not in the registry, so every
# committed finding replays standalone (and the scenario-fixture lint
# family keeps the corpus honest).
# ---------------------------------------------------------------------------

_SPEC_JSON_FIELDS = (
    "name", "seed", "n_nodes", "n_validators", "epochs", "fork",
    "breaker_enabled", "slasher", "traffic", "adversity", "slo",
    "registry_padding", "spec_overrides", "soak",
)


def fixture_scenario_dir() -> str:
    """The in-repo corpus directory (``tests/fixtures/scenarios``)."""
    import os

    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))
    return os.path.join(repo, "tests", "fixtures", "scenarios")


def spec_to_json(spec: ScenarioSpec) -> dict:
    """A JSON-shaped dict ``spec_from_json`` round-trips exactly."""
    return {
        "name": spec.name,
        "seed": spec.seed,
        "n_nodes": spec.n_nodes,
        "n_validators": spec.n_validators,
        "epochs": spec.epochs,
        "fork": spec.fork,
        "breaker_enabled": spec.breaker_enabled,
        "slasher": spec.slasher,
        "traffic": list(spec.traffic),
        "adversity": list(spec.adversity),
        "slo": dict(spec.slo),
        "registry_padding": spec.registry_padding,
        "spec_overrides": [list(p) for p in spec.spec_overrides],
        "soak": spec.soak,
    }


def spec_from_json(d: dict) -> ScenarioSpec:
    """Rebuild a :class:`ScenarioSpec` from ``spec_to_json`` output,
    validating field names and that every SLO key is registered."""
    if not isinstance(d, dict):
        raise ValueError("scenario fixture must be a JSON object")
    unknown = set(d) - set(_SPEC_JSON_FIELDS)
    if unknown:
        raise ValueError(
            f"unknown scenario fixture fields {sorted(unknown)}"
        )
    for req in ("name", "seed"):
        if req not in d:
            raise ValueError(f"scenario fixture missing {req!r}")
    slo = dict(d.get("slo", {}))
    bad = set(slo) - set(DEFAULT_SLO)
    if bad:
        raise ValueError(
            f"scenario fixture names unregistered SLO keys {sorted(bad)}"
        )
    kw = dict(d)
    kw["traffic"] = tuple(kw.get("traffic", ()))
    kw["adversity"] = tuple(kw.get("adversity", ()))
    kw["spec_overrides"] = tuple(
        tuple(p) for p in kw.get("spec_overrides", ())
    )
    kw["slo"] = slo
    return ScenarioSpec(**kw)


def load_fixture_scenario(name: str) -> ScenarioSpec | None:
    """Load one committed corpus entry by name, or None if absent."""
    import json
    import os

    path = os.path.join(fixture_scenario_dir(), f"{name}.json")
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return spec_from_json(json.load(f))


def parse_scenario_arg(arg: str) -> ScenarioSpec:
    """Resolve a CLI ``--scenario`` argument: ``name[:key=val,...]``.

    Names resolve against the :data:`SCENARIOS` registry first, then
    against the committed regression corpus
    (``tests/fixtures/scenarios/<name>.json``).  Supported overrides:
    ``seed``, ``n_nodes``, ``n_validators``, ``epochs`` (all ints).
    Examples::

        --scenario smoke
        --scenario mainnet-shape:seed=99
        --scenario long-non-finality:seed=3,epochs=6
    """
    from dataclasses import replace

    name, _, rest = arg.partition(":")
    name = name.strip()
    if name in SCENARIOS:
        spec = SCENARIOS[name]
    else:
        spec = load_fixture_scenario(name)
        if spec is None:
            raise ValueError(
                f"unknown scenario {name!r}; have {sorted(SCENARIOS)} "
                "plus the committed corpus in tests/fixtures/scenarios"
            )
    if rest:
        for kv in rest.split(","):
            k, _, v = kv.partition("=")
            k = k.strip()
            if k in OVERRIDABLE_INT_FIELDS:
                spec = replace(spec, **{k: int(v)})
            else:
                raise ValueError(
                    f"unknown scenario override {k!r} in {arg!r}"
                )
    return spec
