"""Declarative scenario specs + the named registry.

A :class:`ScenarioSpec` is pure data: how many nodes/validators/epochs,
which traffic shapes run (by name, see :mod:`traffic`), which adversity
tracks fire (``"name:key=val,..."`` specs, see :mod:`adversity`), and the
SLO thresholds the run is gated on (see :mod:`slo`).  The ``SCENARIOS``
dict below is the canonical registry — the static audit cross-checks
every ``--scenario`` example in the docs against its keys, exactly the
way ``--chaos`` specs are validated against the fault-site registry, so
keep the keys literal (AST-parsed, never imported, by
``analysis/registry_lint.py``).

Reproduction workflow: every run's JSON report records ``spec.seed`` and
the injector's fired-fault sequence; re-running the same scenario name
with the same seed replays the identical run (virtual breaker clock, one
shared ``random.Random(seed)``, probability gates drawn from a private
seeded stream).
"""

from __future__ import annotations

from dataclasses import dataclass, field


# Default SLO thresholds; per-scenario overrides merge over these.  A
# ``None`` threshold disables that gate.  See slo.evaluate for semantics.
DEFAULT_SLO: dict = {
    # shed / stall / breaker budgets (counter deltas over the run)
    "max_shed_rate": 0.5,          # shed events / processor enqueues
    "max_sync_stalls": 0,          # sync_stalls_total delta
    "max_breaker_transitions": 12,  # breaker_transitions_total delta
    "max_device_retries": 16,      # verify_device_retries_total delta
    # latency tails (histogram quantiles over the run's delta counts).
    # Gross-regression tripwires, not tight latency targets: the pure-
    # Python pairing fallback costs ~0.5 s/set and CI hosts run loaded,
    # so the budget carries headroom over the ~1 s quiet-host p99.
    "max_import_p99_s": 6.0,       # block_import_latency_seconds
    "max_verify_p99_s": 6.0,       # verify_batch_latency_seconds
    # liveness
    "require_head_convergence": True,
    "min_finalized_advance": 0,    # epochs every node must finalize
    # harness invariants
    "max_never_raise_violations": 0,
    "require_breaker_recovered": True,   # breaker CLOSED at run end
    "require_crash_recovery": True,      # kill -9 iterations all verified
    # "did the adversity actually bite" gates (None = not asserted)
    "min_breaker_transitions": None,     # breaker must have engaged
    "min_slashings_detected": None,      # slashers must have caught it
    # trace-derived overlap efficiency (warn-level; see slo.evaluate and
    # obs/report.py — wall / max(stage busy), 1.0 = perfect overlap)
    "max_overlap_wall_ratio": None,
}


@dataclass(frozen=True)
class ScenarioSpec:
    name: str
    seed: int
    n_nodes: int = 3
    n_validators: int = 32
    epochs: int = 2
    fork: str = "altair"
    breaker_enabled: bool = True
    slasher: bool = True
    traffic: tuple = ()    # shape names from traffic.SHAPES
    adversity: tuple = ()  # track specs "name[:k=v,...]" (adversity.TRACKS)
    slo: dict = field(default_factory=dict)  # overrides over DEFAULT_SLO

    def slo_thresholds(self) -> dict:
        merged = dict(DEFAULT_SLO)
        merged.update(self.slo)
        return merged

    def with_seed(self, seed: int) -> "ScenarioSpec":
        from dataclasses import replace

        return replace(self, seed=seed)


# ---------------------------------------------------------------------------
# The registry.  Keys are the names `--scenario` accepts; keep them
# literal string constants (the registry lint AST-parses this dict).
# ---------------------------------------------------------------------------

SCENARIOS: dict[str, ScenarioSpec] = {
    # Fast tier-1 smoke: 3 nodes, 2 epochs, one fault track.  Gossip
    # drops force the epoch-boundary heal path; no finalization is
    # expected inside 2 minimal-preset epochs.
    "smoke": ScenarioSpec(
        name="smoke",
        seed=1234,
        n_nodes=3,
        n_validators=16,
        epochs=2,
        traffic=("attestation-flood",),
        adversity=("gossip-faults:kind=drop,p=0.15,start=4,end=10",),
        slo={
            "min_finalized_advance": 0,
            "require_crash_recovery": False,
        },
    ),
    # The flagship mainnet-shape run: every traffic shape and every
    # adversity track at once — epoch-boundary attestation floods at
    # committee fan-out, a deposit queue draining through eth1 voting, a
    # proposer reorg, a slashable equivocation, lossy gossip, a
    # breaker-tripping device-fault window, byzantine sync peers on the
    # heal path, and a mid-run kill -9 + recovery — with a fault
    # cool-down tail so convergence + finalization SLOs are honest.
    "mainnet-shape": ScenarioSpec(
        name="mainnet-shape",
        seed=7,
        n_nodes=4,
        n_validators=32,
        epochs=6,
        traffic=(
            "attestation-flood",
            "deposit-queue",
            "proposer-reorg",
            "equivocation",
        ),
        adversity=(
            "gossip-faults:kind=drop,p=0.12,start=6,end=28",
            "device-faults:delay=0.02,start=10,end=14",
            "byzantine-sync",
            "kill-recovery:at=24",
        ),
        slo={
            "min_finalized_advance": 1,
            "min_breaker_transitions": 1,
            "min_slashings_detected": 1,
            # warn-level pipeline-health gate: generous so a loaded CI
            # host never flips it, loud when overlap truly collapses
            "max_overlap_wall_ratio": 8.0,
        },
    ),
    # Pod-serving device loss under mainnet-shape SLOs: the verify path
    # rides a list-mode PodVerifier (4 per-shard fault domains over the
    # resilience ladder) and a mid-epoch window drops shards at the
    # pod.dispatch site until repeat offenders are excluded and batches
    # re-shard onto the surviving mesh — no batch is ever dropped, the
    # breaker must be CLOSED again by run end, and the excluded devices
    # must be probed back in after the window.
    "pod-degrade": ScenarioSpec(
        name="pod-degrade",
        seed=11,
        n_nodes=3,
        n_validators=32,
        epochs=4,
        traffic=("attestation-flood",),
        adversity=(
            "gossip-faults:kind=drop,p=0.10,start=6,end=18",
            "pod-device-drop:shards=4,p=0.7,start=8,end=14",
        ),
        slo={
            "min_finalized_advance": 0,
            "require_crash_recovery": False,
        },
    ),
    # The same run with the circuit breaker disabled (failure threshold
    # parked at infinity): the device-fault window must now blow the
    # device-retry budget — proof the SLO gates catch regressions.
    "mainnet-shape-degraded": ScenarioSpec(
        name="mainnet-shape-degraded",
        seed=7,
        n_nodes=4,
        n_validators=32,
        epochs=6,
        breaker_enabled=False,
        traffic=(
            "attestation-flood",
            "deposit-queue",
            "proposer-reorg",
            "equivocation",
        ),
        adversity=(
            "gossip-faults:kind=drop,p=0.12,start=6,end=28",
            "device-faults:delay=0.02,start=10,end=14",
            "byzantine-sync",
            "kill-recovery:at=24",
        ),
        slo={
            "min_finalized_advance": 1,
            "require_breaker_recovered": False,
        },
    ),
}


def parse_scenario_arg(arg: str) -> ScenarioSpec:
    """Resolve a CLI ``--scenario`` argument: ``name[:key=val,...]``.

    Supported overrides: ``seed`` (int).  Examples::

        --scenario smoke
        --scenario mainnet-shape:seed=99
    """
    name, _, rest = arg.partition(":")
    name = name.strip()
    if name not in SCENARIOS:
        raise ValueError(
            f"unknown scenario {name!r}; have {sorted(SCENARIOS)}"
        )
    spec = SCENARIOS[name]
    if rest:
        for kv in rest.split(","):
            k, _, v = kv.partition("=")
            k = k.strip()
            if k == "seed":
                spec = spec.with_seed(int(v))
            else:
                raise ValueError(
                    f"unknown scenario override {k!r} in {arg!r}"
                )
    return spec
