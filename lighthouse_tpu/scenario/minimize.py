"""Delta-debug a violating ScenarioSpec down to a minimal reproducer.

Given a spec whose run violates at least one fail-level SLO gate and a
``reproduces`` oracle (does this candidate still violate the same way?),
:func:`minimize` greedily strips the spec toward defaults: dropping
traffic shapes, adversity tracks and their ``k=v`` knobs, shrinking
epochs/nodes/validators, and clearing incidental toggles — re-running
the oracle after every candidate and keeping only reductions that still
reproduce.  The result is the smallest spec (under this reduction
lattice) that still fails, plus the oracle-call count; ``render_spec``
turns it into a ready-to-paste ``SCENARIOS`` registry entry.

The loop is the classic ddmin shape specialised to the scenario
dimensions: one-at-a-time removals with a restart whenever anything
sticks (a removal can unlock another), bounded by ``max_steps`` oracle
calls so a flaky oracle can't spin forever.
"""

from __future__ import annotations

from dataclasses import dataclass, fields, replace

from .spec import ScenarioSpec


@dataclass
class MinimizeResult:
    spec: ScenarioSpec        # the minimal reproducing spec
    steps: int                # oracle invocations spent
    removed: list             # human-readable reduction log


def _strip_track_knob(track_spec: str, key: str) -> str:
    """Drop one ``k=v`` knob from a ``"name:k=v,..."`` track spec."""
    name, _, rest = track_spec.partition(":")
    kvs = [kv for kv in rest.split(",") if kv
           and kv.partition("=")[0].strip() != key]
    return name if not kvs else f"{name}:{','.join(kvs)}"


def _track_knobs(track_spec: str) -> list[str]:
    _, _, rest = track_spec.partition(":")
    return [kv.partition("=")[0].strip()
            for kv in rest.split(",") if kv]


def minimize(spec: ScenarioSpec, reproduces, max_steps: int = 64
             ) -> MinimizeResult:
    """Shrink ``spec`` while ``reproduces(candidate)`` stays true.

    ``reproduces`` runs the candidate and answers whether the original
    violation is still present (see ``search.violation_oracle``).  The
    INITIAL spec is assumed to reproduce; it is never re-run.
    """
    steps = 0
    removed: list[str] = []

    def attempt(candidate: ScenarioSpec, what: str) -> bool:
        nonlocal steps, spec
        if steps >= max_steps:
            return False
        steps += 1
        if reproduces(candidate):
            spec = candidate
            removed.append(what)
            return True
        return False

    progress = True
    while progress and steps < max_steps:
        progress = False

        for shape in list(spec.traffic):
            cand = replace(spec, traffic=tuple(
                s for s in spec.traffic if s != shape
            ))
            if attempt(cand, f"traffic -{shape}"):
                progress = True

        for track in list(spec.adversity):
            cand = replace(spec, adversity=tuple(
                t for t in spec.adversity if t != track
            ))
            if attempt(cand, f"adversity -{track.partition(':')[0]}"):
                progress = True

        # knob stripping: a knob whose removal (class default) still
        # reproduces is noise in the regression scenario
        for track in list(spec.adversity):
            for key in _track_knobs(track):
                slim = _strip_track_knob(track, key)
                cand = replace(spec, adversity=tuple(
                    slim if t == track else t for t in spec.adversity
                ))
                if attempt(cand, f"knob -{track.partition(':')[0]}.{key}"):
                    progress = True
                    break  # `track` string changed; restart its knobs

        for epochs in sorted({1, spec.epochs // 2}):
            if 0 < epochs < spec.epochs:
                if attempt(replace(spec, epochs=epochs),
                           f"epochs {epochs}"):
                    progress = True
                    break

        for n in sorted({1, 2, spec.n_nodes // 2}):
            if 0 < n < spec.n_nodes:
                if attempt(replace(spec, n_nodes=n), f"n_nodes {n}"):
                    progress = True
                    break

        # validator floor is 8: one minimal-preset committee's worth —
        # below that the engine can't schedule a meaningful epoch
        for n in sorted({8, spec.n_validators // 2}):
            if 8 <= n < spec.n_validators:
                if attempt(replace(spec, n_validators=n),
                           f"n_validators {n}"):
                    progress = True
                    break

        # incidental toggles back to their defaults
        defaults = {f.name: f.default for f in fields(ScenarioSpec)
                    if f.name in ("breaker_enabled", "slasher",
                                  "registry_padding", "spec_overrides")}
        for fname, dflt in defaults.items():
            if getattr(spec, fname) != dflt:
                if attempt(replace(spec, **{fname: dflt}),
                           f"{fname} -> default"):
                    progress = True

        # per-key SLO overrides that aren't load-bearing
        for key in list(spec.slo):
            slim = {k: v for k, v in spec.slo.items() if k != key}
            if attempt(replace(spec, slo=slim), f"slo -{key}"):
                progress = True

    return MinimizeResult(spec=spec, steps=steps, removed=removed)


def render_spec(spec: ScenarioSpec, name: str | None = None) -> str:
    """A ready-to-register ``SCENARIOS`` entry for a minimized spec —
    literal constructor source (the registry lint AST-parses the dict, so
    the emitted entry lints like any hand-written one).  Only fields that
    differ from the dataclass defaults are rendered."""
    name = name or spec.name
    lines = [f'    "{name}": ScenarioSpec(', f'        name="{name}",',
             f"        seed={spec.seed},"]
    for f in fields(ScenarioSpec):
        if f.name in ("name", "seed", "slo"):
            continue  # always rendered / handled below
        value = getattr(spec, f.name)
        if value == f.default:
            continue
        lines.append(f"        {f.name}={value!r},")
    if spec.slo:
        lines.append("        slo={")
        for k, v in spec.slo.items():
            lines.append(f'            "{k}": {v!r},')
        lines.append("        },")
    lines.append("    ),")
    return "\n".join(lines)
