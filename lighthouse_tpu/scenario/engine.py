"""The scenario run loop: N SimNodes, one seed, SLO-gated.

Determinism contract: one ``random.Random(spec.seed)`` seeds the
FaultInjector's probability stream, the breaker/verifier run on a
*virtual* clock advanced one second per slot (trip/probe/backoff timing
is slot-driven, never wall-clock), and every per-slot action is a pure
function of (spec, seed).  Two runs of the same spec produce the same
fired-fault sequence, the same head roots, the same finalized epochs —
pinned by the report's ``fingerprint``.

The loop per slot: advance the virtual clock, let adversity tracks
arm/disarm, propose (base proposal or a traffic shape's replacement),
attest from the proposer's view, push the attestations through the
BeaconProcessor (where the ResilientVerifier + CircuitBreaker ladder
runs against injected device faults), poll the in-node slashers, and at
epoch boundaries heal gossip-partitioned nodes over the real SyncManager
(byzantine peers included when that track is on) with a canonical-chain
replay fallback.
"""

from __future__ import annotations

import hashlib
import json
import logging
import random
import time

from ..obs import report as trace_report
from ..obs.tracer import TRACER
from ..utils.logging import get_logger, log_with
from .adversity import build_tracks
from .slo import MetricsSnapshot, evaluate, evaluate_epoch
from .spec import ScenarioSpec, parse_scenario_arg
from .traffic import build_shapes

log = get_logger("lighthouse_tpu.scenario")


class ScenarioClock:
    """Virtual monotonic clock: one second per slot, advanced only by the
    engine — so breaker timeouts/backoffs resolve identically every run."""

    def __init__(self, start: float = 1000.0):
        self.t = start

    def now(self) -> float:
        return self.t

    def advance(self, dt: float = 1.0) -> None:
        self.t += dt


class ScenarioEngine:
    def __init__(self, spec: ScenarioSpec, out_path: str | None = None,
                 history_path: str | None = None):
        from ..beacon.processor import (
            BeaconProcessor,
            CircuitBreaker,
            ResilientVerifier,
            WorkKind,
        )
        from ..beacon.simulator import Simulator
        from ..crypto.bls import api as _bls_api
        from ..utils.faults import FaultInjector

        self.spec = spec
        self.out_path = out_path
        self.history_path = history_path
        self.rng = random.Random(spec.seed)
        self.injector = FaultInjector(seed=spec.seed)
        self.sim = Simulator(
            n_nodes=spec.n_nodes, n_validators=spec.n_validators,
            fork=spec.fork, injector=self.injector, slasher=spec.slasher,
            registry_padding=spec.registry_padding,
            spec_overrides=spec.spec_overrides,
        )
        self.slots_per_epoch = self.sim.spec.preset.slots_per_epoch
        self.clock = ScenarioClock()
        # breaker_enabled=False parks the threshold at infinity — the
        # ladder still runs, but nothing ever sheds or short-circuits:
        # the degraded-run proof that the SLO gates catch regressions
        self.breaker = CircuitBreaker(
            failure_threshold=3 if spec.breaker_enabled else 10 ** 9,
            now=self.clock.now,
        )
        self.verifier = ResilientVerifier(
            device_verify=lambda s: _bls_api.get_backend().verify_signature_sets(s),
            cpu_verify=lambda s: _bls_api.cpu_backend().verify_signature_sets(s),
            breaker=self.breaker,
            now=self.clock.now,
            injector=self.injector,
        )
        self._work_kind = WorkKind.GOSSIP_ATTESTATION
        self.processor = BeaconProcessor(
            handlers={WorkKind.GOSSIP_ATTESTATION: self._attestation_handler},
            breaker=self.breaker,
            injector=self.injector,
        )
        self.shapes = build_shapes(spec.traffic)
        self.tracks = build_tracks(spec.adversity)
        self.byzantine_sync = False  # ByzantineSyncTrack flips this
        self.att_filter = None  # FinalityStallTrack sets (att -> bool)
        self.events: list[dict] = []
        self.run_facts: dict = {
            "processor_enqueues": 0,
            "proposal_failures": 0,
            "never_raise_violations": 0,
            "slashings_detected": 0,
            "crash_reports": [],
        }
        self._probe_sets: list = []  # last known-good sets, breaker probes
        # per-epoch SLO snapshots (epoch -> metrics delta + facts + gate
        # results); populated at every epoch boundary so a violation is
        # localized to the epoch it first appears in
        self.epoch_records: list[dict] = []
        self._epoch_prev_snap: MetricsSnapshot | None = None
        self._ssz_base = 0

    # ------------------------------------------------------------ plumbing

    def note(self, event: str, **kw) -> None:
        self.events.append({"event": event, **kw})
        log_with(log, logging.INFO, f"scenario {event}",
                 scenario=self.spec.name, **kw)

    def enqueue_attestation(self, att) -> None:
        from ..beacon.processor import WorkEvent

        self.run_facts["processor_enqueues"] += 1
        self.processor.try_send(
            WorkEvent(kind=self._work_kind, item=att,
                      received_at=self.clock.now())
        )

    def _attestation_handler(self, events: list) -> None:
        """Verify a batch of gossip attestations through the resilience
        ladder — the workload the device-fault track attacks."""
        from ..consensus import committees as cm
        from ..consensus.state_processing.signature_sets import (
            indexed_attestation_signature_set,
        )

        chain = self.sim.nodes[0].chain
        state = chain.head_state()
        sets = []
        for ev in events:
            att = ev.item
            try:
                epoch = int(att.data.slot) // self.slots_per_epoch
                cache = chain.committee_cache(state, epoch)
                committee = cache.committee(
                    int(att.data.slot), int(att.data.index)
                )
                indexed = cm.get_indexed_attestation(committee, att)
                sets.append(
                    indexed_attestation_signature_set(
                        state, chain.get_pubkey, indexed, chain.preset
                    )
                )
            except Exception:
                continue  # a stale view can't index every flooded att
        if not sets:
            return
        self._probe_sets = sets[:1]
        try:
            self.verifier.verify_batch(sets)
        except Exception as exc:  # noqa: BLE001 — contract says never
            self.run_facts["never_raise_violations"] += 1
            self.note("never-raise-violation", where="verify_batch",
                      error=f"{type(exc).__name__}: {exc}")

    # ------------------------------------------------------------ the loop

    def run(self) -> dict:
        t0 = time.time()
        # everything the flight recorder captures past this mark belongs
        # to THIS run — the SLO-failure dump and the overlap gate read
        # only the run's own spans
        self._trace_mark = TRACER.mark()
        before = MetricsSnapshot()
        self._epoch_prev_snap = before
        self._ssz_base = self._ssz_cache_bytes_now()
        for shape in self.shapes:
            shape.install(self)
        for track in self.tracks:
            track.install(self)
        total_slots = self.spec.epochs * self.slots_per_epoch
        for slot in range(1, total_slots + 1):
            self.clock.advance(1.0)
            self.sim.set_slot(slot)
            for track in self.tracks:
                track.on_slot(self, slot)
            self._run_slot(slot)
            if slot % self.slots_per_epoch == 0:
                self._heal(slot)
                self._snapshot_epoch(slot // self.slots_per_epoch)
        self._recover_breaker()
        self._heal(total_slots)  # final convergence pass
        for shape in self.shapes:
            shape.finalize(self)
        for track in self.tracks:
            track.finalize(self)
        after = MetricsSnapshot()
        return self._report(before, after, total_slots, t0)

    def _run_slot(self, slot: int) -> None:
        with TRACER.span("scenario.slot", slot=slot):
            self._run_slot_inner(slot)

    def _run_slot_inner(self, slot: int) -> None:
        sim = self.sim
        shape = next(
            (s for s in self.shapes if s.proposes(self, slot)), None
        )
        try:
            if shape is not None:
                shape.propose(self, slot)
            else:
                node = sim.proposer_node(slot)
                signed = node.chain.produce_block(slot, sim.keypairs)
                node.publish_block(signed)
        except Exception as exc:  # a missed proposal is a liveness fact,
            # not a harness abort — the finalization SLO judges it
            self.run_facts["proposal_failures"] += 1
            self.note("proposal-failed", slot=slot,
                      error=f"{type(exc).__name__}: {exc}")
        try:
            atts = sim.attest(slot, keep=self.att_filter)
        except Exception as exc:
            atts = []
            self.note("attest-failed", slot=slot,
                      error=f"{type(exc).__name__}: {exc}")
        for att in atts:
            self.enqueue_attestation(att)
        for s in self.shapes:
            s.on_attestations(self, slot, atts)
        for t in self.tracks:
            t.on_attestations(self, slot, atts)
        self.processor.drain()
        # a tripped breaker sheds GOSSIP_ATTESTATION at ingress, so the
        # handler alone would never probe the device again; block/sync
        # signature traffic keeps flowing through the ladder in a real
        # node, so feed one known-good batch per slot as that probe
        if not self.breaker.is_closed and self._probe_sets:
            try:
                self.verifier.verify_batch(self._probe_sets)
            except Exception:  # noqa: BLE001
                self.run_facts["never_raise_violations"] += 1
        found = sim.poll_slashers()
        if found:
            self.run_facts["slashings_detected"] += found
            self.note("slashings-detected", slot=slot, found=found)

    # ---------------------------------------------------- epoch snapshots

    @staticmethod
    def _ssz_cache_bytes_now() -> int:
        from ..consensus.ssz import CACHE_BUDGET

        return CACHE_BUDGET.used_bytes + CACHE_BUDGET.memo_bytes

    def _snapshot_epoch(self, epoch: int) -> None:
        """One per-epoch SLO snapshot, taken at the boundary after the
        heal pass.  Pure observation: consumes no engine RNG and fires
        no faults, so run fingerprints are unchanged by snapshotting.
        The metrics delta is against the PREVIOUS boundary (per-epoch
        rates, not cumulative), while the byte/pool facts are absolute
        at this boundary — what the epoch-level budgets gate."""
        snap = MetricsSnapshot()
        deltas = snap.delta(self._epoch_prev_snap)
        self._epoch_prev_snap = snap
        nodes = self.sim.nodes
        facts: dict = {
            # cache growth since run start — process-global counters
            # carry earlier runs' memo bytes, so the run's own growth
            # is the leak signal
            "ssz_cache_bytes": max(
                0, self._ssz_cache_bytes_now() - self._ssz_base
            ),
            "pool_estimated_verify_cost": max(
                n.chain.naive_pool._resident_sigs for n in nodes
            ),
            "naive_pool_groups": max(
                len(n.chain.naive_pool._groups) for n in nodes
            ),
            "op_pool_attestations": max(
                n.chain.op_pool.num_attestations() for n in nodes
            ),
        }
        for shape in self.shapes:
            shape.on_epoch(self, epoch, facts)
        for track in self.tracks:
            track.on_epoch(self, epoch, facts)
        results = evaluate_epoch(self.spec.slo_thresholds(), facts)
        self.epoch_records.append({
            "epoch": epoch,
            "metrics": deltas,
            "facts": facts,
            "slo": [r.to_dict() for r in results],
        })
        # roll the worst-epoch values up into the run facts the
        # run-level gates read — one source of truth for the verdict
        for key in ("deposit_queue_depth", "ssz_cache_bytes",
                    "pool_estimated_verify_cost"):
            if key in facts:
                prev = self.run_facts.get(f"{key}_max", 0)
                self.run_facts[f"{key}_max"] = max(prev, facts[key])

    # ------------------------------------------------------------- healing

    def _heal(self, slot: int) -> None:
        """Epoch-boundary catch-up: lagging/partitioned nodes sync off the
        best node over the real SyncManager, with a canonical replay
        fallback — gossip drops must never strand a node permanently."""
        sim = self.sim
        for n in sim.nodes:
            n.chain.recompute_head()
        best = max(
            sim.nodes,
            key=lambda n: (int(n.chain.head_state().slot), n.chain.head_root),
        )
        for node in sim.nodes:
            if node.chain.head_root == best.chain.head_root:
                continue
            self._sync_from(best, node)
            if node.chain.head_root != best.chain.head_root:
                self._replay_canonical(best, node)
            node.chain.recompute_head()

    def _sync_from(self, best, node) -> None:
        from ..beacon.sync import SyncManager, SyncPeer, serve_blocks_by_range
        from ..network import rpc
        from ..network.peer_manager import PeerManager

        serve = serve_blocks_by_range(best.chain, self.spec.fork)

        def honest(start_slot, count):
            return [rpc.decode_response_chunk(c)
                    for c in serve(start_slot, count)]

        head_slot = int(best.chain.head_state().slot)
        pm = PeerManager()
        mgr = SyncManager(node.chain, fork=self.spec.fork, peer_manager=pm,
                          batch_slots=self.slots_per_epoch,
                          request_timeout=0.5)
        if self.byzantine_sync:
            def reorder(start_slot, count):
                return list(reversed(honest(start_slot, count)))

            def crash(start_slot, count):
                raise RuntimeError("connection reset by peer")

            mgr.add_peer(SyncPeer(peer_id="byz-reorder", head_slot=head_slot,
                                  request_blocks=reorder))
            mgr.add_peer(SyncPeer(peer_id="byz-crash", head_slot=head_slot,
                                  request_blocks=crash))
            self.run_facts["byzantine_heals"] = (
                self.run_facts.get("byzantine_heals", 0) + 1
            )
        mgr.add_peer(SyncPeer(peer_id="honest", head_slot=head_slot,
                              request_blocks=honest))
        try:
            mgr.tick()
        except Exception as exc:  # noqa: BLE001 — tick promises not to
            self.run_facts["never_raise_violations"] += 1
            self.note("never-raise-violation", where="sync.tick",
                      error=f"{type(exc).__name__}: {exc}")

    def _replay_canonical(self, best, node) -> None:
        """Last-resort heal: feed the best node's canonical chain through
        the RPC import path; already-known blocks are expected noise."""
        from ..beacon.chain import BlockError
        from ..network import rpc
        from ..beacon.sync import serve_blocks_by_range

        serve = serve_blocks_by_range(best.chain, self.spec.fork)
        cls = node.chain.types.SignedBeaconBlock_BY_FORK[self.spec.fork]
        head_slot = int(best.chain.head_state().slot)
        for chunk in serve(1, head_slot):
            try:
                _code, payload = rpc.decode_response_chunk(chunk)
                blk = cls.deserialize_value(payload)
                node.chain.process_block(
                    blk, verify_signatures=False, from_rpc=True
                )
            except BlockError as e:
                if "already known" not in str(e):
                    self.note("replay-rejected", error=str(e)[:120])
            except Exception as exc:  # noqa: BLE001
                self.note("replay-failed",
                          error=f"{type(exc).__name__}: {exc}")

    def _recover_breaker(self) -> None:
        """Post-run drain: advance the virtual clock through the backoff
        schedule feeding known-good probe batches until the breaker
        re-closes (the ``require_breaker_recovered`` SLO input)."""
        for _ in range(64):
            if self.breaker.is_closed:
                break
            self.clock.advance(2.0)
            if self._probe_sets:
                try:
                    self.verifier.verify_batch(self._probe_sets)
                except Exception:  # noqa: BLE001
                    self.run_facts["never_raise_violations"] += 1
            elif self.breaker.allow_device():
                self.breaker.record_success()
        self.run_facts["breaker_closed"] = self.breaker.is_closed

    # ------------------------------------------------------------- reports

    def _report(self, before, after, total_slots: int, t0: float) -> dict:
        heads = [h.hex() for h in self.sim.heads()]
        fins = [int(f) for f in self.sim.finalized_epochs()]
        self.run_facts["heads"] = heads
        self.run_facts["finalized_epochs"] = fins
        self.run_facts.setdefault("breaker_closed", self.breaker.is_closed)
        # pool/cache pressure facts for the hostile-regime gates: worst
        # per-node pool sizes at run end, and the shared shuffling-cache
        # population (one dict across all SimNodes)
        nodes = self.sim.nodes
        self.run_facts["op_pool_attestations"] = max(
            n.chain.op_pool.num_attestations() for n in nodes
        )
        self.run_facts["naive_pool_groups"] = max(
            len(n.chain.naive_pool._groups) for n in nodes
        )
        self.run_facts["committee_cache_entries"] = len(
            nodes[0].chain._committee_caches
        )
        trace_mark = getattr(self, "_trace_mark", 0)
        run_events = TRACER.chrome_trace(trace_mark)["traceEvents"]
        self.run_facts["overlap_efficiency"] = trace_report.overlap_efficiency(
            run_events
        )
        deltas = after.delta(before)
        results = evaluate(
            self.spec.slo_thresholds(), deltas, self.run_facts
        )
        fired = [list(f) for f in self.injector.fired_sequence()]
        fingerprint = hashlib.sha256(
            json.dumps(
                {"fired": fired, "heads": heads, "finalized": fins},
                sort_keys=True,
            ).encode()
        ).hexdigest()[:16]
        # warn-level gates are advisory: logged and reported, never the
        # verdict (slo.SLOResult.level)
        ok = all(r.ok for r in results if r.level == "fail")
        # localize: the first epoch whose boundary snapshot failed an
        # epoch-level gate (None = no epoch-localized violation)
        first_violation_epoch = next(
            (rec["epoch"] for rec in self.epoch_records
             if any(not g["ok"] and g["level"] == "fail"
                    for g in rec["slo"])),
            None,
        )
        trace_dump = None
        if not ok:
            # a failing run must leave a flight-recorder artifact: next
            # to the JSON report when one is written, else through the
            # configured dump dir ($LIGHTHOUSE_TPU_TRACE_DIR)
            if self.out_path:
                try:
                    trace_dump = TRACER.dump(
                        f"{self.out_path}.trace.json", since_sid=trace_mark
                    )
                except OSError as exc:
                    log.warning("scenario trace dump failed: %s", exc)
            else:
                trace_dump = TRACER.maybe_dump(
                    f"slo-{self.spec.name}", since_sid=trace_mark
                )
        report = {
            "kind": "scenario",
            "scenario": self.spec.name,
            "seed": self.spec.seed,
            "pass": ok,
            "fingerprint": fingerprint,
            "slots": total_slots,
            "nodes": self.spec.n_nodes,
            "trace_dump": trace_dump,
            "slo": [r.to_dict() for r in results],
            # advisory-gate summary: warn-level failures never flip the
            # verdict, so surface them explicitly for report consumers
            "slo_warnings": [
                r.name for r in results if not r.ok and r.level == "warn"
            ],
            "metrics": deltas,
            "facts": dict(self.run_facts),
            "epochs": self.epoch_records,
            "first_violation_epoch": first_violation_epoch,
            "fired_faults": fired,
            "events": self.events,
            "elapsed_s": round(time.time() - t0, 3),
        }
        if self.out_path:
            with open(self.out_path, "w") as f:
                json.dump(report, f, indent=2, default=str)
        if self.history_path:
            self._record_history(report)
        log_with(log, logging.INFO, "scenario finished",
                 scenario=self.spec.name, seed=self.spec.seed,
                 ok=ok, fingerprint=fingerprint,
                 slo_failed=[r.name for r in results if not r.ok])
        return report

    def _record_history(self, report: dict) -> None:
        from ..utils import device_kind

        entry = {
            "kind": "soak" if self.spec.soak else "scenario",
            "device_kind": device_kind(),
            "measured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "scenario": report["scenario"],
            "seed": report["seed"],
            "pass": report["pass"],
            "fingerprint": report["fingerprint"],
            "slots": report["slots"],
            "nodes": report["nodes"],
            "slo_failed": [r["name"] for r in report["slo"] if not r["ok"]],
            "elapsed_s": report["elapsed_s"],
        }
        if self.spec.soak:
            # the soak row's own facts: how far the run survived, the
            # process's peak RSS, and the worst per-epoch verify p99
            import resource

            epochs = report.get("epochs", [])
            survived = sum(
                1 for rec in epochs
                if all(g["ok"] for g in rec["slo"]
                       if g["level"] == "fail")
            )
            entry["epochs_survived"] = survived
            entry["epochs_total"] = len(epochs)
            entry["peak_rss_kb"] = int(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            )
            entry["worst_epoch_verify_p99_s"] = round(max(
                (rec["metrics"].get("verify_p99_s", 0.0)
                 for rec in epochs), default=0.0,
            ), 4)
            entry["ssz_cache_bytes_max"] = report["facts"].get(
                "ssz_cache_bytes_max", 0
            )
        try:
            with open(self.history_path, "a") as f:
                f.write(json.dumps(entry) + "\n")
        except OSError:
            pass


def run_scenario(spec_or_name, out_path: str | None = None,
                 history_path: str | None = None) -> dict:
    """Run one scenario (by :class:`ScenarioSpec` or registry name) and
    return its JSON-shaped report."""
    spec = spec_or_name
    if isinstance(spec, str):
        # registry names first, then the committed regression corpus
        # (tests/fixtures/scenarios) — parse_scenario_arg does both
        spec = parse_scenario_arg(spec)
    return ScenarioEngine(
        spec, out_path=out_path, history_path=history_path
    ).run()
