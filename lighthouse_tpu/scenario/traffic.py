"""Traffic shapes: the mainnet-shaped load a scenario drives through nodes.

Each shape is a small object with optional hooks the engine calls every
slot.  A shape may *replace* the base proposal at specific slots
(``proposes``/``propose`` — proposer reorgs, equivocations) or piggyback
on the honest flow (``on_attestations`` — floods) or rewire services at
install time (``install`` — the shared eth1 deposit queue).
"""

from __future__ import annotations


class Shape:
    name = ""

    def install(self, engine) -> None:
        """One-time setup before slot 0 (wire services, queue deposits)."""

    def proposes(self, engine, slot: int) -> bool:
        """True when this shape replaces the base proposal at ``slot``."""
        return False

    def propose(self, engine, slot: int):
        raise NotImplementedError

    def on_attestations(self, engine, slot: int, atts: list) -> None:
        """Called after the honest committees attested at ``slot``."""

    def on_epoch(self, engine, epoch: int, facts: dict) -> None:
        """Contribute to the engine's per-epoch snapshot ``facts``
        (taken at every epoch boundary, after the heal pass)."""

    def finalize(self, engine) -> None:
        """End-of-run bookkeeping into the engine report."""


class AttestationFlood(Shape):
    """Epoch-boundary attestation floods at committee fan-out.

    Every attestation seen during an epoch is replayed into the
    BeaconProcessor once per node at the epoch's last slot — the
    burst a real network produces when every subnet's aggregates land
    around the boundary.  Under a tripped breaker these are exactly the
    GOSSIP_ATTESTATION events the scheduler sheds, which is what the
    shed-rate SLO measures.
    """

    name = "attestation-flood"

    def __init__(self):
        self._window: list = []
        self.flooded = 0

    def on_attestations(self, engine, slot: int, atts: list) -> None:
        self._window.extend(atts)
        if (slot + 1) % engine.slots_per_epoch != 0:
            return
        fan_out = len(engine.sim.nodes)
        for att in self._window:
            for _ in range(fan_out):
                engine.enqueue_attestation(att)
        self.flooded += len(self._window) * fan_out
        engine.note("attestation-flood", slot=slot,
                    burst=len(self._window) * fan_out)
        self._window = []

    def finalize(self, engine) -> None:
        engine.run_facts["attestations_flooded"] = self.flooded


class DepositQueue(Shape):
    """A deposit queue draining through eth1 voting.

    One shared :class:`Eth1Service` is wired onto every node's chain with
    a batch of top-up deposits (existing validator pubkeys, so the
    transition's signature check is skipped on the top-up path) and a
    single eth1 block carrying the final deposit root.  Nothing is
    inserted after install — DepositTree proofs are always against the
    tree's *current* root, so a growing tree would invalidate proofs for
    the already-voted block.  Blocks vote for it every slot; once the
    vote clears the period majority the transition demands the pending
    deposits in every subsequent block (the ``expected_deposits`` check).
    """

    name = "deposit-queue"
    n_topups = 4
    topup_gwei = 1_000_000_000  # 1 ETH per top-up

    def install(self, engine) -> None:
        from ..beacon.eth1 import Eth1Block, Eth1Service
        from ..consensus.containers import DepositData

        spec = engine.sim.spec
        state = engine.sim.nodes[0].chain.head_state()
        self._base = int(state.eth1_deposit_index)
        svc = Eth1Service(spec)
        for j in range(self.n_topups):
            v = state.validators[j % len(state.validators)]
            svc.deposit_cache.insert_log(
                self._base + j,
                DepositData(
                    pubkey=bytes(v.pubkey),
                    withdrawal_credentials=bytes(v.withdrawal_credentials),
                    amount=self.topup_gwei,
                ),
            )
        svc.insert_block(
            Eth1Block(
                number=1,
                hash=b"\xe1" * 32,
                timestamp=0,
                deposit_count=svc.deposit_cache.count(),
                deposit_root=svc.deposit_cache.deposit_root(),
            )
        )
        for node in engine.sim.nodes:
            node.chain.eth1 = svc
        engine.note("deposit-queue", queued=self.n_topups)

    def finalize(self, engine) -> None:
        state = engine.sim.nodes[0].chain.head_state()
        engine.run_facts["deposits_applied"] = (
            int(state.eth1_deposit_index) - self._base
        )


class DepositSaturation(Shape):
    """Deposit-queue saturation: inflow pinned ABOVE the drain rate.

    Unlike :class:`DepositQueue` (a fixed batch inserted once at
    install), this shape keeps the eth1 contract LIVE for the whole run:
    every slot it inserts ``inflow_per_slot`` new deposit logs (top-ups
    to existing validators, so the transition's signature check stays
    off the hot path) and one eth1 block snapshot capturing the tree's
    count/root at that instant.  Voting herds onto snapshots that trail
    the tip by ``eth1_follow_distance`` blocks, and blocks drain at most
    ``max_deposits`` per slot against the *voted* snapshot — proofs are
    generated against that historical tree (``DepositTree.proof(index,
    count)``), which is what makes a growing tree safe.  With the
    scenario's override of inflow > drain the backlog grows by design;
    the SLO gates judge whether it stays inside budget and whether the
    drain stays live.
    """

    name = "deposit-saturation"
    inflow_per_slot = 6
    topup_gwei = 1_000_000_000  # 1 ETH per top-up

    def __init__(self):
        self._svc = None
        self._base = 0
        self._inserted = 0
        self.depth_max = 0

    def install(self, engine) -> None:
        from ..beacon.eth1 import Eth1Service

        spec = engine.sim.spec
        state = engine.sim.nodes[0].chain.head_state()
        self._base = int(state.eth1_deposit_index)
        self._svc = Eth1Service(spec)
        # prime the block window so eth1_data_for_vote has a trailing
        # candidate from the first voting period
        self._insert_inflow(engine, slot=0)
        for node in engine.sim.nodes:
            node.chain.eth1 = self._svc
        engine.note("deposit-saturation",
                    inflow_per_slot=self.inflow_per_slot)

    def _insert_inflow(self, engine, slot: int) -> None:
        from ..beacon.eth1 import Eth1Block
        from ..consensus.containers import DepositData

        state = engine.sim.nodes[0].chain.head_state()
        cache = self._svc.deposit_cache
        for _ in range(self.inflow_per_slot):
            v = state.validators[
                self._inserted % engine.spec.n_validators
            ]
            cache.insert_log(
                self._base + self._inserted,
                DepositData(
                    pubkey=bytes(v.pubkey),
                    withdrawal_credentials=bytes(
                        v.withdrawal_credentials
                    ),
                    amount=self.topup_gwei,
                ),
            )
            self._inserted += 1
        self._svc.insert_block(
            Eth1Block(
                number=slot + 1,
                hash=b"\xe1" + slot.to_bytes(8, "little") + bytes(23),
                timestamp=slot,
                deposit_count=cache.count(),
                deposit_root=cache.deposit_root(),
            )
        )

    def on_attestations(self, engine, slot: int, atts: list) -> None:
        self._insert_inflow(engine, slot)

    def _queue_depth(self, engine) -> int:
        state = engine.sim.nodes[0].chain.head_state()
        return max(
            0,
            int(state.eth1_data.deposit_count)
            - int(state.eth1_deposit_index),
        )

    def on_epoch(self, engine, epoch: int, facts: dict) -> None:
        depth = self._queue_depth(engine)
        self.depth_max = max(self.depth_max, depth)
        facts["deposit_queue_depth"] = depth
        facts["deposits_applied"] = (
            int(engine.sim.nodes[0].chain.head_state().eth1_deposit_index)
            - self._base
        )
        facts["deposits_queued"] = self._inserted

    def finalize(self, engine) -> None:
        state = engine.sim.nodes[0].chain.head_state()
        engine.run_facts["deposits_applied"] = (
            int(state.eth1_deposit_index) - self._base
        )
        engine.run_facts["deposits_queued"] = self._inserted
        engine.run_facts["deposit_queue_depth_max"] = max(
            self.depth_max, self._queue_depth(engine)
        )


class ProposerReorg(Shape):
    """At ``slot_at`` the proposer builds on the head's *parent* instead
    of the head — a one-block reorg attempt whose sibling competes in
    fork choice.  Whether it wins or loses, every node must keep
    converging through the competing branches."""

    name = "proposer-reorg"
    slot_at = 12

    def proposes(self, engine, slot: int) -> bool:
        return slot == self.slot_at

    def propose(self, engine, slot: int):
        node = engine.sim.proposer_node(slot)
        parent = bytes(
            node.chain.head_state().latest_block_header.parent_root
        )
        signed = engine.sim.propose_on(slot, parent)
        engine.note("proposer-reorg", slot=slot,
                    parent=parent.hex()[:16])
        return signed


class Equivocation(Shape):
    """At ``slot_at`` the scheduled proposer double-proposes (same slot,
    same parent, differing graffiti) — the slashable offence the in-node
    slashers must detect, turn into a ProposerSlashing, and get included
    on-chain, all without stalling honest head convergence."""

    name = "equivocation"
    slot_at = 21

    def proposes(self, engine, slot: int) -> bool:
        return slot == self.slot_at

    def propose(self, engine, slot: int):
        a, _b = engine.sim.propose_equivocation(slot)
        engine.note("equivocation", slot=slot,
                    proposer=int(a.message.proposer_index))
        return a


class EquivocationStorm(Shape):
    """Mass equivocation: at every slot in ``slots`` the scheduled
    proposer double-proposes (the :class:`Equivocation` offence, times
    four, hitting distinct proposers).  Slashed proposers whose turn
    comes around again fail their proposal — a liveness fact the
    finalization SLO judges, not a harness abort."""

    name = "equivocation-storm"
    slots = (5, 9, 13, 17)

    def __init__(self):
        self.proposers: list[int] = []

    def proposes(self, engine, slot: int) -> bool:
        return slot in self.slots

    def propose(self, engine, slot: int):
        a, _b = engine.sim.propose_equivocation(slot)
        proposer = int(a.message.proposer_index)
        self.proposers.append(proposer)
        engine.note("equivocation-storm", slot=slot, proposer=proposer)
        return a

    def finalize(self, engine) -> None:
        engine.run_facts["equivocations_proposed"] = len(self.proposers)


class ExitFlood(Shape):
    """Mass voluntary-exit traffic: at install, signed exits for the last
    ``n_exits`` interop validators land in every node's op pool (dummy
    signatures — block import in the mesh runs unverified, as gossip
    tests do).  Packing validity-filters them (op_pool._exitable), so a
    spec with the default 256-epoch ``shard_committee_period`` drains
    nothing — the slashing-flood scenario overrides it to 0.  The
    ``exits_processed`` fact counts flooded validators whose
    ``exit_epoch`` actually left FAR_FUTURE, i.e. exits that survived
    packing AND the transition's validity ladder."""

    name = "exit-flood"
    n_exits = 8

    def __init__(self):
        self.indices: list[int] = []

    def install(self, engine) -> None:
        from ..consensus.containers import SignedVoluntaryExit, VoluntaryExit

        n = engine.spec.n_validators
        self.indices = list(range(max(0, n - self.n_exits), n))
        for idx in self.indices:
            signed = SignedVoluntaryExit(
                message=VoluntaryExit(epoch=0, validator_index=idx),
                signature=b"\x00" * 96,
            )
            for node in engine.sim.nodes:
                node.chain.op_pool.insert_voluntary_exit(signed)
        engine.note("exit-flood", queued=len(self.indices))

    def finalize(self, engine) -> None:
        from ..consensus.testing import FAR_FUTURE_EPOCH

        state = engine.sim.nodes[0].chain.head_state()
        engine.run_facts["exits_processed"] = sum(
            1 for i in self.indices
            if int(state.validators[i].exit_epoch) != FAR_FUTURE_EPOCH
        )


SHAPES = {
    cls.name: cls
    for cls in (AttestationFlood, DepositQueue, DepositSaturation,
                ProposerReorg, Equivocation, EquivocationStorm, ExitFlood)
}


def build_shapes(names) -> list[Shape]:
    out = []
    for name in names:
        cls = SHAPES.get(name)
        if cls is None:
            raise ValueError(
                f"unknown traffic shape {name!r}; have {sorted(SHAPES)}"
            )
        out.append(cls())
    return out
