"""Mainnet-shape adversarial scenario harness with SLO gates.

The chaos pieces built by earlier PRs — FaultInjector sites, the
CircuitBreaker + ResilientVerifier ladder, byzantine multi-peer sync,
the ``kill -9`` crash harness, the in-process multi-node Simulator —
exist separately; this package composes them into one repeatable,
seed-deterministic scenario generator:

* :mod:`spec`      — declarative :class:`ScenarioSpec` + the named
                     ``SCENARIOS`` registry (``smoke``,
                     ``mainnet-shape``, ``mainnet-shape-degraded``)
* :mod:`traffic`   — traffic shapes: epoch-boundary attestation floods
                     at committee fan-out, deposit queues, proposer
                     reorgs, slashable equivocations
* :mod:`adversity` — adversity tracks: lossy/corrupting gossip,
                     breaker-tripping device faults, byzantine sync
                     peers, mid-run ``kill -9`` + recovery
* :mod:`slo`       — SLO assertions over the live metrics registry
                     (shed rate, sync stalls, breaker transitions, p99
                     import/verify latency, head convergence,
                     finalization advance, never-raise violations)
* :mod:`engine`    — the :class:`ScenarioEngine` run loop: N SimNodes,
                     one seeded RNG, a virtual breaker clock, a JSON
                     report with the seed + fired-fault sequence, and a
                     BENCH_HISTORY ``scenario`` row

Drivers: ``tools/scenario_run.py`` and ``bn --scenario NAME``.
"""

from .engine import ScenarioEngine, run_scenario  # noqa: F401
from .spec import SCENARIOS, ScenarioSpec, parse_scenario_arg  # noqa: F401
from .slo import SLOResult  # noqa: F401
