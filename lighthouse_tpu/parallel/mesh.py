"""Generic mesh / data-parallel utilities — the framework's SPMD toolkit.

The reference's parallelism inventory (SURVEY §2.8) has one distributed
axis that matters on TPU: data-parallel batch sharding with an
associative combine (the rayon chunk-AND-reduce of
block_signature_verifier.rs:396-405).  These helpers are the generic
form used by the crypto multichip path (crypto/bls/jax_backend/
multichip.py) and available to any batched workload (the epoch pipeline
at multi-host registry scale, KZG blob batches):

* ``make_mesh(n)`` — a 1-D device mesh over the first n devices,
* ``batch_spec(ndim, axis_pos)`` — PartitionSpec splitting one axis,
* ``dp_shard_map(fn, mesh)`` — shard_map a local-compute function over
  the batch axis with everything-sharded in / replicated out,
* ``allgather_tree(tree, axis)`` — gather a pytree's trailing axis
  across the mesh (the tiny ICI combine),
* ``and_reduce(ok, axis)`` — the global conjunction,
* ``compat_shard_map`` / ``compat_jit_sharded`` — the jax-version
  compatibility seams every mesh program in the repo routes through.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as PS

BATCH_AXIS = "batch"


# ---------------------------------------------------------------------------
# jax-version compatibility seams
# ---------------------------------------------------------------------------


def compat_shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax versions: top-level ``jax.shard_map`` with
    ``check_vma`` where available, else ``jax.experimental.shard_map``
    with its older ``check_rep`` spelling.  Both flags are the same
    check disabled for the same reason: every Horner/Montgomery scan in
    fp.py initializes its carry from a replicated constant while the
    loop body mixes in batch-varying limbs, which the vma/rep checker
    rejects (see the scan-carry note in multichip.make_verify_sharded —
    correctness is pinned by the shard-vs-single byte-equality tests
    instead)."""
    try:
        from jax import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def compat_jit_sharded(f, in_shardings=None, out_shardings=None, **jit_kw):
    """``jax.jit`` with explicit shardings across the supported jax
    range — the pjit path the rule-driven sharded program compiles
    through (partition.py).  Modern jax spells pjit as
    ``jax.jit(in_shardings=...)``; older releases only accept the
    sharding kwargs on ``jax.experimental.pjit.pjit``.  The guard is a
    real call probe, not a version parse: a jax that *has* the kwargs
    but rejects our values should raise loudly, so only TypeError on
    the jit() call itself (unknown kwarg) falls through."""
    kw = dict(jit_kw)
    if in_shardings is not None:
        kw["in_shardings"] = in_shardings
    if out_shardings is not None:
        kw["out_shardings"] = out_shardings
    try:
        return jax.jit(f, **kw)
    except TypeError:
        from jax.experimental.pjit import pjit

        return pjit(f, **kw)


def make_mesh(n_devices: int | None = None, axis: str = BATCH_AXIS) -> Mesh:
    """1-D mesh over the first n devices (all by default)."""
    devices = jax.devices()
    n = len(devices) if n_devices is None else n_devices
    if len(devices) < n:
        raise RuntimeError(f"need {n} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n]), (axis,))


def batch_spec(ndim: int, axis_pos: int = -1, axis: str = BATCH_AXIS) -> PS:
    """PartitionSpec for an ndim array sharded on one axis; scalars
    (ndim 0) are replicated."""
    if ndim == 0:
        return PS()
    pos = axis_pos % ndim
    return PS(*[axis if i == pos else None for i in range(ndim)])


def allgather_tree(tree, axis: str = BATCH_AXIS):
    """All-gather every leaf's trailing axis across the mesh (tiled) —
    the ICI combine for small per-device partials."""
    return jax.tree.map(
        lambda a: jax.lax.all_gather(a, axis, axis=a.ndim - 1, tiled=True),
        tree,
    )


def ring_reduce(tree, combine, axis: str = BATCH_AXIS):
    """Ring-reduction of a per-device pytree with an arbitrary associative
    ``combine(acc, incoming)`` — the accumulation pattern ring attention
    uses for softmax partials (SURVEY §2.8/§5 "sequence scaling"): N-1
    ppermute hops around the ring, each device folding its neighbour's
    partial into its accumulator; after the loop every device holds the
    full product.  For non-commutative-friendly shapes prefer this over
    all_gather when the partials are large (one hop in flight instead of
    an N-way gather).

    Replication of the result is *proved*, not assumed: jax's own
    check_rep/check_vma cannot see that N-1 uniform-ring hops of a
    commutative fold cover every shard, so the spmd audit family
    (``analysis/spmd_lint.py``, ``ring_reduce_w*`` programs) tracks the
    offset set through the ppermute chain and fails the audit if the
    fold ever comes up a hop short."""
    try:
        n = jax.lax.axis_size(axis)  # static: the mesh extent
    except AttributeError:
        # older jax (<0.5) has no lax.axis_size; psum of a Python
        # literal over a named axis folds to a static int
        n = jax.lax.psum(1, axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop(t):
        return jax.tree.map(
            lambda a: jax.lax.ppermute(a, axis, perm=perm), t
        )

    acc = tree
    incoming = tree
    for _ in range(n - 1):
        incoming = hop(incoming)
        acc = combine(acc, incoming)
    return acc


def and_reduce(ok, axis: str = BATCH_AXIS):
    """Global conjunction of per-device booleans (the AND-reduce of the
    reference's chunked batch verification)."""
    return jnp.all(jax.lax.all_gather(ok, axis))


def dp_shard_map(local_fn, mesh: Mesh, axis: str = BATCH_AXIS,
                 trailing_batch: bool = True):
    """shard_map ``local_fn`` data-parallel: every input pytree leaf is
    split on its TRAILING axis (the framework's batch convention: limb
    arrays are (26, B), bit arrays (64, B)); outputs are replicated —
    local_fn must end with its own collective combine (allgather_tree /
    and_reduce) so every device holds the full result."""

    def spec_for(x):
        return batch_spec(jnp.ndim(x), -1 if trailing_batch else 0, axis)

    def wrapped(*args):
        in_specs = jax.tree.map(spec_for, args)
        return compat_shard_map(
            local_fn,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=PS(),
        )(*args)

    return wrapped
