"""Rule-driven PartitionSpec pytrees: the sharded verification program.

ROADMAP item 2's sharding half.  ``PodVerifier`` (r10) fanned the
single-chip program out by slicing host-marshalled arrays and gathering
verdicts on host; this module turns that into a real SPMD program in the
fmengine/pjit idiom (SNIPPETS.md): the **whole marshalled operand pytree
is governed by one literal regex->spec rule table**, compiled once per
operand structure, with the verdict reduced on-device over ICI so only a
``(width,)`` bool vector ever returns to host.

The pieces, bottom-up:

* **leaf naming** — ``named_operand_leaves`` walks a marshalled operand
  tuple (``MarshalledBatch.args``) and names every array leaf with a
  stable ``/``-joined path (``pk/x/limbs``, ``sig/y/c1/limbs``,
  ``wbits``, …).  The canonical inventory is the literal
  ``OPERAND_LEAVES`` tuple, machine-checked against the live marshal
  output and against the rule table by the ``partition-rules`` lint.
* **rule matching** — ``match_partition_rules`` maps each leaf name to a
  spec token by first-``re.search``-match over the literal
  ``PARTITION_RULES`` table (scalars replicate; an unmatched leaf is an
  error, exactly the exemplar's contract).  Tokens, not raw specs, keep
  the table AST-parseable: ``batch`` splits the trailing batch axis,
  ``registry`` splits the validator axis of the pubkey registry mirror,
  ``replicated`` pins small constants everywhere.
* **shard/gather fns** — ``make_shard_and_gather_fns`` closes a
  per-leaf ``jax.device_put``-with-``NamedSharding`` (H2D is async, so
  placing shard k+1 overlaps compute of shard k) and the matching
  host-gather.
* **the program** — :class:`ShardedVerifyProgram` wraps the backend's
  *local* verify kernel in ``compat_shard_map`` with the rule-derived
  ``in_specs`` and jits it through ``compat_jit_sharded`` (the pjit
  path) with the matching ``in_shardings``.  Each device verifies its
  batch columns; ``all_gather`` of the per-shard conjunction yields the
  replicated verdict vector — one bool per shard crosses ICI, nothing
  else returns to host.
* **partitioned-registry gather** — in registry mode the pubkey operand
  never exists on host: the program takes the mesh-sharded ``(26, n)``
  registry mirror (``PubkeyLimbCache.registry_device_sharded``) plus a
  ``(B,)`` slot vector, and each device reconstructs the batch's pubkey
  columns with a masked local ``jnp.take`` + ``psum`` — ICI cost is one
  ``(26, B)`` reduction (B ~ 10^3) instead of replicating the
  26 x n_validators mirror (104 MB at mainnet's ~1M keys) on every
  device.
* **epoch streaming** — :func:`stream_epoch` drives an iterator of set
  chunks through the program double-buffered: chunk k+1 is marshalled
  and its H2D enqueued while chunk k's verdict vector is still in
  flight, so a mainnet epoch crosses the mesh without the full operand
  pytree ever materializing on one host.

This module is deliberately field-stack-free (like pod.py): the kernel
and the LFp wrapper for registry gathers are injected by the backend, so
the partition logic is testable with stub kernels and no compiles.
"""

from __future__ import annotations

import re
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Iterator

import numpy as np

from .mesh import BATCH_AXIS, compat_jit_sharded, compat_shard_map

AXIS = BATCH_AXIS


# ---------------------------------------------------------------------------
# The rule table (literal on purpose: the partition-rules lint AST-parses it)
# ---------------------------------------------------------------------------

# token -> PartitionSpec factory (ndim, axis).  Keys are the vocabulary
# the rule table may use; the lint cross-checks every rule's token
# against these keys.
SPEC_TOKENS = {
    "batch": lambda ndim, axis: _ps(*([None] * (ndim - 1)), axis),
    "registry": lambda ndim, axis: _ps(None, axis),
    "replicated": lambda ndim, axis: _ps(),
}

# First-re.search-match-wins, top to bottom.  Every live operand leaf
# must be claimed by exactly one rule (orphans and dead/shadowed rules
# are lint findings):
#   registry/(x|y)  the (26, n_validators) pubkey mirror — split on the
#                   VALIDATOR axis, the one operand that must never be
#                   replicated (26 x 1M x 4 B = 104 MB/device otherwise)
#   slots           (B,) validator-slot vector — batch-sharded like the
#                   work it indexes
#   wbits           (64, B) random-weight bit planes — batch-sharded
#   .../limbs       every field-element limb plane (pk/sig/h/u0/u1
#                   coordinates, (26, B)) — batch-sharded
PARTITION_RULES = (
    (r"^registry/(x|y)$", "registry"),
    (r"^slots$", "batch"),
    (r"^wbits$", "batch"),
    (r"/limbs$", "batch"),
)

# Canonical operand-leaf inventory across every program mode (h2c /
# host-h2c / partitioned-registry).  The runtime test binds this to the
# live marshal output; the lint proves rule-table coverage over it.
OPERAND_LEAVES = (
    "pk/x/limbs",
    "pk/y/limbs",
    "sig/x/c0/limbs",
    "sig/x/c1/limbs",
    "sig/y/c0/limbs",
    "sig/y/c1/limbs",
    "h/x/c0/limbs",
    "h/x/c1/limbs",
    "h/y/c0/limbs",
    "h/y/c1/limbs",
    "u0/c0/limbs",
    "u0/c1/limbs",
    "u1/c0/limbs",
    "u1/c1/limbs",
    "wbits",
    "registry/x",
    "registry/y",
    "slots",
)


def _ps(*parts):
    from jax.sharding import PartitionSpec as PS

    return PS(*parts)


def mesh_width(mesh) -> int:
    """Device count of a mesh via the axis-size product, so device-less
    tracing meshes (``jax.sharding.AbstractMesh``, which the spmd lint
    stages programs over) work the same as real ones."""
    shape = getattr(mesh, "shape", None)
    if shape:
        width = 1
        for n in shape.values():
            width *= int(n)
        return width
    return int(mesh.devices.size)


# ---------------------------------------------------------------------------
# Operand naming: marshalled tuple -> (name, leaf) pairs
# ---------------------------------------------------------------------------

# nested-tuple labels per top-level operand, by depth: G1 points are
# (x, y) coordinate pairs, G2/fp2 values nest (c0, c1) components.
_NEST_LABELS = {
    "pk": (("x", "y"),),
    "sig": (("x", "y"), ("c0", "c1")),
    "h": (("x", "y"), ("c0", "c1")),
    "u0": (("c0", "c1"),),
    "u1": (("c0", "c1"),),
}

# positional -> semantic top names, keyed by (deferred_pk, len(args)).
# Deferred-pk tuples are registry mode: the pubkey operand is gathered
# inside the program from the partitioned registry, so args skip it.
_TOP_NAMES = {
    (False, 5): ("pk", "sig", "u0", "u1", "wbits"),
    (False, 4): ("pk", "sig", "h", "wbits"),
    (True, 4): ("sig", "u0", "u1", "wbits"),
    (True, 3): ("sig", "h", "wbits"),
}


def _is_lfp(x) -> bool:
    return hasattr(x, "limbs") and hasattr(x, "bound")


def _walk(top: str, x, depth: int, prefix: str, out: list) -> None:
    if _is_lfp(x):
        out.append((prefix + "/limbs", x.limbs))
    elif isinstance(x, (tuple, list)):
        levels = _NEST_LABELS.get(top, ())
        labels = (levels[depth] if depth < len(levels)
                  else tuple(str(i) for i in range(len(x))))
        for lbl, e in zip(labels, x):
            _walk(top, e, depth + 1, prefix + "/" + lbl, out)
    else:
        out.append((prefix, x))


def named_operand_leaves(args, *, deferred_pk: bool = False) -> list:
    """``[(leaf_name, array)]`` in flatten order for a marshalled
    operand tuple (``MarshalledBatch.args``)."""
    key = (bool(deferred_pk), len(args))
    tops = _TOP_NAMES.get(key)
    if tops is None:
        raise ValueError(f"unrecognized operand tuple shape: {key}")
    out: list = []
    for top, a in zip(tops, args):
        _walk(top, a, 0, top, out)
    return out


# ---------------------------------------------------------------------------
# Rule matching + shard/gather fns (the SNIPPETS.md exemplar contract)
# ---------------------------------------------------------------------------


def match_partition_rules(rules, named_leaves, axis: str = AXIS) -> list:
    """Leaf name -> PartitionSpec by first-``re.search``-match over
    ``rules``; scalar/singleton leaves replicate; an unmatched leaf is a
    hard error (a silent replication default would hide exactly the
    104 MB registry mistake the table exists to prevent)."""
    specs = []
    for name, leaf in named_leaves:
        ndim = int(np.ndim(leaf))
        if ndim == 0 or int(np.size(leaf)) == 1:
            specs.append(_ps())
            continue
        for rule, token in rules:
            if re.search(rule, name) is not None:
                specs.append(SPEC_TOKENS[token](ndim, axis))
                break
        else:
            raise ValueError(f"partition rule not found for operand "
                             f"leaf: {name}")
    return specs


def operand_partition_specs(args, *, deferred_pk: bool = False,
                            rules=PARTITION_RULES, axis: str = AXIS):
    """The rule-matched spec pytree for a marshalled operand tuple —
    same container structure as ``args`` with one PartitionSpec per
    array/LFp node (a valid shard_map in_specs / jit in_shardings
    prefix tree)."""
    flat = match_partition_rules(
        rules, named_operand_leaves(args, deferred_pk=deferred_pk), axis
    )
    it = iter(flat)

    def rebuild(x):
        if _is_lfp(x) or not isinstance(x, (tuple, list)):
            return next(it)
        return tuple(rebuild(e) for e in x)

    return tuple(rebuild(a) for a in args)


def _map_specs(fn, tree):
    """Map over a spec tree treating PartitionSpec as a leaf (PS
    subclasses tuple, so jax.tree.map would descend into it)."""
    from jax.sharding import PartitionSpec as PS

    if isinstance(tree, PS):
        return fn(tree)
    if isinstance(tree, (tuple, list)):
        return tuple(_map_specs(fn, t) for t in tree)
    return fn(tree)


def tree_apply(fns, tree):
    """Apply a same-structure tree of per-node callables to an operand
    tree (callables sit at LFp/array positions)."""
    if callable(fns):
        return fns(tree)
    return tuple(tree_apply(f, t) for f, t in zip(fns, tree))


def make_shard_and_gather_fns(specs, mesh):
    """Per-leaf (shard_fn, gather_fn) trees from a spec tree.

    ``shard_fn`` is ``jax.device_put`` onto the spec's NamedSharding —
    async, so placing the next batch overlaps the current kernel;
    ``gather_fn`` pulls the leaf back to host numpy.  LFp nodes shard
    their limb plane and keep the static bound."""
    import jax
    from jax.sharding import NamedSharding

    def mk_shard(spec):
        sharding = NamedSharding(mesh, spec)

        def shard_fn(x):
            if _is_lfp(x):
                return type(x)(jax.device_put(x.limbs, sharding), x.bound)
            return jax.device_put(x, sharding)

        return shard_fn

    def mk_gather(spec):
        def gather_fn(x):
            if _is_lfp(x):
                return type(x)(jax.device_get(x.limbs), x.bound)
            return jax.device_get(x)

        return gather_fn

    return _map_specs(mk_shard, specs), _map_specs(mk_gather, specs)


# ---------------------------------------------------------------------------
# Padding (dup-of-column-0, the backend marshal contract: AND-safe)
# ---------------------------------------------------------------------------


def _trailing_extent(args) -> int:
    import jax

    return int(jax.tree.leaves(args)[0].shape[-1])


def _pad_tail(args, pad: int):
    import jax
    import jax.numpy as jnp

    if pad <= 0:
        return args
    return jax.tree.map(
        lambda a: jnp.concatenate(
            [a, jnp.repeat(a[..., :1], pad, axis=-1)], axis=-1
        ),
        args,
    )


def _pad_slots(slots, pad: int):
    """Pad a (B,) slot vector with duplicates of slot 0 — the same
    dup-of-column-0 contract as the operand columns, so a pad lane's
    gathered pubkey matches its (duplicated) operand column."""
    import jax.numpy as jnp

    slots = jnp.asarray(slots)
    if pad <= 0:
        return slots
    return jnp.concatenate([slots, jnp.repeat(slots[:1], pad)])


# ---------------------------------------------------------------------------
# The sharded program
# ---------------------------------------------------------------------------


class ShardedVerifyProgram:
    """One mesh-wide SPMD verify program, rule-partitioned end to end.

    ``local_verify_fn(*args) -> bool`` is the backend's *unjitted*
    kernel (``JaxBackend.local_verify_fn()``); each device runs it on
    its rule-sharded batch columns and the per-shard conjunctions
    all_gather into the replicated ``(width,)`` verdict vector — the
    only thing that returns to host.  A False at index i condemns only
    shard i's column range (``shard_bounds``), which is what lets the
    pod re-verify a failing shard's sets instead of the whole batch.

    ``pk_wrap(x, y) -> pk_operand`` (``JaxBackend.registry_pk_wrap``)
    is required for registry mode only: it wraps the psum-gathered limb
    planes for the kernel without this module importing the field
    stack.

    Stage methods (``pad_operands`` / ``shard_operands`` / ``execute``
    / ``resolve``) are exposed separately so the bench harness can
    attribute H2D vs compute vs gather, and so the epoch driver can
    double-buffer: every stage is async until ``resolve``.
    """

    def __init__(self, mesh, local_verify_fn, *, axis: str = AXIS,
                 pk_wrap: Callable | None = None, rules=PARTITION_RULES):
        self.mesh = mesh
        self.axis = axis
        self.local_verify_fn = local_verify_fn
        self.pk_wrap = pk_wrap
        self.rules = rules
        self.width = mesh_width(mesh)
        self._programs: dict = {}

    # -- stages -------------------------------------------------------------

    def pad_operands(self, args):
        """Pad the trailing batch axis up to a width multiple with
        duplicates of column 0 (AND-safe per the marshal contract)."""
        return _pad_tail(args, (-_trailing_extent(args)) % self.width)

    def shard_operands(self, args, *, deferred_pk: bool = False):
        """Rule-shard the operand tree onto the mesh (async H2D)."""
        specs = operand_partition_specs(
            args, deferred_pk=deferred_pk, rules=self.rules, axis=self.axis
        )
        shard_fns, _ = make_shard_and_gather_fns(specs, self.mesh)
        return tree_apply(shard_fns, args)

    def execute(self, args):
        """Enqueue the sharded program (async); operands must already
        be padded.  Returns the in-flight (width,) verdict vector."""
        return self._program(args, deferred_pk=False)(*args)

    def execute_registry(self, registry, slots, rest_args):
        """Registry mode: ``registry`` is the mesh-sharded (x, y) limb
        mirror, ``slots`` the (B,) validator-slot vector, ``rest_args``
        the marshalled operands *without* the pubkey operand."""
        if self.pk_wrap is None:
            raise ValueError("registry mode needs pk_wrap")
        reg_x, reg_y = registry
        args = (reg_x, reg_y, slots) + tuple(rest_args)
        return self._program(args, deferred_pk=True)(*args)

    @staticmethod
    def resolve(handle) -> np.ndarray:
        """Block on an in-flight verdict vector -> (width,) host bools."""
        import jax

        return np.asarray(jax.device_get(handle)).astype(bool)

    # -- one-shot conveniences ---------------------------------------------

    def dispatch(self, args):
        """pad -> shard -> execute (async), one call."""
        return self.execute(self.shard_operands(self.pad_operands(args)))

    def dispatch_registry(self, registry, slots, rest_args):
        """pad -> shard -> execute_registry (async), one call — slots
        pad with duplicates of slot 0, matching the operand columns."""
        slots = _pad_slots(slots, (-int(np.shape(slots)[0])) % self.width)
        rest = self.pad_operands(tuple(rest_args))
        slots, rest = self._shard_registry_inputs(slots, rest)
        return self.execute_registry(registry, slots, rest)

    def verdict_vector(self, args) -> np.ndarray:
        return self.resolve(self.dispatch(args))

    def verdict_vector_registry(self, registry, slots, rest_args
                                ) -> np.ndarray:
        return self.resolve(self.dispatch_registry(registry, slots,
                                                   rest_args))

    def _shard_registry_inputs(self, slots, rest):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        slots = jax.device_put(
            jnp.asarray(slots, dtype=jnp.int32),
            NamedSharding(self.mesh, _ps(self.axis)),
        )
        return slots, self.shard_operands(rest, deferred_pk=True)

    def shard_bounds(self, total: int) -> tuple:
        """Per-shard [a, b) column ranges over a batch of ``total``
        columns (before padding): shard i's verdict covers exactly the
        sets whose padded column index falls in its range."""
        padded = total + ((-total) % self.width)
        size = padded // self.width
        return tuple(
            (min(i * size, total), min((i + 1) * size, total))
            for i in range(self.width)
        )

    # -- program construction ----------------------------------------------

    def _program(self, args, *, deferred_pk: bool):
        names = tuple(
            n for n, _ in named_operand_leaves(
                self._semantic_args(args, deferred_pk),
                deferred_pk=deferred_pk)
        )
        key = (deferred_pk, names)
        prog = self._programs.get(key)
        if prog is None:
            prog = self._build(args, deferred_pk)
            self._programs[key] = prog
        return prog

    @staticmethod
    def _semantic_args(args, deferred_pk: bool):
        # registry-mode calls carry (reg_x, reg_y, slots) ahead of the
        # marshalled rest; naming applies to the marshalled part
        return args[3:] if deferred_pk else args

    def _build(self, args, deferred_pk: bool):
        from jax.sharding import NamedSharding

        in_specs = program_in_specs(
            self._semantic_args(args, deferred_pk),
            deferred_pk=deferred_pk, rules=self.rules, axis=self.axis,
        )
        local = staged_local(
            self.local_verify_fn, axis=self.axis, deferred_pk=deferred_pk,
            pk_wrap=self.pk_wrap,
        )
        sharded = compat_shard_map(
            local, self.mesh, in_specs=in_specs, out_specs=_ps()
        )
        shardings = _map_specs(
            lambda s: NamedSharding(self.mesh, s), in_specs
        )
        # the pjit path: explicit in_shardings pin the rule table's
        # placement so pre-sharded operands are never silently resharded
        return compat_jit_sharded(sharded, in_shardings=shardings)


def program_in_specs(semantic_args, *, deferred_pk: bool,
                     rules=PARTITION_RULES, axis: str = AXIS):
    """The staged program's full in_specs tree: the rule-matched specs
    for the marshalled operands, prefixed in registry mode by the
    registry-mirror and slot-vector specs.  Shared by ``_build`` and by
    the spmd lint, which re-stages the same program over an abstract
    mesh — one constructor, one proof surface."""
    rest_specs = operand_partition_specs(
        semantic_args, deferred_pk=deferred_pk, rules=rules, axis=axis,
    )
    if deferred_pk:
        return (SPEC_TOKENS["registry"](2, axis),
                SPEC_TOKENS["registry"](2, axis),
                SPEC_TOKENS["batch"](1, axis)) + rest_specs
    return rest_specs


def staged_local(fn, *, axis: str = AXIS, deferred_pk: bool = False,
                 pk_wrap: Callable | None = None):
    """The per-device body of the staged program: registry gather (in
    deferred-pk mode), the local kernel, then the verdict all_gather.
    This is the exact callable ``_build`` wraps in ``compat_shard_map``
    — the spmd lint traces it rather than a paraphrase."""
    import jax
    import jax.numpy as jnp

    if deferred_pk:
        if pk_wrap is None:
            raise ValueError("registry mode needs pk_wrap")

        def local(reg_x, reg_y, slots, *rest):
            x, y = _registry_gather_local(reg_x, reg_y, slots, axis)
            ok = fn(pk_wrap(x, y), *rest)
            return jax.lax.all_gather(jnp.reshape(ok, ()), axis)
    else:
        def local(*a):
            ok = fn(*a)
            return jax.lax.all_gather(jnp.reshape(ok, ()), axis)
    return local


def _registry_gather_local(reg_x, reg_y, slots_local, axis: str):
    """Per-device piece of the partitioned-registry gather.

    Every device holds a contiguous validator-axis shard of the (26, n)
    registry mirror and a batch shard of the slot vector.  The (B,)
    slot vector all_gathers (tiny), each device takes the columns it
    owns (out-of-shard slots masked to zero), and one psum reconstructs
    the full (26, B) pubkey planes replicated — ICI cost O(26*B) versus
    O(26*n_validators) per device for a replicated mirror.  Each device
    then slices back down to its own batch columns for the kernel.
    """
    import jax
    import jax.numpy as jnp

    idx = jax.lax.axis_index(axis)
    n_local = reg_x.shape[1]
    base = (idx * n_local).astype(jnp.int32)
    slots_all = jax.lax.all_gather(slots_local, axis, tiled=True)  # (B,)
    rel = slots_all.astype(jnp.int32) - base
    hit = (rel >= 0) & (rel < n_local)
    safe = jnp.where(hit, rel, 0)
    mask = hit.astype(reg_x.dtype)
    x = jax.lax.psum(jnp.take(reg_x, safe, axis=1) * mask, axis)
    y = jax.lax.psum(jnp.take(reg_y, safe, axis=1) * mask, axis)
    b_local = slots_local.shape[0]
    start = idx * b_local
    x = jax.lax.dynamic_slice_in_dim(x, start, b_local, axis=1)
    y = jax.lax.dynamic_slice_in_dim(y, start, b_local, axis=1)
    return x, y


# ---------------------------------------------------------------------------
# Epoch streaming: double-buffered chunks through the program
# ---------------------------------------------------------------------------


@dataclass
class EpochChunkResult:
    """Verdict for one streamed chunk: ``verdicts`` is the (width,)
    per-shard vector (None when marshal rejected the chunk), ``ok`` the
    chunk conjunction."""

    index: int
    n: int
    verdicts: Any
    ok: bool


def stream_epoch(chunks: Iterable, marshal: Callable,
                 program: ShardedVerifyProgram, *,
                 registry: Any = None, inflight: int = 2,
                 ) -> Iterator[EpochChunkResult]:
    """Stream set chunks through the sharded program, double-buffered.

    ``chunks`` yields lists of signature sets (an epoch's attestations
    in committee-sized bites); ``marshal`` maps one chunk to a
    ``MarshalledBatch``.  Chunk k+1 is marshalled and its (async) H2D +
    program enqueued while chunk k's verdict vector is still in flight,
    overlapping host marshal and transfer with device compute; at most
    ``inflight`` chunks' operands are live at once, so the peak host
    footprint is O(chunk), never O(epoch) — the property the
    peak-host-memory test pins.

    ``registry`` (the mesh-sharded mirror from
    ``PubkeyLimbCache.registry_device_sharded``) activates the
    partitioned-registry path for chunks whose marshal deferred the
    pubkey operand (``mb.slots is not None``).

    Yields :class:`EpochChunkResult` in chunk order.
    """
    inflight = max(1, int(inflight))
    pending: deque = deque()

    def finish(entry) -> EpochChunkResult:
        index, n, handle = entry
        if handle is None:
            return EpochChunkResult(index, n, None, False)
        v = program.resolve(handle)
        return EpochChunkResult(index, n, v, bool(v.all()))

    for index, chunk in enumerate(chunks):
        n = len(chunk)
        mb = marshal(chunk)
        if mb is None or getattr(mb, "invalid", False):
            pending.append((index, n, None))
        elif getattr(mb, "slots", None) is not None and registry is not None:
            pending.append((index, n, program.dispatch_registry(
                registry, mb.slots, mb.args)))
        else:
            pending.append((index, n, program.dispatch(tuple(mb.args))))
        # mb drops out of scope here: the host copy of a dispatched
        # chunk is freed as soon as its device buffers are enqueued
        del mb
        while len(pending) >= inflight:
            yield finish(pending.popleft())
    while pending:
        yield finish(pending.popleft())
