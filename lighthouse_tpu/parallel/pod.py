"""Pod-scale verification service: per-shard fault domains on an N-device mesh.

ROADMAP item 2's serving half: :class:`PodVerifier` data-parallel-shards a
marshalled signature batch across the visible devices and keeps the
never-drop-a-batch contract of the single-device ladder while any subset
of the mesh fails underneath it.  Each shard is its own fault domain —
one hung or dying device costs retries and (past a threshold) its mesh
seat, never the batch:

* **sharded program first** (r14) — when the backend exposes its raw
  kernel (``local_verify_fn``), the batch runs as ONE rule-partitioned
  SPMD program (:mod:`.partition`): the operand pytree is device_put
  straight onto its PartitionSpec shardings, each device verifies its
  batch columns, and the per-shard conjunctions all_gather into a
  ``(width,)`` verdict vector on-device — no host gather loop, no
  per-shard thread, and a slot-mode batch (``mb.slots``) reads its
  pubkey operand from the mesh-partitioned registry mirror instead of
  carrying it over H2D.  A False verdict condemns only that shard's set
  range, so the ladder re-verifies a 1/width slice instead of the whole
  batch.  Any failure of the program (device loss, compile trouble, a
  hang past the sharded deadline) falls back to the per-device
  coordinator below, which still owns health scoring and re-shard.
* **shard planner** — contiguous trailing-axis slices of the marshalled
  batch, one per device.  The mesh width is always a power of two
  (8→4→2→1), so with the backend's power-of-two padded batches every
  shard width is itself a power of two and the per-width programs stay
  inside the existing ≤6-program dispatch budget.
* **per-shard dispatch** — one thread per shard places its slice on its
  device and runs the width-sized program; the coordinator enforces a
  per-shard timeout (a hung device leaks its daemon thread exactly like
  a hung XLA call would) and retries failed shards with exponential
  backoff on the same device.
* **device health** — consecutive-failure scoring per device
  (:class:`DeviceHealth`, the PeerManager idiom): a device that keeps
  failing is excluded, the batch re-shards onto the surviving mesh, and
  an excluded device is re-armed after a later probe shard succeeds.
* **degradation ladder** — pod → reduced mesh → single-device
  :class:`~..beacon.processor.ResilientVerifier` → CPU.  The pod shares
  the resilient verifier's CircuitBreaker (mesh exhaustion is a breaker
  failure; a completed round is a success) and its ``verify_batch`` is
  registered in ``DEFAULT_NEVER_RAISE`` and proven by the never-raise
  prover.

Correctness: the pod only ever short-circuits the all-valid case.  A
completed round whose conjunction is True returns all-True verdicts —
identical to the single-device oracle, because every shard's padding
columns are valid duplicates (the backend marshal contract).  Any shard
verdict of False, any marshal failure, and any mesh exhaustion hand the
*original* sets to ``resilient.verify_batch`` for the unchanged
bisection/CPU ladder, so per-set verdicts are byte-identical to the
oracle under every injected fault.  (A device lying True-for-False is
outside the model, exactly as on the single-device path.)

Chaos: ``pod.dispatch`` fires inside each shard attempt (``shard-drop``
kills the shard, ``device-hang:<s>`` hangs it past the timeout) and
``pod.gather`` fires on the verdict coming back
(``corrupt-shard-result`` inverts it).  Everything is testable on CPU
via ``XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

from ..beacon.processor import BatchOutcome
from ..obs.tracer import TRACER
from ..utils import metrics as M
from ..utils.logging import get_logger

log = get_logger("parallel.pod")


# ---------------------------------------------------------------------------
# Shard planning
# ---------------------------------------------------------------------------


def mesh_width(n_devices: int) -> int:
    """Largest power-of-two mesh that fits on ``n_devices`` (0 when none
    survive) — the 8→4→2→1 degradation ladder's rung selector."""
    if n_devices < 1:
        return 0
    w = 1
    while w * 2 <= n_devices:
        w *= 2
    return w


@dataclass(frozen=True)
class ShardPlan:
    """Contiguous [a, b) ranges over the batch axis, one per shard."""

    shards: int
    bounds: tuple[tuple[int, int], ...]


def plan_shards(total: int, shards: int) -> ShardPlan:
    """Split [0, total) into ``shards`` contiguous near-even ranges.

    With the backend's power-of-two padded batch and a power-of-two mesh
    the ranges are exactly even (and themselves power-of-two wide, which
    is what keeps the per-width program count bounded); ragged totals
    only occur in list-sharding mode, where width is unconstrained.
    Ranges may be empty when ``shards > total`` — callers skip those.
    """
    base, extra = divmod(total, shards)
    bounds = []
    a = 0
    for i in range(shards):
        b = a + base + (1 if i < extra else 0)
        bounds.append((a, b))
        a = b
    return ShardPlan(shards=shards, bounds=tuple(bounds))


def _slice_tree(x, a: int, b: int):
    """Slice the trailing axis of a marshalled-operand tree: LFp-shaped
    leaves (``.limbs``/``.bound``), bare arrays, and nested tuples — the
    same shape contract as the backend's batch slicer, kept local so the
    pod layer does not import the field stack."""
    if hasattr(x, "limbs"):
        return type(x)(x.limbs[..., a:b], x.bound)
    if hasattr(x, "shape"):
        return x[..., a:b]
    if isinstance(x, (tuple, list)):
        return type(x)(_slice_tree(e, a, b) for e in x)
    return x


# ---------------------------------------------------------------------------
# Device health: consecutive-failure scoring, exclusion, probe re-arm
# ---------------------------------------------------------------------------


class DeviceHealth:
    """Per-device consecutive-failure scores (the PeerManager idiom).

    ``exclusion_threshold`` consecutive shard failures pull a device out
    of the mesh; after ``probe_after`` subsequent batches the device
    becomes probe-eligible and a successful probe shard re-arms it.  The
    cooldown is counted in verify_batch calls, not wall time, so tests
    are deterministic without sleeping.
    """

    def __init__(self, n_devices: int, exclusion_threshold: int = 2,
                 probe_after: int = 2):
        self.exclusion_threshold = max(1, exclusion_threshold)
        self.probe_after = max(1, probe_after)
        self._lock = threading.Lock()
        self._failures = [0] * n_devices
        self._excluded: dict[int, int] = {}  # device index -> cooldown left

    def healthy(self) -> list[int]:
        with self._lock:
            return [i for i in range(len(self._failures))
                    if i not in self._excluded]

    def excluded(self) -> list[int]:
        with self._lock:
            return sorted(self._excluded)

    def record_success(self, dev: int) -> None:
        with self._lock:
            self._failures[dev] = 0

    def record_failure(self, dev: int) -> bool:
        """Score one shard failure; True when it crossed the threshold
        and the device was excluded just now."""
        with self._lock:
            if dev in self._excluded:
                return False
            self._failures[dev] += 1
            if self._failures[dev] >= self.exclusion_threshold:
                self._excluded[dev] = self.probe_after
                return True
            return False

    def exclude(self, dev: int) -> bool:
        """Force-exclude (retry budget exhausted); True when newly
        excluded."""
        with self._lock:
            if dev in self._excluded:
                return False
            self._excluded[dev] = self.probe_after
            return True

    def tick(self) -> None:
        """One verify_batch elapsed: age every exclusion cooldown."""
        with self._lock:
            for dev in self._excluded:
                if self._excluded[dev] > 0:
                    self._excluded[dev] -= 1

    def probe_ready(self) -> list[int]:
        with self._lock:
            return sorted(d for d, cd in self._excluded.items() if cd <= 0)

    def defer_probe(self, dev: int) -> None:
        """Failed probe: restart the cooldown."""
        with self._lock:
            if dev in self._excluded:
                self._excluded[dev] = self.probe_after

    def rearm(self, dev: int) -> None:
        with self._lock:
            self._excluded.pop(dev, None)
            self._failures[dev] = 0


# ---------------------------------------------------------------------------
# PodVerifier
# ---------------------------------------------------------------------------


@dataclass
class _PodJob:
    """One batch prepared for sharding: the original sets plus (backend
    mode) the marshalled batch whose trailing axis is the shard axis."""

    sets: list
    mb: Any = None
    total: int = 0


class PodVerifier:
    """Data-parallel batch verification over an N-device mesh with
    per-shard fault domains and the full degradation ladder underneath.

    Two dispatch modes share one coordinator:

    * **backend mode** — ``marshal(sets)`` produces a
      ``MarshalledBatch``; each shard slices the operand tree, places it
      on its own device (``jax.device_put``) and runs the backend's
      width-sized program.  This is the serving configuration.
    * **list mode** — ``shard_verify(sub_sets) -> bool`` is called per
      shard on a contiguous sublist.  The scenario harness and the CPU
      chaos tests ride this one: same planner, same fault domains, same
      ladder, no kernel compiles.

    Drop-in for every ``verify_batch`` consumer (SyncManager,
    BeaconNode, the scenario engine) and for ``PipelinedVerifier``'s
    ``resilient`` slot — ``breaker`` and ``journal`` pass through to the
    wrapped :class:`ResilientVerifier`.
    """

    def __init__(
        self,
        resilient,
        backend=None,
        marshal: Callable[[list], Any] | None = None,
        shard_verify: Callable[[list], bool] | None = None,
        devices: list | None = None,
        shard_timeout: float = 2.0,
        max_shard_retries: int = 2,
        backoff_base: float = 0.02,
        exclusion_threshold: int = 2,
        probe_after: int = 2,
        max_rounds: int = 6,
        injector=None,
        sharded: bool = True,
        sharded_marshal: Callable[[list], Any] | None = None,
        registry_provider: Callable | None = None,
        sharded_timeout: float | None = None,
    ):
        if backend is None and shard_verify is None:
            raise ValueError(
                "PodVerifier needs a backend (device mode) or a "
                "shard_verify callable (list mode)"
            )
        self.resilient = resilient
        self.backend = backend
        self.marshal = (
            marshal if marshal is not None
            else getattr(backend, "marshal_sets", None)
        )
        self.shard_verify = shard_verify
        # sharded-program fast path (parallel/partition.py): on unless
        # disabled, engaged only when the backend exposes its raw
        # kernel.  sharded_marshal may defer the pubkey operand to the
        # partitioned registry (mb.slots); registry_provider maps a
        # mesh to that sharded mirror.
        self.sharded = sharded
        self.sharded_marshal = sharded_marshal
        self.registry_provider = registry_provider
        self.sharded_timeout = (
            sharded_timeout if sharded_timeout is not None
            else 4.0 * shard_timeout
        )
        self._sharded_programs: dict = {}
        self.shard_timeout = shard_timeout
        self.max_shard_retries = max(0, max_shard_retries)
        self.backoff_base = backoff_base
        self.exclusion_threshold = exclusion_threshold
        self.probe_after = probe_after
        self.max_rounds = max(1, max_rounds)
        if injector is None:
            from ..utils import faults as _faults

            injector = _faults.INJECTOR
        self.injector = injector
        self._devices = list(devices) if devices is not None else None
        self.health: DeviceHealth | None = None
        self._health_lock = threading.Lock()
        #: attached IntegrityGuard (integrity/guard.py), wired by
        #: ``guard.attach_pod``: supplies canary batches for per-device
        #: probes and receives readmission notifications
        self.integrity = None

    # -- drop-in ladder surface (PipelinedVerifier's resilient slot) -------

    @property
    def breaker(self):
        return self.resilient.breaker

    @property
    def journal(self):
        return self.resilient.journal

    @classmethod
    def maybe_build(cls, resilient, backend=None, marshal=None, **kw):
        """A :class:`PodVerifier` when more than one device is visible
        and the backend exposes the shard surface, else None.  Never
        raises — pod wiring is strictly opportunistic."""
        try:
            import jax

            devices = list(jax.devices())
            if len(devices) < 2 or backend is None:
                return None
            if not hasattr(backend, "_kernel"):
                return None
            if marshal is None:
                marshal = getattr(backend, "marshal_sets", None)
            if marshal is None:
                return None
            return cls(resilient, backend=backend, marshal=marshal,
                       devices=devices, **kw)
        except Exception as exc:  # noqa: BLE001 — opportunistic wiring
            log.warning("pod wiring unavailable: %s", exc)
            return None

    def devices(self) -> list:
        if self._devices is None:
            import jax

            self._devices = list(jax.devices())
        return self._devices

    # -- entry point (registered in DEFAULT_NEVER_RAISE) -------------------

    def verify_batch(self, sets: list) -> BatchOutcome:
        sets = list(sets)
        if not sets:
            return BatchOutcome(verdicts=[], device_calls=0)
        try:
            from ..utils.metrics import VERIFY_BATCH_LATENCY

            with VERIFY_BATCH_LATENCY.timer(), TRACER.span(
                    "verify.batch", sets=len(sets)):
                return self._pod_verify(sets)
        except Exception as exc:  # noqa: BLE001 — never-raise backstop
            # The pod coordinator already absorbs shard faults and the
            # ladder below it absorbs device faults; this catches a bug
            # in the coordinator itself.  Fail closed, same contract as
            # the single-device ladder.
            log.error("pod verify_batch backstop caught %s: %s",
                      type(exc).__name__, exc)
            return BatchOutcome(verdicts=[False] * len(sets), device_calls=0)

    # -- coordinator --------------------------------------------------------

    def _ensure_health(self) -> DeviceHealth:
        with self._health_lock:
            if self.health is None:
                self.health = DeviceHealth(
                    len(self.devices()),
                    exclusion_threshold=self.exclusion_threshold,
                    probe_after=self.probe_after,
                )
            return self.health

    def _ladder(self, sets: list) -> BatchOutcome:
        M.POD_FALLBACKS.inc()
        return self.resilient.verify_batch(sets)

    # -- integrity surface (integrity/guard.py) -----------------------------

    def healthy_devices(self) -> list[int]:
        """Device indices currently in the mesh (guard attribution sweep)."""
        return list(self._ensure_health().healthy())

    def quarantine(self, dev: int) -> bool:
        """Force ``dev`` out of the mesh on an integrity strike.  True
        when this call newly excluded it.  Readmission goes through the
        canary-only probe in :meth:`_probe_excluded` like any exclusion."""
        if self._ensure_health().exclude(dev):
            M.POD_EXCLUSIONS.inc()
            return True
        return False

    def device_canary_probe(self, dev: int) -> bool:
        """Canary-only probe batch on one device: every known-answer
        verdict must match.  Used for SDC attribution (naming the lying
        device) and as the readmission gate for quarantined devices.
        Requires an attached guard; raises propagate to the caller's
        probe fault domain."""
        guard = self.integrity
        if guard is None:
            return True
        for canary_sets, expected in guard.canary_batches():
            job = self._prepare_canary(canary_sets)
            if job is None:
                return False
            got = self._run_shard(job, dev, 0, job.total)
            if bool(got) != expected:
                return False
        return True

    def _prepare_canary(self, canary_sets: list) -> _PodJob | None:
        if self.shard_verify is not None:
            return _PodJob(sets=list(canary_sets), total=len(canary_sets))
        return self._prepare_plain(list(canary_sets))

    def _pod_verify(self, sets: list) -> BatchOutcome:
        health = self._ensure_health()
        health.tick()
        # one breaker gate per batch, shared with the single-device path:
        # while OPEN the whole pod stands down (the ladder routes to CPU),
        # and the half-open probe batch is admitted here exactly once
        if not self.resilient.breaker.allow_device():
            return self._ladder(sets)
        job = self._prepare(sets)
        if job is None:
            return self._ladder(sets)
        outcome = self._try_sharded(job, sets, health)
        if outcome is not None:
            return outcome
        if job.mb is not None and getattr(job.mb, "slots", None) is not None:
            # a slot-mode batch has no host pubkey operand, so the
            # per-device coordinator below cannot slice it: re-marshal
            # through the standard path before taking the threaded road
            job = self._prepare_plain(sets)
            if job is None:
                return self._ladder(sets)
        for round_no in range(1, self.max_rounds + 1):
            healthy = health.healthy()
            width = mesh_width(len(healthy))
            if width < 1:
                break
            M.POD_ACTIVE_SHARDS.set(width)
            with TRACER.span("pod.dispatch", shards=width,
                             sets=len(sets), round=round_no):
                ok = self._run_round(job, healthy[:width], health)
            if ok is None:
                # the round lost shards past their retry budget:
                # re-shard the batch onto the surviving mesh
                M.POD_RESHARDS.inc()
                TRACER.instant("pod.reshard", round=round_no,
                               survivors=len(health.healthy()))
                continue
            self.resilient.breaker.record_success()
            if ok:
                self.resilient.journal.append(("pod", len(sets)))
                self._probe_excluded(job, health)
                return BatchOutcome(
                    verdicts=[True] * len(sets), device_calls=width
                )
            # some shard's conjunction is False: the single-device ladder
            # re-verifies the ORIGINAL sets with bisection attribution,
            # keeping per-set verdicts byte-identical to the oracle
            return self._ladder(sets)
        # surviving mesh exhausted — that is a backend-level failure.
        # Still probe cooled-down devices here: with the WHOLE mesh
        # excluded no round can ever succeed, so without this probe the
        # pod would stay pinned to the ladder forever.
        self.resilient.breaker.record_failure()
        M.POD_ACTIVE_SHARDS.set(0)
        self._probe_excluded(job, health)
        return self._ladder(sets)

    def _prepare(self, sets: list) -> _PodJob | None:
        try:
            if self.shard_verify is not None:
                return _PodJob(sets=sets, total=len(sets))
            marshal = self.marshal
            if self._sharded_enabled() and self.sharded_marshal is not None:
                marshal = self.sharded_marshal
            mb = marshal(sets)
            if mb is None or getattr(mb, "invalid", False):
                return None
            return _PodJob(sets=sets, mb=mb, total=int(mb.B))
        except Exception as exc:  # noqa: BLE001 — marshal is a ladder rung
            log.warning("pod marshal failed, taking the ladder: %s", exc)
            return None

    def _prepare_plain(self, sets: list) -> _PodJob | None:
        """Standard-marshal re-prepare for the threaded coordinator."""
        try:
            mb = self.marshal(sets)
            if mb is None or getattr(mb, "invalid", False):
                return None
            return _PodJob(sets=sets, mb=mb, total=int(mb.B))
        except Exception as exc:  # noqa: BLE001 — marshal is a ladder rung
            log.warning("pod re-marshal failed, taking the ladder: %s", exc)
            return None

    # -- the sharded-program fast path (parallel/partition.py) --------------

    def _sharded_enabled(self) -> bool:
        return (self.sharded and self.shard_verify is None
                and self.backend is not None
                and hasattr(self.backend, "local_verify_fn"))

    def _sharded_program(self, key: tuple):
        # every program built here (full-pod, post-exclusion re-shard,
        # canary/probe batch) stages through ShardedVerifyProgram, so
        # the spmd audit family's theorem proofs — collective legality,
        # verdict replication, pad absorption, gather bounds — cover
        # these dispatches at their characteristic width/batch shapes
        # (see analysis/spmd_lint.build_live_programs)
        prog = self._sharded_programs.get(key)
        if prog is None:
            import numpy as np
            from jax.sharding import Mesh

            from .mesh import BATCH_AXIS
            from .partition import ShardedVerifyProgram

            devs = [self.devices()[i] for i in key]
            mesh = Mesh(np.array(devs), (BATCH_AXIS,))
            prog = ShardedVerifyProgram(
                mesh,
                self.backend.local_verify_fn(),
                pk_wrap=getattr(self.backend, "registry_pk_wrap", None),
            )
            self._sharded_programs[key] = prog
        return prog

    def _run_sharded(self, program, mb):
        self.injector.fire("pod.dispatch")
        if getattr(mb, "slots", None) is not None:
            if self.registry_provider is None:
                raise RuntimeError(
                    "slot-mode batch without a registry provider")
            registry = self.registry_provider(program.mesh)
            return program.verdict_vector_registry(
                registry, mb.slots, mb.args)
        return program.verdict_vector(mb.args)

    def _try_sharded(self, job: _PodJob, sets: list,
                     health: DeviceHealth) -> BatchOutcome | None:
        """One rule-partitioned SPMD dispatch over the healthy mesh.
        Returns the outcome, or None to fall back to the per-device
        coordinator (program raised, timed out, or mesh too small).
        The program call runs on a daemon worker under
        ``sharded_timeout`` so a hung device costs this path its turn,
        never the batch — the same leak-a-thread economics as a hung
        per-device shard."""
        if not self._sharded_enabled() or job.mb is None:
            return None
        healthy = health.healthy()
        width = mesh_width(len(healthy))
        if width < 2:
            return None
        key = tuple(healthy[:width])
        result: dict = {}

        def run() -> None:
            try:
                program = self._sharded_program(key)
                result["verdicts"] = self._run_sharded(program, job.mb)
                result["bounds"] = program.shard_bounds(job.total)
            except Exception as exc:  # noqa: BLE001 — program fault domain
                result["error"] = exc

        M.POD_ACTIVE_SHARDS.set(width)
        with TRACER.span("pod.dispatch", shards=width, sets=len(sets),
                         round=0, sharded=True):
            worker = threading.Thread(target=run, daemon=True,
                                      name="pod-sharded")
            worker.start()
            worker.join(self.sharded_timeout)
        if "verdicts" not in result:
            err = result.get("error")
            log.warning(
                "pod sharded program %s; falling back to per-device "
                "dispatch: %s",
                "failed" if err is not None else "timed out", err)
            return None
        try:
            verdicts = [
                bool(self.injector.fire("pod.gather", bool(v)))
                for v in result["verdicts"]
            ]
        except Exception as exc:  # noqa: BLE001 — chaos gather domain
            log.warning("pod sharded gather failed: %s", exc)
            return None
        self.resilient.breaker.record_success()
        n = len(sets)
        if all(verdicts):
            self.resilient.journal.append(("pod", n))
            self._probe_excluded(job, health)
            return BatchOutcome(verdicts=[True] * n, device_calls=width)
        # Partial fallback: a shard verdict covers exactly its column
        # range, so only failing shards' sets need the single-device
        # bisection ladder — 1/width of the batch per bad shard instead
        # of all of it.  Padding columns are duplicates of set 0, so a
        # padding-only failing shard implicates set 0 (whose own shard
        # fails too; adding it is belt and braces, never wrong).
        suspect: set[int] = set()
        for sid, ok in enumerate(verdicts):
            if ok:
                continue
            a, b = result["bounds"][sid]
            idxs = range(a, min(b, n))
            if not idxs:
                suspect.add(0)
            suspect.update(idxs)
        order = sorted(suspect)
        sub = self._ladder([sets[i] for i in order])
        merged = [True] * n
        for j, i in enumerate(order):
            merged[i] = bool(sub.verdicts[j])
        return BatchOutcome(verdicts=merged,
                            device_calls=width + sub.device_calls)

    def _run_round(self, job: _PodJob, device_indices: list[int],
                   health: DeviceHealth) -> bool | None:
        """One dispatch round on a fixed mesh.  True/False: every shard
        resolved and this is the conjunction.  None: the round failed
        (device newly excluded or retries exhausted) — re-shard."""
        plan = plan_shards(job.total, len(device_indices))
        pending = [
            (sid, dev, a, b)
            for sid, (dev, (a, b)) in enumerate(
                zip(device_indices, plan.bounds))
            if b > a
        ]
        verdicts: dict[int, bool] = {}
        for attempt in range(self.max_shard_retries + 1):
            if attempt:
                M.POD_RETRIES.inc(len(pending))
                time.sleep(self.backoff_base * (2 ** (attempt - 1)))
            results = self._attempt(job, pending)
            still, dead = [], False
            for sid, dev, a, b in pending:
                res = results.get(sid)
                if res is None:  # shard raised or timed out
                    if health.record_failure(dev):
                        M.POD_EXCLUSIONS.inc()
                        dead = True
                    still.append((sid, dev, a, b))
                else:
                    health.record_success(dev)
                    verdicts[sid] = bool(res)
            pending = still
            if dead:
                return None  # a device left the mesh: re-plan, don't retry
            if not pending:
                return all(verdicts.values()) if verdicts else True
        # retries exhausted with shards outstanding: pull their devices
        # from the mesh so the next round shrinks instead of repeating
        for _sid, dev, _a, _b in pending:
            if health.exclude(dev):
                M.POD_EXCLUSIONS.inc()
        return None

    def _attempt(self, job: _PodJob, pending: list) -> dict[int, bool]:
        """Run every pending shard concurrently, one thread per shard,
        under one wall-clock deadline.  A shard that raises or outlives
        the deadline simply has no entry in the result map; its thread
        is a daemon and leaks if truly hung — the same cost as a hung
        XLA call, paid per shard instead of per batch."""
        results: dict[int, bool] = {}
        lock = threading.Lock()

        def run(sid: int, dev: int, a: int, b: int) -> None:
            try:
                ok = self._run_shard(job, dev, a, b)
            except Exception as exc:  # noqa: BLE001 — shard fault domain
                log.warning("pod shard %d (device %d, [%d:%d)) failed: %s",
                            sid, dev, a, b, exc)
                return
            with lock:
                results[sid] = ok

        threads = [
            threading.Thread(target=run, args=jb, daemon=True,
                             name=f"pod-shard-{jb[0]}")
            for jb in pending
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + self.shard_timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        with lock:
            return dict(results)

    def _run_shard(self, job: _PodJob, dev: int, a: int, b: int) -> bool:
        self.injector.fire("pod.dispatch")
        if self.shard_verify is not None:
            ok = bool(self.shard_verify(job.sets[a:b]))
        else:
            ok = self._run_device_shard(job.mb, dev, a, b)
        return bool(self.injector.fire("pod.gather", ok))

    def _run_device_shard(self, mb, dev: int, a: int, b: int) -> bool:
        import jax

        device = self.devices()[dev]
        args = tuple(_slice_tree(x, a, b) for x in mb.args)
        args = jax.device_put(args, device)
        handle = self.backend._kernel(b - a)(*args)
        resolve = getattr(self.backend, "resolve", None)
        return bool(resolve(handle)) if resolve is not None else bool(handle)

    def _probe_excluded(self, job: _PodJob, health: DeviceHealth) -> None:
        """After a healthy round: one probe shard per cooled-down
        excluded device; success re-arms it into the mesh.  Probe
        failures only restart the cooldown — they never affect the
        batch's verdict (the caller already has it)."""
        ready = health.probe_ready()
        if not ready:
            return
        width = max(1, job.total // mesh_width(len(self.devices())))
        for dev in ready:
            try:
                if self.integrity is not None:
                    # readmission requires the canary-only probe: the
                    # device must produce *correct* known-answer verdicts,
                    # not merely survive a dispatch
                    if not self.device_canary_probe(dev):
                        log.info("pod canary probe on device %d failed", dev)
                        health.defer_probe(dev)
                        continue
                else:
                    self._run_shard(job, dev, 0, min(job.total, width))
            except Exception as exc:  # noqa: BLE001 — probe fault domain
                log.info("pod probe on device %d failed: %s", dev, exc)
                health.defer_probe(dev)
                continue
            health.rearm(dev)
            M.POD_REARMS.inc()
            if self.integrity is not None:
                self.integrity.readmit(dev)
            log.info("pod device %d re-armed after probe", dev)
