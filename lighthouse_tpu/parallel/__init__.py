"""SPMD parallelism toolkit: device meshes + data-parallel sharding
(SURVEY §2.8 — the DP axis of the framework)."""

from .mesh import (  # noqa: F401
    BATCH_AXIS,
    allgather_tree,
    and_reduce,
    batch_spec,
    dp_shard_map,
    make_mesh,
)
