"""SPMD parallelism toolkit: device meshes + data-parallel sharding
(SURVEY §2.8 — the DP axis of the framework), plus the pod-scale
verification service with per-shard fault domains (parallel/pod.py).

``pod`` is imported lazily by its consumers (it pulls in the beacon
processor); only the dependency-free mesh helpers are re-exported here.
"""

from .mesh import (  # noqa: F401
    BATCH_AXIS,
    allgather_tree,
    and_reduce,
    batch_spec,
    dp_shard_map,
    make_mesh,
)
