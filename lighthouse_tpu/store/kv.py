"""Key-value store interfaces + backends (memory, native slabdb).

Twin of beacon_node/store/src/lib.rs: the `KeyValueStore`/`ItemStore` trait
surface (:53,153,318) and `DBColumn` column families (:218).  Two backends,
matching the reference's LevelDB + MemoryStore pair: the C++ slabdb engine
(lighthouse_tpu/native/slabdb.cpp) for disk, a dict for tests.

Crash-safety surface (PR 3): every SlabStore open yields a
:class:`~.wal.RecoveryReport` describing what replay kept/dropped from a
torn or corrupt tail; `flush` is a real fsync; and the `store.open` /
`store.put` / `store.flush` FaultInjector sites make disk failures and torn
writes deterministically injectable (utils/faults.py `io-error` /
`torn-write` kinds).
"""

from __future__ import annotations

import ctypes
import os
from enum import Enum

from ..utils import faults as _faults
from ..utils.metrics import (
    STORE_BYTES_TRUNCATED,
    STORE_CRC_FAILURES,
    STORE_RECORDS_DROPPED,
    STORE_TORN_TAIL_RECOVERIES,
)
from .wal import TAG_PUT, RecoveryReport, encode_record


class DBColumn(Enum):
    """Column families (store/src/lib.rs:218's DBColumn, the subset the
    implemented layers use)."""

    BEACON_META = b"m"
    BEACON_BLOCK = b"b"
    BEACON_STATE = b"s"
    BEACON_STATE_SUMMARY = b"y"
    BEACON_BLOCK_ROOTS = b"r"
    BEACON_STATE_ROOTS = b"t"
    FORK_CHOICE = b"f"
    OP_POOL = b"o"
    ETH1_CACHE = b"e"
    COLD_BLOCK = b"B"
    COLD_STATE = b"S"
    BEACON_BLOB = b"l"
    # slasher database (the MDBX/LMDB equivalent rides the same engine)
    SLASHER_MIN_TARGETS = b"1"
    SLASHER_MAX_TARGETS = b"2"
    SLASHER_ATTESTATIONS = b"3"
    SLASHER_BLOCKS = b"4"


class KeyValueStore:
    """The KeyValueStore trait (get/put/delete/iterate per column)."""

    def get(self, column: DBColumn, key: bytes) -> bytes | None:
        raise NotImplementedError

    def put(self, column: DBColumn, key: bytes, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, column: DBColumn, key: bytes) -> None:
        raise NotImplementedError

    def keys(self, column: DBColumn) -> list[bytes]:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class MemoryStore(KeyValueStore):
    """Ephemeral store for tests (the reference's MemoryStore)."""

    def __init__(self):
        self._d: dict[bytes, bytes] = {}

    def _k(self, column: DBColumn, key: bytes) -> bytes:
        return column.value + key

    def get(self, column, key):
        return self._d.get(self._k(column, key))

    def put(self, column, key, value):
        self._d[self._k(column, key)] = bytes(value)

    def delete(self, column, key):
        self._d.pop(self._k(column, key), None)

    def keys(self, column):
        p = column.value
        return [k[len(p):] for k in self._d if k.startswith(p)]


class SlabStore(KeyValueStore):
    """Disk store over the native C++ slabdb engine (ctypes ABI).

    Opening replays the CRC32-C-framed log; ``recovery_report`` records
    what a torn/corrupt tail cost (always present; ``.clean`` on a healthy
    open).  A ``torn-write`` fault at ``store.put`` appends a truncated
    frame and leaves the store closed — the process "died" mid-write, and
    only a reopen (which runs recovery) brings the data back.
    """

    def __init__(self, path: str):
        from ..native import load

        _faults.fire("store.open", path)
        lib = load("slabdb")
        lib.slab_open.restype = ctypes.c_void_p
        lib.slab_open.argtypes = [ctypes.c_char_p]
        lib.slab_close.argtypes = [ctypes.c_void_p]
        lib.slab_put.restype = ctypes.c_int
        lib.slab_put.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.slab_get.restype = ctypes.c_int64
        lib.slab_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ]
        lib.slab_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.slab_del.restype = ctypes.c_int
        lib.slab_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        lib.slab_count.restype = ctypes.c_uint64
        lib.slab_count.argtypes = [ctypes.c_void_p]
        lib.slab_dead_bytes.restype = ctypes.c_uint64
        lib.slab_dead_bytes.argtypes = [ctypes.c_void_p]
        lib.slab_flush.restype = ctypes.c_int
        lib.slab_flush.argtypes = [ctypes.c_void_p]
        lib.slab_compact.restype = ctypes.c_int
        lib.slab_compact.argtypes = [ctypes.c_void_p]
        lib.slab_iter_prefix.restype = ctypes.c_int64
        lib.slab_iter_prefix.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        for fn in ("slab_recovery_kept", "slab_recovery_dropped",
                   "slab_recovery_truncated"):
            getattr(lib, fn).restype = ctypes.c_uint64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.slab_recovery_flags.restype = ctypes.c_int
        lib.slab_recovery_flags.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._path = path
        self._h = lib.slab_open(path.encode())
        if not self._h:
            raise IOError(f"slabdb failed to open {path}")
        flags = lib.slab_recovery_flags(self._h)
        self.recovery_report = RecoveryReport(
            records_kept=lib.slab_recovery_kept(self._h),
            records_dropped=lib.slab_recovery_dropped(self._h),
            bytes_truncated=lib.slab_recovery_truncated(self._h),
            tail_torn=bool(flags & 1),
            migrated=bool(flags & 2),
            crc_mismatch=bool(flags & 4),
        )
        if self.recovery_report.tail_torn:
            STORE_TORN_TAIL_RECOVERIES.inc()
            STORE_RECORDS_DROPPED.inc(self.recovery_report.records_dropped)
            STORE_BYTES_TRUNCATED.inc(self.recovery_report.bytes_truncated)
        if self.recovery_report.crc_mismatch:
            STORE_CRC_FAILURES.inc()

    def _k(self, column: DBColumn, key: bytes) -> bytes:
        return column.value + key

    def _handle(self):
        if not self._h:
            raise IOError("SlabStore is closed")
        return self._h

    def get(self, column, key):
        k = self._k(column, key)
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.slab_get(self._handle(), k, len(k), ctypes.byref(out))
        if n < 0:
            return None
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.slab_free(out)

    def put(self, column, key, value):
        k = self._k(column, key)
        v = bytes(value)
        try:
            _faults.fire("store.put", (column, key))
        except _faults.TornWrite as tw:
            self._tear(k, v, tw.fraction)
            raise _faults.StorageFault(
                f"injected torn write: crashed mid-append of a "
                f"{len(v)}-byte value"
            ) from tw
        if self._lib.slab_put(self._handle(), k, len(k), v, len(v)) != 0:
            raise IOError("slabdb put failed")

    def _tear(self, k: bytes, v: bytes, fraction: float) -> None:
        """Simulate a SIGKILL mid-``fwrite``: flush and abandon the engine
        handle (the 'crashed' process held it), then append only a prefix
        of the framed record.  The store is unusable afterwards; a reopen
        runs torn-tail recovery."""
        h, self._h = self._h, None
        self._lib.slab_close(h)
        frame = encode_record(TAG_PUT, k, v)
        keep = min(len(frame) - 1, max(1, int(len(frame) * fraction)))
        with open(self._path, "ab") as f:
            f.write(frame[:keep])
            f.flush()
            os.fsync(f.fileno())

    def delete(self, column, key):
        k = self._k(column, key)
        if self._lib.slab_del(self._handle(), k, len(k)) != 0:
            raise IOError("slabdb delete failed")

    def keys(self, column):
        p = column.value
        out = ctypes.POINTER(ctypes.c_uint8)()
        out_len = ctypes.c_uint64()
        n = self._lib.slab_iter_prefix(
            self._handle(), p, len(p), ctypes.byref(out), ctypes.byref(out_len)
        )
        try:
            raw = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.slab_free(out)
        keys, pos = [], 0
        for _ in range(n):
            klen = int.from_bytes(raw[pos : pos + 4], "little")
            keys.append(raw[pos + 4 + len(p) : pos + 4 + klen])
            pos += 4 + klen
        return keys

    def __len__(self):
        return self._lib.slab_count(self._handle())

    def dead_bytes(self) -> int:
        return self._lib.slab_dead_bytes(self._handle())

    def compact(self) -> None:
        if self._lib.slab_compact(self._handle()) != 0:
            raise IOError("slabdb compact failed")

    def flush(self):
        _faults.fire("store.flush", self._path)
        if self._lib.slab_flush(self._handle()) != 0:
            raise IOError("slabdb flush (fsync) failed")

    def close(self):
        if self._h:
            self._lib.slab_close(self._h)
            self._h = None
