"""Storage layer — twin of beacon_node/store (HotColdDB over native KV)."""

from .hot_cold import HotColdDB, Split  # noqa: F401
from .kv import DBColumn, KeyValueStore, MemoryStore, SlabStore  # noqa: F401
from .wal import RecoveryReport, scan_file, verify_file  # noqa: F401
