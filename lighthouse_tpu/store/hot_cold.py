"""HotColdDB: the hot/cold split beacon database.

Twin of beacon_node/store/src/hot_cold_store.rs:43-50: recent (hot) blocks
and full states live ahead of the finalized split; at finalization, blocks
and periodic restore-point states migrate to the cold section and
intermediate hot states are dropped (reconstructable by replay — the
BlockReplayer pattern of store/src/reconstruct.rs).  Schema versioning in
the metadata column mirrors store/src/metadata.rs.
"""

from __future__ import annotations

from dataclasses import dataclass

from .kv import DBColumn, KeyValueStore, MemoryStore

SCHEMA_VERSION = 2
SPLIT_KEY = b"split"
SCHEMA_KEY = b"schema"


def _migrate_v1_to_v2(db: KeyValueStore) -> None:
    """v2 adds the slot → block-root forward index (forwards_iter.rs's
    chain-spine column): backfill it from every stored block."""
    for col in (DBColumn.BEACON_BLOCK, DBColumn.COLD_BLOCK):
        for root in db.keys(col):
            raw = db.get(col, root)
            slot = HotColdDB._block_slot(raw) if raw else None
            if slot is not None:
                db.put(
                    DBColumn.BEACON_BLOCK_ROOTS, slot.to_bytes(8, "big"), root
                )


# schema upgrade registry (store/src/metadata.rs SchemaVersion +
# beacon_chain/src/schema_change* walked by database_manager)
_MIGRATIONS = {1: _migrate_v1_to_v2}


@dataclass
class Split:
    """The hot/cold boundary (finalized slot + state root)."""

    slot: int
    state_root: bytes

    def encode(self) -> bytes:
        return self.slot.to_bytes(8, "little") + self.state_root

    @classmethod
    def decode(cls, data: bytes) -> "Split":
        return cls(int.from_bytes(data[:8], "little"), data[8:40])


class HotColdDB:
    def __init__(
        self,
        store: KeyValueStore | None = None,
        types_family=None,
        slots_per_restore_point: int = 32,
    ):
        self.db = store if store is not None else MemoryStore()
        self.types = types_family
        self.slots_per_restore_point = slots_per_restore_point
        # a store that truncated a torn tail on open may have lost the
        # suffix of the log: re-anchor the head indexes to what survived
        # BEFORE anything reads them (the open-after-SIGKILL contract)
        report = getattr(self.db, "recovery_report", None)
        self.last_recovery = report
        if report is not None and not report.clean:
            self.re_anchor()
        raw = self.db.get(DBColumn.BEACON_META, SCHEMA_KEY)
        if raw is None:
            self.db.put(
                DBColumn.BEACON_META, SCHEMA_KEY,
                SCHEMA_VERSION.to_bytes(4, "little"),
            )
        else:
            found = int.from_bytes(raw, "little")
            if found > SCHEMA_VERSION:
                raise IOError(
                    f"database schema v{found} is NEWER than this build's "
                    f"v{SCHEMA_VERSION}; refusing to downgrade"
                )
            while found < SCHEMA_VERSION:
                migration = _MIGRATIONS.get(found)
                if migration is None:
                    raise IOError(f"no migration path from schema v{found}")
                migration(self.db)
                found += 1
                self.db.put(
                    DBColumn.BEACON_META, SCHEMA_KEY,
                    found.to_bytes(4, "little"),
                )
            self.db.flush()

    # ------------------------------------------------------------- split

    @property
    def split(self) -> Split:
        raw = self.db.get(DBColumn.BEACON_META, SPLIT_KEY)
        return Split.decode(raw) if raw else Split(0, bytes(32))

    # ---------------------------------------------------- crash recovery

    def re_anchor(self) -> dict:
        """Restore block/index consistency after torn-tail recovery.

        Truncation drops a *suffix* of the log, so two shapes of damage are
        possible: a slot→root index entry whose block record was cut (the
        entry itself survived an earlier record), or a block whose index
        entry was cut (put_block writes block first, index second).  Drop
        the former, backfill the latter, and report the resulting head —
        the highest indexed slot whose block actually loads.
        """
        dropped = backfilled = 0
        for slot_key in list(self.db.keys(DBColumn.BEACON_BLOCK_ROOTS)):
            root = self.db.get(DBColumn.BEACON_BLOCK_ROOTS, slot_key)
            if root is not None and not self.block_exists(root):
                self.db.delete(DBColumn.BEACON_BLOCK_ROOTS, slot_key)
                dropped += 1
        for col in (DBColumn.BEACON_BLOCK, DBColumn.COLD_BLOCK):
            for root in self.db.keys(col):
                raw = self.db.get(col, root)
                slot = self._block_slot(raw) if raw else None
                if slot is None:
                    continue
                key = slot.to_bytes(8, "big")
                if self.db.get(DBColumn.BEACON_BLOCK_ROOTS, key) is None:
                    self.db.put(DBColumn.BEACON_BLOCK_ROOTS, key, root)
                    backfilled += 1
        head_slot, head_root = 0, None
        for slot_key in self.db.keys(DBColumn.BEACON_BLOCK_ROOTS):
            slot = int.from_bytes(slot_key, "big")
            if slot >= head_slot:
                head_slot = slot
                head_root = self.db.get(DBColumn.BEACON_BLOCK_ROOTS, slot_key)
        self.db.flush()
        return {
            "head_slot": head_slot,
            "head_root": head_root,
            "index_dropped": dropped,
            "index_backfilled": backfilled,
        }

    # ------------------------------------------------------------- blocks

    def put_block(self, block_root: bytes, signed_block) -> None:
        raw = signed_block.encode()
        self.db.put(DBColumn.BEACON_BLOCK, block_root, raw)
        # slot → root forward index (last writer wins: the caller imports
        # in fork-choice order, so the canonical chain overwrites forks)
        self.db.put(
            DBColumn.BEACON_BLOCK_ROOTS,
            int(signed_block.message.slot).to_bytes(8, "big"),
            block_root,
        )

    def get_block(self, block_root: bytes, block_cls=None):
        for col in (DBColumn.BEACON_BLOCK, DBColumn.COLD_BLOCK):
            raw = self.db.get(col, block_root)
            if raw is not None:
                cls = block_cls or (self.types and self.types.SignedBeaconBlock)
                return cls.deserialize_value(raw) if cls else raw
        return None

    def block_exists(self, block_root: bytes) -> bool:
        return any(
            self.db.get(c, block_root) is not None
            for c in (DBColumn.BEACON_BLOCK, DBColumn.COLD_BLOCK)
        )

    # ------------------------------------------------------------- blobs

    def put_blob(self, block_root: bytes, index: int, sidecar) -> None:
        """Blob sidecars keyed (block_root, index) — store/src's blobs
        column (DBColumn::BeaconBlob)."""
        self.db.put(
            DBColumn.BEACON_BLOB, block_root + bytes([index]), sidecar.encode()
        )

    def get_blobs(self, block_root: bytes, max_blobs: int = 16) -> list:
        out = []
        for i in range(max_blobs):
            raw = self.db.get(DBColumn.BEACON_BLOB, block_root + bytes([i]))
            if raw is None:
                break
            cls = self.types and self.types.BlobSidecar
            out.append(cls.deserialize_value(raw) if cls else raw)
        return out

    # ------------------------------------------------------------- states

    def put_state(self, state_root: bytes, state) -> None:
        self.db.put(DBColumn.BEACON_STATE, state_root, state.encode())
        self.db.put(
            DBColumn.BEACON_STATE_SUMMARY,
            state_root,
            int(state.slot).to_bytes(8, "little"),
        )

    def get_state(self, state_root: bytes, state_cls=None):
        for col in (DBColumn.BEACON_STATE, DBColumn.COLD_STATE):
            raw = self.db.get(col, state_root)
            if raw is not None:
                cls = state_cls or (self.types and self.types.BeaconState)
                return cls.deserialize_value(raw) if cls else raw
        return None

    def state_slot(self, state_root: bytes) -> int | None:
        raw = self.db.get(DBColumn.BEACON_STATE_SUMMARY, state_root)
        return int.from_bytes(raw, "little") if raw else None

    # ------------------------------------------------------- finalization

    def migrate_to_cold(
        self, finalized_slot: int, finalized_state_root: bytes,
        keep_block_roots: set[bytes] | None = None,
    ) -> dict:
        """Advance the split (hot_cold_store freezer migration): move
        finalized blocks cold, keep restore-point states, drop intermediate
        hot states (replayable).  `keep_block_roots`: canonical-chain roots
        to migrate; others (pruned forks) are deleted."""
        stats = {"blocks_cold": 0, "blocks_pruned": 0, "states_dropped": 0,
                 "states_kept": 0}
        for root in list(self.db.keys(DBColumn.BEACON_BLOCK)):
            raw = self.db.get(DBColumn.BEACON_BLOCK, root)
            slot = self._block_slot(raw)
            if slot is None or slot > finalized_slot:
                continue
            if keep_block_roots is None or root in keep_block_roots:
                self.db.put(DBColumn.COLD_BLOCK, root, raw)
                stats["blocks_cold"] += 1
            else:
                stats["blocks_pruned"] += 1
            self.db.delete(DBColumn.BEACON_BLOCK, root)
        for root in list(self.db.keys(DBColumn.BEACON_STATE)):
            slot = self.state_slot(root)
            if slot is None or slot > finalized_slot:
                continue
            raw = self.db.get(DBColumn.BEACON_STATE, root)
            if slot % self.slots_per_restore_point == 0 or root == finalized_state_root:
                # restore point: keep the state AND its slot summary so
                # replay can locate the nearest restore point by slot
                self.db.put(DBColumn.COLD_STATE, root, raw)
                stats["states_kept"] += 1
            else:
                stats["states_dropped"] += 1
                self.db.delete(DBColumn.BEACON_STATE_SUMMARY, root)
            self.db.delete(DBColumn.BEACON_STATE, root)
        self.db.put(
            DBColumn.BEACON_META, SPLIT_KEY,
            Split(finalized_slot, finalized_state_root).encode(),
        )
        self.db.flush()
        return stats

    @staticmethod
    def _block_slot(signed_block_bytes: bytes) -> int | None:
        # SignedBeaconBlock = 4-byte offset to message | signature(96) |
        # message{slot u64 at its head}
        if len(signed_block_bytes) < 108:
            return None
        return int.from_bytes(signed_block_bytes[100:108], "little")

    # ------------------------------------------------------- iteration/GC

    def forwards_block_roots_iterator(self, start_slot: int, end_slot: int):
        """(slot, block_root) ascending over the canonical spine
        (store/src/forwards_iter.rs): slots without a block are skipped
        (empty slots have no root of their own)."""
        for slot in range(start_slot, end_slot + 1):
            root = self.db.get(
                DBColumn.BEACON_BLOCK_ROOTS, slot.to_bytes(8, "big")
            )
            if root is not None:
                yield slot, root

    def garbage_collect(self, keep_state_roots: set[bytes]) -> dict:
        """Drop abandoned hot states (pruned forks that never finalized —
        store/src/garbage_collection.rs): anything hot, at/below the
        split, and not in ``keep_state_roots``."""
        split_slot = self.split.slot
        dropped = 0
        for root in list(self.db.keys(DBColumn.BEACON_STATE)):
            slot = self.state_slot(root)
            if slot is None or slot > split_slot:
                continue
            if root in keep_state_roots:
                continue
            self.db.delete(DBColumn.BEACON_STATE, root)
            self.db.delete(DBColumn.BEACON_STATE_SUMMARY, root)
            dropped += 1
        self.db.flush()
        return {"states_dropped": dropped}

    # ------------------------------------------------------------- misc

    def put_item(self, column: DBColumn, key: bytes, value: bytes) -> None:
        self.db.put(column, key, value)

    def get_item(self, column: DBColumn, key: bytes) -> bytes | None:
        return self.db.get(column, key)

    def flush(self):
        """Durability point: on a SlabStore backend this is a real fsync."""
        self.db.flush()

    def close(self):
        self.db.close()
