"""Checksummed WAL framing for the slabdb log + the independent verifier.

The C++ engine (native/slabdb.cpp) is the writer and replayer of record;
this module is the *independent* Python reader of the same format: the same
CRC32-C (Castagnoli) the snappy framing uses — reused from
network/snappy.py, not re-derived — over the same record layout, with zero
code shared with the engine.  Three consumers:

* ``lighthouse-tpu db verify`` — offline integrity scan (per-column record
  counts, CRC failures, what recovery would keep/drop) without ever
  touching the engine;
* the corrupt-record test fixtures (tests/test_store.py), which use
  ``scan_file`` record offsets to place byte-flips and truncations;
* the ``torn-write`` fault injection (store/kv.py), which appends a
  deliberately truncated ``encode_record`` frame — exactly what a SIGKILL
  mid-``fwrite`` leaves behind.

Record layout (v2, magic "SLB2" on disk)::

    tag u8 | klen u32 | vlen u32 | crc u32 | key | value

``crc`` is CRC32-C over the first 9 header bytes + key + value.  Legacy v1
logs (magic 0x534c4142, no CRC) are recognized and scanned structurally;
the engine migrates them to v2 on first open.

``scan_file``'s kept/dropped/truncated numbers intentionally mirror the
engine's replay semantics (truncate to the last valid prefix; count lost
frames by a bounds-only forward walk), so tests can assert the engine's
``RecoveryReport`` against this module's independent prediction.
"""

from __future__ import annotations

import os
import struct
from dataclasses import asdict, dataclass

from ..network.snappy import crc32c

MAGIC_V1 = (0x534C4142).to_bytes(4, "little")  # legacy, no per-record CRC
MAGIC_V2 = (0x32424C53).to_bytes(4, "little")  # b"SLB2": CRC32-C framed
TAG_PUT = 1
TAG_DEL = 2
_HDR = struct.Struct("<BIII")
HEADER_SIZE = _HDR.size  # 13
_HDR_V1_SIZE = 9
MAX_KLEN = 1 << 20
MAX_VLEN = 1 << 30


def encode_record(tag: int, key: bytes, value: bytes = b"") -> bytes:
    """Frame one record exactly as the engine writes it (pinned against the
    engine's on-disk bytes in tests/test_store.py)."""
    head = struct.pack("<BII", tag, len(key), len(value))
    crc = crc32c(head + key + value)
    return head + struct.pack("<I", crc) + key + value


@dataclass
class RecoveryReport:
    """What opening the log did to a damaged tail (slab_recovery_* ABI)."""

    records_kept: int = 0       # records applied from the valid prefix
    records_dropped: int = 0    # record frames lost past the valid prefix
    bytes_truncated: int = 0    # bytes cut from the tail
    tail_torn: bool = False     # a torn/corrupt tail was truncated
    crc_mismatch: bool = False  # the cut happened at a CRC failure (bit rot)
    migrated: bool = False      # a v1 (no-CRC) log was rewritten as v2

    @property
    def clean(self) -> bool:
        return not self.tail_torn

    def as_dict(self) -> dict:
        return asdict(self)


def scan_file(path: str, keep_records: bool = True) -> dict:
    """Scan a slab log without the engine, verifying every CRC.

    Returns a dict with ``format`` ("v2"/"v1"/"empty"/"unknown"),
    ``records_kept`` / ``records_dropped`` / ``bytes_truncated`` /
    ``valid_prefix_bytes`` / ``stop_reason`` / ``crc_failures``,
    ``per_column`` counts ({column: {"puts", "dels", "live"}}), and — when
    ``keep_records`` — a ``records`` list of
    ``{"offset", "tag", "key", "vlen"}`` for fixture placement.
    """
    from .kv import DBColumn  # local import: kv imports this module

    colname = {c.value: c.name for c in DBColumn}
    size = os.path.getsize(path)
    out: dict = {
        "path": path,
        "file_bytes": size,
        "format": "unknown",
        "records_kept": 0,
        "records_dropped": 0,
        "bytes_truncated": 0,
        "valid_prefix_bytes": min(size, 4),
        "stop_reason": None,
        "crc_failures": 0,
        "per_column": {},
        "records": [] if keep_records else None,
    }
    with open(path, "rb") as f:
        magic = f.read(4)
        if not magic:
            out["format"] = "empty"
            return out
        if magic == MAGIC_V2:
            v2, hdr_size = True, HEADER_SIZE
        elif magic == MAGIC_V1:
            v2, hdr_size = False, _HDR_V1_SIZE
        else:
            out["stop_reason"] = "bad-magic"
            return out
        out["format"] = "v2" if v2 else "v1"

        per_column: dict[str, dict[str, int]] = {}
        live: dict[bytes, str] = {}
        pos = 4
        while True:
            hdr = f.read(hdr_size)
            if len(hdr) < hdr_size:
                if hdr:
                    out["stop_reason"] = "torn-header"
                break
            if v2:
                tag, klen, vlen, crc = _HDR.unpack(hdr)
            else:
                tag, klen, vlen = struct.unpack("<BII", hdr)
                crc = None
            if (
                tag not in (TAG_PUT, TAG_DEL)
                or klen > MAX_KLEN
                or vlen > MAX_VLEN
                or (v2 and tag == TAG_DEL and vlen != 0)
            ):
                out["stop_reason"] = "corrupt-header"
                break
            body = klen + (vlen if tag == TAG_PUT else 0)
            if pos + hdr_size + body > size:
                out["stop_reason"] = "torn-write"
                break
            key = f.read(klen)
            val = f.read(vlen) if tag == TAG_PUT else b""
            if v2 and crc32c(hdr[:_HDR_V1_SIZE] + key + val) != crc:
                out["crc_failures"] += 1
                out["stop_reason"] = "crc-mismatch"
                break
            col = colname.get(key[:1], "?" + key[:1].hex())
            stats = per_column.setdefault(
                col, {"puts": 0, "dels": 0, "live": 0}
            )
            if tag == TAG_PUT:
                stats["puts"] += 1
                live[key] = col
            else:
                stats["dels"] += 1
                live.pop(key, None)
            if keep_records:
                out["records"].append(
                    {"offset": pos, "tag": tag, "key": key, "vlen": vlen}
                )
            out["records_kept"] += 1
            pos += hdr_size + body

        out["valid_prefix_bytes"] = pos
        for col in live.values():
            per_column[col]["live"] += 1
        out["per_column"] = per_column

        if pos < size and out["stop_reason"]:
            out["bytes_truncated"] = size - pos
            # mirror the engine's count_lost: bounds-only forward walk; a
            # frame whose header survived but whose payload runs past EOF
            # counts as one lost record
            f.seek(pos)
            q = pos
            while True:
                hdr = f.read(hdr_size)
                if len(hdr) < hdr_size:
                    break
                if v2:
                    tag, klen, vlen, _ = _HDR.unpack(hdr)
                else:
                    tag, klen, vlen = struct.unpack("<BII", hdr)
                if tag not in (TAG_PUT, TAG_DEL) or klen > MAX_KLEN or vlen > MAX_VLEN:
                    break
                body = klen + (vlen if tag == TAG_PUT else 0)
                out["records_dropped"] += 1
                if q + hdr_size + body > size:
                    break
                f.seek(body, 1)
                q += hdr_size + body
    return out


def verify_file(path: str) -> dict:
    """`lighthouse-tpu db verify` payload: the offline scan minus the raw
    per-record list, plus a recovery-report-shaped summary."""
    scan = scan_file(path, keep_records=False)
    scan.pop("records")
    scan["recovery"] = RecoveryReport(
        records_kept=scan["records_kept"],
        records_dropped=scan["records_dropped"],
        bytes_truncated=scan["bytes_truncated"],
        tail_torn=scan["stop_reason"] is not None,
        crc_mismatch=scan["stop_reason"] == "crc-mismatch",
    ).as_dict()
    scan["ok"] = scan["stop_reason"] is None and scan["format"] in ("v2", "v1", "empty")
    return scan
