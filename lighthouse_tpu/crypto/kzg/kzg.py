"""KZG commitments for Deneb blobs — verification on the shared pairing core.

Capability twin of crypto/kzg (which wraps the C library c-kzg-4844;
`Kzg` holds the setup at src/lib.rs:30-45) and of the beacon chain's blob
gate `verify_blob_kzg_proof_batch` (beacon_node/beacon_chain/src/
kzg_utils.rs:23-35).  Unlike the reference this is NOT a foreign-library
wrapper: proofs verify through the same BLS12-381 pairing stack the
signature path uses (CPU oracle today, the batched JAX Miller loop as the
device path), so blob batches and signature batches share one crypto core.

Implements the deneb polynomial-commitments spec: blob->polynomial in
evaluation form over bit-reversed roots of unity, Fiat-Shamir challenges,
barycentric evaluation, single + batch proof verification (random linear
combination -> ONE pairing check), and proving (commitment/proof
computation) — instant with a dev setup's known tau, MSM over the Lagrange
setup otherwise.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..bls.curve import (
    Fp,
    G1_GENERATOR,
    affine_mul,
    affine_neg,
    from_jacobian,
    g1_from_bytes,
    g1_to_bytes,
    jac_add,
    to_jacobian,
)
from ..bls.curve import G2_GENERATOR
from ..bls.fields import Fp2
from ..bls.pairing import pairing_check
from . import fr
from .fr import BLS_MODULUS

FIELD_ELEMENTS_PER_BLOB = 4096
BYTES_PER_FIELD_ELEMENT = 32
BYTES_PER_BLOB = FIELD_ELEMENTS_PER_BLOB * BYTES_PER_FIELD_ELEMENT
FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"  # 16 bytes, deneb spec
RANDOM_CHALLENGE_KZG_BATCH_DOMAIN = b"RCKZGBATCH___V1_"  # 16 bytes
ENDIANNESS = "big"


class KzgError(ValueError):
    pass


def _hash(data: bytes) -> bytes:
    from ...ops import sha256

    return sha256(data)


def hash_to_bls_field(data: bytes) -> int:
    return int.from_bytes(_hash(data), ENDIANNESS) % BLS_MODULUS


def bytes_to_bls_field(b: bytes) -> int:
    x = int.from_bytes(b, ENDIANNESS)
    if x >= BLS_MODULUS:
        raise KzgError("field element not canonical")
    return x


def blob_to_polynomial(blob: bytes) -> list[int]:
    if len(blob) != BYTES_PER_BLOB:
        raise KzgError(f"blob must be {BYTES_PER_BLOB} bytes")
    return [
        bytes_to_bls_field(blob[i * 32 : (i + 1) * 32])
        for i in range(FIELD_ELEMENTS_PER_BLOB)
    ]


# ---------------------------------------------------------------------------
# Trusted setup
# ---------------------------------------------------------------------------


@dataclass
class TrustedSetup:
    """g1_lagrange: 4096 affine G1 points (evaluation form, bit-reversed
    roots); g2_monomial: [G2, tau*G2, ...]; dev_tau set only for the
    insecure dev setup (enables O(1) proving in tests)."""

    g1_lagrange: list
    g2_monomial: list
    dev_tau: int | None = None

    @classmethod
    def load_mainnet(cls) -> "TrustedSetup":
        """The public KZG ceremony output (converted by
        tools/convert_trusted_setup.py; same constant the reference embeds
        via eth2_network_config)."""
        import os

        import numpy as np

        path = os.path.join(os.path.dirname(__file__), "trusted_setup.npz")
        data = np.load(path)
        g1 = [
            (
                Fp(int.from_bytes(bytes(row[0]), "big")),
                Fp(int.from_bytes(bytes(row[1]), "big")),
            )
            for row in data["g1_lagrange"]
        ]
        g2 = [
            (
                Fp2(
                    int.from_bytes(bytes(row[0]), "big"),
                    int.from_bytes(bytes(row[1]), "big"),
                ),
                Fp2(
                    int.from_bytes(bytes(row[2]), "big"),
                    int.from_bytes(bytes(row[3]), "big"),
                ),
            )
            for row in data["g2_monomial"]
        ]
        return cls(g1_lagrange=g1, g2_monomial=g2)

    @classmethod
    def dev(cls, tau: int = 0x1234_5678_9ABC_DEF0_1357) -> "TrustedSetup":
        """INSECURE known-tau setup for tests (the c-kzg test pattern):
        Lagrange G1 points are [l_i(tau)]G1 over the bit-reversed roots."""
        tau %= BLS_MODULUS
        roots = fr.brp_roots_of_unity(FIELD_ELEMENTS_PER_BLOB)
        # l_i(tau) = (tau^N - 1) / (N * (tau - w_i)) * w_i
        n = FIELD_ELEMENTS_PER_BLOB
        tn = (pow(tau, n, BLS_MODULUS) - 1) % BLS_MODULUS
        lag = [
            tn * w % BLS_MODULUS * fr.inv(n * ((tau - w) % BLS_MODULUS))
            % BLS_MODULUS
            for w in roots
        ]
        g1 = [affine_mul(G1_GENERATOR, l, Fp) for l in lag]
        g2 = [G2_GENERATOR, affine_mul(G2_GENERATOR, tau, Fp2)]
        return cls(g1_lagrange=g1, g2_monomial=g2, dev_tau=tau)


_MAINNET: TrustedSetup | None = None


def mainnet_setup() -> TrustedSetup:
    global _MAINNET
    if _MAINNET is None:
        _MAINNET = TrustedSetup.load_mainnet()
    return _MAINNET


_DEV: TrustedSetup | None = None


def dev_setup() -> TrustedSetup:
    """Process-cached known-tau setup (building the 4096 Lagrange points
    takes ~25 s; every test consumer shares one)."""
    global _DEV
    if _DEV is None:
        _DEV = TrustedSetup.dev()
    return _DEV


# ---------------------------------------------------------------------------
# Polynomial evaluation
# ---------------------------------------------------------------------------


def evaluate_polynomial_in_evaluation_form(poly: list[int], z: int) -> int:
    """Barycentric formula over the bit-reversed roots (spec
    evaluate_polynomial_in_evaluation_form).  The 4096 denominators are
    inverted with ONE Montgomery batch inversion instead of per-term
    Fermat exponentiations (the dominant cost otherwise)."""
    width = len(poly)
    roots = fr.brp_roots_of_unity(width)
    if z in roots:
        return poly[roots.index(z)]
    denoms = [(z - w_i) % BLS_MODULUS for w_i in roots]
    inv_denoms = fr.batch_inv(denoms)
    total = 0
    for p_i, w_i, d_i in zip(poly, roots, inv_denoms):
        total = (total + p_i * w_i % BLS_MODULUS * d_i) % BLS_MODULUS
    zn = (pow(z, width, BLS_MODULUS) - 1) % BLS_MODULUS
    return total * zn % BLS_MODULUS * fr.inv(width) % BLS_MODULUS


def compute_challenge(blob: bytes, commitment: bytes) -> int:
    degree = FIELD_ELEMENTS_PER_BLOB.to_bytes(16, ENDIANNESS)
    return hash_to_bls_field(
        FIAT_SHAMIR_PROTOCOL_DOMAIN + degree + blob + commitment
    )


# ---------------------------------------------------------------------------
# Group helpers
# ---------------------------------------------------------------------------


def g1_lincomb(points: list, scalars: list[int]):
    """MSM: sum scalar_i * P_i (Jacobian accumulation)."""
    acc = to_jacobian(None, Fp)
    for pt, s in zip(points, scalars):
        s %= BLS_MODULUS
        if s == 0 or pt is None:
            continue
        term = affine_mul(pt, s, Fp)
        if term is not None:
            acc = jac_add(acc, to_jacobian(term, Fp), Fp)
    return from_jacobian(acc, Fp)


def _g1_add(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return from_jacobian(jac_add(to_jacobian(a, Fp), to_jacobian(b, Fp), Fp), Fp)



# ---------------------------------------------------------------------------
# Commit / prove
# ---------------------------------------------------------------------------


def blob_to_kzg_commitment(blob: bytes, setup: TrustedSetup) -> bytes:
    poly = blob_to_polynomial(blob)
    if setup.dev_tau is not None:
        y = evaluate_polynomial_in_evaluation_form(poly, setup.dev_tau)
        pt = affine_mul(G1_GENERATOR, y, Fp)
        return g1_to_bytes(pt)
    return g1_to_bytes(g1_lincomb(setup.g1_lagrange, poly))


def compute_kzg_proof_impl(
    poly: list[int], z: int, setup: TrustedSetup
) -> tuple[bytes, int]:
    """Returns (proof, y).  Quotient in evaluation form per spec
    compute_kzg_proof_impl (incl. the on-root special case)."""
    width = len(poly)
    roots = fr.brp_roots_of_unity(width)
    y = evaluate_polynomial_in_evaluation_form(poly, z)
    if setup.dev_tau is not None:
        tau = setup.dev_tau
        w = fr.div(
            (evaluate_polynomial_in_evaluation_form(poly, tau) - y) % BLS_MODULUS,
            (tau - z) % BLS_MODULUS,
        )
        return g1_to_bytes(affine_mul(G1_GENERATOR, w, Fp)), y
    quotient = [0] * width
    for i, (p_i, w_i) in enumerate(zip(poly, roots)):
        if w_i == z:
            continue
        quotient[i] = fr.div((p_i - y) % BLS_MODULUS, (w_i - z) % BLS_MODULUS)
    if z in roots:
        m = roots.index(z)
        for i, w_i in enumerate(roots):
            if i == m:
                continue
            quotient[m] = (
                quotient[m]
                + (poly[i] - y)
                * w_i
                % BLS_MODULUS
                * fr.inv(z * ((z - w_i) % BLS_MODULUS) % BLS_MODULUS)
            ) % BLS_MODULUS
    return g1_to_bytes(g1_lincomb(setup.g1_lagrange, quotient)), y


def compute_blob_kzg_proof(
    blob: bytes, commitment: bytes, setup: TrustedSetup
) -> bytes:
    z = compute_challenge(blob, commitment)
    proof, _ = compute_kzg_proof_impl(blob_to_polynomial(blob), z, setup)
    return proof


# ---------------------------------------------------------------------------
# Verify
# ---------------------------------------------------------------------------


def _decode_g1(b: bytes, what: str):
    try:
        pt = g1_from_bytes(bytes(b), subgroup_check=True)
    except Exception as e:
        raise KzgError(f"invalid {what}: {e}") from None
    return pt  # None = infinity (valid encoding: commitment to zero poly)


def verify_kzg_proof_impl(
    commitment: bytes, z: int, y: int, proof: bytes, setup: TrustedSetup
) -> bool:
    """e(P - [y]G1, -G2) * e(W, [tau - z]G2) == 1."""
    P = _decode_g1(commitment, "commitment")
    W = _decode_g1(proof, "proof")
    tau_g2 = setup.g2_monomial[1]
    z_g2 = affine_mul(G2_GENERATOR, z % BLS_MODULUS, Fp2)
    x_minus_z = from_jacobian(
        jac_add(
            to_jacobian(tau_g2, Fp2),
            to_jacobian(affine_neg(z_g2) if z_g2 else None, Fp2),
            Fp2,
        ),
        Fp2,
    )
    y_g1 = affine_mul(G1_GENERATOR, y % BLS_MODULUS, Fp) if y else None
    p_minus_y = _g1_add(P, affine_neg(y_g1) if y_g1 else None)
    pairs = []
    if p_minus_y is not None:
        pairs.append((p_minus_y, affine_neg(G2_GENERATOR)))
    if W is not None and x_minus_z is not None:
        pairs.append((W, x_minus_z))
    if not pairs:
        return True
    return pairing_check(pairs)


def verify_blob_kzg_proof(
    blob: bytes, commitment: bytes, proof: bytes, setup: TrustedSetup | None = None
) -> bool:
    setup = setup or mainnet_setup()
    z = compute_challenge(blob, commitment)
    poly = blob_to_polynomial(blob)
    y = evaluate_polynomial_in_evaluation_form(poly, z)
    return verify_kzg_proof_impl(commitment, z, y, proof, setup)


def verify_blob_kzg_proof_batch(
    blobs: list[bytes],
    commitments: list[bytes],
    proofs: list[bytes],
    setup: TrustedSetup | None = None,
) -> bool:
    """kzg_utils.rs:23-35 semantics: one random-linear-combination pairing
    check for the whole sidecar batch."""
    setup = setup or mainnet_setup()
    if not (len(blobs) == len(commitments) == len(proofs)):
        raise KzgError("length mismatch")
    if not blobs:
        return True
    zs, ys = [], []
    for blob, c in zip(blobs, commitments):
        z = compute_challenge(blob, bytes(c))
        zs.append(z)
        ys.append(
            evaluate_polynomial_in_evaluation_form(blob_to_polynomial(blob), z)
        )
    return verify_kzg_proof_batch(
        [bytes(c) for c in commitments], zs, ys, [bytes(p) for p in proofs], setup
    )


def verify_kzg_proof_batch(
    commitments: list[bytes], zs: list[int], ys: list[int],
    proofs: list[bytes], setup: TrustedSetup,
) -> bool:
    n = len(commitments)
    if not (len(zs) == len(ys) == len(proofs) == n):
        raise KzgError("batch input length mismatch")
    # Fiat-Shamir the batch randomizer (spec verify_kzg_proof_batch)
    data = (
        RANDOM_CHALLENGE_KZG_BATCH_DOMAIN
        + FIELD_ELEMENTS_PER_BLOB.to_bytes(8, ENDIANNESS)
        + n.to_bytes(8, ENDIANNESS)
    )
    for c, z, y, w in zip(commitments, zs, ys, proofs):
        data += c + z.to_bytes(32, ENDIANNESS) + y.to_bytes(32, ENDIANNESS) + w
    r = hash_to_bls_field(data)
    r_pow = [pow(r, i, BLS_MODULUS) for i in range(n)]

    C = [_decode_g1(c, "commitment") for c in commitments]
    W = [_decode_g1(w, "proof") for w in proofs]
    proof_lincomb = g1_lincomb(W, r_pow)
    proof_z_lincomb = g1_lincomb(W, [ri * z % BLS_MODULUS for ri, z in zip(r_pow, zs)])
    c_minus_y = [
        _g1_add(c_i, affine_neg(affine_mul(G1_GENERATOR, y, Fp)) if y else None)
        for c_i, y in zip(C, ys)
    ]
    c_minus_y_lincomb = g1_lincomb(c_minus_y, r_pow)
    rhs = _g1_add(c_minus_y_lincomb, proof_z_lincomb)
    pairs = []
    if proof_lincomb is not None:
        pairs.append((proof_lincomb, affine_neg(setup.g2_monomial[1])))
    if rhs is not None:
        pairs.append((rhs, G2_GENERATOR))
    if not pairs:
        return True
    return pairing_check(pairs)
