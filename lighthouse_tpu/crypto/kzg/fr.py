"""BLS12-381 scalar field (Fr) helpers for KZG polynomial math.

The polynomial side of KZG lives in Fr (the curve order), not Fp: blobs ARE
polynomials in evaluation form over the 4096th roots of unity in Fr.
"""

from __future__ import annotations

from functools import lru_cache

from ..bls.params import R as BLS_MODULUS  # curve order r

PRIMITIVE_ROOT = 7  # generator of Fr* (standard for BLS12-381)


def inv(x: int) -> int:
    return pow(x, BLS_MODULUS - 2, BLS_MODULUS)


def div(a: int, b: int) -> int:
    return a * inv(b) % BLS_MODULUS


@lru_cache(maxsize=4)
def roots_of_unity(order: int) -> list[int]:
    """The ``order`` distinct order-th roots of unity, natural order."""
    assert (BLS_MODULUS - 1) % order == 0
    root = pow(PRIMITIVE_ROOT, (BLS_MODULUS - 1) // order, BLS_MODULUS)
    out = [1]
    for _ in range(order - 1):
        out.append(out[-1] * root % BLS_MODULUS)
    assert out[-1] * root % BLS_MODULUS == 1
    return out


def bit_reversal_permutation(seq: list) -> list:
    """Reorder by bit-reversed index (the evaluation-form ordering the
    ceremony setup and blobs use)."""
    n = len(seq)
    bits = n.bit_length() - 1
    assert 1 << bits == n, "length must be a power of two"
    return [seq[int(format(i, f"0{bits}b")[::-1], 2)] for i in range(n)]


@lru_cache(maxsize=4)
def brp_roots_of_unity(order: int) -> tuple[int, ...]:
    return tuple(bit_reversal_permutation(roots_of_unity(order)))


def batch_inv(xs: list[int]) -> list[int]:
    """Montgomery batch inversion: one Fermat inverse + 3(n-1) mults.
    Zero inputs map to zero (callers exclude the on-root case upstream)."""
    n = len(xs)
    prefix = [0] * n
    acc = 1
    for i, x in enumerate(xs):
        prefix[i] = acc
        if x:
            acc = acc * x % BLS_MODULUS
    inv_acc = inv(acc)
    out = [0] * n
    for i in range(n - 1, -1, -1):
        if xs[i]:
            out[i] = inv_acc * prefix[i] % BLS_MODULUS
            inv_acc = inv_acc * xs[i] % BLS_MODULUS
    return out
