"""EIP-2386 hierarchical-deterministic wallets over EIP-2335 keystores.

Twin of crypto/eth2_wallet (`Wallet`, src/wallet.rs): a wallet encrypts its
seed with the same KDF/cipher/checksum module as keystores, tracks a
`nextaccount` counter, and derives per-validator keys along EIP-2334 paths.
"""

from __future__ import annotations

import json
import os
import uuid as uuid_mod

from . import keys as kd
from . import keystore as ks
from .bls.api import SecretKey


class WalletError(ValueError):
    pass


def create_wallet(
    name: str, password: str, seed: bytes | None = None, kdf: str = "pbkdf2"
) -> dict:
    """EIP-2386 wallet JSON (type hierarchical deterministic)."""
    seed = seed if seed is not None else os.urandom(32)
    if len(seed) < 32:
        raise WalletError("seed must be at least 32 bytes")
    crypto = ks.encrypt(seed, password, kdf=kdf)["crypto"]
    return {
        "crypto": crypto,
        "name": name,
        "nextaccount": 0,
        "type": "hierarchical deterministic",
        "uuid": str(uuid_mod.uuid4()),
        "version": 1,
    }


def decrypt_seed(wallet: dict | str, password: str) -> bytes:
    w = json.loads(wallet) if isinstance(wallet, str) else wallet
    if w.get("type") != "hierarchical deterministic" or w.get("version") != 1:
        raise WalletError("not an EIP-2386 HD wallet")
    # reuse the keystore decryptor by re-wrapping the crypto section
    shim = {"version": 4, "crypto": w["crypto"]}
    return ks.decrypt(shim, password)


def next_validator(
    wallet: dict, wallet_password: str, keystore_password: str
) -> tuple[dict, dict]:
    """Derive the wallet's next validator: returns (signing_keystore,
    withdrawal_keystore) and bumps `nextaccount` (wallet.rs semantics)."""
    seed = decrypt_seed(wallet, wallet_password)
    index = wallet["nextaccount"]
    out = []
    for path in (
        kd.validator_signing_path(index),
        kd.validator_withdrawal_path(index),
    ):
        sk = SecretKey(kd.derive_path(seed, path))
        out.append(
            ks.encrypt(
                sk.to_bytes(),
                keystore_password,
                path=path,
                pubkey=sk.public_key().to_bytes(),
            )
        )
    wallet["nextaccount"] = index + 1
    return out[0], out[1]
