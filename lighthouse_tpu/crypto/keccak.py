"""Keccak-256 (the pre-NIST Ethereum variant) from scratch.

Ethereum's discovery layer hashes with legacy Keccak (multi-rate padding
0x01), not NIST SHA3 (0x06), so hashlib cannot supply it.  Used for ENR
node ids and "v4" identity-scheme signatures (reference:
`beacon_node/lighthouse_network/src/discovery/enr.rs`, discv5 crate).

Pure-Python Keccak-f[1600] sponge, rate 1088 (capacity 512), 24 rounds.
Host-side only and never on a hot path (a handful of hashes per
discovery message).
"""

from __future__ import annotations

_ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

# rotation offsets r[x][y]
_ROTC = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)

_MASK = (1 << 64) - 1


def _rotl(v: int, n: int) -> int:
    return ((v << n) | (v >> (64 - n))) & _MASK


def _keccak_f(a: list[list[int]]) -> None:
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rotl(a[x][y], _ROTC[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y] & _MASK)
        # iota
        a[0][0] ^= rc


def keccak256(data: bytes) -> bytes:
    """Legacy Keccak-256 digest (rate 136 bytes, pad 0x01 … 0x80)."""
    rate = 136
    state = [[0] * 5 for _ in range(5)]
    # pad10*1 with Keccak domain bit 0x01
    padded = bytearray(data)
    pad_len = rate - (len(padded) % rate)
    padded += b"\x01" + b"\x00" * (pad_len - 2) + b"\x80" if pad_len >= 2 else b"\x81"
    for off in range(0, len(padded), rate):
        block = padded[off : off + rate]
        for i in range(rate // 8):
            lane = int.from_bytes(block[8 * i : 8 * i + 8], "little")
            x, y = i % 5, i // 5
            state[x][y] ^= lane
        _keccak_f(state)
    out = bytearray()
    for i in range(4):  # 32 bytes < rate, one squeeze
        x, y = i % 5, i // 5
        out += state[x][y].to_bytes(8, "little")
    return bytes(out)
