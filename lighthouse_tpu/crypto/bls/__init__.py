"""BLS12-381 signatures, backend-generic — analog of the reference `bls` crate
(reference: crypto/bls/src/lib.rs)."""

from .api import (  # noqa: F401
    AggregatePublicKey,
    AggregateSignature,
    BlsError,
    PublicKey,
    SecretKey,
    Signature,
    SignatureSet,
    aggregate_verify,
    eth_fast_aggregate_verify,
    fast_aggregate_verify,
    get_backend,
    register_backend,
    set_backend,
    verify,
    verify_signature_sets,
)
from . import params  # noqa: F401
