"""BLS12-381 curve parameters.

These are the standard, publicly specified BLS12-381 constants (IETF RFC 9380 /
the Zcash BLS12-381 specification).  The reference client consumes them through
the `blst` library (reference: crypto/bls/src/impls/blst.rs); here they are
first-class Python integers so that both the pure-Python reference backend and
the JAX/TPU backend derive every other constant (Frobenius coefficients,
cofactors, Montgomery parameters) from this single module.

Derived quantities that the reference obtains from blst's precomputed tables
(curve cofactors, twist orders) are *computed* from first principles: the twist
order is selected from the six sextic-twist candidates by actual point
arithmetic in `curve.py`, so nothing here silently depends on a transcription.
"""

import math

# Base field prime.
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

# Subgroup order (scalar field).
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# BLS parameter x (negative for BLS12-381).
X = -0xD201000000010000

# Curve: E(Fp): y^2 = x^3 + 4;  twist E'(Fp2): y^2 = x^3 + 4(u+1)  (M-twist).
B_G1 = 4
B_G2 = (4, 4)  # 4 * (1 + u)  as (c0, c1)

# Trace of Frobenius: #E(Fp) = P + 1 - T_FROB,  T_FROB = X + 1.
T_FROB = X + 1

# G1 cofactor: h1 = #E(Fp) / R  (asserted exact).
N_E1 = P + 1 - T_FROB
H1, _rem = divmod(N_E1, R)
assert _rem == 0, "G1 cofactor must divide the curve order exactly"
assert H1 == (X - 1) ** 2 // 3  # standard identity for BLS12 curves

# Sextic-twist order candidates. With t = T_FROB, the trace over Fp2 is
# t2 = t^2 - 2p. The CM equation at the Fp2 level, 4p^2 = t2^2 + 3*f2^2
# (discriminant -3), has f2 = t*f where 4p = t^2 + 3f^2, because
# 4p^2 - t2^2 = (4p - t^2) * t^2. The six twists of E(Fp2) have traces
# {±t2, ±(t2+3*f2)/2, ±(t2-3*f2)/2}. curve.py selects the one that
# annihilates actual points of E'(Fp2) and asserts divisibility by R.
T2 = T_FROB * T_FROB - 2 * P
_F2, _f2rem = divmod(4 * P - T_FROB * T_FROB, 3)
assert _f2rem == 0
F_CM = math.isqrt(_F2)
assert F_CM * F_CM == _F2
F2_CM = abs(T_FROB * F_CM)
assert 4 * P * P == T2 * T2 + 3 * F2_CM * F2_CM

TWIST_TRACE_CANDIDATES = [
    tt
    for tt in (
        (T2 + 3 * F2_CM) // 2 if (T2 + 3 * F2_CM) % 2 == 0 else None,
        (T2 - 3 * F2_CM) // 2 if (T2 - 3 * F2_CM) % 2 == 0 else None,
        -(T2 + 3 * F2_CM) // 2 if (T2 + 3 * F2_CM) % 2 == 0 else None,
        -(T2 - 3 * F2_CM) // 2 if (T2 - 3 * F2_CM) % 2 == 0 else None,
        T2,
        -T2,
    )
    if tt is not None
]

# Hash-to-curve domain separation tag used by Ethereum consensus
# (reference: crypto/bls/src/impls/blst.rs:13).
DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# Number of random bits in batch-verification weights
# (reference: crypto/bls/src/impls/blst.rs:14).
RAND_BITS = 64

# Generator of G1 (standard).
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)

# Generator of G2 (standard), coordinates in Fp2 as (c0, c1).
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

# Serialized sizes (Zcash encoding, used by the whole Ethereum ecosystem).
G1_COMPRESSED_BYTES = 48
G2_COMPRESSED_BYTES = 96
SCALAR_BYTES = 32
