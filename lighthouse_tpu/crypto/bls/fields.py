"""Pure-Python BLS12-381 field tower: Fp -> Fp2 -> Fp6 -> Fp12.

This is the *reference* arithmetic backend: the correctness anchor against
which the JAX/TPU limb kernels (jax_backend/) are differentially tested, and
the engine of the CPU fallback backend.  It plays the role blst's C/assembly
field code plays for the reference client (reference: crypto/bls/src/impls/
blst.rs uses blst's fp/fp2/fp12 types); here it is deliberately simple Python
over arbitrary-precision ints.

Tower construction (the standard one for BLS12-381):
    Fp2  = Fp[u]  / (u^2 + 1)
    Fp6  = Fp2[v] / (v^3 - xi),  xi = u + 1
    Fp12 = Fp6[w] / (w^2 - v)

Frobenius coefficients are computed at import time from `params.P` (they are
powers of xi), never transcribed.
"""

from __future__ import annotations

from .params import P

# ---------------------------------------------------------------------------
# Fp  — represented as plain ints in [0, P).  Helper functions only.
# ---------------------------------------------------------------------------


def fp_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("inverse of 0 in Fp")
    # CPython's extended-gcd modular inverse: ~9x faster than the Fermat
    # exponentiation for the 381-bit modulus (measured on this image)
    return pow(a, -1, P)


def fp_sqrt(a: int) -> int | None:
    """Square root in Fp (P ≡ 3 mod 4), or None if a is not a QR."""
    if a == 0:
        return 0
    s = pow(a, (P + 1) // 4, P)
    return s if s * s % P == a else None


class Fp:
    """Fp element with the same interface as Fp2/Fp6/Fp12, so curve code can
    be generic over the coordinate field."""

    __slots__ = ("v",)

    def __init__(self, v: int):
        self.v = v % P

    @staticmethod
    def zero() -> "Fp":
        return Fp(0)

    @staticmethod
    def one() -> "Fp":
        return Fp(1)

    def is_zero(self) -> bool:
        return self.v == 0

    def __eq__(self, other) -> bool:
        return isinstance(other, Fp) and self.v == other.v

    def __hash__(self):
        return hash(("Fp", self.v))

    def __repr__(self):
        return f"Fp(0x{self.v:x})"

    def __add__(self, o: "Fp") -> "Fp":
        return Fp(self.v + o.v)

    def __sub__(self, o: "Fp") -> "Fp":
        return Fp(self.v - o.v)

    def __neg__(self) -> "Fp":
        return Fp(-self.v)

    def __mul__(self, o) -> "Fp":
        if isinstance(o, int):
            return Fp(self.v * o)
        return Fp(self.v * o.v)

    __rmul__ = __mul__

    def square(self) -> "Fp":
        return Fp(self.v * self.v)

    def inv(self) -> "Fp":
        return Fp(fp_inv(self.v))

    def pow(self, e: int) -> "Fp":
        if e < 0:
            return self.inv().pow(-e)
        return Fp(pow(self.v, e, P))

    def sqrt(self) -> "Fp | None":
        s = fp_sqrt(self.v)
        return Fp(s) if s is not None else None

    def sgn0(self) -> int:
        return self.v % 2


# ---------------------------------------------------------------------------
# Fp2
# ---------------------------------------------------------------------------


class Fp2:
    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int):
        self.c0 = c0 % P
        self.c1 = c1 % P

    # -- constructors ------------------------------------------------------
    @staticmethod
    def zero() -> "Fp2":
        return Fp2(0, 0)

    @staticmethod
    def one() -> "Fp2":
        return Fp2(1, 0)

    # -- predicates --------------------------------------------------------
    def is_zero(self) -> bool:
        return self.c0 == 0 and self.c1 == 0

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Fp2) and self.c0 == other.c0 and self.c1 == other.c1
        )

    def __hash__(self):
        return hash((self.c0, self.c1))

    def __repr__(self):
        return f"Fp2(0x{self.c0:x}, 0x{self.c1:x})"

    # -- arithmetic --------------------------------------------------------
    def __add__(self, o: "Fp2") -> "Fp2":
        return Fp2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fp2") -> "Fp2":
        return Fp2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fp2":
        return Fp2(-self.c0, -self.c1)

    def __mul__(self, o) -> "Fp2":
        if isinstance(o, int):
            return Fp2(self.c0 * o, self.c1 * o)
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        # (a0 + a1 u)(b0 + b1 u) with u^2 = -1
        return Fp2(a0 * b0 - a1 * b1, a0 * b1 + a1 * b0)

    __rmul__ = __mul__

    def square(self) -> "Fp2":
        a0, a1 = self.c0, self.c1
        # (a0 + a1 u)^2 = (a0-a1)(a0+a1) + 2 a0 a1 u
        return Fp2((a0 - a1) * (a0 + a1), 2 * a0 * a1)

    def inv(self) -> "Fp2":
        a0, a1 = self.c0, self.c1
        norm = (a0 * a0 + a1 * a1) % P
        ninv = fp_inv(norm)
        return Fp2(a0 * ninv, -a1 * ninv)

    def conjugate(self) -> "Fp2":
        return Fp2(self.c0, -self.c1)

    def mul_by_nonresidue(self) -> "Fp2":
        """Multiply by xi = 1 + u."""
        return Fp2(self.c0 - self.c1, self.c0 + self.c1)

    def pow(self, e: int) -> "Fp2":
        if e < 0:
            return self.inv().pow(-e)
        result = Fp2.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def sqrt(self) -> "Fp2 | None":
        """Square root in Fp2 via the norm/trace ('complex') method."""
        if self.is_zero():
            return Fp2.zero()
        a0, a1 = self.c0, self.c1
        if a1 == 0:
            s = fp_sqrt(a0)
            if s is not None:
                return Fp2(s, 0)
            # a0 is a non-residue in Fp; sqrt is purely imaginary:
            # (t*u)^2 = -t^2  => t = sqrt(-a0)
            t = fp_sqrt((-a0) % P)
            return Fp2(0, t) if t is not None else None
        alpha = fp_sqrt((a0 * a0 + a1 * a1) % P)  # norm is QR iff a is a square
        if alpha is None:
            return None
        delta = (a0 + alpha) * fp_inv(2) % P
        x0 = fp_sqrt(delta)
        if x0 is None:
            delta = (a0 - alpha) * fp_inv(2) % P
            x0 = fp_sqrt(delta)
            if x0 is None:
                return None
        x1 = a1 * fp_inv(2 * x0 % P) % P
        cand = Fp2(x0, x1)
        return cand if cand.square() == self else None

    def sgn0(self) -> int:
        """RFC 9380 sign function for Fp2 elements."""
        sign_0 = self.c0 % 2
        zero_0 = 1 if self.c0 == 0 else 0
        sign_1 = self.c1 % 2
        return sign_0 | (zero_0 & sign_1)


XI = Fp2(1, 1)  # the Fp6 non-residue

# ---------------------------------------------------------------------------
# Fp6 = Fp2[v] / (v^3 - xi)
# ---------------------------------------------------------------------------


class Fp6:
    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fp2, c1: Fp2, c2: Fp2):
        self.c0, self.c1, self.c2 = c0, c1, c2

    @staticmethod
    def zero() -> "Fp6":
        return Fp6(Fp2.zero(), Fp2.zero(), Fp2.zero())

    @staticmethod
    def one() -> "Fp6":
        return Fp6(Fp2.one(), Fp2.zero(), Fp2.zero())

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Fp6)
            and self.c0 == other.c0
            and self.c1 == other.c1
            and self.c2 == other.c2
        )

    def __repr__(self):
        return f"Fp6({self.c0}, {self.c1}, {self.c2})"

    def __add__(self, o: "Fp6") -> "Fp6":
        return Fp6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o: "Fp6") -> "Fp6":
        return Fp6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self) -> "Fp6":
        return Fp6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o) -> "Fp6":
        if isinstance(o, (int, Fp2)):
            return Fp6(self.c0 * o, self.c1 * o, self.c2 * o)
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0 = a0 * b0
        t1 = a1 * b1
        t2 = a2 * b2
        # Karatsuba-style (Toom) interpolation
        c0 = ((a1 + a2) * (b1 + b2) - t1 - t2).mul_by_nonresidue() + t0
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_by_nonresidue()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fp6(c0, c1, c2)

    def square(self) -> "Fp6":
        return self * self

    def mul_by_v(self) -> "Fp6":
        """Multiply by v: (c0, c1, c2) -> (xi*c2, c0, c1)."""
        return Fp6(self.c2.mul_by_nonresidue(), self.c0, self.c1)

    def inv(self) -> "Fp6":
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.square() - (a1 * a2).mul_by_nonresidue()
        t1 = a2.square().mul_by_nonresidue() - a0 * a1
        t2 = a1.square() - a0 * a2
        denom = a0 * t0 + (a2 * t1).mul_by_nonresidue() + (a1 * t2).mul_by_nonresidue()
        dinv = denom.inv()
        return Fp6(t0 * dinv, t1 * dinv, t2 * dinv)


# ---------------------------------------------------------------------------
# Fp12 = Fp6[w] / (w^2 - v)
# ---------------------------------------------------------------------------

# Frobenius coefficients: gamma_i = xi^(i*(P-1)/6) in Fp2, i = 1..5.
assert (P - 1) % 6 == 0
FROB_GAMMA = [XI.pow(i * (P - 1) // 6) for i in range(6)]  # index 0 unused (== 1)


def _fp2_frobenius(a: Fp2) -> Fp2:
    return a.conjugate()


def _fp6_frobenius(a: Fp6) -> Fp6:
    return Fp6(
        _fp2_frobenius(a.c0),
        _fp2_frobenius(a.c1) * FROB_GAMMA[2],
        _fp2_frobenius(a.c2) * FROB_GAMMA[4],
    )


class Fp12:
    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fp6, c1: Fp6):
        self.c0, self.c1 = c0, c1

    @staticmethod
    def zero() -> "Fp12":
        return Fp12(Fp6.zero(), Fp6.zero())

    @staticmethod
    def one() -> "Fp12":
        return Fp12(Fp6.one(), Fp6.zero())

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero()

    def __eq__(self, other) -> bool:
        return isinstance(other, Fp12) and self.c0 == other.c0 and self.c1 == other.c1

    def __repr__(self):
        return f"Fp12({self.c0}, {self.c1})"

    def __add__(self, o: "Fp12") -> "Fp12":
        return Fp12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fp12") -> "Fp12":
        return Fp12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fp12":
        return Fp12(-self.c0, -self.c1)

    def __mul__(self, o) -> "Fp12":
        if isinstance(o, (int, Fp2, Fp6)):
            return Fp12(self.c0 * o, self.c1 * o)
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        c0 = t0 + t1.mul_by_v()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1
        return Fp12(c0, c1)

    def square(self) -> "Fp12":
        a0, a1 = self.c0, self.c1
        t = a0 * a1
        c0 = (a0 + a1) * (a0 + a1.mul_by_v()) - t - t.mul_by_v()
        return Fp12(c0, t + t)

    def inv(self) -> "Fp12":
        a0, a1 = self.c0, self.c1
        denom = a0.square() - a1.square().mul_by_v()
        dinv = denom.inv()
        return Fp12(a0 * dinv, -(a1 * dinv))

    def conjugate(self) -> "Fp12":
        """The Fp6-conjugation c0 - c1 w == Frobenius^6; inverse on the
        cyclotomic subgroup (unit-norm elements after the easy part)."""
        return Fp12(self.c0, -self.c1)

    def mul_by_023(self, l0: Fp2, l2: Fp2, l3: Fp2) -> "Fp12":
        """Multiply by the sparse element l0 + l2*w^2 + l3*w^3 (a Miller-loop
        line function in the basis Fp12 = Fp2[w]/(w^6 - xi)).  In the tower
        that element is (b0, b1) with b0 = (l0, l2, 0), b1 = (0, l3, 0);
        exploiting the zeros costs ~15 Fp2 muls vs 18+ for the dense mul."""
        a0, a1 = self.c0, self.c1
        # t0 = a0 * b0, b0 = (l0, l2, 0):
        #   z0 = x0*l0 + xi*(x2*l2); z1 = x0*l2 + x1*l0; z2 = x1*l2 + x2*l0
        t0 = Fp6(
            a0.c0 * l0 + (a0.c2 * l2).mul_by_nonresidue(),
            a0.c0 * l2 + a0.c1 * l0,
            a0.c1 * l2 + a0.c2 * l0,
        )
        # t1 = a1 * b1, b1 = (0, l3, 0):  (x0,x1,x2)*(l3 v) =
        #   xi*(x2*l3) + x0*l3 v + x1*l3 v^2
        t1 = Fp6(
            (a1.c2 * l3).mul_by_nonresidue(),
            a1.c0 * l3,
            a1.c1 * l3,
        )
        # c0 = t0 + t1*v ; c1 = (a0+a1)(b0+b1) - t0 - t1 with
        # b0+b1 = (l0, l2+l3, 0).
        s = a0 + a1
        l23 = l2 + l3
        t2 = Fp6(
            s.c0 * l0 + (s.c2 * l23).mul_by_nonresidue(),
            s.c0 * l23 + s.c1 * l0,
            s.c1 * l23 + s.c2 * l0,
        )
        return Fp12(t0 + t1.mul_by_v(), t2 - t0 - t1)

    def frobenius(self) -> "Fp12":
        c0 = _fp6_frobenius(self.c0)
        c1 = _fp6_frobenius(self.c1)
        # multiply c1 by gamma^(1/1): coefficients of w, w*v, w*v^2 pick up
        # xi^((p-1)/6) * the Fp6 coefficient adjustments
        c1 = Fp6(
            c1.c0 * FROB_GAMMA[1],
            c1.c1 * FROB_GAMMA[1],
            c1.c2 * FROB_GAMMA[1],
        )
        return Fp12(c0, c1)

    def frobenius_n(self, n: int) -> "Fp12":
        out = self
        for _ in range(n % 12):
            out = out.frobenius()
        return out

    def pow(self, e: int) -> "Fp12":
        if e < 0:
            return self.inv().pow(-e)
        result = Fp12.one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result


def fp12_from_fp2_coeffs(coeffs: list[Fp2]) -> Fp12:
    """Build an Fp12 element from coefficients of w^0..w^5 over Fp2, using the
    basis identification Fp12 = Fp2[w]/(w^6 - xi):
        1, w, w^2, w^3, w^4, w^5
    maps to the tower as (c0 = (a0, a2, a4) in v-basis, c1 = (a1, a3, a5)),
    since v = w^2 and w*v = w^3 etc.
    """
    a0, a1, a2, a3, a4, a5 = coeffs
    return Fp12(Fp6(a0, a2, a4), Fp6(a1, a3, a5))
