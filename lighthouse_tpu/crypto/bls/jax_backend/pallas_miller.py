"""Fused Miller-step Pallas kernels (PERF.md plan item 3).

The measured bound at B >= 4096 is per-`pallas_call` dispatch: one Miller
step issues ~25 sequential stacked mont_mul calls (line formulas, fp12
square, two sparse 023 multiplies) through `tower.py`, and there are 63
steps.  These kernels run each step half as ONE Mosaic program — every
fp2/fp6/fp12 intermediate lives in VMEM, the limb loops unroll at trace
time, and the per-step call count drops from ~25 to 2:

* ``_step_dbl_kernel``  — line_dbl + fp12_sqr + mul_by_023
* ``_step_add_kernel``  — line_add + mul_by_023 + bit-select

Bound discipline: `fp.py`'s lazy-representation rules are enforced at
TRACE time by the `KFp` mini-library below — a value bound (in units of
P) rides every in-kernel value as a Python float, additions sum bounds,
biased subtractions pick the same power-of-two k as `fp.fp_sub`, and the
Montgomery product asserts the same bound-product ceiling as
`fp.mont_mul`.  Step outputs are reduced to the stable bound class
(<= 2), exactly like the XLA step, so the two paths are drop-in
interchangeable — `tests/test_pallas_miller.py` proves bit-equality in
interpret mode.

Gated behind LIGHTHOUSE_TPU_MILLER=1 (fp.miller_fused_active) until the
on-chip A/B lands, mirroring the chain kernels.

Cost model (measured r5): each kernel holds ~160 unrolled Montgomery
multiplies, so host-side TRACING of one kernel is minutes-scale on a
single CPU core (the jaxpr is ~10^5 primitives), and the interpret-mode
equality proof runs it eagerly (tests/test_pallas_miller.py; the
ONE-jit-around-everything variant takes >45 min to XLA-compile and is
slow-marked).  On real hardware the trace happens once per batch shape
at node startup — alongside the existing 120-400 s Mosaic compiles —
and is amortized by the persistent compile cache across restarts; the
per-step dispatch saving is what the serving path keeps.

Capability twin: the Miller loop of blst's
verify_multiple_aggregate_signatures (crypto/bls/src/impls/blst.rs:
107-117); the fusion itself is TPU-original.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import params
from . import fp as F
from . import pairing as _PR
from . import pallas_fp as PF

N = F.N
MASK = PF.MASK

_P_NP = np.asarray(F.int_to_limbs(F.P_INT)).reshape(N, 1)
_PP_NP = np.asarray(F.int_to_limbs(F.PPRIME_INT)).reshape(N, 1)
_ONE_NP = np.asarray(F.int_to_limbs(F.R1_INT)).reshape(N, 1)
_BIAS_NP = {k: F._biased_kp(k).reshape(N, 1) for k in F._BIAS_KS}

# the loop pattern is pairing.py's, not a private copy


_CTX_KS = F._BIAS_KS  # THE bias ladder (a private copy would drift)
N_CONSTS = 3 + len(_CTX_KS)  # p, pp, one, biases


class _Ctx:
    """In-kernel constants (pallas forbids closure constants: they ride
    as trailing const-spec inputs, one (26, tile) block reused by every
    grid step) plus the Montgomery core pair — VPU schoolbook by
    default, the MXU dot-product core (pallas_mxu) when the step kernel
    was built with mxu=True."""

    def __init__(self, const_refs, mxu: bool = False):
        self.p = const_refs[0][:]
        self.pp = const_refs[1][:]
        self.one = const_refs[2][:]
        self.bias = {
            k: const_refs[3 + i][:] for i, k in enumerate(_CTX_KS)
        }
        self.mont, self.msqr = PF._core_pair(mxu)


def _const_arrays(tile: int):
    """The host-side operands matching _Ctx's layout."""
    consts = [_P_NP, _PP_NP, _ONE_NP] + [_BIAS_NP[k] for k in _CTX_KS]
    return [
        jnp.broadcast_to(jnp.asarray(c, jnp.uint32), (N, tile))
        for c in consts
    ]


class KFp:
    """In-kernel lazy field element: (26, T) quasi limbs + static bound."""

    __slots__ = ("cols", "bound")

    def __init__(self, cols, bound: float):
        assert bound <= F.MAX_BOUND, f"KFp bound {bound} escapes MAX_BOUND"
        self.cols = cols
        self.bound = bound


def _k_for(bound: float) -> int:
    """fp.py's bias-selection rule, shared — a drifted copy would break
    the fused/XLA bit-equality contract."""
    k = F._k_for(bound)
    assert k in _BIAS_NP, f"no bias constant for k={k}"
    return k


def kadd(ctx, a: KFp, b: KFp) -> KFp:
    return KFp(PF._compress1(a.cols + b.cols), a.bound + b.bound)


def ksub(ctx, a: KFp, b: KFp) -> KFp:
    k = _k_for(b.bound)
    return KFp(
        PF._compress1((a.cols + ctx.bias[k]) - b.cols), a.bound + k
    )


def kneg(ctx, a: KFp) -> KFp:
    k = _k_for(a.bound)
    return KFp(PF._compress1(ctx.bias[k] - a.cols), float(k))


def kdbl(ctx, a: KFp) -> KFp:
    return kadd(ctx, a, a)


def kmul(ctx, a: KFp, b: KFp) -> KFp:
    prod = a.bound * b.bound
    assert prod <= F.MAX_MUL_PRODUCT, (
        f"in-kernel mont product bound {prod} > {F.MAX_MUL_PRODUCT}"
    )
    return KFp(
        ctx.mont(a.cols, b.cols, ctx.p, ctx.pp),
        prod / F.MONT_DIVISOR + F.MONT_EPS,
    )


def ksqr(ctx, a: KFp) -> KFp:
    prod = a.bound * a.bound
    assert prod <= F.MAX_MUL_PRODUCT
    return KFp(
        ctx.msqr(a.cols, ctx.p, ctx.pp),
        prod / F.MONT_DIVISOR + F.MONT_EPS,
    )


def kreduce(ctx, a: KFp) -> KFp:
    out = kmul(ctx, a, KFp(ctx.one, 1.0))
    assert out.bound <= F.REDUCE_PIN
    return KFp(out.cols, F.REDUCE_PIN)


def kguard(ctx, a: KFp, m: float) -> KFp:
    return kreduce(ctx, a) if a.bound > m else a


def kselect(mask, a: KFp, b: KFp) -> KFp:
    return KFp(
        jnp.where(mask != 0, a.cols, b.cols), max(a.bound, b.bound)
    )


# -- fp2 (pairs) — formulas mirror tower.py 1:1 -----------------------------


def k2_add(ctx, a, b):
    return (kadd(ctx, a[0], b[0]), kadd(ctx, a[1], b[1]))


def k2_sub(ctx, a, b):
    return (ksub(ctx, a[0], b[0]), ksub(ctx, a[1], b[1]))


def k2_neg(ctx, a):
    return (kneg(ctx, a[0]), kneg(ctx, a[1]))


def k2_dbl(ctx, a):
    return (kdbl(ctx, a[0]), kdbl(ctx, a[1]))


def k2_guard(ctx, a, m: float = 11.0):
    if max(a[0].bound, a[1].bound) > m:
        return (kreduce(ctx, a[0]), kreduce(ctx, a[1]))
    return a


def k2_mul(ctx, a, b):
    a = k2_guard(ctx, a)
    b = k2_guard(ctx, b)
    s0 = kadd(ctx, a[0], a[1])
    s1 = kadd(ctx, b[0], b[1])
    t0 = kmul(ctx, a[0], b[0])
    t1 = kmul(ctx, a[1], b[1])
    t2 = kmul(ctx, s0, s1)
    return (
        ksub(ctx, t0, t1),
        ksub(ctx, t2, kadd(ctx, t0, t1)),
    )


def k2_sqr(ctx, a):
    a = k2_guard(ctx, a)
    d = ksub(ctx, a[0], a[1])
    s = kadd(ctx, a[0], a[1])
    c0 = kmul(ctx, d, s)
    t = kmul(ctx, a[0], a[1])
    return (c0, kadd(ctx, t, t))


def k2_mul_fp(ctx, a, s: KFp):
    return (kmul(ctx, a[0], s), kmul(ctx, a[1], s))


def k2_mul_small(ctx, a, k: int):
    assert k >= 1
    out = a
    for bit in bin(k)[3:]:
        out = k2_dbl(ctx, out)
        if bit == "1":
            out = k2_add(ctx, out, a)
    return out


def k2_mul_by_nonresidue(ctx, a):
    return (ksub(ctx, a[0], a[1]), kadd(ctx, a[0], a[1]))


def k2_reduce(ctx, a):
    return (kreduce(ctx, a[0]), kreduce(ctx, a[1]))


def k2_select(mask, a, b):
    return (kselect(mask, a[0], b[0]), kselect(mask, a[1], b[1]))


# -- fp6 (triples of fp2) ---------------------------------------------------


def k6_add(ctx, a, b):
    return tuple(k2_add(ctx, x, y) for x, y in zip(a, b))


def k6_sub(ctx, a, b):
    return tuple(k2_sub(ctx, x, y) for x, y in zip(a, b))


def k6_mul_by_v(ctx, a):
    return (k2_mul_by_nonresidue(ctx, a[2]), a[0], a[1])


def k6_reduce(ctx, a):
    return tuple(k2_reduce(ctx, x) for x in a)


def k6_mul(ctx, a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = k2_mul(ctx, a0, b0)
    t1 = k2_mul(ctx, a1, b1)
    t2 = k2_mul(ctx, a2, b2)
    u12 = k2_mul(ctx, k2_add(ctx, a1, a2), k2_add(ctx, b1, b2))
    u01 = k2_mul(ctx, k2_add(ctx, a0, a1), k2_add(ctx, b0, b1))
    u02 = k2_mul(ctx, k2_add(ctx, a0, a2), k2_add(ctx, b0, b2))
    X = k2_sub(ctx, k2_sub(ctx, u12, t1), t2)
    Y = k2_sub(ctx, k2_sub(ctx, u01, t0), t1)
    Z = k2_sub(ctx, k2_sub(ctx, u02, t0), t2)
    c0 = k2_add(ctx, k2_mul_by_nonresidue(ctx, X), t0)
    c1 = k2_add(ctx, Y, k2_mul_by_nonresidue(ctx, t2))
    c2 = k2_add(ctx, Z, t1)
    return k6_reduce(ctx, (c0, c1, c2))


# -- fp12 (pairs of fp6) ----------------------------------------------------


def k12_sqr(ctx, a):
    a0, a1 = a
    t = k6_mul(ctx, a0, a1)
    c0 = k6_sub(
        ctx,
        k6_sub(
            ctx,
            k6_mul(
                ctx, k6_add(ctx, a0, a1),
                k6_add(ctx, a0, k6_mul_by_v(ctx, a1)),
            ),
            t,
        ),
        k6_mul_by_v(ctx, t),
    )
    c1 = k6_add(ctx, t, t)
    return tuple(k6_reduce(ctx, h) for h in (c0, c1))


def k12_mul_by_023(ctx, f, l0, l2, l3):
    a0, a1 = f
    s = k6_add(ctx, a0, a1)
    l23 = k2_add(ctx, l2, l3)
    # fifteen fp2 products, individually (no dispatch cost in-kernel)
    p00 = k2_mul(ctx, a0[0], l0)
    p02 = k2_mul(ctx, a0[2], l2)
    q00 = k2_mul(ctx, a0[0], l2)
    q01 = k2_mul(ctx, a0[1], l0)
    r01 = k2_mul(ctx, a0[1], l2)
    r02 = k2_mul(ctx, a0[2], l0)
    w2 = k2_mul(ctx, a1[2], l3)
    w0 = k2_mul(ctx, a1[0], l3)
    w1 = k2_mul(ctx, a1[1], l3)
    s00 = k2_mul(ctx, s[0], l0)
    s02 = k2_mul(ctx, s[2], l23)
    v00 = k2_mul(ctx, s[0], l23)
    v01 = k2_mul(ctx, s[1], l0)
    x01 = k2_mul(ctx, s[1], l23)
    x02 = k2_mul(ctx, s[2], l0)
    t0 = (
        k2_add(ctx, p00, k2_mul_by_nonresidue(ctx, p02)),
        k2_add(ctx, q00, q01),
        k2_add(ctx, r01, r02),
    )
    t1 = (k2_mul_by_nonresidue(ctx, w2), w0, w1)
    t2 = (
        k2_add(ctx, s00, k2_mul_by_nonresidue(ctx, s02)),
        k2_add(ctx, v00, v01),
        k2_add(ctx, x01, x02),
    )
    c0 = k6_add(ctx, t0, k6_mul_by_v(ctx, t1))
    c1 = k6_sub(ctx, k6_sub(ctx, t2, t0), t1)
    return (k6_reduce(ctx, c0), k6_reduce(ctx, c1))


# -- line formulas (pairing.py twins) ---------------------------------------


def k_line_dbl(ctx, Tpt, xp: KFp, yp: KFp):
    X1, Y1, Z1 = Tpt
    X_sq = k2_sqr(ctx, X1)
    Y_sq = k2_sqr(ctx, Y1)
    Z_sq = k2_sqr(ctx, Z1)
    YZ = k2_mul(ctx, Y1, Z1)
    E = k2_mul_small(ctx, X_sq, 3)
    XB = k2_add(ctx, X1, Y_sq)
    X_cu = k2_mul(ctx, X_sq, X1)
    Z_cu = k2_mul(ctx, Z_sq, Z1)
    XZ = k2_mul(ctx, X_sq, Z_sq)
    C = k2_sqr(ctx, Y_sq)
    t = k2_sqr(ctx, XB)
    Fv = k2_sqr(ctx, k2_guard(ctx, E))
    l0 = k2_sub(ctx, k2_mul_small(ctx, X_cu, 3), k2_dbl(ctx, Y_sq))
    D = k2_dbl(ctx, k2_sub(ctx, k2_sub(ctx, t, X_sq), C))
    X3 = k2_sub(ctx, Fv, k2_dbl(ctx, D))
    YZ3 = k2_dbl(ctx, k2_mul(ctx, Y1, Z_cu))
    m3XZ = k2_neg(ctx, k2_mul_small(ctx, XZ, 3))
    l2 = (kmul(ctx, kguard(ctx, m3XZ[0], 40.0), xp),
          kmul(ctx, kguard(ctx, m3XZ[1], 40.0), xp))
    l3 = (kmul(ctx, YZ3[0], yp), kmul(ctx, YZ3[1], yp))
    m = k2_mul(ctx, k2_guard(ctx, E), k2_sub(ctx, D, X3))
    Y3 = k2_sub(ctx, m, k2_mul_small(ctx, C, 8))
    Z3 = k2_dbl(ctx, YZ)
    out = [k2_reduce(ctx, v) for v in (l0, l2, l3, X3, Y3, Z3)]
    return (out[0], out[1], out[2]), (out[3], out[4], out[5])


def k_line_add(ctx, Tpt, Q, xp: KFp, yp: KFp):
    X1, Y1, Z1 = Tpt
    x2, y2 = Q
    Z_sq = k2_sqr(ctx, Z1)
    Z_cu = k2_mul(ctx, Z_sq, Z1)
    U2 = k2_mul(ctx, x2, Z_sq)
    H = k2_sub(ctx, U2, X1)
    S2 = k2_mul(ctx, y2, Z_cu)
    ZH = k2_mul(ctx, Z1, H)
    H_sq = k2_sqr(ctx, k2_guard(ctx, H))
    rr = k2_sub(ctx, S2, Y1)
    p_rx = k2_mul(ctx, rr, x2)
    p_yZH = k2_mul(ctx, y2, ZH)
    rr2 = k2_sqr(ctx, k2_guard(ctx, rr))
    H_cu = k2_mul(ctx, H, H_sq)
    V = k2_mul(ctx, X1, H_sq)
    l0 = k2_sub(ctx, p_rx, p_yZH)
    X3 = k2_sub(ctx, k2_sub(ctx, rr2, H_cu), k2_dbl(ctx, V))
    m1 = k2_mul(ctx, rr, k2_sub(ctx, V, X3))
    m2 = k2_mul(ctx, Y1, H_cu)
    Y3 = k2_sub(ctx, m1, m2)
    neg_rr = k2_neg(ctx, rr)
    l2 = (kmul(ctx, kguard(ctx, neg_rr[0], 40.0), xp),
          kmul(ctx, kguard(ctx, neg_rr[1], 40.0), xp))
    l3 = (kmul(ctx, ZH[0], yp), kmul(ctx, ZH[1], yp))
    out = [k2_reduce(ctx, v) for v in (l0, l2, l3, X3, Y3, ZH)]
    return (out[0], out[1], out[2]), (out[3], out[4], out[5])


# -- the two fused step kernels ---------------------------------------------

# layout helpers: an fp12 is 12 limb planes, a Jacobian twist point 6,
# an affine twist point 4 — flattened in this fixed order
_F12 = 12
_TPT = 6


def _read_f12(refs, base, bound=2.0):
    vals = [KFp(refs[base + i][:], bound) for i in range(_F12)]
    return (
        ((vals[0], vals[1]), (vals[2], vals[3]), (vals[4], vals[5])),
        ((vals[6], vals[7]), (vals[8], vals[9]), (vals[10], vals[11])),
    )


def _f12_lanes(f):
    return [
        f[0][0][0], f[0][0][1], f[0][1][0], f[0][1][1], f[0][2][0], f[0][2][1],
        f[1][0][0], f[1][0][1], f[1][1][0], f[1][1][1], f[1][2][0], f[1][2][1],
    ]


def _step_dbl_kernel(*refs, mxu: bool = False):
    # refs: f(12) T(6) xp yp consts(N_CONSTS) | out: f'(12) T'(6)
    n_in = _F12 + _TPT + 2 + N_CONSTS
    ins, outs = refs[:n_in], refs[n_in:]
    ctx = _Ctx(ins[_F12 + _TPT + 2 :], mxu=mxu)
    f = _read_f12(ins, 0)
    Tpt = tuple(
        (KFp(ins[_F12 + 2 * i][:], 2.0), KFp(ins[_F12 + 2 * i + 1][:], 2.0))
        for i in range(3)
    )
    xp = KFp(ins[_F12 + 6][:], 2.0)
    yp = KFp(ins[_F12 + 7][:], 2.0)
    line, T2 = k_line_dbl(ctx, Tpt, xp, yp)
    f2 = k12_mul_by_023(ctx, k12_sqr(ctx, f), *line)
    # every lane below is already in the stable bound class (the fp12
    # ops end in k6_reduce; the line formulas end in k2_reduce) — write
    # the limbs straight out, no second reduction
    for ref, v in zip(outs[:_F12], _f12_lanes(f2)):
        assert v.bound <= 2.0
        ref[:] = v.cols
    flat_T = [c for pt in T2 for c in pt]
    for ref, v in zip(outs[_F12:], flat_T):
        assert v.bound <= 2.0
        ref[:] = v.cols


def _step_add_kernel(*refs, mxu: bool = False):
    # refs: f(12) T(6) q(4) xp yp bit consts(N_CONSTS) | out: f'(12) T'(6)
    n_in = _F12 + _TPT + 4 + 2 + 1 + N_CONSTS
    ins, outs = refs[:n_in], refs[n_in:]
    ctx = _Ctx(ins[_F12 + _TPT + 4 + 2 + 1 :], mxu=mxu)
    f = _read_f12(ins, 0)
    Tpt = tuple(
        (KFp(ins[_F12 + 2 * i][:], 2.0), KFp(ins[_F12 + 2 * i + 1][:], 2.0))
        for i in range(3)
    )
    q = (
        (KFp(ins[_F12 + 6][:], 2.0), KFp(ins[_F12 + 7][:], 2.0)),
        (KFp(ins[_F12 + 8][:], 2.0), KFp(ins[_F12 + 9][:], 2.0)),
    )
    xp = KFp(ins[_F12 + 10][:], 2.0)
    yp = KFp(ins[_F12 + 11][:], 2.0)
    bit = ins[_F12 + 12][:]  # (1, T) uint32
    line, T_add = k_line_add(ctx, Tpt, q, xp, yp)
    f_a = k12_mul_by_023(ctx, f, *line)
    f_lanes = _f12_lanes(f)
    fa_lanes = _f12_lanes(f_a)
    # both select arms are already bound <= 2 (inputs arrive reduced;
    # the computed arms end in k6/k2 reductions): write limbs directly
    for ref, va, vf in zip(outs[:_F12], fa_lanes, f_lanes):
        sel = kselect(bit, va, vf)
        assert sel.bound <= 2.0
        ref[:] = sel.cols
    for i in range(3):
        for c in range(2):
            sel = kselect(bit, T_add[i][c], Tpt[i][c])
            assert sel.bound <= 2.0
            outs[_F12 + 2 * i + c][:] = sel.cols


@functools.lru_cache(maxsize=8)
def _dbl_call(n_padded: int, tile: int, interpret: bool,
              mxu: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (n_padded // tile,)
    spec = pl.BlockSpec((N, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    const_spec = pl.BlockSpec((N, tile), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
    n_in = _F12 + _TPT + 2
    out_shape = tuple(
        jax.ShapeDtypeStruct((N, n_padded), jnp.uint32)
        for _ in range(_F12 + _TPT)
    )
    return pl.pallas_call(
        functools.partial(_step_dbl_kernel, mxu=mxu),
        out_shape=out_shape,
        grid=grid,
        in_specs=[spec] * n_in + [const_spec] * N_CONSTS,
        out_specs=(spec,) * (_F12 + _TPT),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=8)
def _add_call(n_padded: int, tile: int, interpret: bool,
              mxu: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid = (n_padded // tile,)
    spec = pl.BlockSpec((N, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    bit_spec = pl.BlockSpec((1, tile), lambda i: (0, i),
                            memory_space=pltpu.VMEM)
    const_spec = pl.BlockSpec((N, tile), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
    n_in = _F12 + _TPT + 4 + 2
    out_shape = tuple(
        jax.ShapeDtypeStruct((N, n_padded), jnp.uint32)
        for _ in range(_F12 + _TPT)
    )
    return pl.pallas_call(
        functools.partial(_step_add_kernel, mxu=mxu),
        out_shape=out_shape,
        grid=grid,
        in_specs=[spec] * n_in + [bit_spec] + [const_spec] * N_CONSTS,
        out_specs=(spec,) * (_F12 + _TPT),
        interpret=interpret,
    )


def _pad_flat(arrs, tile):
    n = arrs[0].shape[-1]
    n_padded = -(-n // tile) * tile
    if n_padded == n:
        return arrs, n, n_padded
    pad = ((0, 0), (0, n_padded - n))
    return [jnp.pad(a, pad) for a in arrs], n, n_padded


def miller_loop_fused(p_aff, q_aff):
    """Drop-in twin of pairing.miller_loop running each step as two fused
    Pallas programs.  Inputs/outputs are LFp pytrees exactly like the XLA
    path; the fp12 result carries the standard conjugation for the
    negative BLS parameter."""
    from . import tower as T

    interpret = jax.default_backend() != "tpu"

    def pin(c):
        return F.relabel(F.guard_le(c, 2.0), 2.0)

    xp, yp = pin(p_aff[0]), pin(p_aff[1])
    q0 = (pin(q_aff[0][0]), pin(q_aff[0][1]))
    q1 = (pin(q_aff[1][0]), pin(q_aff[1][1]))
    batch = xp.limbs.shape[1:]

    def flat(x: F.LFp):
        return x.limbs.reshape(N, -1)

    n = flat(xp).shape[-1]
    tile = PF.pick_tile(n)

    one2 = tuple(F.relabel(c, 2.0) for c in T.fp2_one_like(q0))
    f_init = (
        (one2, (F.zero_like(xp), F.zero_like(xp)),
         (F.zero_like(xp), F.zero_like(xp))),
        ((F.zero_like(xp), F.zero_like(xp)),
         (F.zero_like(xp), F.zero_like(xp)),
         (F.zero_like(xp), F.zero_like(xp))),
    )
    f_lanes = [flat(v) for v in _f12_lanes(f_init)]
    T_lanes = [flat(q0[0]), flat(q0[1]), flat(q1[0]), flat(q1[1]),
               flat(one2[0]), flat(one2[1])]
    q_lanes = [flat(q0[0]), flat(q0[1]), flat(q1[0]), flat(q1[1])]
    pxy = [flat(xp), flat(yp)]

    all_in, n0, n_padded = _pad_flat(
        f_lanes + T_lanes + q_lanes + pxy, tile
    )
    f_arr = jnp.stack(all_in[:_F12])
    T_arr = jnp.stack(all_in[_F12 : _F12 + _TPT])
    q_arr = jnp.stack(all_in[_F12 + _TPT : _F12 + _TPT + 4])
    xp_a, yp_a = all_in[-2], all_in[-1]

    mxu = F.mxu_enabled()
    dbl = _dbl_call(n_padded, tile, interpret, mxu)
    add = _add_call(n_padded, tile, interpret, mxu)
    bits = jnp.array(_PR._X_BITS[1:], dtype=jnp.uint32)
    consts = _const_arrays(tile)

    def step(carry, bit):
        f_arr, T_arr = carry
        outs = dbl(*[f_arr[i] for i in range(_F12)],
                   *[T_arr[i] for i in range(_TPT)], xp_a, yp_a, *consts)
        f_mid = jnp.stack(outs[:_F12])
        T_mid = jnp.stack(outs[_F12:])
        bit_row = jnp.broadcast_to(bit, (1, n_padded)).astype(jnp.uint32)
        outs = add(*[f_mid[i] for i in range(_F12)],
                   *[T_mid[i] for i in range(_TPT)],
                   *[q_arr[i] for i in range(4)], xp_a, yp_a, bit_row,
                   *consts)
        return (jnp.stack(outs[:_F12]), jnp.stack(outs[_F12:])), None

    (f_arr, _), _ = jax.lax.scan(step, (f_arr, T_arr), bits)

    def unflat(i):
        a = f_arr[i][:, :n0].reshape((N,) + batch)
        return F.LFp(a, 2.0)

    vals = [unflat(i) for i in range(_F12)]
    f = (
        ((vals[0], vals[1]), (vals[2], vals[3]), (vals[4], vals[5])),
        ((vals[6], vals[7]), (vals[8], vals[9]), (vals[10], vals[11])),
    )
    return T.fp12_conj(f)
