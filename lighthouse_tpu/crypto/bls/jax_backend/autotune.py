"""Per-device-kind kernel autotuner: measured arm × batch-shape plans.

The backend now has multiple proven kernel *arms* — the VPU 26×15-bit
plane (``fp.py``), the MXU 31×13-bit dot-product core (``pallas_mxu.py``)
— and RANGE_REPORT.json proves a 43×9-bit split would fit the f32 MXU
path.  Until this module, the serving path picked one statically via
``LIGHTHOUSE_TPU_MXU``, so every boot on unfamiliar silicon served a
guess.  The tuner here turns that guess into a measurement:

1. **Arm registry** (``ARM_TABLE``): arm id → LimbSpec plane, ``fp``
   routing toggle, toggle value, and the RANGE_REPORT.json program whose
   clearance the arm requires.  The table is a pure literal — the
   ``tune-plan`` lint family (``analysis/registry_lint.py``) AST-parses
   it and cross-checks toggles against ``fp.py`` and plan kernels
   against ``AOT_KERNELS`` without importing jax.  A future GPU
   (Pallas-Triton) arm is a row here, not a fork.
2. **Legality gate** (``proven_arms``): an arm may enter trials only if
   its proof program is range_lint-proven (``contracts_ok``) at zero
   range-family waivers.  Unproven arms never run, even off-plan.
3. **Trial harness** (``trial``): the shared padding/tiling microbench
   from ``BENCH_MXU`` — one jitted ``pallas_fp.mont_mul_limbs``
   dispatch per call, identical operands for every arm, best-of-iters.
   The timer is injectable (same pattern as the serve batcher's fake
   clock) so fast-tier tests tune deterministically on CPU.
4. **Plan** (``tune`` / ``tune_and_store``): per batch shape, the
   winning arm plus its trial timings, keyed by (device kind × jax
   version) and persisted into the AOT store's signed manifest
   (``AotStore.write_plan``).  ``prewarm`` installs the plan before any
   listener opens (``install_plan`` → ``fp.install_mxu_plan``), so the
   arm is resolved at install/compile time — zero online experiments,
   zero per-batch dispatch overhead.

Override precedence (see ``fp.mxu_enabled``): ``fp.set_mxu`` in-process
A/B > ``LIGHTHOUSE_TPU_MXU`` env flag > installed plan > off.
"""

from __future__ import annotations

import functools
import json
import os
import time
from dataclasses import dataclass

from ....utils import device_kind, get_logger, log_with

log = get_logger("bls.autotune")

# ---------------------------------------------------------------------------
# Arm registry.  Pure literal: the tune-plan lint family AST-parses this
# tuple (never imports the module), exactly like AOT_KERNELS / SPANS.
# Fields: (arm id, LimbSpec name in limbs.py, fp routing toggle, toggle
# value, RANGE_REPORT.json program the arm's legality rides on; "" marks
# an arm that may never enter trials).
# ---------------------------------------------------------------------------

ARM_TABLE = (
    ("vpu15", "SPEC15", "set_mxu", False, "pallas_mont_mul"),
    ("mxu13", "SPEC13", "set_mxu", True, "mxu_mont_mul"),
)

PLAN_SCHEMA = 1

# Default batch-shape ladders: the compiled shapes the serving path
# actually dispatches (bench headline ladder on device; two cheap shapes
# under interpret mode elsewhere).
TPU_SHAPES = (512, 4096, 8192)
CPU_SHAPES = (64, 128)


@dataclass(frozen=True)
class Arm:
    """One kernel arm: a routed limb plane plus its range-proof bond."""

    arm: str      # registry id ("vpu15", "mxu13", ...)
    spec: str     # LimbSpec name in limbs.py (limbs.SPECS key)
    toggle: str   # fp.py routing setter consulted by the traced program
    value: bool   # what the toggle must hold while this arm traces
    proof: str    # RANGE_REPORT.json program name; "" = unproven


ARMS: tuple[Arm, ...] = tuple(Arm(*row) for row in ARM_TABLE)


def arm_by_id(arm_id: str) -> Arm | None:
    for a in ARMS:
        if a.arm == arm_id:
            return a
    return None


# ---------------------------------------------------------------------------
# Legality: range_lint-proven at zero waivers.
# ---------------------------------------------------------------------------

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.abspath(os.path.join(_HERE, "..", "..", "..", ".."))
RANGE_REPORT_PATH = os.path.join(_REPO_ROOT, "RANGE_REPORT.json")
WAIVERS_PATH = os.path.join(
    _REPO_ROOT, "lighthouse_tpu", "analysis", "waivers.toml"
)

_RANGE_RULES = ("range-overflow", "range-contract", "range-lfp", "range-report")


def _range_waiver_count(waivers_path: str) -> int:
    """Number of range-family waivers on file.  Any > 0 voids every
    arm's clearance: "proven at zero waivers" is the legality bar, and a
    waived range finding means the proof no longer stands on its own."""
    if not os.path.exists(waivers_path):
        return 0
    from ....analysis.waivers import load_waivers

    return sum(
        1
        for w in load_waivers(waivers_path)
        if any(fn_match(w.rule, rule) for rule in _RANGE_RULES)
    )


def fn_match(pattern: str, name: str) -> bool:
    from fnmatch import fnmatchcase

    return fnmatchcase(name, pattern)


def proven_arms(
    report_path: str = RANGE_REPORT_PATH,
    waivers_path: str = WAIVERS_PATH,
) -> tuple[Arm, ...]:
    """The arms legal to tune: proof program present in RANGE_REPORT.json
    with ``contracts_ok`` true, and zero range-family waivers on file.
    An arm with no proof program (``proof == ""``) is never legal."""
    try:
        with open(report_path, encoding="utf-8") as f:
            programs = json.load(f).get("programs", {})
    except (OSError, ValueError):
        return ()
    if _range_waiver_count(waivers_path):
        return ()
    out = []
    for arm in ARMS:
        if not arm.proof:
            continue
        prog = programs.get(arm.proof)
        if isinstance(prog, dict) and prog.get("contracts_ok") is True:
            out.append(arm)
    return tuple(out)


# ---------------------------------------------------------------------------
# Trial harness: the BENCH_MXU padding/tiling microbench with an
# injectable timer (serve-batcher fake-clock pattern: ctor-style
# ``timer=time.perf_counter`` default, tests pass a stub).
# ---------------------------------------------------------------------------


def trial(
    arm: Arm,
    batch: int,
    *,
    iters: int = 3,
    timer=time.perf_counter,
    interpret: bool | None = None,
) -> float:
    """Best-of-``iters`` seconds for one jitted Montgomery-multiply
    dispatch under ``arm`` at ``batch`` lanes.  Identical rng operands
    and padding/tiling for every arm (only the routed plane differs), so
    timings are comparable across the registry.  The arm's toggle is
    pinned around compile+measure and restored exactly."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ....obs.tracer import TRACER
    from . import fp as F
    from . import pallas_fp as PF

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    rng = np.random.default_rng(0xA17)
    a = jnp.asarray(rng.integers(0, 1 << 15, size=(26, batch), dtype=np.int64).astype(np.uint32))
    b = jnp.asarray(rng.integers(0, 1 << 15, size=(26, batch), dtype=np.int64).astype(np.uint32))
    setter = getattr(F, arm.toggle)
    prev = setter(arm.value)
    try:
        fn = jax.jit(functools.partial(PF.mont_mul_limbs, interpret=interpret))
        fn(a, b).block_until_ready()  # compile outside the timed window
        best = float("inf")
        with TRACER.span("autotune.trial", arm=arm.arm, batch=batch):
            for _ in range(max(1, iters)):
                t0 = timer()
                fn(a, b).block_until_ready()
                best = min(best, timer() - t0)
    finally:
        setter(prev)
    return best


# ---------------------------------------------------------------------------
# The tuner: trials → plan → persist/install.
# ---------------------------------------------------------------------------


def default_shapes() -> tuple[int, ...]:
    import jax

    return TPU_SHAPES if jax.default_backend() == "tpu" else CPU_SHAPES


def tune(
    shapes=None,
    *,
    arms=None,
    measure=None,
    iters: int = 3,
    timer=time.perf_counter,
    kernel: str = "_verify_kernel",
) -> dict:
    """Run timed trials of every legal arm across the batch-shape ladder
    and return the winning plan (not yet persisted — see
    ``tune_and_store``).  ``measure(arm, batch) -> seconds`` is
    injectable for deterministic tests; the default is the real
    ``trial`` harness with the given ``timer``.  Arms passed explicitly
    are still filtered through the legality gate: an unproven arm never
    enters trials."""
    import jax

    legal = proven_arms()
    if arms is not None:
        allowed = {a.arm for a in legal}
        legal = tuple(a for a in arms if a.arm in allowed and a.proof)
    if not legal:
        raise ValueError("no range-proven arms to tune over")
    if shapes is None:
        shapes = default_shapes()
    if measure is None:
        measure = functools.partial(trial, iters=iters, timer=timer)
    plan: dict = {
        "schema": PLAN_SCHEMA,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": device_kind(),
        "shapes": {},
    }
    for batch in shapes:
        trials = {arm.arm: float(measure(arm, int(batch))) for arm in legal}
        winner = min(trials, key=lambda k: (trials[k], k))
        plan["shapes"][str(int(batch))] = {
            "arm": winner,
            "kernel": kernel,
            "trials_ms": {k: round(v * 1e3, 6) for k, v in trials.items()},
        }
        log_with(
            log,
            20,
            "autotune trial",
            batch=int(batch),
            winner=winner,
            trials_ms=plan["shapes"][str(int(batch))]["trials_ms"],
        )
    return plan


def tune_and_store(store, **tune_kw) -> dict:
    """Tune, persist the plan into ``store``'s signed manifest, and
    install it in-process.  The next ``prewarm`` against the same store
    (same device kind × jax version) reinstalls it with zero trials."""
    plan = tune(**tune_kw)
    store.write_plan(plan)
    install_plan(plan)
    return plan


# ---------------------------------------------------------------------------
# Plan install: resolve the plan into fp's per-shape routing map.
# ---------------------------------------------------------------------------


def plan_current(plan: dict) -> bool:
    """A plan binds only on the exact (device kind × jax version) pair it
    was measured on; anything else behaves cold (stale-plan rejection)."""
    import jax

    return (
        isinstance(plan, dict)
        and plan.get("schema") == PLAN_SCHEMA
        and plan.get("jax") == jax.__version__
        and plan.get("device_kind") == device_kind()
        and isinstance(plan.get("shapes"), dict)
    )


def install_plan(plan: dict) -> int:
    """Install a tuned plan into ``fp``'s routing map.  Returns the
    number of shapes installed (0 = stale/invalid plan, nothing
    installed, boot behaves cold).  The largest tuned shape's arm also
    becomes the ``"*"`` default so off-ladder programs (e.g. the sharded
    epoch kernel) follow the headline arm."""
    from . import fp as F

    if not plan_current(plan):
        return 0
    shapes: dict = {}
    for key, entry in plan["shapes"].items():
        try:
            batch = int(key)
        except (TypeError, ValueError):
            continue
        arm = arm_by_id(entry.get("arm", "")) if isinstance(entry, dict) else None
        if arm is None or arm.toggle != "set_mxu" or not arm.proof:
            continue
        shapes[batch] = bool(arm.value)
    if not shapes:
        return 0
    shapes["*"] = shapes[max(k for k in shapes if isinstance(k, int))]
    F.install_mxu_plan(shapes)
    log_with(
        log,
        20,
        "autotune plan installed",
        shapes=len(shapes) - 1,
        device_kind=plan.get("device_kind"),
    )
    return len(shapes) - 1


def clear_plan() -> None:
    """Drop any installed plan (tests; ``fp`` falls back to env/default)."""
    from . import fp as F

    F.install_mxu_plan(None)
