"""The "jax" BLS backend: batched signature-set verification on TPU.

Device twin of blst's `verify_multiple_aggregate_signatures` as wrapped by
the reference's verify_signature_sets (crypto/bls/src/impls/blst.rs:35-117):

  host:   per-set validation (empty sets, infinity signatures/pubkeys),
          pubkey aggregation, hash-to-curve H(m), nonzero 64-bit random
          weights (RAND_BITS=64, blst.rs:14), marshaling to Montgomery limbs
  device: G2 subgroup checks (Scott's psi test), weight scalar muls
          ([r_i]PK_i in G1, [r_i]sig_i in G2), signature accumulation,
          batched Miller loops, GT product tree, one final exponentiation

The device kernel is jitted once per padded batch size (powers of two), so a
long-running node reuses a handful of compiled programs — the XLA analog of
the reference's "compile the backend once, stream batches through it".
"""

from __future__ import annotations

import hashlib
import json
import secrets
import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from .. import params
from ..curve import Fp, G1_GENERATOR, affine_neg, from_jacobian, jac_add, to_jacobian
from ..fields import Fp2
from ..hash_to_curve import hash_to_g2
from ....obs.tracer import TRACER
from ....utils.metrics import COMPILE_CACHE_ERRORS, JIT_COMPILE_SECONDS
from . import fp as F
from . import pairing as PR
from . import points as P
from . import tower as T


def enable_compile_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` so node
    restarts reuse compiled BLS programs instead of re-paying minutes of
    XLA time (ROADMAP item 4).  Best-effort: returns False (never raises)
    when jax or the cache config is unavailable."""
    import os

    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # the BLS programs are exactly the long-compile case the cache
        # exists for; cache even small/fast entries so tests exercise it
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return True
    except Exception as exc:  # noqa: BLE001 — cache is an optimization,
        # not a dep — but a dead cache re-pays full compile time on every
        # boot, so the failure must be loud: a counter on /metrics plus a
        # structured log line, not a swallowed warning.
        COMPILE_CACHE_ERRORS.inc()
        from ....utils import get_logger, log_with

        log_with(get_logger("bls.jax"), 30,
                 "persistent compile cache unavailable",
                 cache_dir=cache_dir, error=str(exc))
        return False


def program_fingerprint(kernel: str, **attrs) -> str:
    """Stable per-program fingerprint for compile-time attribution: the
    kernel entry point + its static shape/config attrs + the jax version
    and backend (the same identity the AOT cache of ROADMAP item 4 will
    key on).  12 hex chars, sha256-derived."""
    import jax

    blob = json.dumps(
        {"kernel": kernel, "jax": jax.__version__,
         "backend": jax.default_backend(), **attrs},
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def traced_jit(fn, fingerprint: str, *, capture=None, **jit_kw):
    """``jax.jit`` wrapped so the FIRST call per cache entry — the one
    that traces + compiles the program — is timed into the flight
    recorder as a ``jit.compile`` span (per-program fingerprint in its
    fields) and into ``jit_compile_seconds``.  Subsequent calls go
    straight to the compiled callable.

    ``capture``, when given, is invoked as ``capture(call, args)`` right
    after the first call completes — the AOT store's export hook
    (jax_backend/aot.py), which is never-raise by contract and works
    from arg avals only (safe under donation)."""
    import jax

    jitted = jax.jit(fn, **jit_kw)
    state = {"first": True}

    def call(*args):
        if state["first"]:
            state["first"] = False
            t0 = time.perf_counter()
            with TRACER.span("jit.compile", fingerprint=fingerprint,
                             kernel=getattr(fn, "__name__", str(fn))):
                out = jitted(*args)
            JIT_COMPILE_SECONDS.observe(time.perf_counter() - t0)
            if capture is not None:
                capture(call, args)
            return out
        return jitted(*args)

    call.jitted = jitted
    call.fingerprint = fingerprint
    return call


def _tree_reduce_g2(pt):
    """Reduce the trailing batch axis of a Jacobian G2 pytree (X, Y, Z, inf)
    by point addition (log-depth tree).  Uses the COMPLETE jac_add: the
    summands are adversarial signature points, so coincidences must be
    handled, not assumed away."""
    import jax.numpy as jnp

    B = pt[3].shape[-1]
    target = 1 << max(0, (B - 1).bit_length())
    if target != B:
        reps = target - B
        one = F.LFp(F.bcast(F.ONE_MONT, (reps,)), 1.0)
        zero = F.LFp(jnp.zeros_like(one.limbs), 0.0)

        def cat_fp2(c, pad):
            return (
                F.LFp(
                    jnp.concatenate([c[0].limbs, pad[0].limbs], axis=-1),
                    max(c[0].bound, pad[0].bound),
                ),
                F.LFp(
                    jnp.concatenate([c[1].limbs, pad[1].limbs], axis=-1),
                    max(c[1].bound, pad[1].bound),
                ),
            )

        X, Y, Z, inf = pt
        pt = (
            cat_fp2(X, (one, zero)),
            cat_fp2(Y, (one, zero)),
            cat_fp2(Z, (zero, zero)),
            jnp.concatenate([inf, jnp.ones((reps,), dtype=bool)], axis=-1),
        )
    n = target
    while n > 1:
        half = n // 2
        lo = _slice_pt(pt, 0, half)
        hi = _slice_pt(pt, half, 2 * half)
        pt = P.jac_add(P.FP2_OPS, lo, hi)
        n = half
    return pt


def _slice_lfp_tree(x, a, b):
    if isinstance(x, F.LFp):
        return F.LFp(x.limbs[..., a:b], x.bound)
    import jax.numpy as jnp

    if isinstance(x, jnp.ndarray) or hasattr(x, "shape"):
        return x[..., a:b]
    return tuple(_slice_lfp_tree(c, a, b) for c in x)


def _slice_pt(pt, a, b):
    return tuple(_slice_lfp_tree(c, a, b) for c in pt)


def _concat_lfp_tree(x, y):
    import jax.numpy as jnp

    if isinstance(x, F.LFp):
        return F.LFp(
            jnp.concatenate([x.limbs, y.limbs], axis=-1), max(x.bound, y.bound)
        )
    return tuple(_concat_lfp_tree(a, b) for a, b in zip(x, y))


def _verify_kernel(pk_aff, sig_aff, h_aff, wbits):
    """The jitted device program.  All inputs have trailing batch axis B.

    pk_aff:  G1 affine (x, y) Montgomery limbs — per-set aggregated pubkey
    sig_aff: G2 affine pytree — per-set signature
    h_aff:   G2 affine pytree — per-set message point H(m)
    wbits:   (64, B) uint32 — bits of the nonzero random weights, MSB first
    Returns a scalar bool.
    """
    import jax.numpy as jnp

    # 1. signature subgroup checks (blst.rs:71-81)
    ok_sub = jnp.all(P.g2_subgroup_check(sig_aff))
    # 2. weight scalar muls (the dispatch leader after the fused Miller
    # loop: LIGHTHOUSE_TPU_WSM runs each double-and-add bit as one
    # Mosaic program per curve — pallas_wsm.py)
    if F.wsm_fused_active():
        from . import pallas_wsm

        no_inf = jnp.zeros(wbits.shape[1:], dtype=bool)
        wpk = pallas_wsm.scalar_mul_bits_fused(
            P.FP_OPS, pk_aff, no_inf, wbits)
        wsig = pallas_wsm.scalar_mul_bits_fused(
            P.FP2_OPS, sig_aff, no_inf, wbits)
    else:
        wpk = P.scalar_mul_bits(
            P.FP_OPS, P.from_affine(P.FP_OPS, pk_aff), wbits)
        wsig = P.scalar_mul_bits(
            P.FP2_OPS, P.from_affine(P.FP2_OPS, sig_aff), wbits)
    # 3. signature accumulation: S = sum_i [r_i] sig_i
    S = _tree_reduce_g2(wsig)
    s_inf = P.pt_is_infinity(P.FP2_OPS, S)
    # 4. affinize
    wpk_aff = P.to_affine(P.FP_OPS, wpk, F.fp_inv)
    S_aff = P.to_affine(P.FP2_OPS, S, T.fp2_inv)
    # 5. assemble pairs: (wpk_i, H_i) for each set plus (-G1, S)
    neg_gen = _neg_gen_const()
    p_side = (
        _concat_lfp_tree(wpk_aff[0], neg_gen[0]),
        _concat_lfp_tree(wpk_aff[1], neg_gen[1]),
    )
    q_side = (
        _concat_lfp_tree(h_aff[0], S_aff[0]),
        _concat_lfp_tree(h_aff[1], S_aff[1]),
    )
    # 6. Miller loops + GT product + final exponentiation
    f = PR.miller_loop(p_side, q_side)
    # If S is infinity, its pair contributes 1 (e(P, O) = 1): mask the last
    # batch element rather than trusting the (0,0) affinization.
    B = wbits.shape[-1]
    mask = jnp.concatenate(
        [jnp.zeros((B,), dtype=bool), jnp.broadcast_to(s_inf, (1,))]
    )
    one = PR._fp12_one_like_from_fp2(q_side[0])
    f = T.fp12_select(mask, one, f)
    ok_pair = PR.final_exp_is_one(PR.gt_product(f))
    return ok_pair & ok_sub


def _segment_aggregate_g1(pk_aff, pad_inf, positions: int):
    """Aggregate ``positions`` committee pubkeys per set ON DEVICE.

    Layout is position-major: trailing axis = positions*B with element
    ``pos*B + set`` — every tree-reduction step then slices a CONTIGUOUS
    range of the last axis (pallas-friendly 2D limb shapes throughout),
    halving the position count per step: log2(positions) complete
    jac_adds over (B,)-wide lanes.  ``pad_inf`` marks absent members
    (committees are shorter than the padded width); their lanes are the
    infinity point, the identity of the reduction.

    This is SURVEY §7's hard part (d): per-set aggregation of up to 2048
    keys is the marshal bottleneck at epoch scale (~900k host G1 adds per
    epoch); as a device segment-sum it rides the same limb kernels as the
    pairing."""
    p = P.from_affine(P.FP_OPS, pk_aff)
    p = (p[0], p[1], p[2], p[3] | pad_inf)
    total = pad_inf.shape[-1]
    B = total // positions
    n = positions
    while n > 1:
        half = n // 2
        lo = _slice_pt(p, 0, half * B)
        hi = _slice_pt(p, half * B, 2 * half * B)
        p = P.jac_add(P.FP_OPS, lo, hi)
        n = half
    return p


def _epoch_verify_kernel(pk_aff, pad_inf, sig_aff, h_aff, wbits,
                         positions: int):
    """Epoch-scale batch verify: device committee aggregation feeding the
    standard multi-aggregate pipeline (blst.rs:35-117 semantics at the
    BASELINE.json config-4 shape: one mainnet epoch's aggregates)."""
    agg = _segment_aggregate_g1(pk_aff, pad_inf, positions)
    agg_aff = P.to_affine(P.FP_OPS, agg, F.fp_inv)
    return _verify_kernel(agg_aff, sig_aff, h_aff, wbits)


def encode_committee_pubkeys(committees: list, positions: int):
    """Host marshal for the segmented kernel: committees (lists of oracle
    affine G1 points, ragged) -> position-major encoded pytree + padding
    mask.  Padding lanes carry the generator (any valid point) under an
    infinity flag."""
    import numpy as np

    from ..curve import G1_GENERATOR

    B = len(committees)
    flat = []
    mask = np.zeros(positions * B, dtype=bool)
    for pos in range(positions):
        for b, committee in enumerate(committees):
            if pos < len(committee):
                flat.append(committee[pos])
            else:
                flat.append(G1_GENERATOR)
                mask[pos * B + b] = True
    import jax.numpy as jnp

    return P.g1_encode(flat), jnp.asarray(mask)


def _aggregate_verify_kernel(pk_aff, h_aff, sig_aff):
    """Distinct-message aggregate verification (blst.rs:244-255 semantics):
    check prod_i e(pk_i, H(m_i)) * e(-G1, sig) == 1 with ONE final exp.

    pk_aff: G1 affine batch (one per message); h_aff: G2 affine batch of
    message points; sig_aff: batch-1 G2 affine aggregate signature.
    Unlike the signature-set kernel there are no random weights (single
    statement, not a batch of independent claims) and just one subgroup
    check.
    """
    import jax.numpy as jnp

    ok_sub = jnp.all(P.g2_subgroup_check(sig_aff))
    neg_gen = _neg_gen_const()
    p_side = (
        _concat_lfp_tree(pk_aff[0], neg_gen[0]),
        _concat_lfp_tree(pk_aff[1], neg_gen[1]),
    )
    q_side = (
        _concat_lfp_tree(h_aff[0], sig_aff[0]),
        _concat_lfp_tree(h_aff[1], sig_aff[1]),
    )
    f = PR.miller_loop(p_side, q_side)
    ok_pair = PR.final_exp_is_one(PR.gt_product(f))
    return ok_pair & ok_sub


def _verify_kernel_h2c(pk_aff, sig_aff, u0, u1, wbits):
    """_verify_kernel with DEVICE-SIDE map-to-curve: takes the hash-to-field
    outputs (u0, u1 Fp2 batches) instead of precomputed H(m) points, so the
    host's per-set cost drops to SHA-256 expansion (~10 us vs ~30 ms of
    bigint SSWU).  See jax_backend/h2c.py."""
    from . import h2c

    h_aff = h2c.map_to_g2(u0, u1)
    return _verify_kernel(pk_aff, sig_aff, h_aff, wbits)


def _pack_wbits(weights: list[int]) -> np.ndarray:
    """(64, B) MSB-first weight bits, vectorized (was a 64xB Python loop).
    Ingested as two uint32 halves: numpy rejects Python ints >= 2^63 when
    building a uint64 array directly."""
    w_hi = np.array([(w >> 32) & 0xFFFFFFFF for w in weights], dtype=np.uint32)
    w_lo = np.array([w & 0xFFFFFFFF for w in weights], dtype=np.uint32)
    shifts = np.arange(31, -1, -1, dtype=np.uint32)[:, None]
    hi_bits = (w_hi[None, :] >> shifts) & np.uint32(1)
    lo_bits = (w_lo[None, :] >> shifts) & np.uint32(1)
    return np.concatenate([hi_bits, lo_bits], axis=0)


def _neg_gen_const():
    """-G1 generator as a batch-1 device constant."""
    ng = affine_neg(G1_GENERATOR)
    return P.g1_encode([ng])


def _pin_mxu(fn, mxu: bool):
    """Trace ``fn`` under a pinned kernel arm.  The routed plane is read
    by the program body at TRACE time (``fp.mxu_active`` inside the
    Montgomery products), so a per-shape plan that differs from the
    process-wide gate must hold the toggle around the traced call; the
    override is restored exactly, and compiled executions skip the
    Python body entirely — the pin costs nothing after the first call."""
    def armed(*args):
        prev = F.set_mxu(mxu)
        try:
            return fn(*args)
        finally:
            F.set_mxu(prev)

    armed.__name__ = fn.__name__
    return armed


class JaxBackend:
    """Device batch verification backend, registered as "jax"."""

    name = "jax"

    def __init__(self, min_batch: int = 8, device_h2c: bool | None = None):
        self._kernels = {}
        self._aot_store = None
        self.min_batch = min_batch
        # device_h2c: map messages to G2 ON DEVICE (host only hashes).
        # Measured on the v5e at B=512 (PERF.md): host marshal 120 -> 5,008
        # sets/s/core while the kernel pays +70% (2,655 -> 1,565 sets/s) for
        # the two sqrt chains — system throughput is host-bound without it,
        # balanced with it.  Default: on for TPU, off on CPU (where the
        # bigger graph just slows the test oracle).
        if device_h2c is None:
            import jax

            device_h2c = jax.default_backend() == "tpu"
        self.device_h2c = device_h2c

    def _kernel(self, B: int):
        # The arm (mxu) joins the cache key AND the compile fingerprint:
        # a different arm means a different Mosaic program for every
        # Montgomery product in the trace, so a stale cached executable
        # would silently A/A.  The arm itself is resolved per padded
        # batch shape through the installed autotuned plan
        # (fp.mxu_for_batch); set_mxu / LIGHTHOUSE_TPU_MXU remain
        # explicit overrides and force one arm for every shape.  Plan
        # resolution happens HERE, at lookup/compile time — a cache hit
        # never consults it again, so tuned routing costs nothing per
        # dispatched batch.
        mxu = F.mxu_for_batch(B)
        key = (B, self.device_h2c, mxu)
        if key not in self._kernels:
            import jax

            fn = _verify_kernel_h2c if self.device_h2c else _verify_kernel
            fp_hex = program_fingerprint(
                fn.__name__, B=B, device_h2c=self.device_h2c,
                mxu=mxu,
            )
            # Store-first: a cache miss consults the attached AOT store
            # before paying a tracing-compile — a populated store makes
            # the second boot's working set compile-free.
            if self._install_from_store(key, fp_hex):
                return self._kernels[key]
            # Donate the marshalled operands on TPU: they are fresh
            # per-batch buffers, and donation lets XLA alias them for
            # temporaries — required for double-buffered dispatch to
            # keep two batches resident without growing HBM. CPU/test
            # backends ignore donation (XLA warns), so gate it.  The
            # gate itself is load-bearing and lint-enforced: the spmd
            # audit family's donation lint (spmd-donate) fails on any
            # non-empty donate_argnums outside a TPU-backend guard,
            # and on reads of a donated buffer after the donating call.
            donate = ()
            if jax.default_backend() == "tpu":
                donate = tuple(range(5 if self.device_h2c else 4))
            self._kernels[key] = traced_jit(
                _pin_mxu(fn, mxu), fp_hex,
                capture=self._aot_capture(key, fn.__name__),
                donate_argnums=donate,
            )
        return self._kernels[key]

    # -- AOT executable store seams (jax_backend/aot.py) -------------------

    def attach_aot_store(self, store) -> None:
        """Attach an :class:`~.aot.AotStore`: cache misses consult it
        before compiling, and fresh compiles are exported into it (the
        ``traced_jit`` capture hook), so normal operation populates the
        store the next boot prewarms from."""
        self._aot_store = store

    def install_kernel(self, cache_key, fingerprint: str, call) -> None:
        """Install a deserialized AOT executable under a kernel-cache
        key, wearing the ``traced_jit`` surface (``.jitted`` /
        ``.fingerprint``) so dispatch and the dispatch audit cannot tell
        it from an organically compiled program."""
        def installed(*args):
            return call(*args)

        installed.jitted = call
        installed.fingerprint = fingerprint
        installed.aot = True
        self._kernels[tuple(cache_key)] = installed

    def _install_from_store(self, key, fp_hex: str) -> bool:
        if self._aot_store is None:
            return False
        call = self._aot_store.load(fp_hex)
        if call is None:
            return False
        self.install_kernel(key, fp_hex, call)
        return True

    def _aot_capture(self, key, kernel: str):
        """The traced_jit first-call hook bound to this cache key, or
        None when no store is attached (the common test path)."""
        if self._aot_store is None:
            return None
        store = self._aot_store

        def hook(call, args):
            store.capture(call, key, args, kernel=kernel)

        return hook

    def warm_compile(self, B: int) -> bool:
        """Trace+compile the batch-verify kernel for padded size ``B``
        ahead of traffic: one synthetic valid set, marshalled once and
        tiled along the batch axis (every kernel operand is batch-last).
        Goes through the normal ``_kernel`` path, so spans, metrics and
        AOT capture fire exactly as for organic traffic."""
        from ..api import SecretKey, SignatureSet

        import jax

        if B < self.min_batch or B & (B - 1):
            return False
        sk = SecretKey(2)
        msg = b"lighthouse-tpu warm-compile probe"
        s = SignatureSet(sk.sign(msg), [sk.public_key()], msg)
        mb = self.marshal_sets([s], weights=[1])
        if mb.invalid:
            return False
        reps = B // mb.B
        args = jax.tree_util.tree_map(
            lambda a: np.tile(
                np.asarray(a), (1,) * (np.asarray(a).ndim - 1) + (reps,)
            ),
            mb.args,
        )
        self._kernel(B)(*jax.device_put(args))
        return True

    # -- single/aggregate verification reuses the set machinery ------------

    def verify(self, pubkey, msg: bytes, sig) -> bool:
        from ..api import SignatureSet

        return self.verify_signature_sets([SignatureSet(sig, [pubkey], msg)])

    def aggregate_verify(self, pubkeys, msgs, sig) -> bool:
        """Distinct-message aggregate verification (blst.rs:244-255) on the
        device: one multi-pairing over the (pk_i, H(m_i)) pairs plus the
        aggregate signature, one final exp."""
        if not pubkeys or len(pubkeys) != len(msgs):
            return False
        if sig.point is None:
            return False
        import jax

        h_pts = [hash_to_g2(m) for m in msgs]
        pk_pts = [pk.point for pk in pubkeys]
        if any(p is None for p in pk_pts) or any(h is None for h in h_pts):
            return False
        # compiled per distinct n: this path is rare and sizes are small
        B = len(pk_pts)
        key = ("agg", B)
        if key not in self._kernels:
            fp_hex = program_fingerprint("_aggregate_verify_kernel", n=B)
            if not self._install_from_store(key, fp_hex):
                self._kernels[key] = traced_jit(
                    _aggregate_verify_kernel, fp_hex,
                    capture=self._aot_capture(
                        key, "_aggregate_verify_kernel"
                    ),
                )
        fn = self._kernels[key]
        ok = fn(
            P.g1_encode(pk_pts),
            P.g2_encode(h_pts),
            P.g2_encode([sig.point]),
        )
        return bool(ok)

    def fast_aggregate_verify(self, pubkeys, msg: bytes, sig) -> bool:
        from ..api import SignatureSet

        if not pubkeys:
            return False
        return self.verify_signature_sets([SignatureSet(sig, list(pubkeys), msg)])

    # -- the batch hot path ------------------------------------------------

    def verify_signature_sets(self, sets) -> bool:
        # Chaos hook: the armed site for device errors / hung compiles.
        # Unarmed cost is one dict lookup (faults.py).
        from lighthouse_tpu.utils import faults as _faults

        _faults.fire("bls.device_verify")
        mb = self.marshal_sets(sets)
        if mb.invalid:
            return False
        return self.resolve(self.dispatch(mb))

    # -- pipelined three-stage path (marshal | dispatch | resolve) ---------
    #
    # verify_signature_sets == resolve(dispatch(marshal_sets(sets))), but
    # exposing the stages lets the PipelinedVerifier (beacon/processor.py)
    # marshal batch N+1 on host workers while batch N's kernel runs: the
    # host marshal (5,008 sets/s/core with device h2c) and the fused-Miller
    # device rate (6,221 sets/s at B=8192) are near co-bound, so overlap
    # approaches wall = max(marshal, device) instead of their sum.

    def marshal_sets(self, sets, weights=None) -> MarshalledBatch:
        """Pure host stage: validation, pubkey aggregation, hashing, limb
        encode, weight packing.  Thread-safe (no backend state touched
        besides reads), so a marshal pool may run several concurrently.

        ``weights`` pins the per-set random weight draw (one int per
        set).  This is the determinism seam the ingest engine's
        differential suite uses to assert byte-identity between this
        scalar oracle and the vectorized path; production callers leave
        it None and get the secrets-drawn weights.
        """
        if not sets:
            return MarshalledBatch(0, 0, self.device_h2c, invalid=True)
        n = len(sets)
        given = weights
        pk_pts, sig_pts, h_pts, weights = [], [], [], []
        for idx, s in enumerate(sets):
            if s.signature.point is None:
                return MarshalledBatch(n, 0, self.device_h2c, invalid=True)
            if not s.signing_keys:
                return MarshalledBatch(n, 0, self.device_h2c, invalid=True)
            if len(s.signing_keys) == 1:
                # the dominant gossip case: nothing to aggregate
                agg = s.signing_keys[0].point
            else:
                # Aggregate the set's pubkeys host-side (cheap affine adds
                # over cached decompressed keys — the ValidatorPubkeyCache
                # analog).
                acc = to_jacobian(None, Fp)
                for pk in s.signing_keys:
                    acc = jac_add(acc, to_jacobian(pk.point, Fp), Fp)
                agg = from_jacobian(acc, Fp)
            if agg is None:
                return MarshalledBatch(n, 0, self.device_h2c, invalid=True)
            if not self.device_h2c:
                h = hash_to_g2(s.message)
                if h is None:  # probability-zero, but keep the host total
                    return MarshalledBatch(n, 0, self.device_h2c,
                                           invalid=True)
                h_pts.append(h)
            if given is None:
                r = 0
                while r == 0:
                    r = secrets.randbits(params.RAND_BITS)
            else:
                r = int(given[idx])
            pk_pts.append(agg)
            sig_pts.append(s.signature.point)
            weights.append(r)

        # Pad to the kernel batch size by replicating entry 0: a valid
        # duplicate cannot flip the conjunction, an invalid one already
        # fails it.
        B = self._padded_size(n)
        reps = B - n
        pk_pts += [pk_pts[0]] * reps
        sig_pts += [sig_pts[0]] * reps
        weights += [weights[0]] * reps

        pk_aff = P.g1_encode(pk_pts)
        sig_aff = P.g2_encode(sig_pts)
        wbits = _pack_wbits(weights)
        if self.device_h2c:
            from ..hash_to_curve import hash_to_field_fp2

            from . import h2c as _h2c  # noqa: F401 (kernel-side import)

            us = [hash_to_field_fp2(s.message, 2) for s in sets]
            us += [us[0]] * reps  # replicate computed u-values, not hashes
            u0 = T.fp2_encode([u[0] for u in us])
            u1 = T.fp2_encode([u[1] for u in us])
            args = (pk_aff, sig_aff, u0, u1, wbits)
        else:
            h_pts += [h_pts[0]] * reps
            h_aff = P.g2_encode(h_pts)
            args = (pk_aff, sig_aff, h_aff, wbits)
        return MarshalledBatch(n, B, self.device_h2c, args)

    def local_verify_fn(self):
        """The raw (unjitted) batch kernel for SPMD wrapping: the
        rule-driven sharded program (parallel/partition.py) runs this
        per device on its batch shard under shard_map, instead of
        slicing arrays around the jitted single-device program."""
        return _verify_kernel_h2c if self.device_h2c else _verify_kernel

    @staticmethod
    def registry_pk_wrap(x, y):
        """Wrap psum-gathered canonical Montgomery limb planes as the
        kernel's pubkey operand — the partition layer's seam so it
        never imports the field stack (bound 1.0 = encode_mont's)."""
        return (F.LFp(x, 1.0), F.LFp(y, 1.0))

    def dispatch(self, mb: MarshalledBatch):
        """Device stage, NON-BLOCKING: enqueue transfers and the kernel,
        return the in-flight result.  jax dispatch is async — device_put
        starts the host->device copies immediately and the jitted call
        returns before the kernel finishes, so the caller can marshal the
        next batch while this one runs.  ``resolve`` blocks on the value."""
        if mb.invalid:
            return False
        import jax

        args = jax.device_put(mb.args)
        return self._kernel(mb.B)(*args)

    def resolve(self, handle) -> bool:
        """Block on an in-flight dispatch and return the verdict."""
        return bool(handle)

    def verify_marshalled(self, mb: MarshalledBatch) -> bool:
        return False if mb.invalid else self.resolve(self.dispatch(mb))

    def _padded_size(self, n: int) -> int:
        """Next power-of-two batch size >= n (bounded recompiles per size)."""
        B = self.min_batch
        while B < n:
            B *= 2
        return B


@dataclass
class MarshalledBatch:
    """Host-marshalled kernel operands for one padded batch.

    The marshal stage (validation, pubkey aggregation, SHA-256 expansion
    or full hash-to-curve, weight packing, limb encode) is pure host
    work; ``args`` are exactly the positional operands of the jitted
    verify kernel.  ``invalid`` short-circuits dispatch: host validation
    already rejected the batch (empty set, infinity key/signature), the
    verdict is False without touching the device."""

    n: int                      # real (unpadded) set count
    B: int                      # padded kernel batch size
    device_h2c: bool
    args: tuple = field(default=())
    invalid: bool = False
    # registry mode (ingest marshal_for_mesh): the (B,) validator-slot
    # vector when the pubkey operand is DEFERRED to the sharded
    # program's partitioned-registry gather — args then exclude pk, and
    # only the mesh path (parallel/partition.py) may consume the batch.
    slots: Any = None


def register() -> "JaxBackend":
    """Create and register the backend in the api registry."""
    from .. import api

    backend = JaxBackend()
    api.register_backend(backend)
    return backend
