"""Device-side map-to-curve: SSWU + 3-isogeny + cofactor clearing in JAX.

The round-2/3 profile showed host hash_to_curve is THE end-to-end
bottleneck (~30 ms of Python bigint math per set caps the pipeline at
~30 sets/s/core while the device kernel scales with batch).  This module
moves everything after the SHA-256 expansion onto the batch axis:

    host:   expand_message_xmd (hashlib; ~10 us) -> u0, u1 in Fp2
    device: SSWU map (branchless, constant-exponent sqrt candidates),
            derived 3-isogeny, Jacobian add, Budroni-Pintore cofactor
            clearing via the psi endomorphism

Math follows RFC 9380 §6.6.2 (simplified SWU) with the q ≡ 9 (mod 16)
square-root method of appendix F (candidate roots t^((q+7)/16) · {1, c1,
c2, c3} with c1 = sqrt(-1), c2 = sqrt(c1), c3 = sqrt(-c1)) — the same
pipeline the host oracle implements (hash_to_curve.py), differentially
tested against it.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .. import params
from ..fields import Fp2 as OFp2
from ..hash_to_curve import A_PRIME, B_PRIME, Z
from .. import g2_isogeny
from . import fp as F
from . import points as P
from . import tower as T

_P2 = params.P * params.P
SQRT_EXP = (_P2 + 7) // 16
_SQRT_EXP_BITS = [int(b) for b in bin(SQRT_EXP)[2:]]

# sqrt candidate constants (oracle-computed at import, self-checked)
_C1 = OFp2(0, 1)  # sqrt(-1): u^2 = -1 in Fp[u]/(u^2+1)
assert _C1.square() == OFp2(-1 % params.P, 0)
_C2 = _C1.sqrt()
_C3 = (-_C1).sqrt()
assert _C2 is not None and _C3 is not None
assert _C2.square() == _C1 and _C3.square() == -_C1

# SSWU selection constants
_NEG_B_OVER_A = (-B_PRIME) * A_PRIME.inv()
_B_OVER_ZA = B_PRIME * (Z * A_PRIME).inv()

_ISO_X_NUM = [OFp2(c0, c1) for c0, c1 in g2_isogeny.X_NUM]
_ISO_X_DEN = [OFp2(c0, c1) for c0, c1 in g2_isogeny.X_DEN]
_ISO_Y_NUM = [-OFp2(c0, c1) for c0, c1 in g2_isogeny.Y_NUM]
_ISO_Y_DEN = [OFp2(c0, c1) for c0, c1 in g2_isogeny.Y_DEN]

_X_ABS_BITS = [int(c) for c in bin(abs(params.X))[2:]]
assert params.X < 0  # BLS12-381: the BLS parameter is negative


def _stable(a):
    """Reduce both coords to the stable bound class (scan-carry safe)."""
    return (F.relabel(F.guard_le(a[0], 2.0), 2.0), F.relabel(F.guard_le(a[1], 2.0), 2.0))


def fp2_pow_static(a, bits: list[int]):
    """a^e for a static exponent (MSB-first bits), batched."""
    a = _stable(a)
    # real TPU: chunked in-kernel Fp2 square-and-multiply (pallas_fp) —
    # the sqrt/cofactor chains drop from ~1 XLA dispatch per bit to one
    # kernel per 8 bits
    if F.chains_active() and bits[0] == 1 and len(bits) > 4:
        from . import pallas_fp as PF

        bshape = F.batch_shape(a[0])
        r0, r1 = PF.fp2_pow_chain(
            a[0].limbs.reshape(F.N, -1),
            a[1].limbs.reshape(F.N, -1),
            tuple(bits),
        )
        out = (
            F.LFp(r0.reshape((F.N,) + bshape), 6.0),
            F.LFp(r1.reshape((F.N,) + bshape), 6.0),
        )
        return _stable(out)
    bit_arr = jnp.array(bits, dtype=jnp.uint32)

    def step(acc, bit):
        acc = _stable(T.fp2_sqr(acc))
        withmul = _stable(T.fp2_mul(acc, a))
        out = T.fp2_select(bit == 1, withmul, acc)
        out = (F.relabel(out[0], 2.0), F.relabel(out[1], 2.0))
        return out, None

    one = tuple(F.relabel(c, 2.0) for c in T.fp2_one_like(a))
    acc, _ = lax.scan(step, one, bit_arr)
    return acc


def fp2_sqrt_or_flag(gx):
    """(y, is_square): y^2 == gx where is_square, via the q ≡ 9 (mod 16)
    candidate method — ONE big exponentiation + three constant muls."""
    gx = _stable(gx)
    bshape = F.batch_shape(gx[0])
    t = fp2_pow_static(gx, _SQRT_EXP_BITS)
    cands = [t]
    for c in (_C1, _C2, _C3):
        cc = T.fp2_const(c, bshape)
        cands.append(T.fp2_mul(t, cc))
    y = cands[0]
    ok = T.fp2_eq(T.fp2_sqr(cands[0]), gx)
    for cand in cands[1:]:
        match = T.fp2_eq(T.fp2_sqr(cand), gx)
        y = T.fp2_select(match & ~ok, cand, y)
        ok = ok | match
    return _stable(y), ok


def _demont(c):
    """Montgomery -> standard-domain limbs: mont_mul by the literal 1
    (a * 1 * R^-1 = a_std).  Parity/sign live in the STANDARD domain; the
    Montgomery residue's parity is uncorrelated garbage."""
    bshape = F.batch_shape(c)
    one_raw = F.LFp(F.bcast(jnp.asarray(F.int_to_limbs(1)), bshape), 1.0)
    return F.mont_mul(F.guard_le(c, 4.0), one_raw)


def fp2_sgn0(a):
    """RFC 9380 sgn0 for Fp2: parity of c0, tie-broken by c1 when c0 = 0 —
    computed on the standard-domain values."""
    c0 = F.fp_canon(_demont(a[0]))
    c1 = F.fp_canon(_demont(a[1]))
    c0_zero = jnp.all(c0 == 0, axis=0)
    return jnp.where(c0_zero, c1[0] & 1, c0[0] & 1)


def _gx(x, A, B):
    """x^3 + A x + B on the auxiliary curve."""
    x2 = T.fp2_sqr(x)
    (x3,) = T.fp2_mul_many([x2], [x])
    (ax,) = T.fp2_mul_many([A], [x])
    return T.fp2_add(T.fp2_add(x3, ax), B)


def sswu_g2(u):
    """Batched branchless simplified-SWU onto E' (affine)."""
    u = _stable(u)
    bshape = F.batch_shape(u[0])
    Zc = T.fp2_const(Z, bshape)
    Ac = T.fp2_const(A_PRIME, bshape)
    Bc = T.fp2_const(B_PRIME, bshape)
    (u2,) = [T.fp2_sqr(u)]
    (tv,) = T.fp2_mul_many([Zc], [u2])
    tv2 = T.fp2_add(T.fp2_sqr(tv), tv)
    tv2_zero = T.fp2_is_zero(tv2)
    # guard the inversion against the zero case (select afterwards)
    one = T.fp2_one_like(u)
    safe_tv2 = T.fp2_select(tv2_zero, one, tv2)
    inv_tv2 = T.fp2_inv(safe_tv2)
    nboa = T.fp2_const(_NEG_B_OVER_A, bshape)
    (x1_main,) = T.fp2_mul_many([nboa], [T.fp2_add(one, inv_tv2)])
    x1 = T.fp2_select(tv2_zero, T.fp2_const(_B_OVER_ZA, bshape), x1_main)
    x1 = _stable(x1)
    gx1 = _gx(x1, Ac, Bc)
    y1, sq1 = fp2_sqrt_or_flag(gx1)
    (x2,) = T.fp2_mul_many([tv], [x1])
    x2 = _stable(x2)
    gx2 = _gx(x2, Ac, Bc)
    y2, _sq2 = fp2_sqrt_or_flag(gx2)
    x = T.fp2_select(sq1, x1, x2)
    y = T.fp2_select(sq1, y1, y2)
    # sign fix: sgn0(y) must equal sgn0(u)
    flip = fp2_sgn0(u) != fp2_sgn0(y)
    y = T.fp2_select(flip, T.fp2_neg(y), y)
    return _stable(x), _stable(y)


def _horner(coeffs, x, bshape):
    acc = T.fp2_const(coeffs[-1], bshape)
    for c in reversed(coeffs[:-1]):
        (acc_x,) = T.fp2_mul_many([acc], [x])
        acc = T.fp2_add(acc_x, T.fp2_const(c, bshape))
    return acc


def iso_map_g2(xy):
    """The derived 3-isogeny E' -> E2, batched (denominators of hash
    outputs are nonzero with overwhelming probability; the kernel case maps
    through garbage guarded upstream by on-curve construction)."""
    x, y = xy
    bshape = F.batch_shape(x[0])
    xn = _horner(_ISO_X_NUM, x, bshape)
    xd = _horner(_ISO_X_DEN, x, bshape)
    yn = _horner(_ISO_Y_NUM, x, bshape)
    yd = _horner(_ISO_Y_DEN, x, bshape)
    inv_xd = T.fp2_inv(xd)
    inv_yd = T.fp2_inv(yd)
    (X,) = T.fp2_mul_many([xn], [inv_xd])
    (yfrac,) = T.fp2_mul_many([yn], [inv_yd])
    (Y,) = T.fp2_mul_many([y], [yfrac])
    return _stable(X), _stable(Y)


def _pt_stable(p):
    """Reduce every Jacobian coordinate to the stable bound class (point
    negation/addition inflate bounds past scalar_mul_bits' 2.0 pin)."""

    def red(c):
        if isinstance(c, F.LFp):
            return F.relabel(F.guard_le(c, 2.0), 2.0)
        return tuple(red(x) for x in c)

    return tuple(red(c) for c in p[:3]) + (p[3],)


def _bits_for(bshape, bits):
    return jnp.broadcast_to(
        jnp.array(bits, dtype=jnp.uint32).reshape((len(bits),) + (1,) * len(bshape)),
        (len(bits),) + tuple(bshape),
    )


def clear_cofactor_g2(xy):
    """Budroni-Pintore via psi (endo.clear_cofactor_fast's device twin):
    h_eff · P = [x^2 - x - 1]P + [x - 1]psi(P) + psi^2([2]P), computed with
    |x| scalar ladders and sign-corrected adds (x < 0).  Input affine on
    E2, output Jacobian in G2."""
    xy = (_stable(xy[0]), _stable(xy[1]))
    bshape = F.batch_shape(xy[0][0])
    bits = _bits_for(bshape, _X_ABS_BITS)
    Pj = P.from_affine(P.FP2_OPS, xy)
    absxP = P.scalar_mul_bits(P.FP2_OPS, Pj, bits)  # [|x|]P
    xP = _pt_stable(P.pt_neg(P.FP2_OPS, absxP))  # [x]P (x < 0)
    absx_xP = P.scalar_mul_bits(P.FP2_OPS, xP, bits)  # [|x|][x]P
    x2P = P.pt_neg(P.FP2_OPS, absx_xP)  # [x^2]P
    acc = P.jac_add(P.FP2_OPS, x2P, P.pt_neg(P.FP2_OPS, xP))  # [x^2 - x]P
    acc = P.jac_add(P.FP2_OPS, acc, P.pt_neg(P.FP2_OPS, Pj))  # - P
    # [x-1] psi(P) = [x]psi(P) - psi(P)
    psiP_aff = P.psi_affine(xy)
    psiPj = _pt_stable(P.from_affine(P.FP2_OPS, psiP_aff))
    abs_psi = P.scalar_mul_bits(P.FP2_OPS, psiPj, bits)
    x_psi = P.pt_neg(P.FP2_OPS, abs_psi)
    acc = P.jac_add(P.FP2_OPS, acc, x_psi)
    acc = P.jac_add(P.FP2_OPS, acc, P.pt_neg(P.FP2_OPS, psiPj))
    # psi^2([2]P): psi twice on affine 2P — need 2P affine; compute in
    # Jacobian then affinize (one fp2 inversion, batched)
    twoP = P.jac_double(P.FP2_OPS, Pj)
    twoP_aff = P.to_affine(P.FP2_OPS, twoP, T.fp2_inv)
    psi2_aff = P.psi_affine(P.psi_affine(twoP_aff))
    acc = P.jac_add(P.FP2_OPS, acc, P.from_affine(P.FP2_OPS, psi2_aff))
    return acc


def map_to_g2(u0, u1):
    """Device hash_to_curve minus the hashing: (u0, u1) Fp2 batches ->
    affine G2 points (the kernel's h_aff input)."""
    q0 = iso_map_g2(sswu_g2(u0))
    q1 = iso_map_g2(sswu_g2(u1))
    s = P.jac_add(
        P.FP2_OPS, P.from_affine(P.FP2_OPS, q0), P.from_affine(P.FP2_OPS, q1)
    )
    s_aff = P.to_affine(P.FP2_OPS, s, T.fp2_inv)
    g = clear_cofactor_g2(s_aff)
    return P.to_affine(P.FP2_OPS, g, T.fp2_inv)


# ---------------------------------------------------------------------------
# host codec: messages -> u-value limbs
# ---------------------------------------------------------------------------


def encode_u_values(msgs: list[bytes], dst: bytes = params.DST):
    """Host: SHA-256 expansion only (fast), -> two Fp2 limb batches."""
    from ..hash_to_curve import hash_to_field_fp2

    u0s, u1s = [], []
    for m in msgs:
        u0, u1 = hash_to_field_fp2(m, 2, dst)
        u0s.append(u0)
        u1s.append(u1)
    return T.fp2_encode(u0s), T.fp2_encode(u1s)
